"""Executor end-to-end tests: feed/fetch, whole-block jit caching, training
convergence, rng determinism (ref tests/test_executor_and_mul.py)."""
import numpy as np

import paddle_tpu as fluid


def _build_linreg():
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    cost = fluid.layers.square_error_cost(input=pred, label=y)
    avg = fluid.layers.mean(x=cost)
    return pred, avg


def test_feed_fetch_mul():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    y = fluid.layers.fc(input=x, size=2, bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(5, 3).astype('float32')
    out, = exe.run(feed={'x': xv}, fetch_list=[y])
    w_name = [v.name for v in fluid.default_main_program().list_vars()
              if isinstance(v, fluid.Parameter)][0]
    w = fluid.global_scope().get_numpy(w_name)
    np.testing.assert_allclose(out, xv @ w, rtol=1e-4)


def test_training_reduces_loss():
    pred, avg = _build_linreg()
    opt = fluid.optimizer.SGD(learning_rate=0.02)
    opt.minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    W = rng.randn(13, 1).astype('float32')
    losses = []
    for _ in range(60):
        xb = rng.randn(32, 13).astype('float32')
        loss, = exe.run(feed={'x': xb, 'y': xb @ W}, fetch_list=[avg])
        losses.append(float(np.asarray(loss).ravel()[0]))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_adam_training():
    pred, avg = _build_linreg()
    fluid.optimizer.Adam(learning_rate=0.05).minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    W = rng.randn(13, 1).astype('float32')
    losses = []
    for _ in range(60):
        xb = rng.randn(32, 13).astype('float32')
        loss, = exe.run(feed={'x': xb, 'y': xb @ W}, fetch_list=[avg])
        losses.append(float(np.asarray(loss).ravel()[0]))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_fetch_variable_and_name():
    x = fluid.layers.data(name='x', shape=[2], dtype='float32')
    y = fluid.layers.scale(x=x, scale=3.0)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 2), 'float32')
    a, b = exe.run(feed={'x': xv}, fetch_list=[y, y.name])
    np.testing.assert_allclose(a, 3 * xv)
    np.testing.assert_allclose(b, 3 * xv)


def test_dropout_train_vs_test():
    x = fluid.layers.data(name='x', shape=[100], dtype='float32')
    d = fluid.layers.dropout(x=x, dropout_prob=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((4, 100), 'float32')
    out, = exe.run(feed={'x': xv}, fetch_list=[d])
    frac = (np.asarray(out) == 0).mean()
    assert 0.25 < frac < 0.75  # roughly half dropped

    test_prog = fluid.default_main_program().inference_optimize()
    out2, = exe.run(test_prog, feed={'x': xv}, fetch_list=[d.name])
    # reference dropout_op.h is_test path: Out = X * (1 - p)
    np.testing.assert_allclose(out2, xv * 0.5)


def test_run_steps_matches_run_loop():
    """run_steps(K) (one lax.scan-compiled XLA program, donated state)
    is numerics-identical to K successive run() calls — same PRNG chain
    (dropout included), same optimizer state evolution."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.program import reset_unique_name_guard

    def build():
        with reset_unique_name_guard():
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 17
            with fluid.program_guard(main, startup):
                x = fluid.layers.data(name='x', shape=[8],
                                      dtype='float32')
                y = fluid.layers.data(name='y', shape=[1],
                                      dtype='float32')
                h = fluid.layers.fc(input=x, size=16, act='relu')
                h = fluid.layers.dropout(x=h, dropout_prob=0.3)
                p = fluid.layers.fc(input=h, size=1)
                loss = fluid.layers.mean(
                    x=fluid.layers.square_error_cost(input=p, label=y))
                fluid.optimizer.AdamOptimizer(
                    learning_rate=0.01).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(8)
    w = rng.randn(8, 1).astype('float32')
    batches = [{'x': (xb := rng.randn(8, 8).astype('float32')),
                'y': xb @ w} for _ in range(4)]

    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    want = [float(np.ravel(exe.run(main, feed=f, fetch_list=[loss])[0])[0])
            for f in batches]
    params_want = {p.name: np.asarray(fluid.global_scope().find_var(p.name))
                   for p in main.global_block().all_parameters()}

    # stacked-feeds mode
    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got = exe.run_steps(main, feed=batches, fetch_list=[loss])[0]
    np.testing.assert_allclose(np.ravel(got), want, rtol=1e-5, atol=1e-6)
    for n, v in params_want.items():
        np.testing.assert_allclose(
            np.asarray(fluid.global_scope().find_var(n)), v,
            rtol=1e-5, atol=1e-6, err_msg=n)

    # repeat-one-feed mode: equals 4 runs of the same batch
    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    want_rep = [float(np.ravel(exe.run(main, feed=batches[0],
                                       fetch_list=[loss])[0])[0])
                for _ in range(4)]
    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got_rep = exe.run_steps(main, feed=batches[0], fetch_list=[loss],
                            repeat=4)[0]
    np.testing.assert_allclose(np.ravel(got_rep), want_rep, rtol=1e-5,
                               atol=1e-6)


def test_run_steps_stacked_ragged_feeds_match_run_loop():
    """Stacked-feeds run_steps with (array, lengths) ragged feeds: the
    @LEN companions stack and scan along with the data, matching K
    run() calls exactly (ragged mean masks padded positions)."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.program import reset_unique_name_guard

    def build():
        with reset_unique_name_guard():
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 23
            with fluid.program_guard(main, startup):
                x = fluid.layers.data(name='x', shape=[1], dtype='int64',
                                      lod_level=1)
                emb = fluid.layers.embedding(input=x, size=[30, 6])
                pooled = fluid.layers.sequence_pool(input=emb,
                                                    pool_type='sum')
                pred = fluid.layers.fc(input=pooled, size=1)
                loss = fluid.layers.mean(x=fluid.layers.square(x=pred))
                fluid.optimizer.SGDOptimizer(
                    learning_rate=0.01).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(11)
    batches = []
    for _ in range(3):
        ids = rng.randint(0, 30, (4, 7, 1)).astype('int64')
        ln = rng.randint(1, 8, (4,)).astype('int32')
        batches.append({'x': (ids, ln)})

    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    want = [float(np.ravel(exe.run(main, feed=f,
                                   fetch_list=[loss])[0])[0])
            for f in batches]

    main, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got = exe.run_steps(main, feed=batches, fetch_list=[loss])[0]
    np.testing.assert_allclose(np.ravel(got), want, rtol=1e-5,
                               atol=1e-6)


def test_run_steps_inconsistent_feed_keys_named():
    """ADVICE r3: K feed dicts with different key sets fail with an error
    naming the step and the missing/extra keys, not an opaque scan-shape
    mismatch."""
    import pytest

    import paddle_tpu as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[2], dtype='float32')
        y = fluid.layers.data(name='y', shape=[2], dtype='float32')
        fluid.layers.elementwise_add(x=x, y=y)
    exe = fluid.Executor(fluid.CPUPlace())
    a = np.ones((3, 2), 'float32')
    feeds = [{'x': a, 'y': a}, {'x': a}]
    with pytest.raises(ValueError, match=r"step 1 is missing \['y'\]"):
        exe.run_steps(main, feed=feeds, fetch_list=[])


def test_run_steps_out_only_state_single_copy():
    """ADVICE r3: out-only persistables (written, never read — e.g. a
    metric accumulator snapshot) ride the scan carry; the value after
    run_steps(K) equals the K-th run() value."""
    import paddle_tpu as fluid
    from paddle_tpu.core.program import reset_unique_name_guard

    def build():
        with reset_unique_name_guard():
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 5
            with fluid.program_guard(main, startup):
                x = fluid.layers.data(name='x', shape=[4],
                                      dtype='float32')
                h = fluid.layers.fc(input=x, size=4)
                loss = fluid.layers.mean(x=fluid.layers.square(x=h))
                fluid.optimizer.SGDOptimizer(
                    learning_rate=0.1).minimize(loss)
                snap = fluid.layers.assign(loss)
                snap.persistable = True
        return main, startup, loss, snap

    rng = np.random.RandomState(2)
    batches = [{'x': rng.randn(4, 4).astype('float32')}
               for _ in range(3)]

    main, startup, loss, snap = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for f in batches:
        exe.run(main, feed=f, fetch_list=[loss])
    want = np.asarray(fluid.global_scope().find_var(snap.name))

    main, startup, loss, snap = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run_steps(main, feed=batches, fetch_list=[loss])
    got = np.asarray(fluid.global_scope().find_var(snap.name))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_plan_cache_keys_on_scope_uid_not_id():
    """Plan-cache scope identity is a monotonic uid: id() reuse after gc
    must not alias a new scope's plans with a dead scope's."""
    import gc

    import paddle_tpu as fluid

    s1 = fluid.Scope()
    s2 = fluid.Scope()
    assert s1._uid != s2._uid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[3], dtype='float32')
        y = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {'x': np.ones((2, 3), np.float32)}

    scope_a = fluid.Scope()
    exe.run(startup, scope=scope_a)
    exe.run(main, feed=feed, fetch_list=[y], scope=scope_a)
    n_after_a = len(exe._cache)
    uid_a = scope_a._uid
    del scope_a
    gc.collect()

    scope_b = fluid.Scope()
    assert scope_b._uid != uid_a
    exe.run(startup, scope=scope_b)
    exe.run(main, feed=feed, fetch_list=[y], scope=scope_b)
    # a fresh scope compiles fresh plans instead of aliasing the dead
    # scope's entries
    assert len(exe._cache) > n_after_a


def test_use_program_cache_false_bypasses_insertion():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[3], dtype='float32')
        y = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, use_program_cache=False)
    feed = {'x': np.ones((2, 3), np.float32)}
    out1, = exe.run(main, feed=feed, fetch_list=[y],
                    use_program_cache=False)
    assert exe._cache == {}
    out2, = exe.run(main, feed=feed, fetch_list=[y])
    assert len(exe._cache) == 1
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_persistent_compilation_cache_flag(tmp_path, monkeypatch):
    """PADDLE_TPU_COMPILATION_CACHE_DIR wires jax's persistent
    compilation cache: compiled executables land on disk and survive a
    process restart."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core import executor as executor_mod

    cache_dir = tmp_path / 'xla_cache'
    monkeypatch.setenv('PADDLE_TPU_COMPILATION_CACHE_DIR',
                       str(cache_dir))
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[3], dtype='float32')
            y = fluid.layers.fc(input=x, size=2)
        exe = fluid.Executor(fluid.CPUPlace())  # applies the flag
        assert jax.config.jax_compilation_cache_dir == str(cache_dir)
        exe.run(startup)
        exe.run(main, feed={'x': np.ones((2, 3), np.float32)},
                fetch_list=[y])
        assert cache_dir.exists() and any(cache_dir.iterdir())
    finally:
        monkeypatch.delenv('PADDLE_TPU_COMPILATION_CACHE_DIR',
                           raising=False)
        executor_mod._maybe_enable_compilation_cache()  # back to off
        assert jax.config.jax_compilation_cache_dir is None
