"""Executor end-to-end tests: feed/fetch, whole-block jit caching, training
convergence, rng determinism (ref tests/test_executor_and_mul.py)."""
import numpy as np

import paddle_tpu as fluid


def _build_linreg():
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    cost = fluid.layers.square_error_cost(input=pred, label=y)
    avg = fluid.layers.mean(x=cost)
    return pred, avg


def test_feed_fetch_mul():
    x = fluid.layers.data(name='x', shape=[3], dtype='float32')
    y = fluid.layers.fc(input=x, size=2, bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(5, 3).astype('float32')
    out, = exe.run(feed={'x': xv}, fetch_list=[y])
    w_name = [v.name for v in fluid.default_main_program().list_vars()
              if isinstance(v, fluid.Parameter)][0]
    w = fluid.global_scope().get_numpy(w_name)
    np.testing.assert_allclose(out, xv @ w, rtol=1e-4)


def test_training_reduces_loss():
    pred, avg = _build_linreg()
    opt = fluid.optimizer.SGD(learning_rate=0.02)
    opt.minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    W = rng.randn(13, 1).astype('float32')
    losses = []
    for _ in range(60):
        xb = rng.randn(32, 13).astype('float32')
        loss, = exe.run(feed={'x': xb, 'y': xb @ W}, fetch_list=[avg])
        losses.append(float(np.asarray(loss).ravel()[0]))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_adam_training():
    pred, avg = _build_linreg()
    fluid.optimizer.Adam(learning_rate=0.05).minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    W = rng.randn(13, 1).astype('float32')
    losses = []
    for _ in range(60):
        xb = rng.randn(32, 13).astype('float32')
        loss, = exe.run(feed={'x': xb, 'y': xb @ W}, fetch_list=[avg])
        losses.append(float(np.asarray(loss).ravel()[0]))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_fetch_variable_and_name():
    x = fluid.layers.data(name='x', shape=[2], dtype='float32')
    y = fluid.layers.scale(x=x, scale=3.0)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 2), 'float32')
    a, b = exe.run(feed={'x': xv}, fetch_list=[y, y.name])
    np.testing.assert_allclose(a, 3 * xv)
    np.testing.assert_allclose(b, 3 * xv)


def test_dropout_train_vs_test():
    x = fluid.layers.data(name='x', shape=[100], dtype='float32')
    d = fluid.layers.dropout(x=x, dropout_prob=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((4, 100), 'float32')
    out, = exe.run(feed={'x': xv}, fetch_list=[d])
    frac = (np.asarray(out) == 0).mean()
    assert 0.25 < frac < 0.75  # roughly half dropped

    test_prog = fluid.default_main_program().inference_optimize()
    out2, = exe.run(test_prog, feed={'x': xv}, fetch_list=[d.name])
    # reference dropout_op.h is_test path: Out = X * (1 - p)
    np.testing.assert_allclose(out2, xv * 0.5)
