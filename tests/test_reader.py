"""Reader decorators + minibatch + synthetic datasets.

Mirrors reference tests python/paddle/v2/reader/tests/decorator_test.py and
dataset/tests/*."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import reader as rd
from paddle_tpu import datasets


def counter(n):
    def r():
        for i in range(n):
            yield i
    return r


def test_map_readers():
    out = list(rd.map_readers(lambda a, b: a + b, counter(3), counter(3))())
    assert out == [0, 2, 4]


def test_shuffle_preserves_multiset():
    out = list(rd.shuffle(counter(100), 17)())
    assert sorted(out) == list(range(100))


def test_chain_compose():
    assert list(rd.chain(counter(2), counter(3))()) == [0, 1, 0, 1, 2]
    out = list(rd.compose(counter(3), counter(3))())
    assert out == [(0, 0), (1, 1), (2, 2)]
    with pytest.raises(rd.ComposeNotAligned):
        list(rd.compose(counter(3), counter(4))())


def test_buffered_and_firstn_and_cache():
    assert list(rd.buffered(counter(10), 2)()) == list(range(10))
    assert list(rd.firstn(counter(10), 3)()) == [0, 1, 2]
    c = rd.cache(counter(5))
    assert list(c()) == list(c()) == list(range(5))


def test_xmap_readers():
    for order in (False, True):
        out = list(rd.xmap_readers(lambda x: x * 2, counter(32), 4, 8,
                                   order=order)())
        if order:
            assert out == [i * 2 for i in range(32)]
        else:
            assert sorted(out) == [i * 2 for i in range(32)]


def test_batch():
    bs = list(rd.batch(counter(10), 4)())
    assert [len(b) for b in bs] == [4, 4, 2]
    bs = list(rd.batch(counter(10), 4, drop_last=True)())
    assert [len(b) for b in bs] == [4, 4]


def test_mnist_shapes():
    img, label = next(datasets.mnist.train()())
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert 0 <= label < 10


def test_mnist_deterministic():
    a = [l for _, l in rd.firstn(datasets.mnist.train(), 10)()]
    b = [l for _, l in rd.firstn(datasets.mnist.train(), 10)()]
    assert a == b


def test_uci_housing():
    x, y = next(datasets.uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)


def test_cifar():
    img, label = next(datasets.cifar.train10()())
    assert img.shape == (3072,) and 0 <= label < 10
    img, label = next(datasets.cifar.train100()())
    assert 0 <= label < 100


def test_imdb():
    w = datasets.imdb.word_dict()
    ids, label = next(datasets.imdb.train(w)())
    assert all(0 <= i < len(w) for i in ids)
    assert label in (0, 1)


def test_imikolov():
    w = datasets.imikolov.build_dict()
    g = next(datasets.imikolov.train(w, 5)())
    assert len(g) == 5
    src, trg = next(datasets.imikolov.train(
        w, 5, datasets.imikolov.DataType.SEQ)())
    assert len(src) == len(trg)
    assert src[0] == w['<s>'] and trg[-1] == w['<e>']


def test_movielens():
    sample = next(datasets.movielens.train()())
    uid, gender, age, job, mid, cats, title, rating = sample
    assert 1 <= uid <= datasets.movielens.max_user_id()
    assert gender in (0, 1)
    assert 0 <= job <= datasets.movielens.max_job_id()
    assert isinstance(cats, list) and isinstance(title, list)
    assert -5.0 <= rating[0] <= 5.0


def test_wmt14():
    src, trg, trg_next = next(datasets.wmt14.train(1000)())
    assert trg[0] == datasets.wmt14.START_ID
    assert trg_next[-1] == datasets.wmt14.END_ID
    assert trg[1:] == trg_next[:-1]


def test_conll05():
    sample = next(datasets.conll05.test()())
    assert len(sample) == 9
    L = len(sample[0])
    assert all(len(s) == L for s in sample)
    word_d, verb_d, label_d = datasets.conll05.get_dict()
    assert 'B-V' in label_d


def test_mq2007():
    hi, lo = next(datasets.mq2007.train('pairwise')())
    assert hi.shape == (46,) and lo.shape == (46,)


def test_recordio_roundtrip(tmp_path):
    from paddle_tpu.io_recordio import RecordReader, RecordWriter
    p = str(tmp_path / "f.rec")
    with RecordWriter(p) as w:
        for i in range(10):
            w.write(b'payload-%d' % i)
    got = [r for r in RecordReader(p)]
    assert got == [b'payload-%d' % i for i in range(10)]


def test_xmap_mapper_error_propagates():
    """A mapper exception surfaces in the consumer (both ordered and
    unordered paths) instead of hanging the reader."""
    import pytest

    from paddle_tpu.runtime import native as _native

    def bad(x):
        if x == 5:
            raise RuntimeError("boom on 5")
        return x

    # exercise the pure-python fallback even when the native queue built
    orig = _native.available
    _native.available = lambda: False
    try:
        for order in (False, True):
            r = rd.xmap_readers(bad, counter(16), 3, 4, order=order)
            with pytest.raises(RuntimeError, match="boom on 5"):
                list(r())
    finally:
        _native.available = orig
    if orig():  # and the native path, when present
        for order in (False, True):
            r = rd.xmap_readers(bad, counter(16), 3, 4, order=order)
            with pytest.raises(RuntimeError, match="boom on 5"):
                list(r())


def test_xmap_single_worker_full_queue_error():
    """Code-review r4: one worker, input queue full (reader outpaces the
    mapper) — the error must still reach the consumer, not deadlock on a
    blocking in_q.put."""
    import pytest
    from paddle_tpu.runtime import native as _native

    def bad(x):
        raise RuntimeError("always fails")

    orig = _native.available
    _native.available = lambda: False
    try:
        r = rd.xmap_readers(bad, counter(100), 1, 2, order=False)
        with pytest.raises(RuntimeError, match="always fails"):
            list(r())
    finally:
        _native.available = orig


def test_feed_pipeline_error_beats_stalled_sibling_ring():
    """One worker's fill() exception must surface even when the
    consumer is blocked on ANOTHER worker's ring (whose fill never
    completes): the erroring worker closes every ready ring, so the
    consumer wakes, sees the recorded error on the None pop, and
    raises instead of hanging or reporting clean end-of-stream."""
    import threading

    import pytest

    from paddle_tpu.runtime.feed import FeedPipeline

    release = threading.Event()

    def fill(views, step):
        if step % 2 == 0:
            # worker 0 (owns the ring the consumer waits on first):
            # stall until teardown
            release.wait(10)
            return False
        raise RuntimeError('worker 1 fill exploded')

    pipe = FeedPipeline({'x': ((2,), np.float32)}, fill, workers=2,
                        stage=False)
    result = {}

    def consume():
        try:
            for _ in pipe:
                pass
            result['end'] = 'clean'
        except RuntimeError:
            result['end'] = 'raised'

    th = threading.Thread(target=consume, daemon=True)
    th.start()
    th.join(5)
    release.set()
    pipe.close()
    assert result.get('end') == 'raised', result


def test_feed_pipeline_depth_limit_clear_error():
    """depth > 256 (directly or via the 2*workers floor) fails at
    construction with an actionable message, not an opaque bytes()
    ValueError from token encoding."""
    import pytest

    from paddle_tpu.runtime.feed import FeedPipeline

    def fill(views, step):
        return False

    with pytest.raises(ValueError, match='256'):
        FeedPipeline({'x': ((2,), np.float32)}, fill, depth=300)
    with pytest.raises(ValueError, match='2\\*workers'):
        FeedPipeline({'x': ((2,), np.float32)}, fill, workers=129)


def test_xmap_native_stalled_sibling_does_not_swallow_error():
    """PR-4's FeedPipeline ring-close fix, mirrored onto xmap_native:
    one worker's mapper exception must surface in the consumer even
    while a SIBLING worker is stalled inside its mapper — the old
    shutdown pushed the end-sentinel only after EVERY worker counted
    down, so the consumer hung forever waiting on the stalled one."""
    import threading

    from paddle_tpu.runtime.prefetch import xmap_native

    release = threading.Event()

    def stall_or_boom(x):
        if x == 0:
            release.wait(15)  # stalled sibling (released at teardown)
            return x
        raise RuntimeError('mapper exploded')

    def source():
        for i in range(8):
            yield i

    result = {}

    def consume():
        try:
            list(xmap_native(stall_or_boom, source, process_num=2,
                             buffer_size=2)())
            result['end'] = 'clean'
        except RuntimeError:
            result['end'] = 'raised'

    th = threading.Thread(target=consume, daemon=True)
    th.start()
    th.join(5)  # must not need the stalled worker to finish
    alive = th.is_alive()
    release.set()
    assert not alive, 'consumer hung on the stalled sibling'
    assert result.get('end') == 'raised', result


def test_xmap_native_reader_error_with_stalled_worker():
    """The feeder-error ring-close: a READER exception must surface in
    the consumer even while a worker is stalled inside its mapper —
    _END-per-worker alone relies on the n_done countdown, which the
    stalled worker never reaches."""
    import threading

    from paddle_tpu.runtime.prefetch import xmap_native

    release = threading.Event()

    def stall_first(x):
        if x == 0:
            release.wait(15)  # stalled sibling (released at teardown)
        return x

    def bad_reader():
        yield 0
        yield 1
        raise RuntimeError('reader exploded')

    result = {}

    def consume():
        try:
            list(xmap_native(stall_first, bad_reader, process_num=2,
                             buffer_size=2)())
            result['end'] = 'clean'
        except RuntimeError:
            result['end'] = 'raised'

    th = threading.Thread(target=consume, daemon=True)
    th.start()
    th.join(5)  # must not need the stalled worker to finish
    alive = th.is_alive()
    release.set()
    assert not alive, 'consumer hung on the stalled worker'
    assert result.get('end') == 'raised', result


def test_xmap_native_reader_error_propagates():
    """A READER exception inside the feeder thread must reach the
    consumer instead of masquerading as a clean, silently-truncated
    end-of-stream (the worker-side fix alone never saw it: the feeder
    had no except at all)."""
    from paddle_tpu.runtime.prefetch import xmap_native

    def bad_reader():
        yield 1
        yield 2
        raise RuntimeError('reader exploded')

    for order in (False, True):
        with pytest.raises(RuntimeError, match='reader exploded'):
            list(xmap_native(lambda x: x, bad_reader, process_num=2,
                             buffer_size=4, order=order)())
