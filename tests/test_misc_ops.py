"""Remaining op-group tests: tensor-array, conv variants, misc math.

Reference parity: python/paddle/v2/fluid/tests/test_{array_read_write,
conv_shift,row_conv,maxout,spp,prelu,bilinear_tensor_product,clip_by_norm,
norm,sign,minus}_op.py.
"""
import numpy as np

from op_test import run_op

rng = np.random.RandomState(41)


def test_tensor_array_write_read_length():
    arr = run_op('create_array', {}, {
        'capacity': 4, 'elem_shape': [2, 3],
        'elem_dtype': 'float32'})['Out'][0]
    assert np.asarray(arr.data).shape == (4, 2, 3)
    v = rng.randn(2, 3).astype('float32')
    i = np.array([1], dtype='int64')
    arr2 = run_op('write_to_array',
                  {'Array': [arr], 'V': v, 'I': i})['Out'][0]
    np.testing.assert_allclose(np.asarray(arr2.data)[1], v, rtol=1e-6)
    assert np.all(np.asarray(arr2.data)[0] == 0)
    back = np.asarray(run_op('read_from_array',
                             {'X': [arr2], 'I': i})['Out'][0])
    np.testing.assert_allclose(back, v, rtol=1e-6)
    # size tracks the highest written index + 1
    ln = np.asarray(run_op('array_length', {'X': [arr2]})['Out'][0])
    assert int(np.ravel(ln)[0]) == 2


def test_conv_shift():
    x = rng.randn(3, 6).astype('float32')
    y = rng.randn(3, 3).astype('float32')
    got = np.asarray(run_op('conv_shift', {'X': x, 'Y': y})['Out'][0])
    # circular correlation: out[i] = sum_j y[j] * x[(i + j - M//2) mod N]
    B, N = x.shape
    M = y.shape[1]
    want = np.zeros_like(x)
    for b in range(B):
        for i in range(N):
            for j in range(M):
                want[b, i] += y[b, j] * x[b, (i + j - M // 2) % N]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_row_conv():
    B, T, D, W = 2, 5, 3, 2
    x = rng.randn(B, T, D).astype('float32')
    w = rng.randn(W, D).astype('float32')
    lengths = np.array([5, 3], dtype='int64')
    for b in range(B):  # LoD convention: padded tail is zero
        x[b, lengths[b]:] = 0
    got = np.asarray(run_op('row_conv', {'X': x, 'Filter': w})['Out'][0])
    # lookahead conv: out[t] = sum_{j<W, t+j < len} w[j] * x[t+j]
    for b in range(B):
        ln = int(lengths[b])
        for t in range(ln):
            want = np.zeros(D, 'float32')
            for j in range(W):
                if t + j < ln:
                    want += w[j] * x[b, t + j]
            np.testing.assert_allclose(got[b, t], want, rtol=1e-4,
                                       atol=1e-5)


def test_maxout():
    x = rng.randn(2, 6, 3, 3).astype('float32')
    got = np.asarray(run_op('maxout', {'X': x}, {'groups': 2})['Out'][0])
    want = x.reshape(2, 3, 2, 3, 3).max(axis=2)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_spp():
    x = rng.randn(1, 2, 8, 8).astype('float32')
    got = np.asarray(run_op('spp', {'X': x},
                            {'pyramid_height': 2})['Out'][0])
    # levels: 1x1 + 2x2 bins, each C channels → C*(1+4)
    assert got.shape == (1, 2 * 5)
    np.testing.assert_allclose(got[0, :2], x.max(axis=(2, 3))[0],
                               rtol=1e-5)


def test_prelu():
    x = rng.randn(3, 4).astype('float32')
    alpha = np.array([0.25], dtype='float32')
    got = np.asarray(run_op('prelu', {'X': x, 'Alpha': alpha})['Out'][0])
    want = np.where(x > 0, x, 0.25 * x)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_bilinear_tensor_product():
    B, M, N, K = 2, 3, 4, 5
    x = rng.randn(B, M).astype('float32')
    y = rng.randn(B, N).astype('float32')
    w = rng.randn(K, M, N).astype('float32')
    got = np.asarray(run_op('bilinear_tensor_product',
                            {'X': x, 'Y': y, 'Weight': w})['Out'][0])
    want = np.einsum('bm,kmn,bn->bk', x, w, y)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_clip_by_norm():
    x = rng.randn(4, 4).astype('float32') * 10
    got = np.asarray(run_op('clip_by_norm', {'X': x},
                            {'max_norm': 1.0})['Out'][0])
    norm = np.sqrt((x ** 2).sum())
    np.testing.assert_allclose(got, x / norm, rtol=1e-4, atol=1e-5)
    small = rng.randn(2, 2).astype('float32') * 0.01
    got2 = np.asarray(run_op('clip_by_norm', {'X': small},
                             {'max_norm': 1.0})['Out'][0])
    np.testing.assert_allclose(got2, small, rtol=1e-5)


def test_norm_sign_minus():
    x = rng.randn(3, 4).astype('float32')
    # norm op L2-normalizes along axis (operators/norm_op)
    n = np.asarray(run_op('norm', {'X': x})['Out'][0])
    want = x / np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(n, want, rtol=1e-4, atol=1e-5)
    s = np.asarray(run_op('sign', {'X': x})['Out'][0])
    np.testing.assert_array_equal(s, np.sign(x))
    y = rng.randn(3, 4).astype('float32')
    m = np.asarray(run_op('minus', {'X': x, 'Y': y})['Out'][0])
    np.testing.assert_allclose(m, x - y, rtol=1e-5)


def test_is_empty_and_get_places():
    empty = np.zeros((0, 3), 'float32')
    got = np.asarray(run_op('is_empty', {'X': empty})['Out'][0])
    assert bool(np.ravel(got)[0])
    full = np.zeros((2, 3), 'float32')
    got2 = np.asarray(run_op('is_empty', {'X': full})['Out'][0])
    assert not bool(np.ravel(got2)[0])


def test_get_places_layer():
    # layers.device.get_places parity (ref fluid/layers/device.py)
    import paddle_tpu as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = fluid.layers.get_places(device_count=4)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(main, feed={}, fetch_list=[p])
    np.testing.assert_array_equal(np.asarray(got), np.arange(4))
