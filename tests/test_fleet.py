"""ServingFleet: multi-replica dispatch, lifecycle, and hot-swap.

Covers the fleet contract end to end on the CPU smoke config: version
resolution, queue-depth routing, drain-vs-close on the batching server,
dispatch-failure containment (retry + unroutable + health restore),
versioned deploy/rollback under live traffic with zero dropped
requests, warm-cache cold start, and metric labeling/retirement.
"""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io, observability
from paddle_tpu.inference import (BatchingInferenceServer,
                                  InferenceServer, ServingFleet,
                                  export_bucketed)

MAX_BATCH = 4


def _build_mlp(seed):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[6], dtype='float32')
        h = fluid.layers.fc(input=x, size=16, act='relu')
        pred = fluid.layers.fc(input=h, size=4)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    return main, scope, exe, pred


@pytest.fixture(scope='module')
def versions(tmp_path_factory):
    """A TF-Serving-style base dir with two numbered model versions
    (different init seeds, so their outputs differ measurably)."""
    base = tmp_path_factory.mktemp('model_versions')
    for ver, seed in (('1', 11), ('2', 42)):
        main, scope, exe, pred = _build_mlp(seed)
        export_bucketed(str(base / ver), {'x': (6,)}, [pred],
                        executor=exe, main_program=main, scope=scope,
                        max_batch=MAX_BATCH)
    return str(base)


def _feed(rng, rows=1):
    return {'x': rng.randn(rows, 6).astype('float32')}


def _mk_fleet(versions, **kw):
    kw.setdefault('replicas', 2)
    kw.setdefault('max_wait_ms', 20.0)
    kw.setdefault('linger_ms', 0.5)
    kw.setdefault('health_interval_ms', 0)  # off unless a test needs it
    return ServingFleet(versions, **kw)


# -- io.py version resolution -----------------------------------------
def test_resolve_version_dir(versions, tmp_path):
    d, name = io.resolve_version_dir(versions)
    assert name == '2' and d.endswith('2')  # highest number wins
    d1, n1 = io.resolve_version_dir(versions, version='1')
    assert n1 == '1' and io.bucket_artifacts(d1)
    # a bare artifact dir resolves to itself
    d2, n2 = io.resolve_version_dir(os.path.join(versions, '1'))
    assert d2 == os.path.join(versions, '1') and n2 == '1'
    assert sorted(io.bucket_artifacts(d2)) == [1, 2, 4]
    with pytest.raises(ValueError):
        io.resolve_version_dir(versions, version='99')
    # a dir holding neither artifacts nor version subdirs with them
    (tmp_path / 'not_a_version').mkdir()
    with pytest.raises(ValueError):
        io.resolve_version_dir(str(tmp_path))


# -- batching drain / post-close submit hooks --------------------------
def test_drain_flushes_then_rejects(versions):
    paths = io.bucket_artifacts(os.path.join(versions, '1'))
    srv = BatchingInferenceServer(paths, max_wait_ms=40.0,
                                  linger_ms=1.0)
    try:
        rng = np.random.RandomState(0)
        futs = [srv.submit(_feed(rng)) for _ in range(10)]
        assert srv.drain(timeout=30.0) is True
        # everything queued before the drain completed
        for f in futs:
            out, = f.result(timeout=5.0)
            assert out.shape == (1, 4)
        # the server is retired for new work but alive for stats()
        with pytest.raises(RuntimeError, match='draining'):
            srv.submit(_feed(rng))
        st = srv.stats()
        assert st['requests_completed'] == 10
        assert st['queue_depth'] == 0 and st['in_flight_batches'] == 0
        assert srv.queue_state()['accepting'] is False
    finally:
        srv.close()
    with pytest.raises(RuntimeError, match='closed'):
        srv.submit(_feed(np.random.RandomState(1)))


def test_submit_after_close_raises_even_under_backpressure(versions):
    """A submit blocked on queue backpressure must observe close() and
    raise — not enqueue into the dead dispatcher and hang."""
    paths = io.bucket_artifacts(os.path.join(versions, '1'))
    srv = BatchingInferenceServer(paths, warmup=False, max_queue=1,
                                  max_wait_ms=10000.0,
                                  linger_ms=10000.0)
    rng = np.random.RandomState(2)
    srv.submit(_feed(rng))  # fills the queue (dispatcher lingers)
    errors = []

    def blocked_submit():
        try:
            srv.submit(_feed(rng))
        except RuntimeError as e:
            errors.append(e)

    t = threading.Thread(target=blocked_submit)
    t.start()
    time.sleep(0.1)  # let it block on backpressure
    srv.close()
    t.join(10.0)
    assert not t.is_alive(), "submit hung past close()"
    assert len(errors) == 1 and 'closed' in str(errors[0])


def test_queue_wait_compute_split_in_stats(versions):
    paths = io.bucket_artifacts(os.path.join(versions, '1'))
    srv = BatchingInferenceServer(paths, max_wait_ms=20.0,
                                  linger_ms=0.5)
    try:
        rng = np.random.RandomState(3)
        for rows in (1, 2, 4, 1, 3):
            srv.predict(_feed(rng, rows), timeout=30.0)
        st = srv.stats()
        for key in ('queue_wait_p50_ms', 'queue_wait_p99_ms',
                    'compute_p50_ms', 'compute_p99_ms'):
            assert key in st and st[key] >= 0.0
        assert st['per_bucket'], "no per-bucket split recorded"
        for b, row in st['per_bucket'].items():
            assert b in st['buckets']
            assert row['batches'] >= 1
            assert row['compute_p99_ms'] > 0.0
        # the split is consistent with the end-to-end latency: a
        # request waits then computes, so neither span can exceed the
        # p99 of the whole by more than measurement slop
        assert st['queue_wait_p50_ms'] <= st['p99_latency_ms'] + 1.0
        # the same histograms are what /metrics exports
        text = observability.prometheus_text()
        assert 'paddle_tpu_serving_queue_wait_seconds_bucket' in text
        assert 'paddle_tpu_serving_compute_seconds_bucket' in text
    finally:
        srv.close()


# -- fleet routing -----------------------------------------------------
def test_fleet_serves_and_matches_reference(versions):
    fleet = _mk_fleet(versions)
    try:
        assert fleet.version == '2'
        ref = InferenceServer(
            io.bucket_artifacts(os.path.join(versions, '2'))[1])
        rng = np.random.RandomState(4)
        for _ in range(8):
            f = _feed(rng)
            got, = fleet.predict(f, timeout=30.0)
            want, = ref.predict(f)
            np.testing.assert_allclose(got, np.asarray(want),
                                       rtol=1e-5, atol=1e-6)
        st = fleet.stats()
        assert st['failed'] == 0 and st['completed'] == 8
        # round-robin tie-breaking spread the idle-fleet requests over
        # both replicas instead of piling on replica 0
        done = [p['server']['requests_completed']
                for p in st['replicas']]
        assert all(d > 0 for d in done), done
    finally:
        fleet.close()


def test_fleet_routes_to_less_loaded_replica(versions):
    fleet = _mk_fleet(versions)
    try:
        rep_busy, rep_idle = fleet._replicas
        # pile synthetic queue depth onto one replica
        with rep_busy.server._cv:
            rep_busy.server._pending_rows += 1000
        try:
            picked = {fleet._pick(frozenset()).rid for _ in range(6)}
            assert picked == {rep_idle.rid}
        finally:
            with rep_busy.server._cv:
                rep_busy.server._pending_rows -= 1000
    finally:
        fleet.close()


def test_fleet_default_replicas_flag(versions, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_FLEET_REPLICAS', '1')
    fleet = ServingFleet(versions, health_interval_ms=0)
    try:
        assert len(fleet.replica_ids) == 1
    finally:
        fleet.close()


# -- failure containment ----------------------------------------------
def _break(rep):
    """Make a replica's dispatch path fail (simulated dead process)."""
    def boom(feed, **kw):  # accepts request_id= like the real submit
        raise OSError("replica %s: injected dispatch failure" % rep.rid)
    rep.server.submit = boom


def test_dispatch_failure_is_retried_and_marks_unroutable(versions):
    fleet = _mk_fleet(versions, unroutable_after=1, retry_limit=2)
    try:
        bad = fleet._replicas[0]
        _break(bad)
        rng = np.random.RandomState(5)
        # clients still get results: rerouted to the healthy replica
        for _ in range(4):
            out, = fleet.predict(_feed(rng), timeout=30.0)
            assert out.shape == (1, 4)
        st = fleet.stats()
        assert st['failed'] == 0
        assert st['unroutable'] == 1
        bad_stat, = [p for p in st['replicas'] if p['id'] == bad.rid]
        assert bad_stat['state'] == 'unroutable'
        # once unroutable it is out of routing: no more retries needed
        before = st['retries']
        fleet.predict(_feed(rng), timeout=30.0)
        assert fleet.stats()['retries'] == before
    finally:
        fleet.close()


def test_health_loop_restores_recovered_replica(versions):
    fleet = _mk_fleet(versions, unroutable_after=1, retry_limit=2,
                      health_interval_ms=30.0)
    try:
        bad = fleet._replicas[0]
        orig_submit = bad.server.submit
        _break(bad)
        rng = np.random.RandomState(6)
        fleet.predict(_feed(rng), timeout=30.0)  # strikes the replica
        deadline = time.time() + 5.0
        while bad.state != 'unroutable' and time.time() < deadline:
            time.sleep(0.01)
        assert bad.state == 'unroutable'
        # replica recovers: the next health probe restores it
        del bad.server.submit  # back to the class method
        assert bad.server.submit == orig_submit.__func__.__get__(
            bad.server)
        deadline = time.time() + 10.0
        while bad.state != 'ready' and time.time() < deadline:
            time.sleep(0.02)
        assert bad.state == 'ready', "health loop never restored it"
        assert fleet.stats()['health_probes'] >= 1
        assert fleet.stats()['failed'] == 0
    finally:
        fleet.close()


def test_all_replicas_dead_yields_clear_error(versions):
    fleet = _mk_fleet(versions, replicas=2, unroutable_after=1,
                      retry_limit=3)
    try:
        for rep in list(fleet._replicas):
            _break(rep)
        rng = np.random.RandomState(7)
        fut = fleet.submit(_feed(rng))
        with pytest.raises(Exception) as ei:
            fut.result(timeout=30.0)
        assert 'injected dispatch failure' in str(ei.value) \
            or 'no routable replica' in str(ei.value)
        assert fleet.stats()['failed'] == 1
    finally:
        fleet.close()


def test_invalid_feed_fails_fast_without_striking_replicas(versions):
    fleet = _mk_fleet(versions)
    try:
        fut = fleet.submit({'x': np.zeros((1, 7), np.float32)})
        with pytest.raises(ValueError):
            fut.result(timeout=10.0)
        st = fleet.stats()
        assert st['unroutable'] == 0 and st['retries'] == 0
    finally:
        fleet.close()


# -- lifecycle under traffic ------------------------------------------
class _Traffic(object):
    """Background closed-loop client recording per-request outcomes."""

    def __init__(self, fleet, rng, period_s=0.002):
        self.fleet = fleet
        self.rng = rng
        self.period = period_s
        self.errors = []
        self.ok = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            try:
                out, = self.fleet.predict(_feed(self.rng), timeout=30.0)
                assert out.shape == (1, 4)
                self.ok += 1
            except Exception as e:  # pragma: no cover - the assertion
                self.errors.append(e)
            time.sleep(self.period)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(30.0)


def test_remove_add_replica_under_traffic(versions):
    fleet = _mk_fleet(versions, replicas=2)
    try:
        rng = np.random.RandomState(8)
        with _Traffic(fleet, rng) as traffic:
            time.sleep(0.2)
            rid = fleet.remove_replica()
            assert rid not in fleet.replica_ids
            assert len(fleet.replica_ids) == 1
            time.sleep(0.2)
            new_rid = fleet.add_replica()
            assert new_rid in fleet.replica_ids
            time.sleep(0.2)
        assert traffic.errors == []
        assert traffic.ok > 0
        assert fleet.stats()['failed'] == 0
        with pytest.raises(ValueError):
            fleet.remove_replica('nonexistent')
    finally:
        fleet.close()


def test_deploy_hot_swap_and_rollback_under_traffic(versions):
    fleet = ServingFleet(os.path.join(versions, '1'), replicas=2,
                         max_wait_ms=20.0, linger_ms=0.5,
                         health_interval_ms=0)
    try:
        ref1 = InferenceServer(
            io.bucket_artifacts(os.path.join(versions, '1'))[1])
        ref2 = InferenceServer(
            io.bucket_artifacts(os.path.join(versions, '2'))[1])
        rng = np.random.RandomState(9)
        probe = _feed(rng)
        w1 = np.asarray(ref1.predict(probe)[0])
        w2 = np.asarray(ref2.predict(probe)[0])
        assert not np.allclose(w1, w2)  # versions are distinguishable

        np.testing.assert_allclose(fleet.predict(probe, 30.0)[0], w1,
                                   rtol=1e-5, atol=1e-6)
        with _Traffic(fleet, np.random.RandomState(10)) as traffic:
            time.sleep(0.1)
            name = fleet.deploy(os.path.join(versions, '2'))
            assert name == '2' and fleet.version == '2'
            # post-flip requests answer with the NEW version
            np.testing.assert_allclose(
                fleet.predict(probe, 30.0)[0], w2,
                rtol=1e-5, atol=1e-6)
            time.sleep(0.1)
            back = fleet.rollback()
            assert back == '1' and fleet.version == '1'
            np.testing.assert_allclose(
                fleet.predict(probe, 30.0)[0], w1,
                rtol=1e-5, atol=1e-6)
        assert traffic.errors == []  # zero dropped/failed mid-swap
        st = fleet.stats()
        assert st['failed'] == 0
        assert st['deploys'] == 3 and st['rollbacks'] == 1
        # every live replica serves the rolled-back version
        assert {p['version'] for p in st['replicas']} == {'1'}
    finally:
        fleet.close()


def test_deploy_record_prev_protocol(versions, tmp_path):
    """The deploy record rides io.write_rollback_json: the .prev
    archive always holds the superseded deployment."""
    state = str(tmp_path / 'state')
    fleet = ServingFleet(os.path.join(versions, '1'), replicas=1,
                         state_dir=state, health_interval_ms=0)
    try:
        rec = io.read_rollback_json(os.path.join(state, 'DEPLOY.json'))
        assert rec['version'] == '1'
        assert io.read_rollback_json(
            os.path.join(state, 'DEPLOY.json'), prev=True) is None
        fleet.deploy(os.path.join(versions, '2'))
        rec = io.read_rollback_json(os.path.join(state, 'DEPLOY.json'))
        prev = io.read_rollback_json(
            os.path.join(state, 'DEPLOY.json'), prev=True)
        assert rec['version'] == '2' and prev['version'] == '1'
    finally:
        fleet.close()
    assert os.path.isdir(state)  # caller-owned state dir survives


# -- AOT-warmed cold start --------------------------------------------
def test_cold_replica_with_warm_cache_reports_zero_compiles(
        versions, tmp_path, monkeypatch):
    """Acceptance: with a pre-populated persistent compile cache, a
    cold replica joining the fleet reports 0 post-warmup compiles
    before its first routed request — and its warmup is pure cache
    hits (the cache directory gains no new entries)."""
    cache = str(tmp_path / 'xla_cache')
    monkeypatch.setenv('PADDLE_TPU_COMPILATION_CACHE_DIR', cache)
    fleet = _mk_fleet(versions, replicas=1)
    try:
        assert os.path.isdir(cache) and os.listdir(cache), \
            "warmup did not populate the persistent cache"
        n_entries = len(os.listdir(cache))
        first, = fleet._replicas
        n_buckets = len(io.bucket_artifacts(
            os.path.join(versions, '2')))
        assert fleet.stats()['replicas'][0]['compiles'] == n_buckets
        rid = fleet.add_replica()  # the cold replica joining
        st = fleet.stats()
        cold, = [p for p in st['replicas'] if p['id'] == rid]
        # the joiner shares the live sibling's compiled servable:
        # serving-ready with ZERO compiles of its own, and the
        # persistent cache gains nothing (no recompile anywhere)
        assert cold['compiles'] == 0
        assert cold['compiles_after_warmup'] == 0
        added, = [r for r in fleet._replicas if r.rid == rid]
        assert added.server._compiled is first.server._compiled
        assert len(os.listdir(cache)) == n_entries, \
            "cold replica warmup recompiled instead of cache-hitting"
        # and after serving real traffic it STAYS zero
        rng = np.random.RandomState(11)
        for rows in (1, 2, 4):
            fleet.predict(_feed(rng, rows), timeout=30.0)
        st = fleet.stats()
        assert all(p['compiles_after_warmup'] == 0
                   for p in st['replicas'])
    finally:
        fleet.close()


# -- telemetry ---------------------------------------------------------
def test_fleet_metrics_labels_and_retirement(versions):
    fleet = _mk_fleet(versions)
    fid = fleet._fid
    try:
        rng = np.random.RandomState(12)
        fleet.predict(_feed(rng), timeout=30.0)
        text = observability.prometheus_text()
        assert ('paddle_tpu_fleet_requests_total{fleet="%s"} 1'
                % fid) in text
        assert ('paddle_tpu_fleet_replicas{fleet="%s",state="ready"} 2'
                % fid) in text
        # per-replica series carry replica AND version labels
        assert 'version="2"' in text and 'replica="r' in text
        # callback gauges read live state at scrape time
        snap = observability.snapshot()
        g = snap['paddle_tpu_fleet_replicas']['samples']
        ready = [s for s in g if s['labels'].get('fleet') == fid
                 and s['labels']['state'] == 'ready']
        assert ready and ready[0]['value'] == 2
    finally:
        fleet.close()
    text = observability.prometheus_text()
    assert ('fleet="%s"' % fid) not in text, \
        "closed fleet's series were not retired"


def test_callback_gauge_primitive():
    """Gauge.set_function: pulled at read time, exception falls back to
    the last pushed value, set_function(None) reverts to push mode."""
    from paddle_tpu.observability import MetricsRegistry
    reg = MetricsRegistry()
    g = reg.gauge('paddle_tpu_test_cb_gauge', 'x', ('k',))
    child = g.labels(k='a')
    child.set(7.0)
    live = {'v': 1.0}
    child.set_function(lambda: live['v'])
    assert child.value == 1.0
    live['v'] = 3.5
    assert child.value == 3.5

    def broken():
        raise RuntimeError("scrape-time failure")
    child.set_function(broken)
    assert child.value == 7.0  # falls back to the pushed value
    child.set_function(None)
    assert child.value == 7.0
    snap = reg.snapshot()
    assert snap['paddle_tpu_test_cb_gauge']['samples'][0]['value'] == 7.0


# -- HBM observability PR: resident-bytes gauges + budget precheck --------

def test_resident_bytes_gauges_and_shared_dedupe(versions):
    fleet = _mk_fleet(versions, replicas=2)
    try:
        st = fleet.stats()
        per = st['replicas']
        assert all(p['resident_bytes'] > 0 for p in per)
        # replicas of one version share ONE compiled servable: the
        # aggregate counts it once, not once per dispatch lane
        assert st['resident_bytes'] == per[0]['resident_bytes']
        assert st['resident_bytes_watermark'] >= st['resident_bytes']
        # per-replica gauge series exist, labeled fleet/replica/version
        fam = fleet._m._resident
        for rep in fleet._replicas:
            assert rep.m_resident.value == \
                rep.resident['total_bytes'] > 0
        # the aggregate callback gauge reads the deduped total live
        agg = fleet._m._g_resident.labels(fleet=fleet._fid)
        assert agg.value == st['resident_bytes']
    finally:
        fleet.close()


def test_deploy_overlap_raises_resident_watermark(versions):
    fleet = _mk_fleet(versions, replicas=2, version='1')
    try:
        v1 = fleet.stats()['resident_bytes']
        fleet.deploy(versions, version='2')
        st = fleet.stats()
        # at the rollout overlap both versions were live: the
        # watermark saw more than either steady state alone
        assert st['resident_bytes_watermark'] > st['resident_bytes']
        assert st['resident_bytes_watermark'] > v1
    finally:
        fleet.close()


def test_hbm_budget_precheck_is_warn_only(versions, caplog):
    import logging
    fleet = _mk_fleet(versions, replicas=1, version='1')
    try:
        before = fleet.stats()
        assert before['hbm_budget_precheck_failures'] == 0
        with caplog.at_level(logging.WARNING,
                             logger='paddle_tpu.inference.fleet'):
            vname = fleet.deploy(versions, version='2',
                                 hbm_budget_bytes=1)
        assert vname == '2'  # warn-only: the deploy went through
        st = fleet.stats()
        assert st['hbm_budget_precheck_failures'] == 1
        assert any('would exceed the HBM budget' in r.message
                   for r in caplog.records)
        # and the fleet still serves the new version
        rng = np.random.RandomState(1)
        out, = fleet.predict(_feed(rng), timeout=30.0)
        assert out.shape == (1, 4)
        # a roomy budget passes silently
        fleet.deploy(versions, version='1',
                     hbm_budget_bytes=1 << 40)
        assert fleet.stats()['hbm_budget_precheck_failures'] == 1
    finally:
        fleet.close()


def test_fleet_budget_defaults_to_peak_hbm_flag(versions, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PEAK_HBM_BYTES', '1')
    fleet = _mk_fleet(versions, replicas=1)  # ctor deploy prechecks
    try:
        st = fleet.stats()
        assert st['hbm_budget_bytes'] == 1
        assert st['hbm_budget_precheck_failures'] == 1
    finally:
        fleet.close()


def test_fleet_routing_span_carries_request_id(versions, monkeypatch,
                                               tmp_path):
    from paddle_tpu.observability import timeline
    monkeypatch.setenv('PADDLE_TPU_TRACE_DIR', str(tmp_path))
    timeline.reset()
    fleet = _mk_fleet(versions, replicas=2)
    try:
        rng = np.random.RandomState(2)
        out, = fleet.predict(_feed(rng), timeout=30.0)
        deadline = time.time() + 10.0
        disp = qw = None
        while time.time() < deadline and not (disp and qw):
            evs = timeline.ring().events()
            disp = [e for e in evs
                    if e['name'] == 'fleet.dispatch'] or None
            qw = [e for e in evs
                  if e['name'] == 'serving.queue_wait'] or None
            time.sleep(0.01)
        assert disp, 'fleet routing span missing'
        assert qw, 'replica queue-wait span missing'
        rid = disp[0]['args']['request_id']
        assert disp[0]['args']['replica'] in fleet.replica_ids
        assert disp[0]['args']['version'] == fleet.version
        # ONE id names the request across routing and replica spans
        assert any(e['args'].get('request_id') == rid for e in qw)
    finally:
        fleet.close()
        timeline.reset()
