"""Expert parallelism (ep axis): fixed-capacity MoE dispatch/combine
over all_to_all vs a dense single-device reference.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import api, collective, expert_parallel as ep


def need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip("needs %d devices" % n)


def test_moe_layer_matches_dense_experts():
    need_devices(4)
    E = 4
    mesh = api.make_mesh((E,), ('ep',))
    rng = np.random.RandomState(7)
    T, D, H, C = 8, 6, 12, 8  # capacity >= T: nothing drops
    # one expert per member
    w1 = rng.randn(E, D, H).astype('float32') * 0.5
    b1 = rng.randn(E, H).astype('float32') * 0.1
    w2 = rng.randn(E, H, D).astype('float32') * 0.5
    b2 = rng.randn(E, D).astype('float32') * 0.1
    x = rng.randn(E, T, D).astype('float32')  # [members, T, D]
    gates = rng.randn(E, T, E).astype('float32')

    def f(x, gates, w1, b1, w2, b2):
        return ep.moe_layer(x[0], gates[0], w1[0], b1[0], w2[0], b2[0],
                            'ep', capacity=C)[None]

    out = collective.shard_map(
        f, mesh=mesh,
        in_specs=(P('ep', None, None), P('ep', None, None),
                  P('ep', None, None), P('ep', None),
                  P('ep', None, None), P('ep', None)),
        out_specs=P('ep', None, None))(x, gates, w1, b1, w2, b2)
    out = np.asarray(out)  # [E_members, T, D]

    # dense reference: each token goes to argmax expert's FFN
    for m in range(E):
        for t in range(T):
            e = int(np.argmax(gates[m, t]))
            h = np.maximum(x[m, t] @ w1[e] + b1[e], 0)
            want = h @ w2[e] + b2[e]
            np.testing.assert_allclose(out[m, t], want, rtol=1e-4,
                                       atol=1e-4,
                                       err_msg='member %d token %d' %
                                               (m, t))


def test_moe_capacity_drops_overflow():
    need_devices(4)
    E = 4
    mesh = api.make_mesh((E,), ('ep',))
    rng = np.random.RandomState(9)
    T, D, H, C = 8, 4, 8, 2  # capacity 2 < T: overflow drops to zero
    w1 = rng.randn(E, D, H).astype('float32')
    b1 = np.zeros((E, H), 'float32')
    w2 = rng.randn(E, H, D).astype('float32')
    b2 = np.zeros((E, D), 'float32')
    x = rng.randn(E, T, D).astype('float32')
    # every token on every member routes to expert 0 -> only 2 survive
    gates = np.zeros((E, T, E), 'float32')
    gates[:, :, 0] = 1.0

    def f(x, gates, w1, b1, w2, b2):
        return ep.moe_layer(x[0], gates[0], w1[0], b1[0], w2[0], b2[0],
                            'ep', capacity=C)[None]

    out = np.asarray(collective.shard_map(
        f, mesh=mesh,
        in_specs=(P('ep', None, None), P('ep', None, None),
                  P('ep', None, None), P('ep', None),
                  P('ep', None, None), P('ep', None)),
        out_specs=P('ep', None, None))(x, gates, w1, b1, w2, b2))
    for m in range(E):
        # first C tokens of each member processed by expert 0, rest zero
        for t in range(C):
            h = np.maximum(x[m, t] @ w1[0], 0)
            np.testing.assert_allclose(out[m, t], h @ w2[0], rtol=1e-4,
                                       atol=1e-4)
        assert np.all(out[m, C:] == 0)
