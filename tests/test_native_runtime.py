"""N1-N3/A4 — native runtime tests: queue order/termination/concurrency,
recordio round-trip + cross-compat with the python format, staging arena
reuse, prefetch/xmap pipelines.

Reference parity: the reference's threadpool tests
(paddle/framework/threadpool_test.cc) and recordio round-trips.
"""
import os
import threading

import numpy as np
import pytest

from paddle_tpu.runtime import (available, NativeQueue, NativeRecordReader,
                                NativeRecordWriter, StagingArena,
                                prefetch_reader, xmap_native)
from paddle_tpu import io_recordio


def test_native_library_builds():
    # g++ is in the image: the C++ path must actually be exercised by CI
    assert available(), "native runtime failed to build/load"


def test_queue_fifo_order_and_close():
    q = NativeQueue(capacity=4)
    assert q.native == available()
    for i in range(4):
        assert q.push(b'item%d' % i)
    assert q.qsize() == 4
    for i in range(4):
        assert q.pop() == b'item%d' % i
    q.close()
    assert q.pop() is None  # closed + drained
    assert not q.push(b'late')  # push after close fails


def test_queue_blocking_backpressure():
    q = NativeQueue(capacity=2)
    results = []

    def producer():
        for i in range(10):
            q.push(bytes([i]))
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    while True:
        b = q.pop()
        if b is None:
            break
        results.append(b[0])
    t.join(5)
    assert results == list(range(10))  # bounded queue, order preserved


def test_queue_multi_producer_consumer_totals():
    q = NativeQueue(capacity=8)
    n_prod, per = 4, 50
    seen = []
    seen_lock = threading.Lock()
    done = threading.Barrier(n_prod + 1)

    def producer(k):
        for i in range(per):
            q.push(b'%d:%d' % (k, i))
        done.wait()

    def consumer():
        while True:
            b = q.pop()
            if b is None:
                return
            with seen_lock:
                seen.append(b)

    cons = [threading.Thread(target=consumer) for _ in range(3)]
    for c in cons:
        c.start()
    prods = [threading.Thread(target=producer, args=(k,))
             for k in range(n_prod)]
    for p in prods:
        p.start()
    done.wait()  # all producers finished
    q.close()
    for t in prods + cons:
        t.join(5)
    assert len(seen) == n_prod * per
    assert len(set(seen)) == n_prod * per  # no dupes, no losses


def test_recordio_native_roundtrip(tmp_path):
    path = str(tmp_path / 'native.rio')
    payloads = [b'alpha', b'', b'x' * 10000, np.arange(100).tobytes()]
    with NativeRecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    got = list(NativeRecordReader(path))
    assert got == payloads


@pytest.mark.skipif(not available(), reason="needs the C++ runtime")
def test_recordio_cross_compat(tmp_path):
    """python writer <-> native reader and vice versa: the wire format is
    one format (io_recordio.py is the authority)."""
    payloads = [b'one', b'two' * 1000, b'']
    py_path = str(tmp_path / 'py.rio')
    io_recordio.write_records(py_path, payloads)
    assert list(NativeRecordReader(py_path)) == payloads

    nat_path = str(tmp_path / 'nat.rio')
    with NativeRecordWriter(nat_path) as w:
        for p in payloads:
            w.write(p)
    assert list(io_recordio.read_records(nat_path)) == payloads


@pytest.mark.skipif(not available(), reason="needs the C++ runtime")
def test_recordio_crc_detects_corruption(tmp_path):
    path = str(tmp_path / 'corrupt.rio')
    with NativeRecordWriter(path) as w:
        w.write(b'payload-payload')
    with open(path, 'r+b') as f:
        f.seek(-3, os.SEEK_END)
        f.write(b'XXX')
    with pytest.raises(IOError, match='crc'):
        list(NativeRecordReader(path))


def test_staging_arena_reuse():
    arena = StagingArena(block_size=1024, blocks=2)
    assert arena.free_blocks() == 2
    mv1, tok1 = arena.acquire()
    mv2, tok2 = arena.acquire()
    assert arena.free_blocks() == 0
    mv1[:5] = b'hello'
    arr = np.frombuffer(mv1, dtype=np.uint8, count=5)
    assert bytes(arr) == b'hello'
    del arr, mv1, mv2
    arena.release(tok1)
    arena.release(tok2)
    assert arena.free_blocks() == 2
    # reacquire reuses a released block (no new allocation)
    mv3, tok3 = arena.acquire()
    assert len(mv3) == 1024
    del mv3
    arena.release(tok3)


def test_prefetch_reader_equivalence():
    def source():
        for i in range(100):
            yield (np.full((4,), i, np.float32), i)

    got = list(prefetch_reader(source, buf_size=8)())
    assert len(got) == 100
    for i, (arr, lab) in enumerate(got):
        assert lab == i
        np.testing.assert_array_equal(arr, np.full((4,), i, np.float32))


def test_xmap_native_unordered_and_ordered():
    def source():
        for i in range(50):
            yield i

    mapped = list(xmap_native(lambda x: x * 2, source, process_num=4,
                              buffer_size=8)())
    assert sorted(mapped) == [2 * i for i in range(50)]

    ordered = list(xmap_native(lambda x: x * 3, source, process_num=4,
                               buffer_size=8, order=True)())
    assert ordered == [3 * i for i in range(50)]


def test_dataset_convert_recordio_roundtrip(tmp_path):
    """datasets.common.convert -> reader.creator.recordio round trip
    (V3 dataset cache over the N3 record format), multiple chunk files."""
    from paddle_tpu.datasets import common
    from paddle_tpu.reader import creator

    samples = [(np.arange(4, dtype='float32') + i, i) for i in range(10)]

    def source():
        return iter(samples)

    out = str(tmp_path)
    common.convert(out, source, line_count=3, name_prefix='unit')
    files = sorted(os.listdir(out))
    assert len(files) == 4  # 10 samples / 3 per chunk
    got = list(creator.recordio([os.path.join(out, f)
                                 for f in files])())
    assert len(got) == 10
    for (arr, lab), (w_arr, w_lab) in zip(got, samples):
        assert lab == w_lab
        np.testing.assert_array_equal(arr, w_arr)


def test_buffered_creator_surfaces_corruption(tmp_path):
    """A CRC error mid-stream re-raises through the buffered readahead
    instead of silently truncating the dataset."""
    import pickle
    path = str(tmp_path / 'corrupt.rio')
    with NativeRecordWriter(path) as w:
        for i in range(5):
            w.write(pickle.dumps(i))
    with open(path, 'r+b') as f:
        f.seek(-2, os.SEEK_END)
        f.write(b'XX')
    from paddle_tpu.reader import creator
    with pytest.raises((IOError, OSError)):
        list(creator.recordio(path)())  # default buffered path


def test_record_reader_close_then_next_stops(tmp_path):
    path = str(tmp_path / 'c.rio')
    with NativeRecordWriter(path) as w:
        w.write(b'one')
        w.write(b'two')
    r = NativeRecordReader(path)
    assert next(r) == b'one'
    r.close()
    with pytest.raises(StopIteration):
        next(r)


def test_creator_np_array_and_text_file(tmp_path):
    from paddle_tpu.reader import creator

    arr = np.arange(6).reshape(3, 2)
    rows = list(creator.np_array(arr)())
    assert len(rows) == 3
    np.testing.assert_array_equal(rows[1], [2, 3])

    p = tmp_path / 'lines.txt'
    p.write_text('alpha\nbeta\n')
    assert list(creator.text_file(str(p))()) == ['alpha', 'beta']


def test_feed_pipeline_streams_device_batches():
    from paddle_tpu.runtime import FeedPipeline

    n_steps = 12

    def fill(views, step):
        if step >= n_steps:
            return False
        views['x'][:] = step
        views['y'][:] = step * 2

    pipe = FeedPipeline(
        {'x': ((4, 8), np.float32), 'y': ((4, 1), np.int32)}, fill,
        depth=3)
    got = list(pipe)
    assert len(got) == n_steps
    for i, feed in enumerate(got):
        np.testing.assert_array_equal(np.asarray(feed['x']),
                                      np.full((4, 8), i, np.float32))
        np.testing.assert_array_equal(np.asarray(feed['y']),
                                      np.full((4, 1), 2 * i, np.int32))


def test_xmap_native_mapper_error_propagates_no_hang():
    def source():
        for i in range(20):
            yield i

    def bad_mapper(x):
        if x == 7:
            raise ValueError("corrupt sample")
        return x

    with pytest.raises(ValueError, match='corrupt sample'):
        list(xmap_native(bad_mapper, source, process_num=3,
                         buffer_size=4)())


def test_record_reader_exhaustion_keeps_raising(tmp_path):
    path = str(tmp_path / 'r.rio')
    with NativeRecordWriter(path) as w:
        w.write(b'one')
    r = NativeRecordReader(path)
    assert list(r) == [b'one']
    with pytest.raises(StopIteration):
        next(r)  # must raise again, not crash on the closed handle
    with pytest.raises(StopIteration):
        next(r)
    w2 = NativeRecordWriter(str(tmp_path / 'w.rio'))
    w2.close()
    if available():
        with pytest.raises(ValueError, match='closed'):
            w2.write(b'late')


def test_feed_pipeline_fill_error_raises():
    from paddle_tpu.runtime import FeedPipeline

    def fill(views, step):
        if step == 2:
            raise IOError("shard unreadable")
        views['x'][:] = step

    pipe = FeedPipeline({'x': ((2,), np.float32)}, fill, depth=2)
    with pytest.raises(RuntimeError, match='producer failed'):
        list(pipe)


def test_xmap_readers_uses_native_backend():
    from paddle_tpu.reader.decorator import xmap_readers

    def source():
        for i in range(20):
            yield i

    out = list(xmap_readers(lambda x: x + 1, source, 2, 4)())
    assert sorted(out) == list(range(1, 21))


def test_feed_pipeline_multiworker_preserves_order():
    """workers=3: fills run concurrently but batches arrive in step
    order (worker w owns steps w, w+N, ...; consumer round-robins)."""
    import numpy as np

    from paddle_tpu.runtime.feed import FeedPipeline

    n = 11

    def fill(views, step):
        if step >= n:
            return False
        views['x'][...] = step
        return True

    pipe = FeedPipeline({'x': ((4,), np.float32)}, fill, depth=6,
                        workers=3)
    got = [int(np.asarray(f['x'])[0]) for f in pipe]
    assert got == list(range(n)), got
    pipe.close()


def test_feed_pipeline_multiworker_propagates_error():
    import numpy as np
    import pytest

    from paddle_tpu.runtime.feed import FeedPipeline

    def fill(views, step):
        if step == 4:
            raise ValueError("boom")
        views['x'][...] = step
        return True

    pipe = FeedPipeline({'x': ((2,), np.float32)}, fill, depth=6,
                        workers=2)
    with pytest.raises(RuntimeError, match="producer failed"):
        for i, f in enumerate(pipe):
            if i > 16:  # the error step must surface promptly
                break
    pipe.close()
