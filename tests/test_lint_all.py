"""tools/lint_all.py wiring (tier-1).

One entrypoint runs every tools/check_*.py with a summary table; this
test keeps it — and every future checker — wired into tier-1, so a new
checker cannot be added half-wired and silently skipped.
"""
import importlib.util
import os


def _load_lint_all(tools_dir=None):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'tools', 'lint_all.py')
    spec = importlib.util.spec_from_file_location('lint_all', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if tools_dir is not None:
        mod._TOOLS = tools_dir
    return mod


def test_every_checker_discovered_and_green():
    mod = _load_lint_all()
    names = mod.discover()
    # the full checker roster; a removed checker must be removed here
    # deliberately, a new one joins automatically via discovery
    for expected in ('check_amp_lists', 'check_concurrency',
                     'check_flags_doc', 'check_metric_names',
                     'check_pass_registry'):
        assert expected in names, names
    results = mod.run_all()
    assert set(results) == set(names)
    failing = {n: errs for n, (errs, _w) in results.items() if errs}
    assert failing == {}, failing


def test_contractless_checker_cannot_hide(tmp_path):
    """A tools/check_*.py without check() is a FAILURE, not a skip —
    the wiring contract every checker rides into tier-1 on."""
    (tmp_path / 'check_good.py').write_text(
        'def check():\n    return []\n')
    (tmp_path / 'check_nocontract.py').write_text(
        'def lint():\n    return []\n')
    (tmp_path / 'check_crashes.py').write_text(
        'def check():\n    raise RuntimeError("boom")\n')
    mod = _load_lint_all(tools_dir=str(tmp_path))
    results = mod.run_all()
    assert results['check_good'][0] == []
    assert 'defines no check()' in results['check_nocontract'][0][0]
    assert 'boom' in results['check_crashes'][0][0]
