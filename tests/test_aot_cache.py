"""AotCache: serialized-executable store/load, corruption contract,
schema-mismatch-as-miss, and the orphan-tombstone sweep.

The serving-level contract (a fresh process's deploy() hitting this
cache performs zero compiles) is pinned in test_tenancy.py; this file
covers the cache mechanics in isolation with a plain jitted function.
"""
import json
import os
import pickle

import jax
import numpy as np
import pytest

from paddle_tpu.inference.aot_cache import AotCache, artifact_digest


@pytest.fixture
def cache(tmp_path, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_AOT_CACHE_DIR', str(tmp_path))
    c = AotCache()
    if not c.enabled():  # pragma: no cover - container jax has it
        pytest.skip('jax.experimental.serialize_executable unavailable')
    return c


@pytest.fixture
def compiled():
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    return fn.lower(np.zeros((4,), np.float32)).compile()


def _artifact(tmp_path, name='bucket_4.stablehlo', data=b'module'):
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


def test_disabled_without_flag(monkeypatch):
    monkeypatch.delenv('PADDLE_TPU_AOT_CACHE_DIR', raising=False)
    c = AotCache()
    assert not c.enabled()
    assert c.load_compiled('0' * 40) is None
    assert c.store('0' * 40, object()) is False
    assert c.sweep_orphans() == []


def test_key_is_stable_and_sensitive(tmp_path):
    art = _artifact(tmp_path)
    d = artifact_digest(art)
    assert d == artifact_digest(art)  # content-keyed, not path-keyed
    k = AotCache.key(d, 4)
    assert k == AotCache.key(d, 4)
    assert k != AotCache.key(d, 8)                    # bucket
    assert k != AotCache.key(d, 4, device_kind='tpu')  # hardware
    d2 = artifact_digest(_artifact(tmp_path, 'other.stablehlo', b'x'))
    assert k != AotCache.key(d2, 4)                    # model bytes


def test_store_load_roundtrip(cache, compiled, tmp_path):
    art = _artifact(tmp_path)
    key = AotCache.key(artifact_digest(art), 4)
    s0 = AotCache.stats()
    assert cache.load_compiled(key) is None        # cold: miss
    assert cache.store(key, compiled, artifact=art, bucket=4)
    fn = cache.load_compiled(key)
    assert fn is not None
    out = fn(np.arange(4, dtype=np.float32))
    np.testing.assert_allclose(
        np.asarray(out), np.arange(4, dtype=np.float32) * 2 + 1)
    s1 = AotCache.stats()
    assert s1['misses'] == s0['misses'] + 1
    assert s1['stores'] == s0['stores'] + 1
    assert s1['hits'] == s0['hits'] + 1
    assert s1['corrupt'] == s0['corrupt']


def test_header_mismatch_is_counted_miss(cache, compiled, tmp_path):
    """A parseable header for another jax version / device kind is a
    MISS (the entry is valid, just not for this process) — never
    corrupt, never a wrong executable."""
    art = _artifact(tmp_path)
    key = AotCache.key(artifact_digest(art), 4)
    assert cache.store(key, compiled, artifact=art, bucket=4)
    p = cache.path(key)
    with open(p, 'rb') as f:
        hdr = json.loads(f.readline().decode())
        body = f.read()
    hdr['jax'] = '0.0.0-someday'
    with open(p, 'wb') as f:
        f.write(json.dumps(hdr).encode() + b'\n' + body)
    s0 = AotCache.stats()
    assert cache.load_compiled(key) is None
    s1 = AotCache.stats()
    assert s1['misses'] == s0['misses'] + 1
    assert s1['corrupt'] == s0['corrupt']


def test_corrupt_entry_counts_and_reads_as_miss(cache, compiled,
                                                tmp_path):
    art = _artifact(tmp_path)
    key = AotCache.key(artifact_digest(art), 4)
    assert cache.store(key, compiled, artifact=art, bucket=4)
    p = cache.path(key)
    # poison the pickled body but keep the valid header
    with open(p, 'rb') as f:
        hdr_line = f.readline()
    with open(p, 'wb') as f:
        f.write(hdr_line + b'\x00garbage-not-a-pickle')
    s0 = AotCache.stats()
    assert cache.load_compiled(key) is None
    assert AotCache.stats()['corrupt'] == s0['corrupt'] + 1
    # unparseable header too
    with open(p, 'wb') as f:
        f.write(b'\xff\xfe not json\n')
    assert cache.load_compiled(key) is None
    assert AotCache.stats()['corrupt'] == s0['corrupt'] + 2


def test_unpicklable_executable_degrades_quietly(cache, tmp_path):
    art = _artifact(tmp_path)
    key = AotCache.key(artifact_digest(art), 4)
    assert cache.store(key, object(), artifact=art) is False
    assert not os.path.exists(cache.path(key))


def test_sweep_orphans(cache, compiled, tmp_path):
    live_art = _artifact(tmp_path, 'live.stablehlo', b'live')
    dead_art = _artifact(tmp_path, 'dead.stablehlo', b'dead')
    k_live = AotCache.key(artifact_digest(live_art), 1)
    k_dead = AotCache.key(artifact_digest(dead_art), 2)
    k_anon = AotCache.key(artifact_digest(live_art), 3)
    assert cache.store(k_live, compiled, artifact=live_art, bucket=1)
    assert cache.store(k_dead, compiled, artifact=dead_art, bucket=2)
    # no provenance recorded: the sweep must keep it (cannot prove
    # the source is gone)
    assert cache.store(k_anon, compiled, artifact=None, bucket=3)
    os.remove(dead_art)  # simulate gc_versions removing the version
    # a crashed foreign writer's tmp leftover, and our own in-flight
    foreign_tmp = os.path.join(cache.root,
                               'aot_dead.bin.tmp.%d' % (os.getpid() + 1))
    own_tmp = os.path.join(cache.root,
                           'aot_x.bin.tmp.%d' % os.getpid())
    open(foreign_tmp, 'wb').close()
    open(own_tmp, 'wb').close()
    # a foreign file in the dir: never touched
    alien = os.path.join(cache.root, 'NOT_OURS.txt')
    open(alien, 'wb').close()
    removed = cache.sweep_orphans()
    assert os.path.basename(cache.path(k_dead)) in removed
    assert os.path.basename(foreign_tmp) in removed
    assert os.path.exists(cache.path(k_live))
    assert os.path.exists(cache.path(k_anon))
    assert os.path.exists(own_tmp)
    assert os.path.exists(alien)
    # the survivor still loads
    assert cache.load_compiled(k_live) is not None


def test_poisoned_header_is_swept(cache, compiled, tmp_path):
    art = _artifact(tmp_path)
    key = AotCache.key(artifact_digest(art), 4)
    assert cache.store(key, compiled, artifact=art, bucket=4)
    with open(cache.path(key), 'wb') as f:
        f.write(b'\xff\xfe broken\n')
    removed = cache.sweep_orphans()
    assert os.path.basename(cache.path(key)) in removed
