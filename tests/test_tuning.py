"""ISSUE 16 autotuner: registry / search / cache / executor-apply.

The tier-1 contract this file pins (ISSUE.md acceptance):

- deterministic search: fixed fake measurements give an identical
  winner and trace, twice;
- cost-model pruning: HBM-budget blowouts and modeled-much-worse
  candidates are never measured;
- persistence: winners round-trip through the on-disk cache keyed by
  (plan key, device kind, mesh) — a second build does zero search, a
  changed plan key or mesh misses, a corrupted file is counted and
  falls back safely;
- CPU dry-run smoke on a real program: the chosen config is modeled at
  least as fast as the defaults;
- PADDLE_TPU_TUNE=off (the default) leaves executor behavior bitwise
  identical.
"""
import json
import logging
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.tuning import cache as tcache
from paddle_tpu.tuning import registry, roofline
from paddle_tpu.tuning import runtime as trt
from paddle_tpu.tuning import search as tsearch


@pytest.fixture(autouse=True)
def _clean_tuner_env(monkeypatch):
    """Every test starts untuned: no tuner-applied env, no memo."""
    saved = set(registry._TUNER_APPLIED)
    for env in saved:
        monkeypatch.delenv(env, raising=False)
    registry._TUNER_APPLIED.clear()
    trt.reset()
    yield
    for t in registry.registered_tunables():
        if t.env in registry._TUNER_APPLIED:
            os.environ.pop(t.env, None)
    registry._TUNER_APPLIED.clear()
    registry._TUNER_APPLIED.update(saved)
    trt.reset()


def _fake_tunables():
    """A private two-knob registry slice for search unit tests."""
    return [
        registry.Tunable('tile', (1, 2, 4), 2, 'test',
                         env='PADDLE_TPU_FLAT_TILE_BUDGET'),
        registry.Tunable('mode', ('a', 'b'), 'a', 'test',
                         env='PADDLE_TPU_AMP'),
    ]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_covers_the_hand_set_tunables():
    names = [t.name for t in registry.registered_tunables()]
    for expected in ('flat_tile_budget', 'device_prefetch_chunk', 'amp',
                     'mesh', 'embed_bucket_tile', 'embed_cache_rows',
                     'serving_max_wait_ms', 'serving_max_batch',
                     'train_batch', 'run_steps_k'):
        assert expected in names, names
    for t in registry.registered_tunables():
        assert isinstance(t.domain, tuple) and 1 < len(t.domain) <= 64
        assert t.default in t.domain, t
        assert t.env.startswith('PADDLE_TPU_'), t
        for v in t.domain:
            assert t.coerce(t.encode(v)) == v, (t.name, v)


def test_pinning_and_applied_restore(monkeypatch):
    t = registry.tunable('device_prefetch_chunk')
    assert not registry.is_pinned(t)
    monkeypatch.setenv(t.env, '4')
    assert registry.is_pinned(t)  # user-set env pins
    assert registry.current_config([t])[t.name] == 4
    monkeypatch.delenv(t.env)
    with registry.applied({t.name: 8}):
        assert os.environ[t.env] == '8'
    assert t.env not in os.environ  # restored


def test_apply_persistent_masks_in_base_env_and_never_repins(
        monkeypatch):
    t = registry.tunable('flat_tile_budget')
    done = registry.apply_persistent({t.name: 1 << 20})
    assert done == {t.name: 1 << 20}
    assert os.environ[t.env] == str(1 << 20)
    # the tuner set it, so it does NOT pin and base_env masks it
    assert not registry.is_pinned(t)
    with registry.base_env():
        assert t.env not in os.environ
    assert os.environ[t.env] == str(1 << 20)
    # a user-pinned tunable is never overwritten
    p = registry.tunable('amp')
    monkeypatch.setenv(p.env, 'bf16')
    assert registry.apply_persistent({p.name: 'f16'}) == {}
    assert os.environ[p.env] == 'bf16'


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

def test_search_deterministic_fixed_measurements():
    model = {(1, 'a'): 1.0, (2, 'a'): 0.8, (4, 'a'): 0.7,
             (1, 'b'): 0.9, (2, 'b'): 0.5, (4, 'b'): 0.4}

    def run_once():
        tun = _fake_tunables()
        tuner = tsearch.Autotuner(
            model_fn=lambda c: {'score': model[(c['tile'], c['mode'])],
                                'peak_bytes': 0},
            measure_fn=lambda c: model[(c['tile'], c['mode'])],
            tunables=tun, hbm_budget_bytes=0, measure_budget=100)
        return tuner.search()

    r1, r2 = run_once(), run_once()
    assert r1.winners == r2.winners == {'tile': 4, 'mode': 'b'}
    assert r1.trace == r2.trace
    assert r1.best_score == pytest.approx(0.4)
    assert 'winner' in r1.format_trace()


def test_ties_keep_the_incumbent():
    tun = [registry.Tunable('tile', (1, 2), 1, 'test',
                            env='PADDLE_TPU_FLAT_TILE_BUDGET')]
    tuner = tsearch.Autotuner(
        model_fn=lambda c: {'score': 1.0, 'peak_bytes': 0},
        measure_fn=lambda c: 1.0, tunables=tun, hbm_budget_bytes=0,
        measure_budget=10)
    assert tuner.search().winners == {}


def test_hbm_budget_prunes_without_measuring():
    measured = []

    def measure(c):
        measured.append(dict(c))
        return 1.0

    tun = [registry.Tunable('tile', (1, 2, 4), 1, 'test',
                            env='PADDLE_TPU_FLAT_TILE_BUDGET')]
    tuner = tsearch.Autotuner(
        model_fn=lambda c: {'score': 1.0,
                            'peak_bytes': c['tile'] * 10 ** 9},
        measure_fn=measure, tunables=tun,
        hbm_budget_bytes=2 * 10 ** 9, measure_budget=100)
    r = tuner.search()
    # tile=4 models at 4GB > 2GB budget: pruned, never measured
    assert not any(c['tile'] == 4 for c in measured)
    pruned = [e for e in r.trace if e['action'] == 'pruned']
    assert any('HBM budget' in (e['reason'] or '') for e in pruned)


def test_modeled_worse_prunes_and_budget_bounds_measurements():
    measured = []
    tun = [registry.Tunable('tile', (1, 2, 4, 8), 1, 'test',
                            env='PADDLE_TPU_FLAT_TILE_BUDGET')]
    tuner = tsearch.Autotuner(
        model_fn=lambda c: {'score': float(c['tile']), 'peak_bytes': 0},
        measure_fn=lambda c: measured.append(dict(c)) or 1.0,
        tunables=tun, hbm_budget_bytes=0, prune_slack=0.15,
        measure_budget=100)
    r = tuner.search()
    # every candidate models worse than the incumbent (score=tile):
    # all pruned, only the baseline measured
    assert len(measured) == 1
    assert r.winners == {}
    reasons = [e['reason'] for e in r.trace
               if e['action'] == 'pruned']
    assert any('worse than incumbent' in (x or '') for x in reasons)
    # measure budget: with pruning disabled, the cap binds
    measured.clear()
    tuner = tsearch.Autotuner(
        model_fn=None,
        measure_fn=lambda c: measured.append(dict(c)) or 1.0,
        tunables=tun, hbm_budget_bytes=0, measure_budget=2)
    r = tuner.search()
    assert len(measured) == 2
    assert any('budget exhausted' in (e['reason'] or '')
               for e in r.trace)


def test_pinned_tunable_skipped_by_search(monkeypatch):
    t = registry.tunable('amp')
    monkeypatch.setenv(t.env, 'bf16')
    tuner = tsearch.Autotuner(
        model_fn=lambda c: {'score': 1.0, 'peak_bytes': 0},
        tunables=[t, registry.tunable('device_prefetch_chunk')])
    r = tuner.search()
    assert all(e['tunable'] != 'amp' for e in r.trace[1:])
    assert r.config.get('amp') == 'bf16'  # pinned value rides along


def test_infeasible_mesh_candidates_never_measured():
    t = registry.tunable('mesh')
    import jax
    ndev = len(jax.devices())
    tuner = tsearch.Autotuner(
        model_fn=lambda c: {'score': 1.0, 'peak_bytes': 0},
        tunables=[t])
    r = tuner.search()
    bad = [e for e in r.trace
           if e.get('reason') == 'infeasible on this backend']
    # conftest forces 8 virtual devices: every candidate needing more
    # than ndev is pruned as infeasible
    needs = {'dp=2': 2, 'dp=4': 4, 'dp=8': 8, 'fsdp=8': 8,
             'dp=4,fsdp=2': 8, 'dp=2,tp=2': 4}
    for spec, n in needs.items():
        if n > ndev:
            assert any(e['value'] == spec for e in bad)


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------

def test_cache_roundtrip_and_keying(tmp_path):
    c = tcache.TuneCache(str(tmp_path))
    k1 = c.key(('pm', 2, 'bf16'), 'cpu', None)
    k2 = c.key(('pm', 2, None), 'cpu', None)      # different plan key
    k3 = c.key(('pm', 2, 'bf16'), 'cpu', (('dp', 2),))  # different mesh
    k4 = c.key(('pm', 2, 'bf16'), 'TPU v5e', None)  # different device
    assert len({k1, k2, k3, k4}) == 4
    assert c.load(k1) is None  # miss
    assert c.store(k1, {'amp': 'bf16'}, meta={'base_score': 1.0})
    assert c.load(k1) == {'amp': 'bf16'}
    assert c.load(k2) is None
    assert c.load(k3) is None


def test_corrupted_cache_file_counts_and_falls_back(tmp_path):
    c = tcache.TuneCache(str(tmp_path))
    k = c.key(('pm',), 'cpu', None)
    assert c.store(k, {'amp': 'bf16'})
    before = tcache.TuneCache.stats()['corrupt']
    with open(c.path(k), 'w') as f:
        f.write('{not json')
    assert c.load(k) is None  # no crash
    # wrong schema is corruption too, not a silent hit
    with open(c.path(k), 'w') as f:
        json.dump({'schema': 999, 'winners': {'amp': 'bf16'}}, f)
    assert c.load(k) is None
    assert tcache.TuneCache.stats()['corrupt'] == before + 2


def test_cache_disabled_without_dir(monkeypatch):
    monkeypatch.delenv('PADDLE_TPU_TUNE_CACHE_DIR', raising=False)
    monkeypatch.delenv('PADDLE_TPU_COMPILATION_CACHE_DIR',
                       raising=False)
    c = tcache.TuneCache()
    assert not c.enabled()
    assert c.load('deadbeef') is None
    assert not c.store('deadbeef', {'amp': 'bf16'})


def test_autotune_cached_mode_zero_search(tmp_path):
    tun = _fake_tunables()
    cache = tcache.TuneCache(str(tmp_path))
    key = cache.key(('pm', 2), 'cpu', None)
    model = lambda c: {'score': 1.0 / c['tile'], 'peak_bytes': 0}  # noqa: E731
    r = tsearch.autotune(model, tunables=tun, cache=cache,
                         cache_key=key, mode='search')
    assert not r.cached and r.winners == {'tile': 4}

    def boom(c):
        raise AssertionError('cached mode must not search or measure')

    r2 = tsearch.autotune(boom, measure_fn=boom, tunables=tun,
                          cache=cache, cache_key=key, mode='cached')
    assert r2.cached and r2.winners == {'tile': 4}
    assert 'cache hit' in r2.format_trace()
    # a cold key in cached mode returns defaults untouched, no search
    r3 = tsearch.autotune(boom, measure_fn=boom, tunables=tun,
                          cache=cache,
                          cache_key=cache.key(('pm', 3), 'cpu', None),
                          mode='cached')
    assert not r3.cached and r3.winners == {}
    assert tsearch.autotune(boom, mode='off') is None


# ---------------------------------------------------------------------------
# CPU dry-run smoke on a real program (the tier-1 acceptance check)
# ---------------------------------------------------------------------------

def _small_program():
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data(name='x', shape=[32], dtype='float32')
        label = fluid.layers.data(name='label', shape=[1],
                                  dtype='int64')
        h = fluid.layers.fc(input=x, size=64, act='relu')
        pred = fluid.layers.fc(input=h, size=10, act='softmax')
        cost = fluid.layers.mean(x=fluid.layers.cross_entropy(
            input=pred, label=label))
        fluid.optimizer.SGDOptimizer(0.1).minimize(cost)
    return main_p, startup, cost


def test_dryrun_smoke_chosen_config_modeled_no_worse(tmp_path):
    prog, _startup, cost = _small_program()
    feed_specs = {'x': ((8, 32), 'float32'), 'label': ((8, 1), 'int32')}
    tun = [registry.tunable('amp'),
           registry.tunable('flat_tile_budget')]

    def model_fn(cfg):
        with registry.applied(cfg):
            return trt.model_program(prog, fetch_names=(cost.name,),
                                     feed_specs=feed_specs)

    base_model = model_fn(registry.current_config(tun))
    assert base_model is not None and base_model['score'] > 0
    assert base_model['peak_bytes'] > 0

    cache = tcache.TuneCache(str(tmp_path))
    key = trt.cache_key_for(prog)
    r = tsearch.autotune(model_fn, tunables=tun, cache=cache,
                         cache_key=key, mode='search')
    assert not r.cached
    # dry-run contract: the chosen config is modeled at least as fast
    # as the defaults (strict < to adopt, ties keep the incumbent)
    assert r.best_score <= base_model['score']
    # winners round-trip: the second build is a cache hit, zero search
    def boom(c):
        raise AssertionError('second build must not search')
    r2 = tsearch.autotune(boom, tunables=tun, cache=cache,
                          cache_key=key, mode='cached')
    assert r2.cached and r2.winners == r.winners


def test_cache_key_separates_programs_but_not_rebuilds():
    prog_a, _s, _c = _small_program()
    prog_a2, _s2, _c2 = _small_program()  # same model, later build
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        loss = fluid.layers.mean(x=fluid.layers.square_error_cost(
            input=fluid.layers.fc(input=x, size=1), label=y))
        fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    # distinct models never share winners; in-process rebuilds (whose
    # var-name counters differ) and fresh-process builds do — the
    # fingerprint is the op-type multiset, not names
    assert trt.cache_key_for(prog_a) == trt.cache_key_for(prog_a2)
    assert trt.cache_key_for(prog_a) != trt.cache_key_for(main_p)


def test_cache_key_stable_under_tuner_applied_env(tmp_path):
    prog, _startup, _cost = _small_program()
    key_fresh = trt.cache_key_for(prog)
    # after the tuner applies a plan-affecting winner, the key must not
    # move (base_env masks it) — the zero-search-restart contract
    registry.apply_persistent({'amp': 'bf16'})
    assert trt.cache_key_for(prog) == key_fresh
    # but a USER-pinned plan-affecting env legitimately changes the key
    os.environ.pop('PADDLE_TPU_AMP', None)
    registry._TUNER_APPLIED.discard('PADDLE_TPU_AMP')
    os.environ['PADDLE_TPU_AMP'] = 'bf16'
    try:
        assert trt.cache_key_for(prog) != key_fresh
    finally:
        os.environ.pop('PADDLE_TPU_AMP', None)


# ---------------------------------------------------------------------------
# executor integration
# ---------------------------------------------------------------------------

def _feed():
    rng = np.random.default_rng(0)
    return {'x': rng.normal(size=(8, 32)).astype(np.float32),
            'label': rng.integers(0, 10, (8, 1)).astype(np.int32)}


def test_executor_applies_cached_winners(tmp_path, monkeypatch):
    prog, startup, cost = _small_program()
    monkeypatch.setenv('PADDLE_TPU_TUNE_CACHE_DIR', str(tmp_path))
    cache = tcache.TuneCache(str(tmp_path))
    cache.store(trt.cache_key_for(prog), {'device_prefetch_chunk': 4})
    monkeypatch.setenv('PADDLE_TPU_TUNE', 'cached')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = exe.run(prog, feed=_feed(), fetch_list=[cost])
    assert np.isfinite(np.asarray(out[0])).all()
    # the winner was applied to the process env by the executor hook
    assert os.environ.get('PADDLE_TPU_DEVICE_PREFETCH_CHUNK') == '4'
    assert 'PADDLE_TPU_DEVICE_PREFETCH_CHUNK' in \
        registry.tuner_applied_env()


def test_tune_off_is_bitwise_identical(tmp_path, monkeypatch):
    prog, startup, cost = _small_program()
    prog.random_seed = startup.random_seed = 7  # deterministic init
    # a poisoned cache that would change behavior if it were consulted
    cache = tcache.TuneCache(str(tmp_path))
    cache.store(trt.cache_key_for(prog), {'device_prefetch_chunk': 4})
    monkeypatch.setenv('PADDLE_TPU_TUNE_CACHE_DIR', str(tmp_path))
    monkeypatch.delenv('PADDLE_TPU_TUNE', raising=False)
    env_before = dict(os.environ)

    def run_twice():  # two SGD steps in a fresh scope
        scope = fluid.core.scope.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            a = np.asarray(exe.run(prog, feed=_feed(),
                                   fetch_list=[cost])[0])
            b = np.asarray(exe.run(prog, feed=_feed(),
                                   fetch_list=[cost])[0])
        return a, b

    a1, b1 = run_twice()
    assert trt.maybe_apply_cached(prog) is None  # off: no-op
    a2, b2 = run_twice()
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(b1, b2)
    assert dict(os.environ) == env_before  # nothing applied


def test_compilation_cache_late_set_applies_with_warning(
        tmp_path, monkeypatch, caplog):
    from paddle_tpu.core import executor as exmod
    prog, startup, cost = _small_program()
    monkeypatch.delenv('PADDLE_TPU_COMPILATION_CACHE_DIR',
                       raising=False)
    saved = (exmod._compilation_cache_dir,
             exmod._compilation_cache_resolved)
    try:
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        exe.run(prog, feed=_feed(), fetch_list=[cost])
        # the wart this PR fixes: setting the dir AFTER first use used
        # to be silently ignored until reset_cache()
        monkeypatch.setenv('PADDLE_TPU_COMPILATION_CACHE_DIR',
                           str(tmp_path / 'cc'))
        with caplog.at_level(logging.WARNING,
                             logger='paddle_tpu.core.executor'):
            prog2, startup2, cost2 = _small_program()
            exe.run(startup2)
            exe.run(prog2, feed=_feed(), fetch_list=[cost2])
        assert any('applied now' in r.getMessage()
                   for r in caplog.records), caplog.records
        assert exmod._compilation_cache_dir == str(tmp_path / 'cc')
    finally:
        monkeypatch.delenv('PADDLE_TPU_COMPILATION_CACHE_DIR',
                           raising=False)
        import jax
        try:
            jax.config.update('jax_compilation_cache_dir', None)
        except Exception:
            pass
        (exmod._compilation_cache_dir,
         exmod._compilation_cache_resolved) = saved


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

def _fake_cost():
    return {
        'total': {'flops': 4.0e9, 'bytes': 1.0e8},
        'memory': {'peak_bytes': 123},
        'per_op': [
            {'index': 0, 'type': 'mul', 'role': 'forward',
             'flops': 3.8e11, 'bytes': 1.0e6},     # mxu-bound
            {'index': 1, 'type': 'relu', 'role': 'forward',
             'flops': 1.0e6, 'bytes': 9.0e7},      # hbm-bound
            {'index': 2, 'type': 'add', 'role': 'forward',
             'flops': 1.0e5, 'bytes': 9.0e6},
        ],
    }


def test_roofline_report_names_top_ops_and_limiting_resource():
    rep = roofline.report(_fake_cost(), measured_step_s=1e-2, top=2)
    assert rep['floor_s'] > 0 and rep['gap'] > 1
    assert len(rep['top']) == 2
    # ordered by modeled floor: the big matmul first, mxu-bound;
    # the relu second, hbm-bound
    assert rep['top'][0]['type'] == 'mul'
    assert rep['top'][0]['bound'] == 'mxu'
    assert rep['top'][1]['type'] == 'relu'
    assert rep['top'][1]['bound'] == 'hbm'
    assert rep['top'][0]['lost_s'] > rep['top'][1]['lost_s']
    text = roofline.format_report(rep)
    assert 'off roofline' in text and 'mxu-bound' in text


def test_roofline_flag_overrides(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PEAK_TFLOPS', '100')
    monkeypatch.setenv('PADDLE_TPU_HBM_GBPS', '400')
    assert roofline.resolved_peak_tflops() == 100.0
    assert roofline.resolved_hbm_gbps() == 400.0
    rep = roofline.report(_fake_cost())
    assert rep['peak_tflops'] == 100.0


# ---------------------------------------------------------------------------
# lint wiring
# ---------------------------------------------------------------------------

def test_check_tunables_green():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'tools', 'check_tunables.py')
    spec = importlib.util.spec_from_file_location('check_tunables', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check() == []
