"""Aux subsystem tests: FGSM (M12), debug nan/inf (A3), memory_optimize
remat (P14), net_drawer (P17), flags (A5), op-doc generator (A6),
profiler cost analysis (A1).
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid


def _mnist_like_program():
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name='img', shape=[16], dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        h = fluid.layers.fc(input=img, size=32, act='relu')
        predict = fluid.layers.fc(input=h, size=4, act='softmax')
        cost = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=predict, label=label))
    return main, startup, img, label, predict, cost


def test_fgsm_finds_adversarial_example():
    from paddle_tpu.adversarial import FGSM, TPUModel
    main, startup, img, label, predict, cost = _mnist_like_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    model = TPUModel(main, img.name, label.name, predict.name, cost.name,
                     bounds=(-3, 3))
    assert model.num_classes() == 4
    rng = np.random.RandomState(0)
    x = rng.randn(1, 16).astype('float32')
    y_pred = int(np.argmax(model.predict(x), axis=-1)[0])
    adv = FGSM(model)(x, np.array([[y_pred]]))
    assert adv is not None, 'FGSM failed to flip an untrained model'
    assert adv.shape == x.shape
    adv_pred = int(np.argmax(model.predict(adv), axis=-1)[0])
    assert adv_pred != y_pred


def test_ifgsm_runs():
    from paddle_tpu.adversarial import IFGSM, TPUModel
    main, startup, img, label, predict, cost = _mnist_like_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    model = TPUModel(main, img.name, label.name, predict.name, cost.name,
                     bounds=(-3, 3))
    x = np.random.RandomState(1).randn(1, 16).astype('float32')
    y = int(np.argmax(model.predict(x), axis=-1)[0])
    adv = IFGSM(model)(x, np.array([[y]]), epsilon=0.05, steps=20)
    assert adv is None or adv.shape == x.shape


def test_debug_nan_inf_checks():
    from paddle_tpu import debug
    assert not debug.has_nan_inf(np.ones(3))
    assert debug.has_nan_inf(np.array([1.0, np.nan]))
    assert debug.has_nan_inf(np.array([np.inf]))
    assert not debug.has_nan_inf(np.array([1, 2], dtype=np.int32))
    with pytest.raises(RuntimeError, match='1 NaN and 1 Inf'):
        debug.check_nan_inf(np.array([np.nan, np.inf, 0.0]), 'x')
    debug.guarded_fetches([np.ones(2)], ['ok'])


def test_nan_guard_catches_bad_op():
    import jax
    import jax.numpy as jnp
    from paddle_tpu import debug
    with debug.nan_guard():
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)).block_until_ready()
    assert not jax.config.jax_debug_nans  # restored


def test_memory_optimize_same_numerics():
    main, startup, img, label, predict, cost = _mnist_like_program()
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(cost)
    rng = np.random.RandomState(2)
    feed = {'img': rng.randn(8, 16).astype('float32'),
            'label': rng.randint(0, 4, (8, 1)).astype('int64')}

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    plain = [float(np.ravel(exe.run(main, feed=feed,
                                    fetch_list=[cost])[0])[0])
             for _ in range(3)]

    # fresh executor: its PRNG chain starts at step 0, so the startup
    # re-init reproduces the exact same weights
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(startup)
    fluid.memory_optimize(main, level='full')
    remat = [float(np.ravel(exe2.run(main, feed=feed,
                                     fetch_list=[cost])[0])[0])
             for _ in range(3)]
    np.testing.assert_allclose(remat, plain, rtol=1e-5, atol=1e-6)
    fluid.release_memory(main)  # API parity no-op


def test_net_drawer_dot_output(tmp_path):
    from paddle_tpu.utils import net_drawer
    main, startup, img, label, predict, cost = _mnist_like_program()
    path = str(tmp_path / 'g.dot')
    dot = net_drawer.draw_graph(startup, main, path=path)
    assert dot.startswith('digraph G {') and dot.rstrip().endswith('}')
    assert 'softmax' in dot and 'img' in dot
    assert os.path.exists(path)


def test_flags_env_roundtrip(monkeypatch):
    from paddle_tpu.flags import FLAGS, DEFINE_int
    assert FLAGS.check_nan_inf is False
    monkeypatch.setenv('PADDLE_TPU_CHECK_NAN_INF', '1')
    assert FLAGS.check_nan_inf is True
    DEFINE_int('test_only_flag', 7, 'test flag')
    assert FLAGS.test_only_flag == 7
    monkeypatch.setenv('PADDLE_TPU_TEST_ONLY_FLAG', '13')
    assert FLAGS.test_only_flag == 13
    with pytest.raises(AttributeError):
        FLAGS.never_defined
    assert 'PADDLE_TPU_CHECK_NAN_INF' in FLAGS.help()


def test_op_doc_generator(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..',
                                    'tools'))
    import gen_op_docs
    out = str(tmp_path / 'ops.md')
    text = gen_op_docs.generate(out)
    assert os.path.exists(out)
    assert '| `conv2d` |' in text and '| `lstm` |' in text
    assert text.count('| `') >= 170  # every registered op present


def test_profiler_cost_analysis():
    from paddle_tpu import profiler
    main, startup, img, label, predict, cost = _mnist_like_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {'img': rng.randn(4, 16).astype('float32'),
            'label': rng.randint(0, 4, (4, 1)).astype('int64')}
    costs = profiler.cost_analysis(main, feed, [cost])
    assert isinstance(costs, dict)
    # a [4,16]x[16,32] + [4,32]x[32,4] model: flops must be visible
    assert costs.get('flops', 0) > 1000


def test_ploter_headless(tmp_path, monkeypatch):
    # v2 plot parity: series accumulate and render headless (DISABLE_PLOT)
    monkeypatch.setenv('DISABLE_PLOT', 'True')
    from paddle_tpu.plot import Ploter
    p = Ploter('train cost', 'test cost')
    for i in range(3):
        p.append('train cost', i, 2.0 - 0.1 * i)
    p.append('test cost', 0, 1.5)
    assert p['train cost'].step == [0, 1, 2]
    p.plot()  # text fallback, no matplotlib needed
    p.reset()
    assert p['train cost'].value == []


def test_ploter_savefig(tmp_path, monkeypatch):
    monkeypatch.delenv('DISABLE_PLOT', raising=False)
    from paddle_tpu.plot import Ploter
    p = Ploter('cost')
    p.append('cost', 0, 1.0)
    p.append('cost', 1, 0.5)
    out = tmp_path / 'curve.png'
    p.plot(str(out))
    assert out.exists() and out.stat().st_size > 0


def test_profiler_sorted_table(tmp_path):
    """VERDICT r3 #7: stop_profiler(sorted_key=...) renders the per-op
    table (calls/total/min/max/ave) from the captured XLA trace, sorted
    by the requested key — profiler.cc ParseEvents parity."""
    from paddle_tpu import profiler
    main, startup, img, label, predict, cost = _mnist_like_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {'img': rng.randn(4, 16).astype('float32'),
            'label': rng.randint(0, 4, (4, 1)).astype('int64')}
    d = str(tmp_path / 'prof')
    profiler.start_profiler(log_dir=d)
    for _ in range(3):
        exe.run(main, feed=feed, fetch_list=[cost])
    table = profiler.stop_profiler(sorted_key='total',
                                   profile_path=str(tmp_path / 'p.txt'))
    assert table is not None
    lines = table.splitlines()
    assert lines[0].split()[:2] == ['Event', 'Calls']
    assert len(lines) > 1, "no trace rows parsed"
    totals = [float(l.split()[-4]) for l in lines[1:]]
    assert totals == sorted(totals, reverse=True)
    assert (tmp_path / 'p.txt').exists()

    # ave ordering differs from total ordering in general; just assert
    # it renders and is sorted by the requested key
    t2 = profiler.profile_table(sorted_key='ave', log_dir=d)
    aves = [float(l.split()[-1]) for l in t2.splitlines()[1:]]
    assert aves == sorted(aves, reverse=True)

    import pytest
    with pytest.raises(ValueError, match='sorted_key'):
        profiler.profile_table(sorted_key='bogus', log_dir=d)
