"""Learning-rate decay schedules.

Reference parity: python/paddle/v2/fluid/learning_rate_decay.py
(exponential_decay, natural_exp_decay, inverse_time_decay, polynomial_decay,
piecewise_decay) — each builds ops computing the LR from a step counter, so
the schedule runs inside the same compiled step as the update ops.
"""
from . import layers
from .core.program import unique_name
from .initializer import ConstantInitializer
from .layers.layer_helper import LayerHelper

__all__ = [
    'exponential_decay', 'natural_exp_decay', 'inverse_time_decay',
    'polynomial_decay', 'piecewise_decay', 'global_step_counter',
]


def global_step_counter(counter_name=None, begin=0, step=1):
    """A persistable float32 step counter incremented once per executor run
    (parity with fluid's autoincreased_step_counter)."""
    helper = LayerHelper('global_step_counter')
    name = counter_name or unique_name('@STEP_COUNTER@')
    counter = helper.create_global_variable(
        name=name, dtype='float32', shape=[1], persistable=True)
    helper.set_variable_initializer(
        counter, ConstantInitializer(float(begin - step)))
    helper.append_op(
        type='increment', inputs={'X': [counter]},
        outputs={'Out': [counter]}, attrs={'step': float(step)},
        infer_shape=False)
    counter.stop_gradient = True
    return counter


def _decay_step_counter():
    return global_step_counter(begin=1)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    global_step = _decay_step_counter()
    div_res = layers.scale(x=global_step, scale=1.0 / float(decay_steps))
    if staircase:
        div_res = layers.floor(x=div_res)
    base = layers.fill_constant(shape=[1], dtype='float32',
                                value=float(decay_rate))
    decay = layers.elementwise_pow(x=base, y=div_res)
    return layers.scale(x=decay, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    global_step = _decay_step_counter()
    div_res = layers.scale(x=global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = layers.floor(x=div_res)
    exponent = layers.scale(x=div_res, scale=-float(decay_rate))
    decay = layers.exp(x=exponent)
    return layers.scale(x=decay, scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    global_step = _decay_step_counter()
    div_res = layers.scale(x=global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = layers.floor(x=div_res)
    denom = layers.scale(x=div_res, scale=float(decay_rate), bias=1.0)
    one = layers.fill_constant(shape=[1], dtype='float32',
                               value=float(learning_rate))
    return layers.elementwise_div(x=one, y=denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    global_step = _decay_step_counter()
    if cycle:
        # decay_steps grows to decay_steps * ceil(step/decay_steps) so the
        # schedule restarts each period (fluid polynomial_decay parity).
        periods = layers.ceil(
            x=layers.scale(x=global_step, scale=1.0 / float(decay_steps)))
        periods = layers.elementwise_max(
            x=periods,
            y=layers.fill_constant(shape=[1], dtype='float32', value=1.0))
        steps = layers.scale(x=periods, scale=float(decay_steps))
        frac = layers.elementwise_div(x=global_step, y=steps)
    else:
        gs = layers.elementwise_min(
            x=global_step,
            y=layers.fill_constant(shape=[1], dtype='float32',
                                   value=float(decay_steps)))
        frac = layers.scale(x=gs, scale=1.0 / float(decay_steps))
    one_minus = layers.scale(x=frac, scale=-1.0, bias=1.0)
    powed = layers.pow(x=one_minus, attrs={'factor': float(power)})
    return layers.scale(x=powed,
                        scale=float(learning_rate - end_learning_rate),
                        bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    """LR = values[i] for step in (boundaries[i-1], boundaries[i]]."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    global_step = _decay_step_counter()
    lr = layers.fill_constant(shape=[1], dtype='float32', value=values[-1])
    # build nested selection from the last interval back to the first
    for b, v in reversed(list(zip(boundaries, values[:-1]))):
        bconst = layers.fill_constant(shape=[1], dtype='float32',
                                      value=float(b))
        cond = layers.less_than(x=global_step, y=bconst)
        vconst = layers.fill_constant(shape=[1], dtype='float32',
                                      value=float(v))
        lr = layers.select(cond, vconst, lr)
    return lr
