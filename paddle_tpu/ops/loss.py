"""Loss ops.

Reference parity: paddle/operators/{cross_entropy,softmax_with_cross_entropy,
sigmoid_cross_entropy_with_logits,squared_l2_distance (square_error_cost),
smooth_l1_loss,hinge_loss,huber_loss,log_loss,rank_loss,margin_rank_loss,
modified_huber_loss,bpr?,nce}_op.*.  All computed in fp32.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out


def _label_idx(label):
    lab = label.astype(jnp.int32)
    if lab.ndim >= 2 and lab.shape[-1] == 1:
        lab = lab.squeeze(-1)
    return lab


@register_op('cross_entropy')
def _cross_entropy(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.float32)  # probabilities [N, D]
    label = first(ins, 'Label')
    if attrs.get('soft_label', False):
        y = -jnp.sum(label.astype(jnp.float32) * jnp.log(x + 1e-12), axis=-1,
                     keepdims=True)
    else:
        lab = _label_idx(label)
        p = jnp.take_along_axis(x, lab[..., None], axis=-1)
        y = -jnp.log(p + 1e-12)
    return {'Y': [y]}


@register_op('softmax_with_cross_entropy')
def _softmax_with_ce(ctx, ins, attrs):
    logits = first(ins, 'Logits').astype(jnp.float32)
    label = first(ins, 'Label')
    logp = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get('soft_label', False):
        loss = -jnp.sum(label.astype(jnp.float32) * logp, axis=-1,
                        keepdims=True)
    else:
        lab = _label_idx(label)
        loss = -jnp.take_along_axis(logp, lab[..., None], axis=-1)
    return {'Loss': [loss], 'Softmax': [jnp.exp(logp)]}


@register_op('sigmoid_cross_entropy_with_logits')
def _sigmoid_ce(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.float32)
    label = first(ins, 'Label').astype(jnp.float32)
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return out(loss)


@register_op('smooth_l1')  # the reference op name (smooth_l1_op.cc)
@register_op('smooth_l1_loss')
def _smooth_l1(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.float32)
    y = first(ins, 'Y').astype(jnp.float32)
    sigma = attrs.get('sigma', 1.0)
    s2 = sigma * sigma
    diff = x - y
    iw = first(ins, 'InsideWeight')
    if iw is not None:
        diff = diff * iw
    ad = jnp.abs(diff)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff,
                     ad - 0.5 / s2)
    ow = first(ins, 'OutsideWeight')
    if ow is not None:
        elem = elem * ow
    loss = jnp.sum(elem.reshape(x.shape[0], -1), axis=1, keepdims=True)
    return {'Out': [loss], 'Diff': [diff]}


@register_op('hinge_loss')
def _hinge(ctx, ins, attrs):
    logits = first(ins, 'Logits').astype(jnp.float32)
    labels = first(ins, 'Labels').astype(jnp.float32)
    return {'Loss': [jnp.maximum(0.0, 1.0 - (2 * labels - 1) * logits)]}


@register_op('huber_loss')
def _huber(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.float32)
    y = first(ins, 'Y').astype(jnp.float32)
    delta = attrs.get('delta', 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r,
                     delta * (ar - 0.5 * delta))
    return {'Out': [loss], 'Residual': [r]}


@register_op('log_loss')
def _log_loss(ctx, ins, attrs):
    p = first(ins, 'Predicted').astype(jnp.float32)
    label = first(ins, 'Labels').astype(jnp.float32)
    eps = attrs.get('epsilon', 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {'Loss': [loss]}


@register_op('rank_loss')
def _rank_loss(ctx, ins, attrs):
    label = first(ins, 'Label').astype(jnp.float32)
    left = first(ins, 'Left').astype(jnp.float32)
    right = first(ins, 'Right').astype(jnp.float32)
    d = left - right
    loss = jnp.log1p(jnp.exp(d)) - label * d
    return out(loss)


@register_op('margin_rank_loss')
def _margin_rank_loss(ctx, ins, attrs):
    label = first(ins, 'Label').astype(jnp.float32)
    x1 = first(ins, 'X1').astype(jnp.float32)
    x2 = first(ins, 'X2').astype(jnp.float32)
    margin = attrs.get('margin', 0.0)
    act = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {'Out': [act], 'Activated': [(act > 0).astype(jnp.float32)]}


@register_op('modified_huber_loss')
def _modified_huber(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.float32)
    y = first(ins, 'Y').astype(jnp.float32)
    a = (2 * y - 1) * x
    loss = jnp.where(a < -1, -4 * a,
                     jnp.where(a < 1, jnp.square(1 - a), 0.0))
    return {'Out': [loss], 'IntermediateVal': [a]}


@register_op('square_error_cost')
def _square_error_cost(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.float32)
    y = first(ins, 'Y').astype(jnp.float32)
    return out(jnp.square(x - y))


@register_op('nce')
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation loss (operators/nce_op.{cc,h}) with
    uniform noise distribution; negatives drawn per batch."""
    x = first(ins, 'Input').astype(jnp.float32)  # [N, D]
    label = _label_idx(first(ins, 'Label'))  # [N] or [N, num_true]
    w = first(ins, 'Weight').astype(jnp.float32)  # [num_classes, D]
    b = first(ins, 'Bias')
    num_neg = attrs.get('num_neg_samples', 10)
    num_classes = attrs.get('num_total_classes', w.shape[0])
    if label.ndim == 1:
        label = label[:, None]
    num_true = label.shape[1]
    neg = jax.random.randint(ctx.rng(), (x.shape[0], num_neg), 0,
                             num_classes)
    samples = jnp.concatenate([label, neg], axis=1)  # [N, T+S]
    sw = w[samples]  # [N, T+S, D]
    logits = jnp.einsum('nd,nsd->ns', x, sw)
    if b is not None:
        logits = logits + b.astype(jnp.float32)[samples]
    p_noise = num_neg / float(num_classes)
    # true part
    lt = logits[:, :num_true]
    pos = jnp.log1p(jnp.exp(-(lt - jnp.log(p_noise))))
    ls = logits[:, num_true:]
    negl = jnp.log1p(jnp.exp(ls - jnp.log(p_noise)))
    cost = jnp.sum(pos, axis=1, keepdims=True) + \
        jnp.sum(negl, axis=1, keepdims=True)
    return {'Cost': [cost], 'SampleLogits': [logits],
            'SampleLabels': [samples.astype(jnp.int32)]}
