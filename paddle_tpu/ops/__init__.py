"""Op library: importing this package registers every op implementation.

Reference parity: paddle/operators/* (one jax function per reference op
kernel family; see SURVEY.md §2.2).
"""
from . import (activations, amp_ops, attention, beam_search, chunked_ce,
               collective_ops, common, control_flow, conv, crf, ctc,
               detection, embedding, loss, math, metrics, misc, norm,
               optim_ops, pool, random, rnn, sequence, tensor_array,
               tensor_ops)  # noqa: F401
