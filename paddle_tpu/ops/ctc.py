"""CTC loss.

Reference parity: paddle/operators/warpctc_op.* (Baidu warp-ctc CUDA
kernel).  TPU-native design: the standard alpha (forward) recursion in log
space, vectorized over the batch and scanned over time with lax.scan —
static shapes, runs fused on device; the gradient comes from functional
autodiff instead of warp-ctc's hand-written backward.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first

_NEG_INF = -1e30


def _logaddexp(a, b):
    return jnp.logaddexp(a, b)


def ctc_loss(log_probs, logit_lengths, labels, label_lengths, blank=0):
    """log_probs [B, T, V] (log-softmax already applied), labels [B, L].
    Returns per-sequence negative log likelihood [B]."""
    b, t, v = log_probs.shape
    l = labels.shape[1]
    s = 2 * l + 1
    labels = labels.astype(jnp.int32)
    # extended label sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((b, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    # allow skip transitions where ext[i] != ext[i-2] and not blank
    ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)),
                        constant_values=-1)[:, :s]
    can_skip = (ext != blank) & (ext != ext_prev2)

    def emit(lp_t):
        # lp_t [B, V] -> [B, S] emission scores for the extended labels
        return jnp.take_along_axis(lp_t, ext, axis=1)

    alpha0 = jnp.full((b, s), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(log_probs[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.take_along_axis(log_probs[:, 0], ext[:, 1:2], axis=1)[:, 0])
    # rows with zero labels have no position 1
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_lengths > 0, alpha0[:, 1], _NEG_INF))

    def step(alpha, inputs):
        lp_t, t_idx = inputs
        shift1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                         constant_values=_NEG_INF)[:, :s]
        shift2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                         constant_values=_NEG_INF)[:, :s]
        merged = _logaddexp(alpha, shift1)
        merged = jnp.where(can_skip, _logaddexp(merged, shift2), merged)
        new_alpha = merged + emit(lp_t)
        # freeze rows whose logit sequence already ended
        active = (t_idx < logit_lengths)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        return new_alpha, None

    ts = jnp.arange(1, t)
    alpha, _ = jax.lax.scan(step, alpha0,
                            (jnp.swapaxes(log_probs[:, 1:], 0, 1), ts))
    # final: sum of the last two extended positions (per row's own S)
    final_s = 2 * label_lengths.astype(jnp.int32)
    last = jnp.take_along_axis(alpha, final_s[:, None], axis=1)[:, 0]
    second = jnp.take_along_axis(
        alpha, jnp.maximum(final_s - 1, 0)[:, None], axis=1)[:, 0]
    second = jnp.where(label_lengths > 0, second, _NEG_INF)
    ll = _logaddexp(last, second)
    return -ll


@register_op('warpctc')
def _warpctc(ctx, ins, attrs):
    logits = first(ins, 'Logits')  # [B, T, V] padded
    labels = first(ins, 'Label')  # [B, L] padded int
    logit_len = first(ins, 'LogitsLen')
    label_len = first(ins, 'LabelLen')
    if labels.ndim == 3 and labels.shape[-1] == 1:
        labels = labels[..., 0]
    b, t, v = logits.shape
    if logit_len is None:
        logit_len = jnp.full((b,), t, jnp.int32)
    if label_len is None:
        label_len = jnp.sum((labels > 0).astype(jnp.int32), axis=1)
    logit_len = logit_len.astype(jnp.int32).reshape(-1)
    label_len = label_len.astype(jnp.int32).reshape(-1)
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    loss = ctc_loss(lp, logit_len, labels, label_len,
                    blank=attrs.get('blank', 0))
    if attrs.get('norm_by_times', False):
        loss = loss / jnp.maximum(logit_len.astype(jnp.float32), 1.0)
    return {'Loss': [loss.reshape(b, 1)], 'WarpCTCGrad': [lp]}
