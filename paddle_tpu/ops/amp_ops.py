"""AMP dynamic-loss-scaling ops (f16 mode of the transpiler/amp.py pass).

Reference parity: paddle/operators' later check_finite_and_unscale +
update_loss_scaling pair (Micikevicius et al. 2018, "Mixed Precision
Training"): the loss is multiplied by a scale before backward so small
f16 gradients don't flush to zero, gradients are divided back down
before clipping/regularization/apply, a step whose gradients contain
inf/nan is skipped wholesale (the executor gates optimize-role ops on
FoundInfinite — see executor._run_one), and the scale grows after N
consecutive finite steps / shrinks after M consecutive overflows.

Both ops are pure jnp over their inputs — the grow/backoff counters and
the scale are ordinary persistable [1] vars, so under Executor.run_steps
they ride the lax.scan carry like any optimizer state.
"""
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows
from .common import first


def _all_finite(x):
    return jnp.all(jnp.isfinite(x.astype(jnp.float32)))


@register_op('check_finite_and_unscale')
def _check_finite_and_unscale(ctx, ins, attrs):
    """Out[i] = X[i] / Scale; FoundInfinite = any X has inf/nan (OR'd
    with the optional FoundAcc input so multi-minimize programs chain
    one check per autodiff into a single verdict).  SelectedRows grads
    unscale their values in place (rows untouched)."""
    scale = first(ins, 'Scale').astype(jnp.float32).reshape(())
    inv = 1.0 / scale
    found = jnp.zeros((), bool)
    for acc in ins.get('FoundAcc', []):
        found = found | jnp.reshape(acc, ()).astype(bool)
    outs = []
    for g in ins.get('X', []):
        if isinstance(g, SelectedRows):
            v = g.values.astype(jnp.float32)
            found = found | ~_all_finite(v)
            outs.append(SelectedRows(g.rows,
                                     (v * inv).astype(g.values.dtype),
                                     g.height))
        else:
            found = found | ~_all_finite(g)
            outs.append((g.astype(jnp.float32) * inv).astype(g.dtype))
    return {'Out': outs, 'FoundInfinite': [jnp.reshape(found, (1,))]}


@register_op('update_loss_scale')
def _update_loss_scale(ctx, ins, attrs):
    """Grow/backoff the dynamic loss scale.  Non-finite step: bad+1,
    good=0, and after decr_every_n_nan_or_inf consecutive overflows the
    scale halves (floored at 1.0).  Finite step: good+1, bad=0, and
    after incr_every_n_steps consecutive finite steps the scale doubles
    (capped at 2^31).  SkippedSteps counts overflowed (gated-away)
    steps cumulatively for the observability layer."""
    found = jnp.reshape(first(ins, 'FoundInfinite'), ()).astype(bool)
    scale = first(ins, 'LossScale').astype(jnp.float32).reshape(())
    good = first(ins, 'GoodSteps').reshape(()).astype(jnp.int32)
    bad = first(ins, 'BadSteps').reshape(()).astype(jnp.int32)
    skipped = first(ins, 'SkippedSteps').reshape(()).astype(jnp.int32)
    incr_every = int(attrs.get('incr_every_n_steps', 1000))
    decr_every = int(attrs.get('decr_every_n_nan_or_inf', 2))
    incr_ratio = float(attrs.get('incr_ratio', 2.0))
    decr_ratio = float(attrs.get('decr_ratio', 0.5))
    bad_new = jnp.where(found, bad + 1, 0)
    good_new = jnp.where(found, 0, good + 1)
    shrink = bad_new >= decr_every
    grow = good_new >= incr_every
    scale_new = jnp.where(
        shrink, jnp.maximum(scale * decr_ratio, 1.0),
        jnp.where(grow, jnp.minimum(scale * incr_ratio, 2.0 ** 31),
                  scale))
    return {
        'LossScaleOut': [scale_new.reshape((1,))],
        'GoodStepsOut': [jnp.where(grow, 0, good_new).reshape((1,))],
        'BadStepsOut': [jnp.where(shrink, 0, bad_new).reshape((1,))],
        'SkippedStepsOut': [(skipped +
                             found.astype(jnp.int32)).reshape((1,))],
    }
