"""Sequence (LoD) ops on the padded+lengths representation.

Reference parity: paddle/operators/sequence_*_op.* and
paddle/operators/math/sequence_*.  The reference stores ragged batches as a
flat tensor + offset table (LoD) and walks offsets on the host; TPU-native
design keeps a dense [batch, max_time, ...] tensor + int32 lengths [batch]
and uses masks — static shapes, fully vectorized, MXU/VPU friendly.

Convention: ops take slot 'X' (padded) and optional slot 'XLen' (lengths).
Missing lengths means "every row is full length".
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out


def _lengths(ins, slot, x, time_axis=1):
    ln = first(ins, slot)
    if ln is None:
        return jnp.full((x.shape[0],), x.shape[time_axis], jnp.int32)
    return ln.astype(jnp.int32).reshape(-1)


def _time_mask(x, lengths, time_axis=1):
    """Boolean mask [B, T] broadcastable against x."""
    t = x.shape[time_axis]
    mask = jnp.arange(t)[None, :] < lengths[:, None]
    extra = x.ndim - 2
    return mask.reshape(mask.shape + (1,) * extra)


@register_op('sequence_pool')
def _sequence_pool(ctx, ins, attrs):
    x = first(ins, 'X')  # [B, T, ...]
    lengths = _lengths(ins, 'XLen', x)
    ptype = attrs.get('pooltype', attrs.get('pool_type', 'AVERAGE')).upper()
    mask = _time_mask(x, lengths)
    xf = x.astype(jnp.float32)
    lf = jnp.maximum(lengths.astype(jnp.float32), 1.0)
    lf = lf.reshape((-1,) + (1,) * (x.ndim - 2))
    if ptype == 'SUM':
        y = jnp.sum(jnp.where(mask, xf, 0.0), axis=1)
    elif ptype == 'AVERAGE':
        y = jnp.sum(jnp.where(mask, xf, 0.0), axis=1) / lf
    elif ptype == 'SQRT':
        y = jnp.sum(jnp.where(mask, xf, 0.0), axis=1) / jnp.sqrt(lf)
    elif ptype == 'MAX':
        y = jnp.max(jnp.where(mask, xf, -jnp.inf), axis=1)
    elif ptype == 'LAST':
        idx = jnp.maximum(lengths - 1, 0)
        y = jnp.take_along_axis(
            xf, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)
        y = y.squeeze(1)
    elif ptype == 'FIRST':
        y = xf[:, 0]
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    return out(y.astype(x.dtype))


@register_op('sequence_softmax')
def _sequence_softmax(ctx, ins, attrs):
    """Softmax over the valid time steps of each row.  Accepts [B, T] or
    [B, T, 1] (parity: operators/sequence_softmax_op).  attr `axis` picks
    the time axis masked by the lengths (axis=2 on [B, Td, Ts] scores is
    batched attention over another sequence's steps)."""
    x = first(ins, 'X')
    axis = int(attrs.get('axis', 1))
    lengths = _lengths(ins, 'XLen', x, time_axis=axis)
    squeeze = axis == 1 and x.ndim == 3 and x.shape[-1] == 1
    xs = x[..., 0] if squeeze else x
    T = xs.shape[axis]
    mshape = [1] * xs.ndim
    mshape[0] = xs.shape[0]
    mshape[axis] = T
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    mask = mask.reshape(mshape)
    logits = jnp.where(mask, xs.astype(jnp.float32), -jnp.inf)
    y = jax.nn.softmax(logits, axis=axis)
    y = jnp.where(mask, y, 0.0).astype(x.dtype)
    return out(y[..., None] if squeeze else y)


@register_op('sequence_conv')
def _sequence_conv(ctx, ins, attrs):
    """Context-window convolution over time (operators/sequence_conv_op):
    each output step sees [context_start, context_start+context_length)
    neighbouring steps, flattened, times Filter [ctx_len*D, M].  Lowered to
    one MXU matmul over gathered context frames."""
    x = first(ins, 'X')  # [B, T, D]
    w = first(ins, 'Filter')  # [ctx_len*D, M]
    lengths = _lengths(ins, 'XLen', x)
    ctx_len = attrs.get('contextLength', attrs.get('context_length', 3))
    ctx_start = attrs.get('contextStart', attrs.get('context_start',
                                                    -(ctx_len // 2)))
    b, t, d = x.shape
    mask = _time_mask(x, lengths)
    xm = jnp.where(mask, x.astype(jnp.float32), 0.0)
    frames = []
    for k in range(ctx_len):
        off = ctx_start + k
        shifted = jnp.roll(xm, -off, axis=1)
        idx = jnp.arange(t) + off
        valid = ((idx >= 0) & (idx < t))[None, :, None]
        # also invalid past each row's length
        valid = valid & (idx[None, :, None] < lengths[:, None, None])
        frames.append(jnp.where(valid, shifted, 0.0))
    ctx_frames = jnp.concatenate(frames, axis=-1)  # [B, T, ctx_len*D]
    y = jnp.einsum('btc,cm->btm', ctx_frames, w.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    y = jnp.where(mask, y, 0.0)
    return out(y.astype(x.dtype))


@register_op('sequence_expand')
def _sequence_expand(ctx, ins, attrs):
    """Expand per-sequence rows over Y's time dimension
    (operators/sequence_expand_op): X [B, D] (one row per sequence) →
    [B, Ty, D] masked to Y's lengths."""
    x = first(ins, 'X')
    y = first(ins, 'Y')
    ylen = _lengths(ins, 'YLen', y)
    ty = y.shape[1]
    if x.ndim == 2:
        expanded = jnp.broadcast_to(x[:, None, :],
                                    (x.shape[0], ty, x.shape[1]))
    else:
        expanded = jnp.broadcast_to(x[:, None, ...],
                                    (x.shape[0], ty) + x.shape[1:])
    mask = jnp.arange(ty)[None, :] < ylen[:, None]
    mask = mask.reshape(mask.shape + (1,) * (expanded.ndim - 2))
    return out(jnp.where(mask, expanded, jnp.zeros_like(expanded)))


@register_op('sequence_concat')
def _sequence_concat(ctx, ins, attrs):
    """Concatenate two ragged batches along time (axis=1 repacking —
    operators/sequence_concat_op with axis=0 level=0 semantics)."""
    xs = ins['X']
    lens = ins.get('XLen')
    if lens is None or len(lens) != len(xs):
        lens = [jnp.full((x.shape[0],), x.shape[1], jnp.int32) for x in xs]
    acc = xs[0]
    acc_len = lens[0].astype(jnp.int32).reshape(-1)
    total_t = sum(x.shape[1] for x in xs)
    pad_spec = [(0, 0)] * acc.ndim
    pad_spec[1] = (0, total_t - acc.shape[1])
    acc = jnp.pad(acc, pad_spec)
    for x, ln in zip(xs[1:], lens[1:]):
        ln = ln.astype(jnp.int32).reshape(-1)

        def place(row_acc, row_x, start):
            start_idx = (start,) + (0,) * (row_acc.ndim - 1)
            return jax.lax.dynamic_update_slice(
                row_acc, row_x.astype(row_acc.dtype), start_idx)

        acc = jax.vmap(place)(acc, x, acc_len)
        acc_len = acc_len + ln
    mask = jnp.arange(acc.shape[1])[None, :] < acc_len[:, None]
    mask = mask.reshape(mask.shape + (1,) * (acc.ndim - 2))
    acc = jnp.where(mask, acc, jnp.zeros_like(acc))
    return {'Out': [acc], 'OutLen': [acc_len]}


@register_op('sequence_slice')
def _sequence_slice(ctx, ins, attrs):
    """Per-row slice [offset, offset+length) (operators/
    sequence_slice_op)."""
    x = first(ins, 'X')
    offset = first(ins, 'Offset').astype(jnp.int32).reshape(-1)
    length = first(ins, 'Length').astype(jnp.int32).reshape(-1)
    max_len = int(attrs.get('max_length', x.shape[1]))

    def slice_row(row, off):
        start = (off,) + (0,) * (row.ndim - 1)
        sizes = (max_len,) + row.shape[1:]
        padded = jnp.pad(row, [(0, max_len)] + [(0, 0)] * (row.ndim - 1))
        return jax.lax.dynamic_slice(padded, start, sizes)

    y = jax.vmap(slice_row)(x, offset)
    mask = jnp.arange(max_len)[None, :] < length[:, None]
    mask = mask.reshape(mask.shape + (1,) * (y.ndim - 2))
    y = jnp.where(mask, y, jnp.zeros_like(y))
    return {'Out': [y], 'OutLen': [length]}


@register_op('sequence_erase')
def _sequence_erase(ctx, ins, attrs):
    """Remove tokens in `tokens` and compact left (operators/
    sequence_erase_op)."""
    x = first(ins, 'X')  # [B, T] int tokens
    lengths = _lengths(ins, 'XLen', x)
    tokens = jnp.asarray(attrs.get('tokens', []), jnp.int32)
    t = x.shape[1]
    valid = jnp.arange(t)[None, :] < lengths[:, None]
    erase = jnp.isin(x.astype(jnp.int32), tokens) & valid
    keep = valid & ~erase
    # stable partition: keys push erased/padding to the right
    keys = jnp.where(keep, jnp.arange(t)[None, :], t + jnp.arange(t))
    order = jnp.argsort(keys, axis=1)
    y = jnp.take_along_axis(x, order, axis=1)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    pad_mask = jnp.arange(t)[None, :] < new_len[:, None]
    y = jnp.where(pad_mask, y, jnp.zeros_like(y))
    return {'Out': [y], 'OutLen': [new_len]}


@register_op('lod_reset')
def _lod_reset(ctx, ins, attrs):
    x = first(ins, 'X')
    target = first(ins, 'Y')
    if target is None:
        target = jnp.asarray(attrs['target_lod'], jnp.int32)
    return {'Out': [x], 'OutLen': [target.astype(jnp.int32).reshape(-1)]}


@register_op('sequence_first_step')
def _sequence_first_step(ctx, ins, attrs):
    return _pool_shim(ctx, ins, 'FIRST')


@register_op('sequence_last_step')
def _sequence_last_step(ctx, ins, attrs):
    return _pool_shim(ctx, ins, 'LAST')


def _pool_shim(ctx, ins, ptype):
    from ..core.registry import get_op_impl
    return get_op_impl('sequence_pool').compute(ctx, ins,
                                                {'pooltype': ptype})


@register_op('reorder_lod_tensor_by_rank')
def _reorder_lod_tensor_by_rank(ctx, ins, attrs):
    """Reorder batch rows by descending rank-table length (operators/
    reorder_lod_tensor_by_rank_op.cc).  The reference sorts sequences so
    RNNs can shrink their batch; on padded batches the op is a stable
    argsort by length — masks make it a no-op numerically, but the order
    (and its inverse, for restoration) is exposed for parity."""
    x = first(ins, 'X')
    table = first(ins, 'RankTable').astype(jnp.int32).reshape(-1)
    # stable sort by descending length
    order = jnp.argsort(-table, stable=True)
    y = jnp.take(x, order, axis=0)
    new_len = table[order]
    return {'Out': [y], 'OutLen': [new_len],
            'OrderedIndex': [order.astype(jnp.int32)]}
