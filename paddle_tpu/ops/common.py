"""Shared helpers for op implementations."""
import jax.numpy as jnp


def first(ins, slot):
    vals = ins.get(slot) or []
    return vals[0] if vals else None


def out(x):
    return {'Out': [x]}


def f32(x):
    """Accumulate in float32 (MXU-friendly: inputs may be bf16)."""
    return x.astype(jnp.float32)


def bcast_axis(x, y, axis):
    """Fluid elementwise broadcast: y's shape must match a contiguous
    suffix-run of x's shape starting at `axis`.  Reshape y with trailing
    1s so numpy broadcasting applies."""
    if x.ndim == y.ndim:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    new_shape = y.shape + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)
