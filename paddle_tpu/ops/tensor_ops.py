"""Tensor-manipulation ops.

Reference parity: paddle/operators/{reshape,transpose,concat,split,expand,
pad,crop,cast,assign,fill_*,gather,scatter,multiplex,one_hot,increment,
compare,logical}_op.* — all pure jnp/lax; static shapes for XLA.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core import datatypes
from ..core.registry import register_op
from .common import first, out


@register_op('reshape')
def _reshape(ctx, ins, attrs):
    x = first(ins, 'X')
    shape = list(attrs['shape'])
    # fluid semantics: 0 means "copy this dim from x", -1 infers
    for i, d in enumerate(shape):
        if d == 0:
            shape[i] = x.shape[i]
    return out(x.reshape(shape))


@register_op('transpose')
def _transpose(ctx, ins, attrs):
    return out(jnp.transpose(first(ins, 'X'), attrs['axis']))


@register_op('concat')
def _concat(ctx, ins, attrs):
    return out(jnp.concatenate(ins['X'], axis=attrs.get('axis', 0)))


@register_op('split')
def _split(ctx, ins, attrs):
    x = first(ins, 'X')
    axis = attrs.get('axis', 0)
    if attrs.get('sections'):
        idx = np.cumsum(attrs['sections'])[:-1].tolist()
        pieces = jnp.split(x, idx, axis=axis)
    else:
        pieces = jnp.split(x, attrs['num'], axis=axis)
    return out_list(pieces)


def out_list(pieces):
    return {'Out': list(pieces)}


@register_op('expand')
def _expand(ctx, ins, attrs):
    x = first(ins, 'X')
    times = attrs['expand_times']
    return out(jnp.tile(x, times))


@register_op('pad')
def _pad(ctx, ins, attrs):
    x = first(ins, 'X')
    p = attrs['paddings']
    pad_width = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return out(jnp.pad(x, pad_width,
                       constant_values=attrs.get('pad_value', 0.0)))


@register_op('crop')
def _crop(ctx, ins, attrs):
    x = first(ins, 'X')
    offsets = attrs['offsets']
    shape = attrs['shape']
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return out(x[slices])


@register_op('cast')
def _cast(ctx, ins, attrs):
    dtype = datatypes.as_numpy_dtype(attrs['out_dtype'])
    if dtype == np.int64:
        dtype = np.int32  # x64 disabled on TPU
    elif dtype == np.float64:
        dtype = np.float32
    x = first(ins, 'X')
    if getattr(x, 'dtype', None) == np.dtype(dtype):
        # same-dtype cast is the identity: pass the value through so it
        # contributes zero HLO and its VJP is exactly the identity (the
        # AMP weaver leans on both — a cast-to-bf16 of an already-bf16
        # value must not perturb the graph)
        return out(x)
    return out(x.astype(dtype))


@register_op('assign')
def _assign(ctx, ins, attrs):
    return out(first(ins, 'X'))


@register_op('assign_value')
def _assign_value(ctx, ins, attrs):
    vals = np.array(attrs['values'],
                    dtype=datatypes.as_numpy_dtype(attrs.get('dtype',
                                                             'float32')))
    return out(jnp.asarray(vals.reshape(attrs['shape'])))


@register_op('fill_constant')
def _fill_constant(ctx, ins, attrs):
    dtype = datatypes.as_numpy_dtype(attrs.get('dtype', 'float32'))
    if dtype == np.int64:
        dtype = np.int32
    elif dtype == np.float64:
        dtype = np.float32
    return out(jnp.full(tuple(attrs['shape']), attrs['value'], dtype=dtype))


@register_op('fill')
def _fill(ctx, ins, attrs):
    dtype = datatypes.as_numpy_dtype(attrs.get('dtype', 'float32'))
    data = np.array(attrs['value'], dtype=dtype).reshape(attrs['shape'])
    return out(jnp.asarray(data))


@register_op('fill_zeros_like')
def _fill_zeros_like(ctx, ins, attrs):
    return out(jnp.zeros_like(first(ins, 'X')))


@register_op('fill_constant_batch_size_like')
def _fill_cbsl(ctx, ins, attrs):
    ref = first(ins, 'Input')
    shape = list(attrs['shape'])
    in_idx = attrs.get('input_dim_idx', 0)
    out_idx = attrs.get('output_dim_idx', 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = datatypes.as_numpy_dtype(attrs.get('dtype', 'float32'))
    if dtype == np.int64:
        dtype = np.int32
    return out(jnp.full(tuple(shape), attrs.get('value', 0.0), dtype=dtype))


@register_op('gather')
def _gather(ctx, ins, attrs):
    x = first(ins, 'X')
    index = first(ins, 'Index').astype(jnp.int32).reshape(-1)
    return out(jnp.take(x, index, axis=0))


@register_op('scatter')
def _scatter(ctx, ins, attrs):
    """Overwrite rows of X at Ids with Updates (operators/scatter_op)."""
    x = first(ins, 'X')
    ids = first(ins, 'Ids').astype(jnp.int32).reshape(-1)
    upd = first(ins, 'Updates')
    return out(x.at[ids].set(upd))


@register_op('multiplex')
def _multiplex(ctx, ins, attrs):
    ids = first(ins, 'Ids').astype(jnp.int32).reshape(-1)
    stack = jnp.stack(ins['X'], axis=0)  # [n_candidates, batch, ...]
    batch = jnp.arange(stack.shape[1])
    return out(stack[ids, batch])


@register_op('one_hot')
def _one_hot(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.int32)
    depth = attrs['depth']
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.squeeze(-1)
    return out(jax.nn.one_hot(x, depth, dtype=jnp.float32))


@register_op('increment')
def _increment(ctx, ins, attrs):
    x = first(ins, 'X')
    return out(x + jnp.asarray(attrs.get('step', 1.0), dtype=x.dtype))


def _compare(name, fn):
    @register_op(name)
    def _impl(ctx, ins, attrs, _fn=fn):
        x = first(ins, 'X')
        y = first(ins, 'Y')
        return out(_fn(x, y))

    return _impl


_compare('less_than', jnp.less)
_compare('less_equal', jnp.less_equal)
_compare('greater_than', jnp.greater)
_compare('greater_equal', jnp.greater_equal)
_compare('equal', jnp.equal)
_compare('not_equal', jnp.not_equal)


def _logical(name, fn, binary=True):
    @register_op('logical_' + name)
    def _impl(ctx, ins, attrs, _fn=fn, _b=binary):
        x = first(ins, 'X')
        if _b:
            return out(_fn(x, first(ins, 'Y')))
        return out(_fn(x))

    return _impl


_logical('and', jnp.logical_and)
_logical('or', jnp.logical_or)
_logical('xor', jnp.logical_xor)
_logical('not', jnp.logical_not, binary=False)


@register_op('sign_of')
def _sign_of(ctx, ins, attrs):
    return out(jnp.sign(first(ins, 'X')))


@register_op('sequence_reshape')
def _sequence_reshape(ctx, ins, attrs):
    x = first(ins, 'X')
    new_dim = attrs['new_dim']
    return out(x.reshape(x.shape[0], -1, new_dim))


@register_op('im2sequence')
def _im2sequence(ctx, ins, attrs):
    """Extract conv patches as a sequence (operators/im2sequence_op): output
    [N, out_h*out_w, C*kh*kw] (padded-batch form of the reference's LoD
    output)."""
    x = first(ins, 'X')  # NCHW
    kh, kw = attrs['kernels']
    sh, sw = attrs.get('strides', [1, 1])
    p = attrs.get('paddings', [0, 0, 0, 0])
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw),
        [(p[0], p[2] if len(p) > 2 else p[0]),
         (p[1], p[3] if len(p) > 3 else p[1])],
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    n, ckk, oh, ow = patches.shape
    return out(patches.reshape(n, ckk, oh * ow).transpose(0, 2, 1))


@register_op('select')
def _select(ctx, ins, attrs):
    """Elementwise where(Cond, X, Y)."""
    cond = first(ins, 'Condition')
    return out(jnp.where(cond.astype(bool), first(ins, 'X'),
                         first(ins, 'Y')))
