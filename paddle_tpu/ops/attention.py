"""Program-level fused attention op riding the Pallas kernel.

Reference parity: the reference composes attention from matmul+softmax ops
(fluid nets.py scaled_dot_product_attention); this op is the TPU-native
fused form — ops/pallas/flash_attention.py online-softmax kernel, O(block)
on-chip memory instead of a [Tq, Tk] HBM score matrix.
"""
from ..core.registry import register_op
from .common import first, out


@register_op('flash_attention')
def _flash_attention(ctx, ins, attrs):
    # lazy: jax.experimental.pallas loads only when the op actually runs,
    # keeping `import paddle_tpu` free of the pallas extras
    from .pallas import flash_attention
    q = first(ins, 'Q')  # [B, T, H, D] or [B, T, D]
    k = first(ins, 'K')
    v = first(ins, 'V')
    y = flash_attention(
        q, k, v,
        causal=attrs.get('causal', False),
        scale=attrs.get('scale', None),
        block_q=attrs.get('block_q', 512),
        block_k=attrs.get('block_k', 512))
    return out(y.astype(q.dtype))
