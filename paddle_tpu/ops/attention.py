"""Program-level fused attention op riding the Pallas kernel.

Reference parity: the reference composes attention from matmul+softmax ops
(fluid nets.py scaled_dot_product_attention); this op is the TPU-native
fused form — ops/pallas/flash_attention.py online-softmax kernel, O(block)
on-chip memory instead of a [Tq, Tk] HBM score matrix.  When the
executor's place is NOT a TPU (ctx.backend), the op computes the same
math densely in jnp — a CPUPlace run on a TPU-attached host must not
compile Pallas for CPU, and interpret mode would be orders slower.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out


def _dense_attention(q, k, v, causal, scale):
    squeeze = q.ndim == 3
    if squeeze:
        q, k, v = (x[:, :, None, :] for x in (q, k, v))
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    s = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[2], s.shape[3]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32))
    return o[:, :, 0, :] if squeeze else o


def paged_attention_math(q, k_pool, v_pool, page_table, ctx_len,
                         scale=None):
    """Decode-step attention against a paged KV cache, the jnp math the
    registered op and the decode engine share.

    ``q`` [S, H, D] — one new token per stream slot; ``k_pool``/
    ``v_pool`` [N, P, H, D] page pools; ``page_table`` [S, MPP] int32
    page ids per stream (unused entries may point anywhere — typically
    the trash page — their keys are masked); ``ctx_len`` [S] int32
    VALID key count per stream, current token included.  Returns
    [S, H, D].  Gathers each stream's pages, masks positions >= ctx_len
    to -1e30, and softmaxes in f32 — identical masking/accumulation to
    ``_dense_attention``, so paged decode logits sit within ulps of the
    full-context recompute (tests/test_decode.py pins it).
    """
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    n, p = k_pool.shape[0], k_pool.shape[1]
    s, h, d = q.shape
    mpp = page_table.shape[1]
    idx = jnp.clip(page_table, 0, n - 1)
    k = k_pool[idx].reshape(s, mpp * p, h, d)   # [S, T, H, D]
    v = v_pool[idx].reshape(s, mpp * p, h, d)
    scores = jnp.einsum('shd,sthd->sht', q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    valid = jnp.arange(mpp * p)[None, :] < ctx_len[:, None]  # [S, T]
    scores = jnp.where(valid[:, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum('sht,sthd->shd', probs, v.astype(jnp.float32))
    return o.astype(q.dtype)


def chunked_prefill_attention_math(q, k_pool, v_pool, page_table, pos0,
                                   scale=None):
    """Chunked-prefill attention for ONE stream against a partial page
    table: chunk queries attend over every already-cached position —
    prior chunks AND the chunk's own keys (scattered before the call) —
    via the stream's page table.

    ``q`` [C, H, D] — a prompt chunk whose query ``i`` sits at ABSOLUTE
    position ``pos0 + i``; ``k_pool``/``v_pool`` [N, P, H, D] page
    pools; ``page_table`` [MPP] int32 page ids for the stream (entries
    past the claimed span may point anywhere — typically the trash
    page — their keys are causally masked); ``pos0`` scalar int32.
    Returns [C, H, D].  Key at absolute position ``j`` is valid for
    query ``i`` iff ``j <= pos0 + i`` — the causal mask on the
    absolute-position grid, so stale pages, trash entries, and the
    chunk's padded tail all mask out.  f32 scores/softmax, identical
    accumulation order to ``paged_attention_math``: a chunk sequence
    over the same cached pages reproduces the prefix bitwise
    (tests/test_decode_prefix.py pins hit-vs-cold equality).
    """
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    n, p = k_pool.shape[0], k_pool.shape[1]
    c, h, d = q.shape
    mpp = page_table.shape[0]
    idx = jnp.clip(page_table, 0, n - 1)
    k = k_pool[idx].reshape(mpp * p, h, d)      # [T, H, D]
    v = v_pool[idx].reshape(mpp * p, h, d)
    scores = jnp.einsum('chd,thd->cht', q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    qpos = pos0 + jnp.arange(c)                  # absolute positions
    valid = jnp.arange(mpp * p)[None, :] <= qpos[:, None]  # [C, T]
    scores = jnp.where(valid[:, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum('cht,thd->chd', probs, v.astype(jnp.float32))
    return o.astype(q.dtype)


@register_op('chunked_prefill_attention')
def _chunked_prefill_attention(ctx, ins, attrs):
    q = first(ins, 'Q')              # [C, H, D]
    k_pool = first(ins, 'KPool')     # [N, P, H, D]
    v_pool = first(ins, 'VPool')
    page_table = first(ins, 'PT')    # [MPP] int32
    pos0 = first(ins, 'Pos0')        # scalar int32
    return out(chunked_prefill_attention_math(
        q, k_pool, v_pool, page_table.astype(jnp.int32),
        jnp.asarray(pos0, jnp.int32).reshape(()),
        scale=attrs.get('scale', None)))


@register_op('paged_attention')
def _paged_attention(ctx, ins, attrs):
    q = first(ins, 'Q')              # [S, H, D]
    k_pool = first(ins, 'KPool')     # [N, P, H, D]
    v_pool = first(ins, 'VPool')
    page_table = first(ins, 'PT')    # [S, MPP] int32
    ctx_len = first(ins, 'CtxLen')   # [S] int32
    return out(paged_attention_math(
        q, k_pool, v_pool, page_table.astype(jnp.int32),
        ctx_len.astype(jnp.int32), scale=attrs.get('scale', None)))


@register_op('flash_attention')
def _flash_attention(ctx, ins, attrs):
    q = first(ins, 'Q')  # [B, T, H, D] or [B, T, D]
    k = first(ins, 'K')
    v = first(ins, 'V')
    causal = attrs.get('causal', False)
    scale = attrs.get('scale', None)
    backend = getattr(ctx, 'backend', jax.default_backend())
    if backend != 'tpu' and not attrs.get('pallas_interpret', False):
        return out(_dense_attention(q, k, v, causal, scale)
                   .astype(q.dtype))
    # lazy: jax.experimental.pallas loads only when the op actually runs,
    # keeping `import paddle_tpu` free of the pallas extras
    from .pallas import flash_attention
    y = flash_attention(
        q, k, v,
        causal=causal,
        scale=scale,
        block_q=attrs.get('block_q'),   # None -> head-dim-aware auto
        block_k=attrs.get('block_k'),
        interpret=backend != 'tpu')
    return out(y.astype(q.dtype))
