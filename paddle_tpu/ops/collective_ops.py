"""O18 — program-level collective ops.

Reference parity: paddle/operators/nccl_op.cc (ncclAllReduce/Bcast/
Reduce as graph ops) and the pserver send/recv pair.  TPU-native design:
the op bodies call the named-axis collectives from parallel/collective.py,
so a Program containing them executes under `shard_map` over a Mesh axis
(collectives ride ICI); interpreted on a single device with no axis bound,
each op degrades to its one-participant semantics (identity), matching
nccl with a world size of 1.
"""
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out
from ..parallel import collective


def _axis_bound(axis_name):
    """True when `axis_name` is a mapped axis of the current trace
    (i.e. the op is being traced inside shard_map over that axis)."""
    import jax.core as jc
    try:
        return axis_name in jc.unsafe_get_axis_names_DO_NOT_USE()
    except Exception:
        try:  # fallback: an unbound axis raises NameError at trace time
            collective.axis_size(axis_name)
            return True
        except NameError:
            return False


@register_op('allreduce')
def _allreduce(ctx, ins, attrs):
    x = first(ins, 'X')
    axis = attrs.get('axis', attrs.get('ring_id', 'dp'))
    op = attrs.get('reduction', attrs.get('op', 'sum'))
    if not _axis_bound(axis):
        return out(x)  # world size 1
    return out(collective.allreduce(x, axis, op=op))


@register_op('broadcast')
def _broadcast(ctx, ins, attrs):
    x = first(ins, 'X')
    axis = attrs.get('axis', 'dp')
    root = attrs.get('root', 0)
    if not _axis_bound(axis):
        return out(x)
    return out(collective.broadcast(x, axis, root=root))


@register_op('allgather')
def _allgather(ctx, ins, attrs):
    x = first(ins, 'X')
    axis = attrs.get('axis', 'dp')
    if not _axis_bound(axis):
        return out(x)
    return out(collective.allgather(x, axis,
                                    axis=attrs.get('concat_axis', 0)))


@register_op('reducescatter')
def _reducescatter(ctx, ins, attrs):
    x = first(ins, 'X')
    axis = attrs.get('axis', 'dp')
    if not _axis_bound(axis):
        return out(x)
    return out(collective.reduce_scatter(
        x, axis, axis=attrs.get('scatter_axis', 0)))


@register_op('send')
def _send(ctx, ins, attrs):
    """pserver send ≡ the grad side of an fsdp reduce_scatter; as a
    single op it reduces over the axis (params flow back via 'recv')."""
    x = first(ins, 'X')
    axis = attrs.get('axis', 'fsdp')
    if not _axis_bound(axis):
        return out(x)
    return out(collective.allreduce(x, axis, op='sum'))


@register_op('recv')
def _recv(ctx, ins, attrs):
    """pserver recv ≡ broadcast of the updated value from the owner."""
    x = first(ins, 'X')
    axis = attrs.get('axis', 'fsdp')
    if not _axis_bound(axis):
        return out(x)
    return out(collective.broadcast(x, axis, root=attrs.get('root', 0)))


def _noop_import():  # keep jnp import referenced for future ops
    return jnp
