"""Chunked fused vocab-projection + softmax cross-entropy.

Reference parity: the loss half of operators/softmax_with_cross_entropy_op.cc
composed with the vocab fc (mul_op) — but computed ONLINE over vocab
chunks so the [N, V] logits matrix never reaches HBM.  For a 30k vocab
at batch·seq = 8192 the dense path writes (and backward re-reads) a
~1 GB fp32 logits buffer plus the saved softmax; this op's forward is
one matmul stream with a running (max, sumexp, label-logit) triple, and
its backward recomputes each chunk's logits to form softmax−onehot on
the fly — the same recompute-instead-of-store trade the flash-attention
kernel makes, applied to the classifier head.

FLOP cost: 4 N·D·V matmul passes (logits, logits-recompute, dx, dW)
vs 3 for the dense path; HBM savings: ~2×N·V fp32 reads+writes.  Net
win whenever V is large enough that the logits don't fit cache — the
regime the vocab head lives in.
"""
import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import first

_DEF_CHUNK = 4096


def _pad_to_multiple(v, c):
    return ((v + c - 1) // c) * c


def _chunk_logits(x, wp, bp, i, chunk, out_dtype=jnp.float32):
    """Logits for vocab chunk i: x @ W[:, iC:(i+1)C] + b, fp32 accum."""
    wc = lax.dynamic_slice_in_dim(wp, i * chunk, chunk, axis=1)
    bc = lax.dynamic_slice_in_dim(bp, i * chunk, chunk, axis=0)
    logits = jnp.matmul(x, wc.astype(x.dtype),
                        preferred_element_type=jnp.float32)
    return logits.astype(out_dtype) + bc.astype(out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _chunked_linear_ce(x, w, b, lab, chunk):
    """loss[n] = logsumexp_v(x@w + b)[n] - (x@w + b)[n, lab[n]]."""
    loss, _ = _chunked_ce_fwd_impl(x, w, b, lab, chunk)
    return loss


def _chunked_ce_fwd_impl(x, w, b, lab, chunk):
    n, _d = x.shape
    v = w.shape[1]
    vp = _pad_to_multiple(v, chunk)
    nc = vp // chunk
    # pad bias with -inf-ish so padded columns vanish from the logsumexp
    wp = jnp.pad(w, ((0, 0), (0, vp - v)))
    bp = jnp.pad(b, (0, vp - v), constant_values=-1e30)

    def body(carry, i):
        m, s, ll = carry
        logits = _chunk_logits(x, wp, bp, i, chunk)  # [N, C] fp32
        cmax = jnp.max(logits, axis=1)
        m2 = jnp.maximum(m, cmax)
        s2 = s * jnp.exp(m - m2) + jnp.sum(
            jnp.exp(logits - m2[:, None]), axis=1)
        local = lab - i * chunk
        hit = (local >= 0) & (local < chunk)
        lg = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=1)[:, 0]
        ll2 = jnp.where(hit, lg, ll)
        return (m2, s2, ll2), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, ll), _ = lax.scan(body, init, jnp.arange(nc))
    lse = m + jnp.log(s)
    return lse - ll, lse


def _chunked_ce_fwd(x, w, b, lab, chunk):
    loss, lse = _chunked_ce_fwd_impl(x, w, b, lab, chunk)
    return loss, (x, w, b, lab, lse)


def _chunked_ce_bwd(chunk, res, g):
    x, w, b, lab, lse = res
    n, d = x.shape
    v = w.shape[1]
    vp = _pad_to_multiple(v, chunk)
    nc = vp // chunk
    wp = jnp.pad(w, ((0, 0), (0, vp - v)))
    bp = jnp.pad(b, (0, vp - v), constant_values=-1e30)
    g32 = g.astype(jnp.float32)
    cols = jnp.arange(chunk)

    def body(dx, i):
        logits = _chunk_logits(x, wp, bp, i, chunk)
        p = jnp.exp(logits - lse[:, None])  # softmax slice, fp32
        # one-hot subtract as a broadcast compare: a scatter here costs
        # ~18 ms/step on a v5e (slow TPU scatter path); the compare
        # fuses into the surrounding elementwise for free
        local = lab - i * chunk
        p = p - (local[:, None] == cols[None, :]).astype(jnp.float32)
        dl = p * g32[:, None]              # dLogits chunk [N, C]
        dlc = dl.astype(x.dtype)           # matmuls ride the activation
        wc = lax.dynamic_slice_in_dim(wp, i * chunk, chunk, axis=1)
        dx = dx + jnp.matmul(dlc, wc.astype(x.dtype).T,
                             preferred_element_type=jnp.float32)
        dwc = jnp.matmul(x.T, dlc, preferred_element_type=jnp.float32)
        return dx, (dwc, jnp.sum(dl, axis=0))

    # dW rides the scan OUTPUT (one [nc, D, C] write + one transpose),
    # not the carry: a dynamic_update_slice on a [D, Vp] carry makes XLA
    # copy the whole buffer per iteration when aliasing fails
    dx, (dws, dbs) = lax.scan(body, jnp.zeros((n, d), jnp.float32),
                              jnp.arange(nc))
    dw = jnp.moveaxis(dws, 0, 1).reshape(d, vp)[:, :v]
    db = dbs.reshape(vp)[:v]
    dlab = np.zeros(lab.shape, dtype=jax.dtypes.float0)
    return (dx.astype(x.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            dlab)


_chunked_linear_ce.defvjp(_chunked_ce_fwd, _chunked_ce_bwd)


@jax.custom_vjp
def _dense_linear_ce(x, w, b, lab):
    """Dense-mode fused linear+CE: ONE logits matmul whose reductions
    (max, sumexp, label gather) fuse onto the dot output; the only
    [N, V] residual is a HALF-WIDTH copy of the logits in the activation
    dtype (bf16 under mixed precision) for the backward softmax — the
    fp32 logits, log-softmax, and saved-softmax buffers of the naive
    composition never exist.  At vocab 30k the bf16 store (~0.6 ms of
    HBM) beats the chunked mode's recompute matmul (~4 ms of MXU); the
    chunked mode wins when even the half-width logits don't fit."""
    loss, _, _ = _dense_ce_fwd_impl(x, w, b, lab)
    return loss


def _dense_ce_fwd_impl(x, w, b, lab):
    logits = jnp.matmul(x, w.astype(x.dtype),
                        preferred_element_type=jnp.float32) + b
    m = jnp.max(logits, axis=1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=1))
    ll = jnp.take_along_axis(logits, lab[:, None], axis=1)[:, 0]
    return lse - ll, lse, logits.astype(x.dtype)


def _dense_ce_fwd(x, w, b, lab):
    loss, lse, logits_act = _dense_ce_fwd_impl(x, w, b, lab)
    return loss, (x, w, b, lab, lse, logits_act)


def _dense_ce_bwd(res, g):
    x, w, b, lab, lse, logits_act = res
    n = x.shape[0]
    v = w.shape[1]
    p = jnp.exp(logits_act.astype(jnp.float32) - lse[:, None])
    p = p - (lab[:, None] == jnp.arange(v)[None, :]).astype(jnp.float32)
    dl = p * g.astype(jnp.float32)[:, None]
    dlc = dl.astype(x.dtype)
    dx = jnp.matmul(dlc, w.astype(x.dtype).T,
                    preferred_element_type=jnp.float32)
    dw = jnp.matmul(x.T, dlc, preferred_element_type=jnp.float32)
    db = jnp.sum(dl, axis=0)
    dlab = np.zeros(lab.shape, dtype=jax.dtypes.float0)
    return (dx.astype(x.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            dlab)


_dense_linear_ce.defvjp(_dense_ce_fwd, _dense_ce_bwd)


def _dense_bytes_budget():
    """Budget for the dense path's activation-dtype logits residual:
    1/8 of the attached device's HBM (2 GB on a 16 GB v5e — the
    measured crossover on that part), derived from memory_stats()
    rather than hardcoded so smaller/larger-HBM parts switch to the
    chunked scan at an equivalent occupancy.
    PADDLE_TPU_DENSE_CE_BUDGET_MB overrides."""
    mb = os.environ.get('PADDLE_TPU_DENSE_CE_BUDGET_MB')
    if mb:
        try:
            return int(float(mb) * 1024 * 1024)
        except ValueError:
            pass
    try:
        stats = jax.devices()[0].memory_stats() or {}
        hbm = int(stats.get('bytes_limit', 0))
    except Exception:
        hbm = 0
    if hbm <= 0:
        hbm = 16 << 30  # v5e default when the backend has no stats
    return hbm // 8


@register_op('fused_linear_softmax_ce')
def _fused_linear_softmax_ce(ctx, ins, attrs):
    """X [.., D] → per-position CE loss [.., 1] against Label [.., 1]
    through the W [D, V] / Bias [V] vocab head.  mode='auto' (default)
    picks the dense single-matmul VJP while its activation-dtype logits
    residual fits _dense_bytes_budget(), else the chunked scan that never
    materializes [N, V] at all.  'dense'/'chunked' force a path."""
    x = first(ins, 'X')
    w = first(ins, 'W')
    b = first(ins, 'Bias')
    label = first(ins, 'Label')
    chunk = int(attrs.get('chunk', _DEF_CHUNK))
    mode = attrs.get('mode', 'auto')
    # feature dims start at `flatten` (the layer's num_flatten_dims
    # resolution) — everything before is batch-like
    flatten = int(attrs.get('flatten', x.ndim - 1))
    lead = x.shape[:flatten]
    d = int(np.prod(x.shape[flatten:]))
    v = w.shape[1]
    if b is None:
        b = jnp.zeros((v,), jnp.float32)
    lab = label.astype(jnp.int32).reshape(-1)
    n = int(np.prod(lead)) if lead else 1
    if mode == 'auto':
        mode = ('dense' if n * v * x.dtype.itemsize <= _dense_bytes_budget()
                else 'chunked')
    if mode == 'dense':
        loss = _dense_linear_ce(x.reshape(-1, d), w, b, lab)
    else:
        loss = _chunked_linear_ce(x.reshape(-1, d), w, b, lab, chunk)
    return {'Loss': [loss.reshape(lead + (1,))]}


@register_op('vocab_parallel_ce')
def _vocab_parallel_ce(ctx, ins, attrs):
    """Tensor-parallel form of fused_linear_softmax_ce: the W [D, V]
    vocab head is column-sharded over the ``tp_axis`` mesh axis and the
    loss runs parallel/tensor_parallel.vocab_parallel_cross_entropy
    inside shard_map — neither the full head nor any [N, V] logits ever
    exist on one chip; the global logsumexp is one pmax + one psum over
    ICI.  TensorParallelTranspiler swaps fused_linear_softmax_ce ops to
    this type (ref precedent: distribute_transpiler.py transpile()
    rewriting programs for distribution).  With no mesh bound, or a
    1-wide/absent tp axis, it degrades to the single-chip fused op —
    the same program runs anywhere."""
    from jax.sharding import PartitionSpec as P

    from ..parallel import api as papi

    x = first(ins, 'X')
    w = first(ins, 'W')
    b = first(ins, 'Bias')
    label = first(ins, 'Label')
    axis = attrs.get('tp_axis', 'tp')
    flatten = int(attrs.get('flatten', x.ndim - 1))
    lead = x.shape[:flatten]
    d = int(np.prod(x.shape[flatten:]))
    v = w.shape[1]

    mesh = papi.current_mesh()
    if (mesh is None or axis not in mesh.axis_names
            or mesh.shape[axis] == 1):
        return _fused_linear_softmax_ce(ctx, ins, attrs)
    size = mesh.shape[axis]
    if v % size:
        raise ValueError(
            "vocab_parallel_ce: vocab %d not divisible by tp axis %r "
            "size %d" % (v, axis, size))

    if b is None:
        b = jnp.zeros((v,), jnp.float32)
    xf = x.reshape(-1, d)
    lab = label.astype(jnp.int32).reshape(-1)

    # batch stays sharded over the remaining mesh axes (dp/fsdp riders
    # compose); only the vocab dim maps onto tp inside the shard_map
    batch_axes = tuple(a for a in mesh.axis_names
                       if a != axis and mesh.shape[a] > 1)
    bspec = batch_axes if batch_axes else None

    from ..parallel.collective import shard_map
    from ..parallel.tensor_parallel import vocab_parallel_cross_entropy

    def body(xs, ws, bs, ls):
        return vocab_parallel_cross_entropy(xs, ws, bs, ls, axis)

    loss = shard_map(
        body, mesh,
        in_specs=(P(bspec, None), P(None, axis), P(axis), P(bspec)),
        out_specs=P(bspec), check_vma=False)(xf, w, b, lab)
    return {'Loss': [loss.reshape(lead + (1,))]}
