"""SSD detection ops (O15).

Reference parity: paddle/operators/detection_output_op.{h,cc} — decode
prior boxes with variances, softmax the class scores, per-class greedy
NMS, global top-k.  The reference walks std::vector<BBox> per image on the
host; TPU-native design keeps a dense [N, P] lattice: decode is one fused
elementwise pass, NMS is a `lax.fori_loop` of vectorized IoU suppression
(static shapes), and the output is a fixed [N, keep_top_k, 6] tensor with
label -1 padding instead of a ragged LoD.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first


def decode_box(prior, loc):
    """Center-form decode with variances (reference math::DecodeBBox).
    prior [P, 8] = (xmin, ymin, xmax, ymax, v0, v1, v2, v3); loc [P, 4]."""
    p = prior.astype(jnp.float32)
    pw = p[:, 2] - p[:, 0]
    ph = p[:, 3] - p[:, 1]
    pcx = (p[:, 0] + p[:, 2]) * 0.5
    pcy = (p[:, 1] + p[:, 3]) * 0.5
    v = p[:, 4:8]
    l = loc.astype(jnp.float32)
    cx = v[:, 0] * l[:, 0] * pw + pcx
    cy = v[:, 1] * l[:, 1] * ph + pcy
    w = jnp.exp(v[:, 2] * l[:, 2]) * pw
    h = jnp.exp(v[:, 3] * l[:, 3]) * ph
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5, cy + h * 0.5], axis=1)


def iou_matrix(boxes):
    """Pairwise IoU [P, P] for boxes [P, 4] (xmin, ymin, xmax, ymax)."""
    b = boxes.astype(jnp.float32)
    area = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms_mask(boxes, scores, iou_threshold, score_threshold, max_keep):
    """Greedy NMS keep-mask [P] with static shapes: `max_keep` rounds of
    pick-best-then-suppress (the vectorized form of the reference's
    applyNMSFast)."""
    p = boxes.shape[0]
    iou = iou_matrix(boxes)
    alive = scores > score_threshold
    keep = jnp.zeros((p,), bool)

    def body(_, state):
        alive, keep = state
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        any_alive = jnp.any(alive)
        keep = jnp.where(any_alive, keep.at[best].set(True), keep)
        # suppress overlaps with the pick (and the pick itself)
        suppress = (iou[best] >= iou_threshold) | \
            (jnp.arange(p) == best)
        alive = alive & ~suppress & jnp.full((p,), any_alive)
        return alive, keep

    _, keep = jax.lax.fori_loop(0, min(max_keep, p), body, (alive, keep))
    return keep


@register_op('roi_pool')
def _roi_pool(ctx, ins, attrs):
    """RoI max pooling (reference paddle/operators/roi_pool_op.h).

    X [N, C, H, W]; ROIs [R, 5] rows (batch_idx, x1, y1, x2, y2) in image
    coordinates.  The reference walks each bin with data-dependent loop
    bounds; the TPU design is dense: per (roi, bin) boolean masks over the
    full H/W iotas, max-reduced in one vectorized pass (static shapes,
    vmap over rois — gradient falls out of autodiff).  Outputs Out
    [R, C, ph, pw] and Argmax (flat h*W+w, -1 for empty bins, parity with
    the reference's argmax bookkeeping).
    """
    x = first(ins, 'X').astype(jnp.float32)
    rois = first(ins, 'ROIs').astype(jnp.float32)
    ph_n = int(attrs['pooled_height'])
    pw_n = int(attrs['pooled_width'])
    scale = float(attrs.get('spatial_scale', 1.0))
    n, c, h, w = x.shape

    hh = jnp.arange(h)
    ww = jnp.arange(w)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        # C round(): half away from zero; coords are non-negative
        sw = jnp.floor(roi[1] * scale + 0.5).astype(jnp.int32)
        sh = jnp.floor(roi[2] * scale + 0.5).astype(jnp.int32)
        ew = jnp.floor(roi[3] * scale + 0.5).astype(jnp.int32)
        eh = jnp.floor(roi[4] * scale + 0.5).astype(jnp.int32)
        rh = jnp.maximum(eh - sh + 1, 1)  # malformed rois -> 1x1
        rw = jnp.maximum(ew - sw + 1, 1)
        bin_h = rh.astype(jnp.float32) / ph_n
        bin_w = rw.astype(jnp.float32) / pw_n
        ph_i = jnp.arange(ph_n, dtype=jnp.float32)
        pw_i = jnp.arange(pw_n, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(ph_i * bin_h).astype(jnp.int32) + sh,
                          0, h)
        hend = jnp.clip(jnp.ceil((ph_i + 1) * bin_h).astype(jnp.int32) + sh,
                        0, h)
        wstart = jnp.clip(jnp.floor(pw_i * bin_w).astype(jnp.int32) + sw,
                          0, w)
        wend = jnp.clip(jnp.ceil((pw_i + 1) * bin_w).astype(jnp.int32) + sw,
                        0, w)
        hmask = (hh[None, :] >= hstart[:, None]) & \
            (hh[None, :] < hend[:, None])      # [ph, H]
        wmask = (ww[None, :] >= wstart[:, None]) & \
            (ww[None, :] < wend[:, None])      # [pw, W]
        feat = jnp.take(x, b, axis=0)          # [C, H, W]
        # separable two-pass max keeps the peak at O(C*ph*H*W) instead of
        # the joint O(C*ph*pw*H*W) mask (argmax tie-order can differ from
        # the reference's h-major walk; exact-float ties only)
        mh = jnp.where(hmask[None, :, :, None], feat[:, None, :, :],
                       -jnp.inf)               # [C, ph, H, W]
        col_max = jnp.max(mh, axis=2)          # [C, ph, W]
        col_argh = jnp.argmax(mh, axis=2)      # [C, ph, W]
        mw = jnp.where(wmask[None, None, :, :], col_max[:, :, None, :],
                       -jnp.inf)               # [C, ph, pw, W]
        out = jnp.max(mw, axis=-1)             # [C, ph, pw]
        argw = jnp.argmax(mw, axis=-1)         # [C, ph, pw]
        argh = jnp.take_along_axis(col_argh, argw, axis=-1)
        # reference keeps int64 argmax; x64 is disabled under jax so int32
        arg = (argh * w + argw).astype(jnp.int32)
        empty = (hend <= hstart)[:, None] | (wend <= wstart)[None, :]
        out = jnp.where(empty[None], 0.0, out)
        arg = jnp.where(empty[None], -1, arg)
        return out, arg

    # sequential over rois (lax.map): each roi's pass is already wide
    # enough to fill the chip, and vmap would multiply the peak by R
    outs, args_ = jax.lax.map(one_roi, rois)
    return {'Out': [outs], 'Argmax': [args_]}


@register_op('detection_output')
def _detection_output(ctx, ins, attrs):
    """Inputs: Loc [N, P, 4] offsets, Conf [N, P, C] logits,
    PriorBox [P, 8].  Output [N, keep_top_k, 6] rows
    (label, score, xmin, ymin, xmax, ymax), label -1 past the detections."""
    loc = first(ins, 'Loc')
    conf = first(ins, 'Conf')
    prior = first(ins, 'PriorBox')
    num_classes = int(attrs['num_classes'])
    background = int(attrs.get('background_label_id', 0))
    nms_threshold = float(attrs.get('nms_threshold', 0.45))
    conf_threshold = float(attrs.get('confidence_threshold', 0.01))
    nms_top_k = int(attrs.get('nms_top_k', 400))
    keep_top_k = int(attrs.get('top_k', attrs.get('keep_top_k', 200)))

    probs = jax.nn.softmax(conf.astype(jnp.float32), axis=-1)  # [N, P, C]

    def per_image(loc_i, probs_i):
        boxes = decode_box(prior, loc_i)  # [P, 4]
        p = boxes.shape[0]

        def per_class(c_probs):
            return nms_mask(boxes, c_probs, nms_threshold, conf_threshold,
                            nms_top_k)

        cls_probs = jnp.moveaxis(probs_i, 1, 0)  # [C, P]
        keep = jax.vmap(per_class)(cls_probs)  # [C, P]
        keep = keep.at[background].set(jnp.zeros((p,), bool))
        scores = jnp.where(keep, cls_probs, 0.0).reshape(-1)  # [C*P]
        k = min(keep_top_k, scores.shape[0])
        top_scores, top_idx = jax.lax.top_k(scores, k)
        top_cls = (top_idx // p).astype(jnp.float32)
        top_box = boxes[top_idx % p]
        valid = top_scores > 0
        label = jnp.where(valid, top_cls, -1.0)
        rows = jnp.concatenate(
            [label[:, None], top_scores[:, None], top_box], axis=1)
        rows = jnp.where(valid[:, None], rows,
                         jnp.concatenate([jnp.full((k, 1), -1.0),
                                          jnp.zeros((k, 5))], axis=1))
        if k < keep_top_k:
            pad = jnp.zeros((keep_top_k - k, 6)).at[:, 0].set(-1.0)
            rows = jnp.concatenate([rows, pad], axis=0)
        return rows

    out = jax.vmap(per_image)(loc.astype(jnp.float32), probs)
    return {'Out': [out]}
