"""SSD detection ops (O15).

Reference parity: paddle/operators/detection_output_op.{h,cc} — decode
prior boxes with variances, softmax the class scores, per-class greedy
NMS, global top-k.  The reference walks std::vector<BBox> per image on the
host; TPU-native design keeps a dense [N, P] lattice: decode is one fused
elementwise pass, NMS is a `lax.fori_loop` of vectorized IoU suppression
(static shapes), and the output is a fixed [N, keep_top_k, 6] tensor with
label -1 padding instead of a ragged LoD.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first


def decode_box(prior, loc):
    """Center-form decode with variances (reference math::DecodeBBox).
    prior [P, 8] = (xmin, ymin, xmax, ymax, v0, v1, v2, v3); loc [P, 4]."""
    p = prior.astype(jnp.float32)
    pw = p[:, 2] - p[:, 0]
    ph = p[:, 3] - p[:, 1]
    pcx = (p[:, 0] + p[:, 2]) * 0.5
    pcy = (p[:, 1] + p[:, 3]) * 0.5
    v = p[:, 4:8]
    l = loc.astype(jnp.float32)
    cx = v[:, 0] * l[:, 0] * pw + pcx
    cy = v[:, 1] * l[:, 1] * ph + pcy
    w = jnp.exp(v[:, 2] * l[:, 2]) * pw
    h = jnp.exp(v[:, 3] * l[:, 3]) * ph
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5, cy + h * 0.5], axis=1)


def iou_matrix(boxes):
    """Pairwise IoU [P, P] for boxes [P, 4] (xmin, ymin, xmax, ymax)."""
    b = boxes.astype(jnp.float32)
    area = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms_mask(boxes, scores, iou_threshold, score_threshold, max_keep):
    """Greedy NMS keep-mask [P] with static shapes: `max_keep` rounds of
    pick-best-then-suppress (the vectorized form of the reference's
    applyNMSFast)."""
    p = boxes.shape[0]
    iou = iou_matrix(boxes)
    alive = scores > score_threshold
    keep = jnp.zeros((p,), bool)

    def body(_, state):
        alive, keep = state
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        any_alive = jnp.any(alive)
        keep = jnp.where(any_alive, keep.at[best].set(True), keep)
        # suppress overlaps with the pick (and the pick itself)
        suppress = (iou[best] >= iou_threshold) | \
            (jnp.arange(p) == best)
        alive = alive & ~suppress & jnp.full((p,), any_alive)
        return alive, keep

    _, keep = jax.lax.fori_loop(0, min(max_keep, p), body, (alive, keep))
    return keep


@register_op('detection_output')
def _detection_output(ctx, ins, attrs):
    """Inputs: Loc [N, P, 4] offsets, Conf [N, P, C] logits,
    PriorBox [P, 8].  Output [N, keep_top_k, 6] rows
    (label, score, xmin, ymin, xmax, ymax), label -1 past the detections."""
    loc = first(ins, 'Loc')
    conf = first(ins, 'Conf')
    prior = first(ins, 'PriorBox')
    num_classes = int(attrs['num_classes'])
    background = int(attrs.get('background_label_id', 0))
    nms_threshold = float(attrs.get('nms_threshold', 0.45))
    conf_threshold = float(attrs.get('confidence_threshold', 0.01))
    nms_top_k = int(attrs.get('nms_top_k', 400))
    keep_top_k = int(attrs.get('top_k', attrs.get('keep_top_k', 200)))

    probs = jax.nn.softmax(conf.astype(jnp.float32), axis=-1)  # [N, P, C]

    def per_image(loc_i, probs_i):
        boxes = decode_box(prior, loc_i)  # [P, 4]
        p = boxes.shape[0]

        def per_class(c_probs):
            return nms_mask(boxes, c_probs, nms_threshold, conf_threshold,
                            nms_top_k)

        cls_probs = jnp.moveaxis(probs_i, 1, 0)  # [C, P]
        keep = jax.vmap(per_class)(cls_probs)  # [C, P]
        keep = keep.at[background].set(jnp.zeros((p,), bool))
        scores = jnp.where(keep, cls_probs, 0.0).reshape(-1)  # [C*P]
        k = min(keep_top_k, scores.shape[0])
        top_scores, top_idx = jax.lax.top_k(scores, k)
        top_cls = (top_idx // p).astype(jnp.float32)
        top_box = boxes[top_idx % p]
        valid = top_scores > 0
        label = jnp.where(valid, top_cls, -1.0)
        rows = jnp.concatenate(
            [label[:, None], top_scores[:, None], top_box], axis=1)
        rows = jnp.where(valid[:, None], rows,
                         jnp.concatenate([jnp.full((k, 1), -1.0),
                                          jnp.zeros((k, 5))], axis=1))
        if k < keep_top_k:
            pad = jnp.zeros((keep_top_k - k, 6)).at[:, 0].set(-1.0)
            rows = jnp.concatenate([rows, pad], axis=0)
        return rows

    out = jax.vmap(per_image)(loc.astype(jnp.float32), probs)
    return {'Out': [out]}
