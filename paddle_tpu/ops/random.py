"""Random ops — threaded PRNG keys (no global mutable RNG state on device).

Reference parity: paddle/operators/{uniform_random,gaussian_random,
dropout}_op.*.  Keys derive deterministically from (program seed, step,
block, op index) via ExecutionContext.rng(), so dropout masks are identical
between the forward interpretation and its autodiff replay.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core import datatypes
from ..core.registry import register_op
from .common import first, out


def _key(ctx, attrs):
    """Per-op, per-step key.  A nonzero `seed` attr folds into the stream
    (reproducible but still varying across steps — parity with the
    reference's seeded Philox streams), it does not freeze it."""
    seed = attrs.get('seed', 0)
    key = ctx.rng()
    if seed:
        key = jax.random.fold_in(key, seed)
    return key


@register_op('uniform_random', stateful_rng=True)
def _uniform_random(ctx, ins, attrs):
    dtype = datatypes.as_numpy_dtype(attrs.get('dtype', 'float32'))
    if dtype == np.float64:
        dtype = np.float32
    shape = tuple(attrs['shape'])
    u = jax.random.uniform(_key(ctx, attrs), shape, dtype=jnp.float32,
                           minval=attrs.get('min', -1.0),
                           maxval=attrs.get('max', 1.0))
    return out(u.astype(dtype))


@register_op('gaussian_random', stateful_rng=True)
def _gaussian_random(ctx, ins, attrs):
    dtype = datatypes.as_numpy_dtype(attrs.get('dtype', 'float32'))
    if dtype == np.float64:
        dtype = np.float32
    shape = tuple(attrs['shape'])
    g = jax.random.normal(_key(ctx, attrs), shape, dtype=jnp.float32)
    g = g * attrs.get('std', 1.0) + attrs.get('mean', 0.0)
    return out(g.astype(dtype))


@register_op('truncated_gaussian_random', stateful_rng=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    dtype = datatypes.as_numpy_dtype(attrs.get('dtype', 'float32'))
    shape = tuple(attrs['shape'])
    g = jax.random.truncated_normal(_key(ctx, attrs), -2.0, 2.0, shape,
                                    dtype=jnp.float32)
    g = g * attrs.get('std', 1.0) + attrs.get('mean', 0.0)
    return out(g.astype(dtype))


@register_op('dropout', stateful_rng=True)
def _dropout(ctx, ins, attrs):
    x = first(ins, 'X')
    p = attrs.get('dropout_prob', 0.5)
    if p == 0.0:
        return {'Out': [x], 'Mask': [jnp.ones_like(x)]}
    if attrs.get('is_test', False):
        # reference dropout_op.h test path: Out = X * (1 - p) (non-inverted)
        return {'Out': [(x * (1.0 - p)).astype(x.dtype)],
                'Mask': [jnp.ones_like(x)]}
    keep = 1.0 - p
    mask = jax.random.bernoulli(_key(ctx, attrs), keep, x.shape)
    # reference dropout_op.h train path: Out = X * Mask, no 1/keep rescale
    y = jnp.where(mask, x, jnp.zeros_like(x))
    return {'Out': [y.astype(x.dtype)], 'Mask': [mask.astype(x.dtype)]}


@register_op('random_crop', stateful_rng=True)
def _random_crop(ctx, ins, attrs):
    x = first(ins, 'X')
    shape = attrs['shape']
    key = _key(ctx, attrs)
    starts = []
    for i, (xs, os_) in enumerate(zip(x.shape[-len(shape):], shape)):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, xs - os_ + 1))
    batch_dims = x.ndim - len(shape)
    start_idx = [jnp.asarray(0)] * batch_dims + starts
    sizes = list(x.shape[:batch_dims]) + list(shape)
    return out(jax.lax.dynamic_slice(x, start_idx, sizes))
