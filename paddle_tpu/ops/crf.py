"""Linear-chain CRF ops.

Reference parity: paddle/operators/linear_chain_crf_op.{h,cc} and
crf_decoding_op.{h,cc}.  The reference walks each LoD sequence on the
host CPU; here emissions are padded [B, T, N] + lengths and both the
forward (log-partition) recursion and Viterbi ride one `lax.scan` over T
for the whole batch — masked steps carry state through unchanged, so the
padded tail contributes nothing.

Transition parameter layout (same as the reference): [N+2, N] where row 0
holds start scores, row 1 end scores, rows 2.. the N x N transitions.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first

maybe = first  # absent slot -> None

__all__ = ['crf_nll', 'crf_viterbi']


def _unpack(transition):
    start = transition[0]
    end = transition[1]
    trans = transition[2:]
    return start, end, trans


def crf_nll(emission, lengths, transition, labels):
    """Negative log-likelihood per sequence: [B] (fp32)."""
    B, T, N = emission.shape
    emission = emission.astype(jnp.float32)
    transition = transition.astype(jnp.float32)
    start, end, trans = _unpack(transition)
    labels = labels.astype(jnp.int32)
    t_idx = jnp.arange(T)
    mask = t_idx[None, :] < lengths[:, None]  # [B, T]

    # ---- log partition via forward recursion
    alpha0 = start[None, :] + emission[:, 0, :]  # [B, N]

    def fwd(alpha, inputs):
        emit_t, m_t = inputs  # [B, N], [B]
        scores = alpha[:, :, None] + trans[None, :, :]  # [B, N, N]
        new = jax.scipy.special.logsumexp(scores, axis=1) + emit_t
        alpha = jnp.where(m_t[:, None], new, alpha)
        return alpha, None

    xs = (jnp.moveaxis(emission, 1, 0)[1:], jnp.moveaxis(mask, 1, 0)[1:])
    alpha_T, _ = jax.lax.scan(fwd, alpha0, xs)
    log_z = jax.scipy.special.logsumexp(alpha_T + end[None, :], axis=1)

    # ---- gold path score
    b_idx = jnp.arange(B)
    emit_scores = jnp.take_along_axis(
        emission, labels[:, :, None], axis=2)[..., 0]  # [B, T]
    emit_sum = jnp.sum(jnp.where(mask, emit_scores, 0.0), axis=1)
    prev_l, next_l = labels[:, :-1], labels[:, 1:]
    trans_scores = trans[prev_l, next_l]  # [B, T-1]
    trans_sum = jnp.sum(jnp.where(mask[:, 1:], trans_scores, 0.0), axis=1)
    last_idx = jnp.maximum(lengths - 1, 0)
    last_label = labels[b_idx, last_idx]
    gold = emit_sum + trans_sum + start[labels[:, 0]] + end[last_label]
    return log_z - gold


def crf_viterbi(emission, lengths, transition):
    """Viterbi decode: returns [B, T] int32 best path (zeros past length)."""
    B, T, N = emission.shape
    emission = emission.astype(jnp.float32)
    transition = transition.astype(jnp.float32)
    start, end, trans = _unpack(transition)
    t_idx = jnp.arange(T)
    mask = t_idx[None, :] < lengths[:, None]

    delta0 = start[None, :] + emission[:, 0, :]

    def step(delta, inputs):
        emit_t, m_t = inputs
        scores = delta[:, :, None] + trans[None, :, :]  # [B, prev, cur]
        best_prev = jnp.argmax(scores, axis=1)  # [B, N]
        new = jnp.max(scores, axis=1) + emit_t
        delta_next = jnp.where(m_t[:, None], new, delta)
        # past the end, backpointer is identity so backtrace passes through
        bp = jnp.where(m_t[:, None], best_prev,
                       jnp.arange(N)[None, :])
        return delta_next, bp

    xs = (jnp.moveaxis(emission, 1, 0)[1:], jnp.moveaxis(mask, 1, 0)[1:])
    delta_T, bps = jax.lax.scan(step, delta0, xs)  # bps: [T-1, B, N]

    last = jnp.argmax(delta_T + end[None, :], axis=1)  # [B]

    def back(lab, bp_t):
        # bp_t holds time-t's predecessor pointers; emit the predecessor
        # (the tag at bp_t's own time step), not the carried-in tag
        prev = jnp.take_along_axis(bp_t, lab[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(back, last, bps, reverse=True)
    path = jnp.concatenate([path_rev, last[None, :]], axis=0)  # [T, B]
    path = jnp.moveaxis(path, 0, 1).astype(jnp.int32)
    return jnp.where(mask, path, 0)


from .sequence import _lengths as _lengths_of_slot


def _lengths_of(ins, key, x):
    return _lengths_of_slot(ins, key, x)


@register_op('linear_chain_crf')
def _linear_chain_crf(ctx, ins, attrs):
    emission = first(ins, 'Emission')  # [B, T, N]
    transition = first(ins, 'Transition')  # [N+2, N]
    label = first(ins, 'Label')  # [B, T] or [B, T, 1]
    if label.ndim == 3:
        label = label[..., 0]
    lengths = _lengths_of(ins, 'EmissionLen', emission)
    nll = crf_nll(emission, lengths, transition, label)  # [B]
    return {'LogLikelihood': [nll[:, None]]}


@register_op('crf_decoding')
def _crf_decoding(ctx, ins, attrs):
    emission = first(ins, 'Emission')
    transition = first(ins, 'Transition')
    lengths = _lengths_of(ins, 'EmissionLen', emission)
    path = crf_viterbi(emission, lengths, transition)  # [B, T]
    label = maybe(ins, 'Label')
    if label is not None:
        if label.ndim == 3:
            label = label[..., 0]
        # parity with crf_decoding_op.h (`path[i] = label[i] == path[i]`):
        # with Label, emit 1 where the Viterbi tag AGREES with the gold tag.
        # Padded positions are forced to 0 (the reference compares over the
        # flat LoD layout and has no padding to speak of).
        mask = jnp.arange(emission.shape[1])[None, :] < lengths[:, None]
        hit = (path == label.astype(jnp.int32)) & mask
        return {'ViterbiPath': [hit.astype(jnp.int32)[..., None]]}
    return {'ViterbiPath': [path[..., None]]}
