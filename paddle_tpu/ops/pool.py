"""Pooling ops via lax.reduce_window.

Reference parity: paddle/operators/{pool_op,pool_cudnn_op,
pool_with_index_op,spp_op,unpool_op}.*.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .common import first


def _pair(v, n=2):
    return list(v) if isinstance(v, (list, tuple)) else [v] * n


def _pool2d(x, pooling_type, ksize, strides, paddings, global_pooling,
            exclusive=True, fmt='NCHW'):
    if fmt == 'NCHW':
        sp = (2, 3)
    else:
        sp = (1, 2)
    if global_pooling:
        ksize = [x.shape[sp[0]], x.shape[sp[1]]]
        paddings = [0, 0]
    window = [1, 1, 1, 1]
    stride = [1, 1, 1, 1]
    pad = [(0, 0)] * 4
    for i, d in enumerate(sp):
        window[d] = ksize[i]
        stride[d] = strides[i]
        pad[d] = (paddings[i], paddings[i])
    if pooling_type == 'max':
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, stride,
                                     pad)
    s = jax.lax.reduce_window(x.astype(jnp.float32), 0.0, jax.lax.add,
                              window, stride, pad)
    if exclusive and (paddings[0] or paddings[1]):
        ones = jnp.ones(x.shape, jnp.float32)
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride,
                                    pad)
        return (s / cnt).astype(x.dtype)
    return (s / float(np.prod(ksize))).astype(x.dtype)


@register_op('pool2d')
def _pool2d_op(ctx, ins, attrs):
    x = first(ins, 'X')
    y = _pool2d(x, attrs.get('pooling_type', 'max'),
                _pair(attrs.get('ksize', [2, 2])),
                _pair(attrs.get('strides', [1, 1])),
                _pair(attrs.get('paddings', [0, 0])),
                attrs.get('global_pooling', False),
                attrs.get('exclusive', True),
                attrs.get('data_format', 'NCHW'))
    return {'Out': [y]}


@register_op('pool3d')
def _pool3d_op(ctx, ins, attrs):
    x = first(ins, 'X')
    ksize = _pair(attrs.get('ksize', [2, 2, 2]), 3)
    strides = _pair(attrs.get('strides', [1, 1, 1]), 3)
    paddings = _pair(attrs.get('paddings', [0, 0, 0]), 3)
    if attrs.get('global_pooling', False):
        ksize = list(x.shape[2:])
        paddings = [0, 0, 0]
    window = [1, 1] + ksize
    stride = [1, 1] + strides
    pad = [(0, 0), (0, 0)] + [(p, p) for p in paddings]
    if attrs.get('pooling_type', 'max') == 'max':
        y = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, stride,
                                  pad)
    else:
        s = jax.lax.reduce_window(x.astype(jnp.float32), 0.0, jax.lax.add,
                                  window, stride, pad)
        y = (s / float(np.prod(ksize))).astype(x.dtype)
    return {'Out': [y]}


@register_op('max_pool2d_with_index')
def _max_pool_with_index(ctx, ins, attrs):
    """Returns pooled values and flat spatial argmax indices
    (operators/pool_with_index_op)."""
    x = first(ins, 'X')  # NCHW
    ksize = _pair(attrs.get('ksize', [2, 2]))
    strides = _pair(attrs.get('strides', ksize))
    paddings = _pair(attrs.get('paddings', [0, 0]))
    if attrs.get('global_pooling', False):
        ksize = list(x.shape[2:])
        paddings = [0, 0]
    n, c, h, w = x.shape
    flat_idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)

    def select(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    window = [1, 1, ksize[0], ksize[1]]
    stride = [1, 1, strides[0], strides[1]]
    pad = [(0, 0), (0, 0), (paddings[0], paddings[0]),
           (paddings[1], paddings[1])]
    vals, idxs = jax.lax.reduce_window(
        (x.astype(jnp.float32), flat_idx),
        (-jnp.inf, jnp.float32(-1)),
        select, window, stride, pad)
    return {'Out': [vals.astype(x.dtype)], 'Mask': [idxs.astype(jnp.int32)]}


@register_op('unpool')
def _unpool(ctx, ins, attrs):
    """Max-unpool using indices from max_pool2d_with_index."""
    x = first(ins, 'X')  # [N,C,h,w]
    mask = first(ins, 'Indices').astype(jnp.int32)
    out_h, out_w = attrs['unpooled_height'], attrs['unpooled_width']
    n, c, h, w = x.shape
    flat = jnp.zeros((n, c, out_h * out_w), x.dtype)
    idx = mask.reshape(n, c, -1)
    upd = x.reshape(n, c, -1)
    ni = jnp.arange(n)[:, None, None]
    ci = jnp.arange(c)[None, :, None]
    flat = flat.at[ni, ci, idx].add(upd)
    return {'Out': [flat.reshape(n, c, out_h, out_w)]}


@register_op('spp')
def _spp(ctx, ins, attrs):
    """Spatial pyramid pooling (operators/spp_op.cc)."""
    x = first(ins, 'X')  # NCHW
    levels = attrs.get('pyramid_height', 3)
    pool_type = attrs.get('pooling_type', 'max')
    n, c, h, w = x.shape
    outs = []
    for level in range(levels):
        bins = 2 ** level
        kh, kw = int(np.ceil(h / bins)), int(np.ceil(w / bins))
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        y = _pool2d(x, pool_type, [kh, kw], [kh, kw], [ph, pw], False,
                    exclusive=False)
        outs.append(y.reshape(n, -1))
    return {'Out': [jnp.concatenate(outs, axis=1)]}
