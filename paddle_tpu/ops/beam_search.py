"""Beam search ops (O14).

Reference parity: operators/beam_search_op.cc + beam_search_decode_op.cc.
The reference prunes LoD-nested candidate lists on the host per step; the
TPU design is dense and static-shape: beams live in a fixed [B, K] lattice,
one `lax.top_k` over K*V flattened continuations per step, finished beams
(emitted end_id) freeze their score and only propose end_id, and the
decode op backtracks the [T, B, K] parent lattice with a reverse scan —
the whole search jits into the same program as the model.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import first

__all__ = ['beam_search_step', 'beam_search_backtrack']

NEG_INF = -1e9


def beam_search_step(pre_ids, pre_scores, scores, beam_size, end_id):
    """One pruning step.

    pre_ids, pre_scores: [B, K]; scores: [B, K, V] log-probs of the next
    token.  Returns (ids [B,K], accumulated scores [B,K], parents [B,K]).
    """
    B, K, V = scores.shape
    finished = (pre_ids == end_id)  # [B, K]
    total = pre_scores[:, :, None] + scores.astype(jnp.float32)
    # finished beams: only candidate is end_id, score frozen
    fin = jnp.full_like(total, NEG_INF)
    fin = fin.at[:, :, end_id].set(pre_scores)
    total = jnp.where(finished[:, :, None], fin, total)
    flat = total.reshape(B, K * V)
    top_scores, top_idx = lax.top_k(flat, beam_size)  # [B, K]
    parents = top_idx // V
    ids = top_idx % V
    return ids.astype(jnp.int32), top_scores, parents.astype(jnp.int32)


@register_op('beam_search')
def _beam_search(ctx, ins, attrs):
    pre_ids = first(ins, 'pre_ids')
    pre_scores = first(ins, 'pre_scores')
    scores = first(ins, 'scores')
    beam_size = int(attrs['beam_size'])
    end_id = int(attrs['end_id'])
    if pre_ids.ndim == 3:
        pre_ids = pre_ids[..., 0]
    if pre_scores.ndim == 3:
        pre_scores = pre_scores[..., 0]
    ids, sc, parents = beam_search_step(pre_ids, pre_scores, scores,
                                        beam_size, end_id)
    return {'selected_ids': [ids], 'selected_scores': [sc],
            'parent_idx': [parents]}


def beam_search_backtrack(ids_tbk, parents_tbk, steps, end_id):
    """ids/parents: [T, B, K] lattices; steps: valid step count (traced).
    Returns sequences [B, K, T] (end_id-padded) ordered best-first."""
    T, B, K = ids_tbk.shape
    t_idx = jnp.arange(T)
    valid = t_idx < steps  # [T]

    def back(beam_ptr, inp):
        ids_t, parents_t, is_valid = inp
        tok = jnp.take_along_axis(ids_t, beam_ptr, axis=1)  # [B, K]
        par = jnp.take_along_axis(parents_t, beam_ptr, axis=1)
        tok = jnp.where(is_valid, tok, end_id)
        new_ptr = jnp.where(is_valid, par, beam_ptr)
        return new_ptr, tok

    init_ptr = jnp.tile(jnp.arange(K, dtype=jnp.int32)[None, :], (B, 1))
    _, toks = lax.scan(back, init_ptr,
                       (ids_tbk, parents_tbk, valid), reverse=True)
    return jnp.moveaxis(toks, 0, 2)  # [B, K, T] in forward order


@register_op('beam_search_init')
def _beam_search_init(ctx, ins, attrs):
    """Seed the dense beam lattice: ids [B, K] all start_id; scores [B, K]
    with column 0 at 0.0 and the rest NEG_INF so step 1 expands only one
    beam (the reference gets this for free from its LoD nesting —
    beam_search_op.cc grows real beams lazily)."""
    ref = first(ins, 'X')  # any [B, ...] tensor; batch size source
    beam_size = int(attrs['beam_size'])
    start_id = int(attrs['start_id'])
    B = ref.shape[0]
    ids = jnp.full((B, beam_size), start_id, jnp.int32)
    scores = jnp.full((B, beam_size), NEG_INF, jnp.float32)
    scores = scores.at[:, 0].set(0.0)
    return {'Ids': [ids], 'Scores': [scores]}


@register_op('beam_gather')
def _beam_gather(ctx, ins, attrs):
    """Reorder per-beam state [B, K, ...] by parent indices [B, K] — the
    state shuffle the reference does on the host when pruning LoD beams."""
    x = first(ins, 'X')
    idx = first(ins, 'Index').astype(jnp.int32)
    idxe = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return {'Out': [jnp.take_along_axis(x, idxe, axis=1)]}


@register_op('beam_search_decode')
def _beam_search_decode(ctx, ins, attrs):
    ids_arr = first(ins, 'Ids')  # TArray [T, B, K] (or raw array)
    parents_arr = first(ins, 'Parents')
    scores_arr = first(ins, 'Scores')
    end_id = int(attrs['end_id'])
    from .tensor_array import TArray
    if isinstance(ids_arr, TArray):
        steps = ids_arr.size
        ids_tbk, parents_tbk = ids_arr.data, parents_arr.data
    else:
        ids_tbk, parents_tbk = ids_arr, parents_arr
        steps = jnp.asarray(ids_tbk.shape[0], jnp.int32)
    seqs = beam_search_backtrack(ids_tbk, parents_tbk, steps, end_id)
    if isinstance(scores_arr, TArray):
        last = jnp.maximum(scores_arr.size - 1, 0)
        final_scores = jax.lax.dynamic_index_in_dim(
            scores_arr.data, last, 0, keepdims=False)  # [B, K]
    else:
        final_scores = scores_arr[-1]
    return {'SentenceIds': [seqs], 'SentenceScores': [final_scores]}
