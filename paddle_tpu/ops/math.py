"""Math ops: matmul family, elementwise family, reductions, softmax.

Reference parity: paddle/operators/{mul,matmul,elementwise_*,scale,sum,
minus,mean,clip,clip_by_norm,reduce,softmax,cos_sim,norm,top_k}_op.*.
Matmuls run on the MXU; `preferred_element_type=float32` keeps bf16 inputs
accumulating in fp32 (the TPU-native mixed-precision recipe).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .common import bcast_axis, first, out

_ACC = dict(preferred_element_type=jnp.float32)


def _matmul_acc(a, b):
    # fp32 master weights meet bf16 activations here: compute in the
    # activation dtype (MXU bf16 path, internal fp32 accumulation)
    b = b.astype(a.dtype)
    y = jnp.matmul(a, b, **_ACC)
    return y.astype(a.dtype)


@register_op('mul')
def _mul(ctx, ins, attrs):
    """Fluid `mul`: flatten X to 2-D at x_num_col_dims, Y at
    y_num_col_dims, then matmul (operators/mul_op.cc)."""
    x = first(ins, 'X')
    y = first(ins, 'Y')
    xnc = attrs.get('x_num_col_dims', 1)
    ync = attrs.get('y_num_col_dims', 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(_prod(xs[:xnc])), int(_prod(xs[xnc:]))))
    y2 = y.reshape((int(_prod(ys[:ync])), int(_prod(ys[ync:]))))
    o = _matmul_acc(x2, y2)
    return out(o.reshape(xs[:xnc] + ys[ync:]))


def _prod(t):
    p = 1
    for d in t:
        p *= int(d)
    return p


@register_op('matmul')
def _matmul(ctx, ins, attrs):
    x = first(ins, 'X')
    y = first(ins, 'Y')
    if attrs.get('transpose_X', False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get('transpose_Y', False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    if x.ndim == 1 and y.ndim == 1:
        return out(jnp.dot(x, y, **_ACC).astype(x.dtype))
    return out(_matmul_acc(x, y) * attrs.get('alpha', 1.0))


def _elementwise(name, fn):
    @register_op('elementwise_' + name)
    def _impl(ctx, ins, attrs, _fn=fn):
        x = first(ins, 'X')
        y = bcast_axis(x, first(ins, 'Y'), attrs.get('axis', -1))
        if y.dtype != x.dtype and jnp.issubdtype(x.dtype, jnp.floating) \
                and jnp.issubdtype(y.dtype, jnp.floating):
            # fp32 master params meeting low-precision activations: stay
            # in the activation dtype instead of silently promoting
            y = y.astype(x.dtype)
        return out(_fn(x, y))

    return _impl


_elementwise('add', jnp.add)
_elementwise('sub', jnp.subtract)
_elementwise('mul', jnp.multiply)
_elementwise('div', jnp.divide)
_elementwise('pow', jnp.power)
_elementwise('max', jnp.maximum)
_elementwise('min', jnp.minimum)
_elementwise('mod', jnp.mod)


@register_op('scale')
def _scale(ctx, ins, attrs):
    x = first(ins, 'X')
    scale = attrs.get('scale', 1.0)
    bias = attrs.get('bias', 0.0)
    if attrs.get('bias_after_scale', True):
        return out(x * scale + bias)
    return out((x + bias) * scale)


@register_op('sum')
def _sum(ctx, ins, attrs):
    xs = ins.get('X', [])
    acc = xs[0]
    for x in xs[1:]:
        acc = acc + x
    return out(acc)


@register_op('minus')
def _minus(ctx, ins, attrs):
    return out(first(ins, 'X') - first(ins, 'Y'))


@register_op('mean')
def _mean(ctx, ins, attrs):
    """mean_op.cc parity.  For a ragged input (XLen companion wired by
    the layer) the reference's LoDTensor holds only REAL elements, so the
    padded-dense equivalent averages over valid positions only — a plain
    mean would dilute short sequences with padding."""
    x = first(ins, 'X')
    lengths = first(ins, 'XLen')
    xf = x.astype(jnp.float32)
    if lengths is None:
        m = jnp.mean(xf)
    else:
        ln = lengths.astype(jnp.int32).reshape(-1)
        t = x.shape[1]
        mask = (jnp.arange(t)[None, :] < ln[:, None])
        mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        feat = int(np.prod(x.shape[2:])) if x.ndim > 2 else 1
        # count in f32: int32 sum(lengths)*feat overflows past 2^31 elems
        count = jnp.sum(ln.astype(jnp.float32)) * float(feat)
        m = jnp.sum(jnp.where(mask, xf, 0.0)) / jnp.maximum(count, 1.0)
    return out(m.astype(x.dtype).reshape((1,)))


@register_op('clip')
def _clip(ctx, ins, attrs):
    return out(jnp.clip(first(ins, 'X'), attrs['min'], attrs['max']))


@register_op('clip_by_norm')
def _clip_by_norm(ctx, ins, attrs):
    x = first(ins, 'X')
    max_norm = attrs['max_norm']
    norm = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      1.0)
    return out((x.astype(jnp.float32) * scale).astype(x.dtype))


def _reduce(name, fn):
    @register_op('reduce_' + name)
    def _impl(ctx, ins, attrs, _fn=fn):
        x = first(ins, 'X')
        dim = attrs.get('dim', None)
        keep_dim = attrs.get('keep_dim', False)
        if attrs.get('reduce_all', dim is None):
            r = _fn(x, axis=None, keepdims=keep_dim)
        else:
            axes = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
            r = _fn(x, axis=axes, keepdims=keep_dim)
        if r.ndim == 0:
            r = r.reshape((1,))
        return out(r)

    return _impl


_reduce('sum', jnp.sum)
_reduce('mean', jnp.mean)
_reduce('max', jnp.max)
_reduce('min', jnp.min)
_reduce('prod', jnp.prod)


@register_op('softmax')
def _softmax(ctx, ins, attrs):
    x = first(ins, 'X')
    return out(jax.nn.softmax(x.astype(jnp.float32),
                              axis=-1).astype(x.dtype))


@register_op('cos_sim')
def _cos_sim(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.float32)
    y = first(ins, 'Y').astype(jnp.float32)
    if y.shape[0] == 1 and x.shape[0] != 1:
        y = jnp.broadcast_to(y, x.shape)
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    o = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    return {'Out': [o], 'XNorm': [xn], 'YNorm': [yn]}


@register_op('l1_norm')
def _l1_norm(ctx, ins, attrs):
    x = first(ins, 'X')
    return out(jnp.sum(jnp.abs(x.astype(jnp.float32))).reshape((1,)))


@register_op('squared_l2_norm')
def _squared_l2_norm(ctx, ins, attrs):
    x = first(ins, 'X')
    return out(jnp.sum(jnp.square(x.astype(jnp.float32))).reshape((1,)))


@register_op('squared_l2_distance')
def _squared_l2_distance(ctx, ins, attrs):
    x = first(ins, 'X').astype(jnp.float32)
    y = first(ins, 'Y').astype(jnp.float32)
    if y.shape[0] == 1 and x.shape[0] != 1:
        y = jnp.broadcast_to(y, x.shape)
    diff = x - y
    o = jnp.sum(jnp.square(diff).reshape(x.shape[0], -1), axis=1,
                keepdims=True)
    return {'Out': [o], 'sub_result': [diff]}


@register_op('top_k')
def _top_k(ctx, ins, attrs):
    x = first(ins, 'X')
    k = attrs.get('k', 1)
    vals, idxs = jax.lax.top_k(x, k)
    return {'Out': [vals], 'Indices': [idxs.astype(jnp.int32)]}


@register_op('norm')
def _norm(ctx, ins, attrs):
    """L2-normalize along axis (operators/norm_op)."""
    x = first(ins, 'X').astype(jnp.float32)
    axis = attrs.get('axis', 1)
    eps = attrs.get('epsilon', 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {'Out': [(x / norm).astype(first(ins, 'X').dtype)],
            'Norm': [norm]}


@register_op('maxout')
def _maxout(ctx, ins, attrs):
    x = first(ins, 'X')  # NCHW
    groups = attrs['groups']
    n, c, h, w = x.shape
    return out(jnp.max(x.reshape(n, c // groups, groups, h, w), axis=2))


@register_op('bilinear_tensor_product')
def _bilinear_tensor_product(ctx, ins, attrs):
    """Out[n,k] = X[n,:] @ W[k] @ Y[n,:] + b (operators/
    bilinear_tensor_product_op.cc)."""
    x = first(ins, 'X').astype(jnp.float32)
    y = first(ins, 'Y').astype(jnp.float32)
    w = first(ins, 'Weight').astype(jnp.float32)
    o = jnp.einsum('ni,kij,nj->nk', x, w, y)
    b = first(ins, 'Bias')
    if b is not None:
        o = o + b.astype(jnp.float32).reshape(1, -1)
    return out(o.astype(first(ins, 'X').dtype))
