"""Misc ops (O19): print, is_empty, split/merge_lod_tensor, get_places.

Reference parity: operators/print_op.cc, is_empty_op.cc,
split_lod_tensor_op.cc, merge_lod_tensor_op.cc.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out

__all__ = []


@register_op('print')
def _print(ctx, ins, attrs):
    x = first(ins, 'In')
    msg = attrs.get('message') or ''
    jax.debug.print(msg + "{x}", x=x)
    return out(x)


@register_op('is_empty')
def _is_empty(ctx, ins, attrs):
    x = first(ins, 'X')
    return out(jnp.asarray([x.size == 0]))


def _row_mask(mask, x):
    m = jnp.asarray(mask).reshape(-1).astype(bool)
    return m.reshape((x.shape[0],) + (1,) * (x.ndim - 1))


@register_op('split_lod_tensor')
def _split_lod_tensor(ctx, ins, attrs):
    """Dense split: both outputs keep the full batch; rows outside the
    half are zeroed.  merge selects per row, so split∘merge == identity —
    the fluid split/merge pair without gather/scatter (static shapes)."""
    x = first(ins, 'X')
    m = _row_mask(first(ins, 'Mask'), x)
    return {'OutTrue': [jnp.where(m, x, 0)],
            'OutFalse': [jnp.where(m, 0, x)]}


@register_op('merge_lod_tensor')
def _merge_lod_tensor(ctx, ins, attrs):
    x = first(ins, 'X')
    in_true = first(ins, 'InTrue')
    in_false = first(ins, 'InFalse')
    m = _row_mask(first(ins, 'Mask'), in_true)
    return out(jnp.where(m, in_true, in_false))


@register_op('get_places')
def _get_places(ctx, ins, attrs):
    n = int(attrs.get('device_count', 1))
    return out(jnp.arange(n, dtype=jnp.int32))
