"""Convolution ops.

Reference parity: paddle/operators/{conv_op,conv_cudnn_op,conv_transpose_op,
conv_shift_op,row_conv_op}.*.  All lower to lax.conv_general_dilated which
XLA tiles onto the MXU; bf16 inputs accumulate in fp32.  User-facing layout
is NCHW (parity with fluid); pass data_format='NHWC' for the TPU-preferred
layout (the flagship models do).
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out

_ACC = dict(preferred_element_type=jnp.float32)


def _acc(x):
    """fp32 accumulation hint.  Omitted for bf16 operands: jax's conv
    TRANSPOSE rule rejects preferred_element_type != operand dtype, and on
    TPU the MXU accumulates bf16 dots in fp32 internally anyway (rounding
    once at the output tile)."""
    return _ACC if x.dtype == jnp.float32 else {}


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


@register_op('conv2d')
def _conv2d(ctx, ins, attrs):
    x = first(ins, 'Input')
    w = first(ins, 'Filter')  # OIHW
    strides = _pair(attrs.get('strides', [1, 1]))
    paddings = _pair(attrs.get('paddings', [0, 0]))
    dilations = _pair(attrs.get('dilations', [1, 1]))
    groups = attrs.get('groups', 1) or 1
    fmt = attrs.get('data_format', 'NCHW')
    dn = (fmt, 'OIHW', fmt)
    y = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        dimension_numbers=dn,
        feature_group_count=groups,
        **_acc(x))
    return {'Output': [y.astype(x.dtype)]}


@register_op('conv3d')
def _conv3d(ctx, ins, attrs):
    x = first(ins, 'Input')
    w = first(ins, 'Filter')  # OIDHW
    strides = _pair(attrs.get('strides', [1, 1, 1]), 3)
    paddings = _pair(attrs.get('paddings', [0, 0, 0]), 3)
    dilations = _pair(attrs.get('dilations', [1, 1, 1]), 3)
    groups = attrs.get('groups', 1) or 1
    y = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=strides,
        padding=[(p, p) for p in paddings],
        rhs_dilation=dilations,
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'),
        feature_group_count=groups,
        **_acc(x))
    return {'Output': [y.astype(x.dtype)]}


def _conv_transpose(x, w, strides, paddings, dilations, spatial):
    """conv_transpose via input-dilated conv: output = (H-1)*s - 2p + k."""
    # w comes as (in_c, out_c, k...) -> (out_c, in_c, k...) flipped
    perm = (1, 0) + tuple(range(2, 2 + spatial))
    wt = jnp.transpose(w, perm)
    wt = jnp.flip(wt, axis=tuple(range(2, 2 + spatial)))
    k = [wt.shape[2 + i] for i in range(spatial)]
    pad = [((k[i] - 1) * dilations[i] - paddings[i],
            (k[i] - 1) * dilations[i] - paddings[i]) for i in range(spatial)]
    dn = ('NCHW', 'OIHW', 'NCHW') if spatial == 2 else \
         ('NCDHW', 'OIDHW', 'NCDHW')
    y = jax.lax.conv_general_dilated(
        x, wt.astype(x.dtype),
        window_strides=[1] * spatial,
        padding=pad,
        lhs_dilation=strides,
        rhs_dilation=dilations,
        dimension_numbers=dn,
        **_acc(x))
    return y.astype(x.dtype)


@register_op('conv2d_transpose')
def _conv2d_transpose(ctx, ins, attrs):
    x = first(ins, 'Input')
    w = first(ins, 'Filter')
    y = _conv_transpose(x, w, _pair(attrs.get('strides', [1, 1])),
                        _pair(attrs.get('paddings', [0, 0])),
                        _pair(attrs.get('dilations', [1, 1])), 2)
    return {'Output': [y]}


@register_op('conv3d_transpose')
def _conv3d_transpose(ctx, ins, attrs):
    x = first(ins, 'Input')
    w = first(ins, 'Filter')
    y = _conv_transpose(x, w, _pair(attrs.get('strides', [1, 1, 1]), 3),
                        _pair(attrs.get('paddings', [0, 0, 0]), 3),
                        _pair(attrs.get('dilations', [1, 1, 1]), 3), 3)
    return {'Output': [y]}


@register_op('conv_shift')
def _conv_shift(ctx, ins, attrs):
    """Circular 1-D correlation (operators/conv_shift_op.cc): Out[i,j] =
    sum_k X[i, (j+k-M/2) mod N] * Y[i,k]."""
    x = first(ins, 'X')  # [B, N]
    y = first(ins, 'Y')  # [B, M]
    n = x.shape[1]
    m = y.shape[1]
    half = m // 2
    idx = (jnp.arange(n)[:, None] + jnp.arange(m)[None, :] - half) % n
    gathered = x[:, idx]  # [B, N, M]
    return out(jnp.einsum('bnm,bm->bn', gathered, y))


@register_op('row_conv')
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (operators/row_conv_op.cc) on padded
    sequences: Out[b,t] = sum_{k<K} X[b,t+k] * W[k]."""
    x = first(ins, 'X')  # [B, T, D]
    w = first(ins, 'Filter')  # [K, D]
    k = w.shape[0]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    acc = jnp.zeros_like(x)
    for i in range(k):
        acc = acc + xp[:, i:i + x.shape[1], :] * w[i]
    return out(acc)
