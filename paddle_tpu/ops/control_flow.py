"""Control-flow ops (O13): while / conditional_block / recurrent.

Reference parity: paddle/operators/while_op.cc, conditional_block_op.cc,
recurrent_op.cc.  The reference interprets sub-blocks per iteration on the
host; here a sub-block is traced ONCE and lowered to `lax.scan`:

- `while`: a bounded masked scan — runs `max_iters` ticks, each tick
  applies the sub-block and keeps the old carry where the loop condition
  has gone false.  Static shapes, reverse-mode differentiable (unlike
  lax.while_loop), and the mask converges to a no-op XLA select on the
  padded tail.  `max_iters` comes from the While layer (explicit argument
  or inferred from a `less_than(counter, fill_constant)` condition).
- `conditional_block`: both paths are computed and the written vars are
  selected by the scalar condition (the TPU answer to divergent control
  flow; fluid's scope-isolation semantics are preserved by the select).
- `recurrent` (StaticRNN/DynamicRNN): one lax.scan over time with
  memories as carry; per-sequence lengths mask memory updates so padded
  steps carry state through unchanged.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first
from .tensor_array import EmptyTArray, TArray

__all__ = []


def _block_rw(program, block_idx):
    """(read, written) var-name sets of a block, nested blocks included."""
    block = program.blocks[block_idx]
    read, written = set(), set()
    for op in block.ops:
        read.update(op.input_arg_names)
        written.update(op.output_arg_names)
        for attr in ('sub_block', 'block'):
            if attr in op.attrs:
                r2, w2 = _block_rw(program, int(op.attrs[attr]))
                read |= r2
                written |= w2
    return read, written


def _scalar_bool(x):
    return jnp.asarray(x).reshape(()).astype(bool)


def _select(pred, new, old):
    def sel(a, b):
        return jnp.where(pred, a, b)
    return jax.tree_util.tree_map(sel, new, old)


@register_op('while', needs_env=True)
def _while(ctx, ins, attrs):
    sub_idx = int(attrs['sub_block'])
    cond_name = attrs['condition']
    max_iters = attrs.get('max_iters')
    if max_iters is None:
        raise ValueError(
            "while op needs max_iters (pass max_iters= to layers.While, or "
            "use a less_than(counter, fill_constant) condition so the bound "
            "is inferable)")
    max_iters = int(max_iters)

    program = ctx.program
    read, written = _block_rw(program, sub_idx)
    env = ins['__env__'][0]  # executor hands the live env dict
    carry_names = sorted(n for n in written if n in env)
    if cond_name not in carry_names and cond_name in env:
        carry_names.append(cond_name)

    carry0 = {n: env[n] for n in carry_names}
    if any(isinstance(v, EmptyTArray) for v in carry0.values()):
        # arrays first written INSIDE the loop: learn their allocated
        # shape with one speculative trace of the body, then start the
        # scan from zeroed buffers (structure must be loop-invariant)
        env_probe = dict(env)
        ctx.run_block(sub_idx, env_probe)
        for n, v in list(carry0.items()):
            if isinstance(v, EmptyTArray):
                probed = env_probe.get(n)
                if not isinstance(probed, TArray):
                    raise ValueError(
                        "tensor array %r is read in a while loop before "
                        "any write; write once before the loop or pass "
                        "elem_shape to create_array" % n)
                carry0[n] = TArray(jnp.zeros_like(probed.data),
                                   jnp.asarray(0, jnp.int32))

    def body(carry, _):
        active = _scalar_bool(carry[cond_name])
        env2 = dict(env)
        env2.update(carry)
        ctx.run_block(sub_idx, env2)
        new_carry = {n: env2[n] for n in carry_names}
        new_carry = _select(active, new_carry, carry)
        return new_carry, None

    final, _ = jax.lax.scan(body, carry0, None, length=max_iters)
    return {'__env_update__': [final]}


@register_op('conditional_block', needs_env=True)
def _conditional_block(ctx, ins, attrs):
    sub_idx = int(attrs['sub_block'])
    cond = _scalar_bool(first(ins, 'Cond'))
    env = ins['__env__'][0]
    program = ctx.program
    read, written = _block_rw(program, sub_idx)

    env2 = dict(env)
    ctx.run_block(sub_idx, env2)
    update = {}
    for n in written:
        if n in env2:
            if n in env:
                update[n] = _select(cond, env2[n], env[n])
            else:
                # var born inside the block: zero when cond is false
                update[n] = _select(cond, env2[n],
                                    jax.tree_util.tree_map(
                                        jnp.zeros_like, env2[n]))
    return {'__env_update__': [update]}


@register_op('parallel_do', needs_env=True)
def _parallel_do(ctx, ins, attrs):
    """operators/parallel_do_op.cc: batch-split the declared inputs, run
    the sub-block per mesh member via shard_map, concatenate the declared
    outputs along dim 0.  Differentiable: shard_map's transpose inserts
    the cross-member grad psum for replicated reads (params), matching
    the reference's cross-place gradient accumulation.  With no mesh (or
    a 1-device mesh) the body runs inline on the full batch."""
    import numpy as np

    sub_idx = int(attrs['sub_block'])
    split_names = list(attrs['split_inputs'])
    out_names = list(attrs['output_names'])
    env = ins['__env__'][0]

    from ..parallel import api as papi
    mesh = papi.current_mesh()
    n_dev = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    if n_dev == 1:
        env2 = dict(env)
        ctx.run_block(sub_idx, env2)
        update = {n: env2[n] for n in out_names if n in env2}
        return {'__env_update__': [update]}

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    axis = attrs.get('mesh_axis') or mesh.axis_names[0]
    size = mesh.shape[axis]
    read, _written = _block_rw(ctx.program, sub_idx)

    def _is_arr(v):
        return isinstance(v, jnp.ndarray) or hasattr(v, 'dtype')

    split = {}
    for n in split_names:
        v = env[n]
        if v.shape[0] % size:
            raise ValueError(
                "parallel_do input %r batch %d is not divisible by the "
                "%d members of mesh axis %r" % (n, v.shape[0], size, axis))
        split[n] = v
    repl = {n: env[n] for n in sorted(read)
            if n in env and n not in split and _is_arr(env[n])}
    key = ctx.rng()

    block = ctx.program.blocks[sub_idx]

    def run_body(split_d, repl_d, k):
        from ..core.executor import _run_ops
        sub_ctx = ctx.sub_context(block)
        sub_ctx.rng_key = k
        env2 = {}
        env2.update(repl_d)
        env2.update(split_d)
        _run_ops(block.ops, env2, sub_ctx)
        # rank-0 outputs concat like the reference's per-place scalars:
        # lift to (1,) so the axis concat yields [n_places]
        return {n: (env2[n].reshape((1,)) if env2[n].ndim == 0
                    else env2[n]) for n in out_names}

    def run_local(split_d, repl_d, k):
        # distinct randomness per place: fold the member index into the
        # key, else every shard would draw the same dropout masks
        return run_body(split_d, repl_d,
                        jax.random.fold_in(k, jax.lax.axis_index(axis)))

    out_struct = jax.eval_shape(run_body, split, repl, key)
    in_specs = ({n: P(axis, *([None] * (v.ndim - 1)))
                 for n, v in split.items()},
                {n: P() for n in repl}, P())
    out_specs = {n: P(axis, *([None] * (s.ndim - 1)))
                 for n, s in out_struct.items()}
    fn = shard_map(run_local, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    update = fn(split, repl, key)
    return {'__env_update__': [update]}


@register_op('recurrent', needs_env=True)
def _recurrent(ctx, ins, attrs):
    """StaticRNN/DynamicRNN: lax.scan over the time axis.

    attrs: sub_block, step_inputs [(outer_name, inner_name)],
    memories [(inner_mem_name, inner_updated_name)], boot ins 'Boot:<mem>',
    step_outputs [inner_name], lengths var optional ('XLen' slot).
    """
    sub_idx = int(attrs['sub_block'])
    step_inputs = [tuple(p) for p in attrs['step_inputs']]
    memories = [tuple(p) for p in attrs['memories']]
    step_outputs = list(attrs['step_outputs'])
    env = ins['__env__'][0]

    xs = {inner: jnp.moveaxis(env[outer], 1, 0)
          for outer, inner in step_inputs}  # [T, B, ...]
    T = next(iter(xs.values())).shape[0] if xs else int(attrs['seq_len'])

    boots = {mem: ins['Boot_' + mem][0] for mem, _ in memories}
    lengths = first(ins, 'XLen')

    def body(carry, inp):
        t, mems = carry
        env2 = dict(env)
        env2.update({inner: inp[inner] for _, inner in
                     [(o, i) for o, i in step_inputs]})
        env2.update(mems)
        ctx.run_block(sub_idx, env2)
        new_mems = {}
        for mem, upd in memories:
            new = env2[upd]
            if lengths is not None:
                active = (t < lengths.astype(jnp.int32))
                shape = (new.shape[0],) + (1,) * (new.ndim - 1)
                new = jnp.where(active.reshape(shape), new, mems[mem])
            new_mems[mem] = new
        outs_t = []
        for n in step_outputs:
            o = env2[n]
            if lengths is not None:
                active = (t < lengths.astype(jnp.int32))
                shape = (o.shape[0],) + (1,) * (o.ndim - 1)
                o = jnp.where(active.reshape(shape), o, jnp.zeros_like(o))
            outs_t.append(o)
        return (t + 1, new_mems), tuple(outs_t)

    init = (jnp.asarray(0, jnp.int32), boots)
    xs_stacked = {inner: xs[inner] for _, inner in step_inputs}
    (_, final_mems), outs = jax.lax.scan(
        body, init, xs_stacked if xs_stacked else None,
        length=None if xs_stacked else T)

    result = {'Out_' + n: [jnp.moveaxis(o, 0, 1)]
              for n, o in zip(step_outputs, outs)}  # [B, T, ...]
    for mem, _ in memories:
        result['FinalMem_' + mem] = [final_mems[mem]]
    return result
