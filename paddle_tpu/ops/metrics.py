"""Metric ops.

Reference parity: paddle/operators/{accuracy,auc,precision_recall,
edit_distance,positive_negative_pair}_op.*.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first


@register_op('accuracy')
def _accuracy(ctx, ins, attrs):
    """Top-k indices in 'Out' (from a top_k op) vs int labels."""
    idx = first(ins, 'Indices').astype(jnp.int32)
    label = first(ins, 'Label').astype(jnp.int32)
    if label.ndim == 2 and label.shape[1] == 1:
        label = label[:, 0]
    hit = jnp.any(idx == label[:, None], axis=1)
    total = jnp.asarray(idx.shape[0], jnp.int32)
    correct = jnp.sum(hit).astype(jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {'Accuracy': [acc.reshape((1,))], 'Correct': [correct.reshape((1,))],
            'Total': [total.reshape((1,))]}


@register_op('auc')
def _auc(ctx, ins, attrs):
    """Streaming-free AUC over the batch via thresholded confusion counts
    (reference uses 200 thresholds in auc_op.h)."""
    probs = first(ins, 'Out').astype(jnp.float32)
    label = first(ins, 'Label').astype(jnp.int32).reshape(-1)
    if probs.ndim == 2 and probs.shape[1] == 2:
        score = probs[:, 1]
    else:
        score = probs.reshape(-1)
    num_t = int(attrs.get('num_thresholds', 200))
    thresholds = (jnp.arange(num_t, dtype=jnp.float32) + 0.5) / num_t
    pos = (label == 1)
    above = score[None, :] >= thresholds[:, None]
    tp = jnp.sum(above & pos[None, :], axis=1).astype(jnp.float32)
    fp = jnp.sum(above & ~pos[None, :], axis=1).astype(jnp.float32)
    npos = jnp.maximum(jnp.sum(pos).astype(jnp.float32), 1e-6)
    nneg = jnp.maximum(jnp.sum(~pos).astype(jnp.float32), 1e-6)
    tpr = tp / npos
    fpr = fp / nneg
    # trapezoid over decreasing threshold order
    auc = -jnp.trapezoid(tpr, fpr)
    return {'AUC': [jnp.abs(auc).reshape((1,))]}


@register_op('precision_recall')
def _precision_recall(ctx, ins, attrs):
    """Per-class macro/micro precision, recall, F1 for the batch."""
    num_classes = attrs['class_number']
    idx = first(ins, 'MaxProbs')
    pred = first(ins, 'Indices').astype(jnp.int32).reshape(-1)
    label = first(ins, 'Labels').astype(jnp.int32).reshape(-1)
    cls = jnp.arange(num_classes)
    pred_is = pred[None, :] == cls[:, None]
    lab_is = label[None, :] == cls[:, None]
    tp = jnp.sum(pred_is & lab_is, axis=1).astype(jnp.float32)
    fp = jnp.sum(pred_is & ~lab_is, axis=1).astype(jnp.float32)
    fn = jnp.sum(~pred_is & lab_is, axis=1).astype(jnp.float32)
    prec = tp / jnp.maximum(tp + fp, 1e-6)
    rec = tp / jnp.maximum(tp + fn, 1e-6)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-6)
    macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
    stp, sfp, sfn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    mprec = stp / jnp.maximum(stp + sfp, 1e-6)
    mrec = stp / jnp.maximum(stp + sfn, 1e-6)
    mf1 = 2 * mprec * mrec / jnp.maximum(mprec + mrec, 1e-6)
    micro = jnp.stack([mprec, mrec, mf1])
    metrics = jnp.concatenate([macro, micro]).reshape(1, 6)
    states = jnp.stack([tp, fp, fn, tp * 0], axis=1)
    return {'BatchMetrics': [metrics], 'AccumMetrics': [metrics],
            'AccumStatesInfo': [states]}


@register_op('edit_distance')
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance between padded hypothesis/reference token rows
    (operators/edit_distance_op) via dynamic-programming lax.scan."""
    hyp = first(ins, 'Hyps').astype(jnp.int32)
    ref = first(ins, 'Refs').astype(jnp.int32)
    hyp_len = first(ins, 'HypsLen')
    ref_len = first(ins, 'RefsLen')
    if hyp.ndim == 1:
        hyp = hyp[None, :]
        ref = ref[None, :]
    b, m = hyp.shape
    _, n = ref.shape
    if hyp_len is None:
        hyp_len = jnp.full((b,), m, jnp.int32)
    if ref_len is None:
        ref_len = jnp.full((b,), n, jnp.int32)
    hyp_len = hyp_len.reshape(-1).astype(jnp.int32)
    ref_len = ref_len.reshape(-1).astype(jnp.int32)

    def per_seq(h, r, hl, rl):
        row0 = jnp.arange(n + 1, dtype=jnp.float32)
        row0 = jnp.where(jnp.arange(n + 1) <= rl, row0, jnp.inf)

        def step(row, i):
            cost_sub = (r != h[i]).astype(jnp.float32)
            valid = (i < hl)

            def inner(prev_row):
                new = jnp.full((n + 1,), jnp.inf)
                new = new.at[0].set(i + 1.0)

                def body(j, nr):
                    d = jnp.minimum(
                        jnp.minimum(nr[j - 1] + 1, prev_row[j] + 1),
                        prev_row[j - 1] + cost_sub[j - 1])
                    return nr.at[j].set(d)

                return jax.lax.fori_loop(1, n + 1, body, new)

            row = jnp.where(valid, inner(row), row)
            return row, None

        rowf, _ = jax.lax.scan(step, row0, jnp.arange(m))
        return rowf[rl]

    d = jax.vmap(per_seq)(hyp, ref, hyp_len, ref_len)
    if attrs.get('normalized', True):
        d = d / jnp.maximum(ref_len.astype(jnp.float32), 1.0)
    return {'Out': [d.reshape(b, 1)],
            'SequenceNum': [jnp.asarray([b], jnp.int32)]}


@register_op('positive_negative_pair')
def _pos_neg_pair(ctx, ins, attrs):
    score = first(ins, 'Score').astype(jnp.float32).reshape(-1)
    label = first(ins, 'Label').astype(jnp.float32).reshape(-1)
    qid = first(ins, 'QueryID').astype(jnp.int32).reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    li = label[:, None]
    lj = label[None, :]
    si = score[:, None]
    sj = score[None, :]
    mask = same_q & (li > lj)
    pos = jnp.sum(mask & (si > sj))
    neg = jnp.sum(mask & (si < sj))
    neu = jnp.sum(mask & (si == sj))
    pos = pos.astype(jnp.float32) + 0.5 * neu
    neg = neg.astype(jnp.float32) + 0.5 * neu
    ratio = pos / jnp.maximum(neg, 1e-6)
    return {'PositivePair': [pos.reshape((1,))],
            'NegativePair': [neg.reshape((1,))],
            'NeutralPair': [neu.astype(jnp.float32).reshape((1,))],
            'PositiveRatio': [ratio.reshape((1,))]}


def _chunk_flags(tags, num_chunk_types, scheme, valid):
    """Per-position (in_chunk, type, start, end) for a [B, T] tag batch
    under the conll chunking schemes the reference supports
    (operators/chunk_eval_op.h): plain, IOB, IOE, IOBES."""
    t = tags.shape[1]
    if scheme == 'plain':
        n_tag = 1
        kind = jnp.zeros_like(tags)
        ctype = tags
        outside = tags >= num_chunk_types
    else:
        n_tag = {'IOB': 2, 'IOE': 2, 'IOBES': 4}[scheme]
        kind = tags % n_tag
        ctype = tags // n_tag
        outside = tags >= num_chunk_types * n_tag
    in_chunk = (~outside) & valid
    ctype = jnp.where(in_chunk, ctype, -1)

    prev_in = jnp.pad(in_chunk, ((0, 0), (1, 0)))[:, :t]
    prev_type = jnp.pad(ctype, ((0, 0), (1, 0)),
                        constant_values=-1)[:, :t]
    next_in = jnp.pad(in_chunk, ((0, 0), (0, 1)))[:, 1:]
    next_type = jnp.pad(ctype, ((0, 0), (0, 1)),
                        constant_values=-1)[:, 1:]
    boundary_prev = (~prev_in) | (prev_type != ctype)
    boundary_next = (~next_in) | (next_type != ctype)

    if scheme == 'plain':
        start = in_chunk & boundary_prev
        end = in_chunk & boundary_next
    elif scheme == 'IOB':  # kinds: B=0, I=1
        start = in_chunk & ((kind == 0) | boundary_prev)
        nxt_starts = next_in & ((jnp.pad(kind, ((0, 0), (0, 1)))[:, 1:]
                                 == 0))
        end = in_chunk & (boundary_next | nxt_starts)
    elif scheme == 'IOE':  # kinds: I=0, E=1
        prev_ended = prev_in & (jnp.pad(kind, ((0, 0), (1, 0)))[:, :t] == 1)
        start = in_chunk & (boundary_prev | prev_ended)
        end = in_chunk & ((kind == 1) | boundary_next)
    else:  # IOBES: B=0, I=1, E=2, S=3
        start = in_chunk & ((kind == 0) | (kind == 3) | boundary_prev)
        end = in_chunk & ((kind == 2) | (kind == 3) | boundary_next)
    return in_chunk, ctype, start, end


@register_op('chunk_eval')
def _chunk_eval(ctx, ins, attrs):
    """Chunk-level precision/recall/F1 (operators/chunk_eval_op).  A chunk
    is correct iff its [start, end] span and type agree exactly between
    inference and label."""
    inference = first(ins, 'Inference').astype(jnp.int32)
    label = first(ins, 'Label').astype(jnp.int32)
    if inference.ndim == 3:
        inference = inference[..., 0]
    if label.ndim == 3:
        label = label[..., 0]
    lengths = first(ins, 'XLen')
    b, t = label.shape
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)
    lengths = lengths.astype(jnp.int32).reshape(-1)
    valid = jnp.arange(t)[None, :] < lengths[:, None]
    scheme = attrs.get('chunk_scheme', 'IOB')
    num_types = attrs['num_chunk_types']
    excluded = jnp.asarray(attrs.get('excluded_chunk_types') or [-99],
                           jnp.int32)

    i_in, i_ty, i_st, i_en = _chunk_flags(inference, num_types, scheme,
                                          valid)
    l_in, l_ty, l_st, l_en = _chunk_flags(label, num_types, scheme, valid)

    def count(in_c, ty, st):
        ok = st & ~jnp.isin(ty, excluded)
        return jnp.sum(ok)

    num_infer = count(i_in, i_ty, i_st)
    num_label = count(l_in, l_ty, l_st)

    # a chunk matches when both sides agree on (in_chunk, type) at every
    # position of the span and share the same start/end flags.
    agree = (i_in == l_in) & (i_ty == l_ty)
    both_start = i_st & l_st & agree & ~jnp.isin(l_ty, excluded)
    both_end = i_en & l_en & agree
    # mismatch prefix-sums let us check "agree over the whole span"
    mismatch = (~agree).astype(jnp.int32)
    mis_cum = jnp.cumsum(mismatch, axis=1)

    def row_correct(bs, be, mc):
        # for each start s (both_start), find its end: the first position
        # e >= s with both_end; correct iff no mismatch within [s, e].
        t_idx = jnp.arange(t)
        # end position for the label chunk starting at s: next l_en >= s
        def first_end_from(s):
            cand = jnp.where((t_idx >= s) & be, t_idx, t)
            return jnp.min(cand)

        ends = jax.vmap(first_end_from)(t_idx)
        span_clean = jnp.where(
            ends < t,
            (mc[jnp.minimum(ends, t - 1)] -
             jnp.where(t_idx > 0, mc[jnp.maximum(t_idx - 1, 0)], 0)) == 0,
            False)
        return jnp.sum(bs & span_clean)

    num_correct = jnp.sum(jax.vmap(row_correct)(both_start, both_end,
                                                mis_cum))
    num_infer_f = num_infer.astype(jnp.float32)
    num_label_f = num_label.astype(jnp.float32)
    num_correct_f = num_correct.astype(jnp.float32)
    precision = num_correct_f / jnp.maximum(num_infer_f, 1e-6)
    recall = num_correct_f / jnp.maximum(num_label_f, 1e-6)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-6)
    return {
        'Precision': [precision.reshape((1,))],
        'Recall': [recall.reshape((1,))],
        'F1-Score': [f1.reshape((1,))],
        'NumInferChunks': [num_infer.astype(jnp.int32).reshape((1,))],
        'NumLabelChunks': [num_label.astype(jnp.int32).reshape((1,))],
        'NumCorrectChunks': [num_correct.astype(jnp.int32).reshape((1,))],
    }
