"""Row-sparse table update as a Pallas TPU kernel family.

Reference parity: the sparse branches of paddle/operators/{sgd,adagrad,
adam}_op — whose whole point is touching only the gradient's rows of a
vocab-height table.  The XLA:TPU lowering of the scatter-adds those
branches compile to defeats that: every `table.at[rows].add(upd)` runs a
full pass over the table operand (~1 ns/table-row + ~28 ns/touched-row
per scattered table — PERF.md "CTR at Criteo scale"), so the optimizer
apply at 26 slots x 1M rows moves ~0.9 GB of table per step while the
gradients are row-sparse end-to-end.

These kernels make the apply O(touched rows x row width), independent of
table height: the grid walks the touched rows; each program's BlockSpec
index map (computed from the scalar-prefetched row ids) DMAs exactly one
[1, D] row of each state table out of HBM, applies the optimizer rule on
the VPU, and stores the row back through `input_output_aliases` — the
table is donated, never copied, and untouched rows are never read.

Three fused rules ship, matching the sparse branches in ops/optim_ops.py
expression-for-expression (bitwise parity is tested, not hoped for):

  sparse_apply_sgd      param                      (linear; duplicates
                                                    accumulate in slot
                                                    order, like scatter)
  sparse_apply_adagrad  param + moment, ONE pass   (halves the 2-scatter
                                                    cost of today's path)
  sparse_apply_adam     param + moment1 + moment2  (lazy adam: moments
                                                    decay only on
                                                    touched rows)

Row-id contract (the whole family): ids are sorted ascending before the
kernel sees them.  Sorting makes duplicate rows CONSECUTIVE, which is
what lets a revisited row ride Mosaic's resident-block rule — when the
index map output doesn't change between grid steps, the block stays in
VMEM with no refetch and no intermediate store, so sequential
accumulation into the out block is race-free.  Ids follow the oracle's
index semantics exactly: negatives in [-height, 0) wrap Python-style
(like XLA scatter/gather), and anything else outside [0, height) is a
sentinel — it sorts to the tail (clamped into range for the index map
only), the kernel skips its update, and the XLA oracle drops it too
(out-of-bounds scatter updates are dropped) — so ragged touched-row
counts can be padded to a bucket-friendly length with `height` and stay
bitwise-exact.  merge_rows_sentinel (core/selected_rows.py) produces
exactly this layout.

On non-TPU backends the kernels run with interpret=True — CPU CI
executes the same code path (how the tier-1 parity tests work).  The
mode switch lives in `sparse_apply_mode()`:
PADDLE_TPU_SPARSE_APPLY=pallas|xla forces a path, default is pallas on
TPU and xla elsewhere; ops/optim_ops.py routes on it per trace.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core.selected_rows import merge_rows_sentinel
from ._compat import CompilerParams as _CompilerParams

__all__ = ['sparse_apply_sgd', 'sparse_apply_adagrad', 'sparse_apply_adam',
           'sparse_apply_mode']


def sparse_apply_mode():
    """Resolved sparse-apply path: 'pallas' or 'xla'.

    PADDLE_TPU_SPARSE_APPLY=pallas|xla pins it; the default ('auto')
    picks pallas on a TPU backend and xla elsewhere.  Read at trace
    time and part of the executor's plan cache key, so a flip retraces
    instead of silently serving the old path."""
    from ...flags import FLAGS
    mode = FLAGS.sparse_apply
    if mode in ('pallas', 'xla'):
        return mode
    return 'pallas' if jax.default_backend() == 'tpu' else 'xla'


def _rowwise_kernel(rows_ref, *refs, nt, nv, ns, height, accumulate,
                    rule):
    """One grid step = one touched row.  refs layout: nt table blocks,
    nv value blocks, ns scalar blocks, then nt aliased out blocks.

    Block identity is the CLAMPED row (the index map clamps sentinels
    into range), so `fresh` — "this grid step targets a different table
    row than the previous one" — must compare clamped ids: a sentinel
    step immediately after a real update of row height-1 shares its
    block and must not be treated as a first visit."""
    i = pl.program_id(0)
    row = rows_ref[i]
    h1 = height - 1
    bi = jnp.minimum(row, h1)
    prev_bi = jnp.minimum(rows_ref[jnp.maximum(i - 1, 0)], h1)
    fresh = jnp.logical_or(i == 0, bi != prev_bi)
    valid = jnp.logical_and(row >= 0, row < height)
    tabs = refs[:nt]
    vals = refs[nt:nt + nv]
    scalars = tuple(r[0, 0] for r in refs[nt + nv:nt + nv + ns])
    outs = refs[nt + nv + ns:]

    @pl.when(jnp.logical_and(valid, fresh))
    def _update():
        for o, new in zip(outs, rule(tuple(t[...] for t in tabs),
                                     tuple(v[...] for v in vals),
                                     scalars)):
            o[...] = new

    if accumulate:
        # duplicate of the previous row: the block is resident (no
        # refetch, no store happened in between) — accumulate into the
        # out block, reproducing scatter-add's per-row slot order
        @pl.when(jnp.logical_and(valid, jnp.logical_not(fresh)))
        def _accum():
            for o, new in zip(outs, rule(tuple(o[...] for o in outs),
                                         tuple(v[...] for v in vals),
                                         scalars)):
                o[...] = new

    # first visit of a clamped sentinel block with no real update for
    # that row: write the fetched content back unchanged — every block a
    # grid step maps is stored, so leaving it unwritten would store
    # garbage over the row
    @pl.when(jnp.logical_and(jnp.logical_not(valid), fresh))
    def _copy_back():
        for o, t in zip(outs, tabs):
            o[...] = t[...]


def _rowwise_call(rows, tables, vals, scalars, rule, accumulate,
                  interpret):
    """Launch the row-walking grid: rows [K] int32 (sorted, sentinels at
    the tail), tables/vals lists of [H, D] / [K, D] f32, scalars a list
    of () f32.  Returns the updated tables (input_output_aliased, so
    under donation the update is in place)."""
    height, width = tables[0].shape
    k = int(rows.shape[0])
    nt, nv, ns = len(tables), len(vals), len(scalars)
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'

    def _tab_map(i, rows_ref):
        return (jnp.minimum(rows_ref[i], height - 1), 0)

    row_spec = pl.BlockSpec((1, width), _tab_map)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k,),
        in_specs=(
            [row_spec] * nt +
            [pl.BlockSpec((1, width), lambda i, r: (i, 0))] * nv +
            [pl.BlockSpec((1, 1), lambda i, r: (0, 0))] * ns),
        out_specs=[row_spec] * nt,
    )
    kernel = functools.partial(
        _rowwise_kernel, nt=nt, nv=nv, ns=ns, height=height,
        accumulate=accumulate, rule=rule)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(t.shape, t.dtype) for t in tables],
        # operand i (0 = the scalar-prefetched rows) aliases out t: the
        # tables are updated in place under donation
        input_output_aliases={1 + t: t for t in range(nt)},
        # the grid is sequential by construction (resident-block
        # accumulation and sentinel skips depend on visit order)
        compiler_params=_CompilerParams(
            dimension_semantics=('arbitrary',)),
        interpret=interpret,
    )(rows, *tables, *vals, *scalars)
    return tuple(outs) if nt > 1 else outs[0]


def _prep(rows, values, height):
    """int32 [K] ids + f32 values, with ids normalized to the oracle's
    index semantics: XLA scatter/gather wraps Python-style negatives
    (verified: `p.at[[-1]].add(u)` updates the last row; ids below
    -height are dropped), so ids in [-height, 0) wrap by +height and
    anything still outside [0, height) becomes the skip-sentinel
    `height` — which the oracle drops too."""
    rows = rows.astype(jnp.int32).reshape(-1)
    rows = jnp.where(rows < 0, rows + height, rows)
    rows = jnp.where((rows < 0) | (rows >= height), height, rows)
    return rows, values.astype(jnp.float32)


def sparse_apply_sgd(param, rows, values, lr, interpret=None):
    """param[rows] -= lr * values, O(touched rows).

    Bitwise-matches `param.at[rows].add(-lr * values)`: the update
    vector is computed identically outside the kernel, rows are stably
    sorted so duplicates stay in slot order, and duplicate visits
    accumulate sequentially in the resident block — the same per-row
    association XLA's scatter-add applies.  Ids wrap/drop exactly like
    the oracle's (see _prep); the canonical sentinel sorts to the
    tail."""
    height = param.shape[0]
    rows, values = _prep(rows, values, height)
    if rows.shape[0] == 0:
        return param
    u = -lr * values  # outside the kernel: bitwise-identical to the
    #                   XLA path's update vector
    order = jnp.argsort(rows, stable=True)

    def rule(tabs, vals, _scalars):
        (p,), (u_blk,) = tabs, vals
        return (p + u_blk,)

    return _rowwise_call(rows[order], [param], [u[order]], [], rule,
                         accumulate=True, interpret=interpret)


def sparse_apply_adagrad(param, moment, rows, values, lr, epsilon,
                         interpret=None):
    """Fused sparse Adagrad: moment accumulate + param step on the
    touched rows in ONE kernel pass (today's XLA path pays two full
    table scatters).  Duplicates are pre-merged (merge_rows_sentinel),
    so the nonlinear rule sees each row once; expressions mirror
    ops/optim_ops.py's sparse branch term for term.  Returns
    (param_new, moment_new)."""
    height = param.shape[0]
    rows, values = _prep(rows, values, height)
    if rows.shape[0] == 0:
        return param, moment
    mrows, g, _valid = merge_rows_sentinel(rows, values, height)
    # the XLA branch rounds "moment + g^2" TWICE, differently: the step's
    # mom_row rides a gather+add that XLA:CPU contracts to fma(g, g,
    # mom), while the moment OUTPUT scatter-adds a separately-rounded
    # g^2.  Bitwise parity means reproducing both: square(g) computed
    # in-kernel contracts the same way for the step; the pre-rounded
    # `sq` operand gives the moment output its plain add.
    sq = jnp.square(g)
    neg_lr = jnp.reshape(-lr, (1, 1)).astype(jnp.float32)

    def rule(tabs, vals, scalars):
        (p, mom), (g_blk, sq_blk), (nlr,) = tabs, vals, scalars
        mom_row = mom + jnp.square(g_blk)
        p_new = p + nlr * g_blk / (jnp.sqrt(mom_row) + epsilon)
        return (p_new, mom + sq_blk)

    return _rowwise_call(mrows, [param, moment], [g, sq], [neg_lr], rule,
                         accumulate=False, interpret=interpret)


def sparse_apply_adam(param, moment1, moment2, rows, values, lr_t,
                      beta1, beta2, epsilon, interpret=None):
    """Fused lazy sparse Adam: param + both moments in ONE kernel pass.
    `lr_t` is the bias-corrected rate (lr * sqrt(1-b2^t)/(1-b1^t)) the
    caller computed from the pow accumulators — it rides into the
    kernel as a (1, 1) SMEM-class scalar operand.  Moments decay and
    the param moves only on touched rows; sentinel slots are skipped,
    so padding never decays anything.  Returns (p, m1, m2)."""
    height = param.shape[0]
    rows, values = _prep(rows, values, height)
    if rows.shape[0] == 0:
        return param, moment1, moment2
    mrows, g, _valid = merge_rows_sentinel(rows, values, height)
    neg_lrt = jnp.reshape(-lr_t, (1, 1)).astype(jnp.float32)

    def rule(tabs, vals, scalars):
        (p, m, v), (g_blk,), (nlrt,) = tabs, vals, scalars
        # expression-for-expression the XLA branch's jaxpr, so XLA makes
        # the SAME fma-contraction choices in both lowerings (see the
        # adagrad note: pre-rounding a factor outside the kernel can
        # change the rounding the contraction would have produced)
        m_row = beta1 * m + (1 - beta1) * g_blk
        v_row = beta2 * v + (1 - beta2) * jnp.square(g_blk)
        # m + (m_row - m), not m_row: the oracle scatter-ADDS the delta,
        # and bitwise parity means reproducing its rounding
        m_new = m + (m_row - m)
        v_new = v + (v_row - v)
        step = nlrt * m_row / (jnp.sqrt(v_row) + epsilon)
        return (p + step, m_new, v_new)

    return _rowwise_call(mrows, [param, moment1, moment2], [g],
                         [neg_lrt], rule, accumulate=False,
                         interpret=interpret)
