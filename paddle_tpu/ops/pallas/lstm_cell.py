"""Fused LSTM and GRU time-loops as Pallas TPU kernels.

Reference parity: paddle/operators/lstm_op.cc runs per-timestep GEMMs +
separate elementwise gate kernels.  XLA's lax.scan version (ops/rnn.py)
already fuses decently; this kernel goes further — the recurrent h@W
matmul and ALL gate nonlinearities of a step execute in one grid
iteration with the (h, c) carry living in VMEM scratch, so the time loop
never round-trips the carry through HBM (TPU grid iterations run
sequentially, which is exactly a scan).

Forward: pallas kernel, grid=(T,), time-major [T, B, 4H] gate inputs.
Backward: custom_vjp recomputes with the numerically-identical lax.scan
(ops/rnn.py math) and differentiates that — exact grads, no hand-written
backward-through-time kernel to maintain.

Masking/length handling stays in ops/rnn.py (the caller); this kernel
computes the full-length unrolled recurrence.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ['lstm_scan', 'gru_scan']


def _lstm_kernel(x_ref, w_ref, o_h_ref, o_c_ref, h_scr, c_scr, *, hidden):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr[...])
        c_scr[...] = jnp.zeros_like(c_scr[...])

    g = x_ref[0].astype(jnp.float32)  # [B, 4H] pre-projected gates
    w = w_ref[...].astype(jnp.float32)  # [H, 4H]
    h_p = h_scr[...]
    c_p = c_scr[...]
    g = g + jax.lax.dot_general(h_p, w, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(g[:, :hidden])
    f = jax.nn.sigmoid(g[:, hidden:2 * hidden])
    cand = jnp.tanh(g[:, 2 * hidden:3 * hidden])
    o = jax.nn.sigmoid(g[:, 3 * hidden:])
    c = f * c_p + i * cand
    h = o * jnp.tanh(c)
    h_scr[...] = h
    c_scr[...] = c
    o_h_ref[0] = h.astype(o_h_ref.dtype)
    o_c_ref[0] = c.astype(o_c_ref.dtype)


def _scan_reference(x_tm, w):
    """The identical recurrence as a lax.scan (the backward path)."""
    hdim = w.shape[0]

    def step(carry, g_t):
        h_p, c_p = carry
        g = g_t.astype(jnp.float32) + jnp.matmul(
            h_p, w.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        i = jax.nn.sigmoid(g[:, :hdim])
        f = jax.nn.sigmoid(g[:, hdim:2 * hdim])
        cand = jnp.tanh(g[:, 2 * hdim:3 * hdim])
        o = jax.nn.sigmoid(g[:, 3 * hdim:])
        c = f * c_p + i * cand
        h = o * jnp.tanh(c)
        return (h, c), (h, c)

    b = x_tm.shape[1]
    init = (jnp.zeros((b, hdim), jnp.float32),
            jnp.zeros((b, hdim), jnp.float32))
    _, (hs, cs) = jax.lax.scan(step, init, x_tm)
    return hs.astype(x_tm.dtype), cs.astype(x_tm.dtype)


@jax.custom_vjp
def lstm_scan(x_tm, w):
    """Fused LSTM over time-major gates x_tm [T, B, 4H], recurrent weight
    w [H, 4H]; zero initial state.  Returns (hs, cs) [T, B, H] each."""
    t, b, four_h = x_tm.shape
    hidden = four_h // 4
    interpret = jax.default_backend() != 'tpu'
    kernel = functools.partial(_lstm_kernel, hidden=hidden)
    hs, cs = pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, four_h), lambda i: (i, 0, 0)),
            pl.BlockSpec((hidden, four_h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, b, hidden), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, hidden), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, hidden), x_tm.dtype),
            jax.ShapeDtypeStruct((t, b, hidden), x_tm.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, hidden), jnp.float32),
            pltpu.VMEM((b, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(x_tm, w)
    return hs, cs


def _fwd(x_tm, w):
    return lstm_scan(x_tm, w), (x_tm, w)


def _bwd(res, cts):
    # exact grads by differentiating the identical scan formulation
    x_tm, w = res
    _, vjp = jax.vjp(_scan_reference, x_tm, w)
    return vjp(cts)


lstm_scan.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------- GRU
def _gru_kernel(x_ref, w_ref, o_ref, h_scr, *, hidden):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr[...])

    g = x_ref[0].astype(jnp.float32)  # [B, 3H] pre-projected gates
    w = w_ref[...].astype(jnp.float32)  # [H, 3H]
    h_p = h_scr[...]
    rz = g[:, :2 * hidden] + jax.lax.dot_general(
        h_p, w[:, :2 * hidden], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    u = jax.nn.sigmoid(rz[:, :hidden])      # update gate
    r = jax.nn.sigmoid(rz[:, hidden:])      # reset gate
    c = jnp.tanh(g[:, 2 * hidden:] + jax.lax.dot_general(
        r * h_p, w[:, 2 * hidden:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32))
    h = u * h_p + (1.0 - u) * c
    h_scr[...] = h
    o_ref[0] = h.astype(o_ref.dtype)


def _gru_scan_reference(x_tm, w):
    """Identical recurrence as lax.scan (ops/rnn.py gru math)."""
    hdim = w.shape[0]
    w_rz = w[:, :2 * hdim].astype(jnp.float32)
    w_c = w[:, 2 * hdim:].astype(jnp.float32)

    def step(h_p, g_t):
        g = g_t.astype(jnp.float32)
        rz = g[:, :2 * hdim] + jnp.matmul(
            h_p, w_rz, preferred_element_type=jnp.float32)
        u = jax.nn.sigmoid(rz[:, :hdim])
        r = jax.nn.sigmoid(rz[:, hdim:])
        c = jnp.tanh(g[:, 2 * hdim:] + jnp.matmul(
            r * h_p, w_c, preferred_element_type=jnp.float32))
        h = u * h_p + (1.0 - u) * c
        return h, h

    b = x_tm.shape[1]
    _, hs = jax.lax.scan(step, jnp.zeros((b, hdim), jnp.float32), x_tm)
    return hs.astype(x_tm.dtype)


@jax.custom_vjp
def gru_scan(x_tm, w):
    """Fused GRU over time-major gates x_tm [T, B, 3H], recurrent weight
    w [H, 3H] ([:, :2H] update/reset, [:, 2H:] candidate); zero initial
    state.  Returns hs [T, B, H]."""
    t, b, three_h = x_tm.shape
    hidden = three_h // 3
    interpret = jax.default_backend() != 'tpu'
    kernel = functools.partial(_gru_kernel, hidden=hidden)
    return pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, three_h), lambda i: (i, 0, 0)),
            pl.BlockSpec((hidden, three_h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, b, hidden), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((t, b, hidden), x_tm.dtype),
        scratch_shapes=[pltpu.VMEM((b, hidden), jnp.float32)],
        interpret=interpret,
    )(x_tm, w)


def _gru_fwd(x_tm, w):
    return gru_scan(x_tm, w), (x_tm, w)


def _gru_bwd(res, ct):
    x_tm, w = res
    _, vjp = jax.vjp(_gru_scan_reference, x_tm, w)
    return vjp(ct)


gru_scan.defvjp(_gru_fwd, _gru_bwd)
