"""Fused LSTM and GRU time-loops as Pallas TPU kernels.

Reference parity: paddle/operators/lstm_op.cc runs per-timestep GEMMs +
separate elementwise gate kernels.  XLA's lax.scan version (ops/rnn.py)
already fuses decently; this kernel goes further — the recurrent h@W
matmul and ALL gate nonlinearities of a step execute in one grid
iteration with the (h, c) carry living in VMEM scratch, so the time loop
never round-trips the carry through HBM (TPU grid iterations run
sequentially, which is exactly a scan).

Forward: pallas kernel, grid=(T,), time-major [T, B, 4H] gate inputs;
post-activation gates are emitted as an extra f32 output.  Backward:
hand-written reverse-time BPTT kernels — grid step idx processes
t = T-1-idx with the (dh, dc) chain and the dW/dpw accumulators living
in VMEM, replaying the saved gates instead of recomputing the forward
(`_scan_reference` remains as the CI cross-check oracle).

Masking/length handling stays in ops/rnn.py (the caller): lengths are
prefixes, so the kernel runs the full-length unrolled recurrence and the
caller zero-masks padded positions (fwd and bwd both exact — see the
ops/rnn.py pallas branch comment).
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ['lstm_scan', 'gru_scan', 'pick_batch_tile']


def pick_batch_tile(b, hidden, gate_width, budget):
    """Largest batch tile bt (a divisor of b, sublane-aligned when
    possible) whose BPTT working set — resident weight + f32 dW
    accumulator + ~8 per-step [bt, gate_width] tiles — fits `budget`
    bytes of VMEM.  Returns None when even the smallest tile doesn't
    fit.  Tiling the batch is what lets large-batch training keep the
    fused kernel instead of falling back to lax.scan."""
    resident = 2 * hidden * gate_width * 4

    def fits(bt):
        return resident + 8 * bt * gate_width * 4 <= budget

    divs = [d for d in range(b, 0, -1) if b % d == 0]
    # prefer sublane-aligned tiles, but only over unaligned ones when an
    # aligned candidate actually fits
    for bt in divs:
        if (bt % 8 == 0 or bt == b) and fits(bt):
            return bt
    for bt in divs:
        if fits(bt):
            return bt
    return None


def _lstm_kernel(x_ref, w_ref, pw_ref, o_h_ref, o_c_ref, *o_g_and_scr,
                 hidden, with_gates):
    o_g_ref = o_g_and_scr[0] if with_gates else None
    h_scr, c_scr = o_g_and_scr[-2:]
    t = pl.program_id(1)  # grid = (batch_tiles, time); time innermost

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr[...])
        c_scr[...] = jnp.zeros_like(c_scr[...])

    g = x_ref[0].astype(jnp.float32)  # [B, 4H] pre-projected gates
    w = w_ref[...].astype(jnp.float32)  # [H, 4H]
    pw = pw_ref[...].astype(jnp.float32)  # [3, H] peepholes (or zeros)
    h_p = h_scr[...]
    c_p = c_scr[...]
    g = g + jax.lax.dot_general(h_p, w, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(g[:, :hidden] + c_p * pw[0:1, :])
    f = jax.nn.sigmoid(g[:, hidden:2 * hidden] + c_p * pw[1:2, :])
    cand = jnp.tanh(g[:, 2 * hidden:3 * hidden])
    c = f * c_p + i * cand
    o = jax.nn.sigmoid(g[:, 3 * hidden:] + c * pw[2:3, :])
    h = o * jnp.tanh(c)
    h_scr[...] = h
    c_scr[...] = c
    o_h_ref[0] = h.astype(o_h_ref.dtype)
    o_c_ref[0] = c.astype(o_c_ref.dtype)
    if with_gates:
        # post-activation gates saved f32 for the BPTT kernel's replay
        o_g_ref[0] = jnp.concatenate([i, f, cand, o], axis=1)


def _lstm_bwd_kernel(gates_ref, c_ref, cprev_ref, hprev_ref, cth_ref,
                     ctc_ref, w_ref, pw_ref, dx_ref, dw_ref, dpw_ref,
                     dh_scr, dc_scr, dw_scr, dpw_scr, *, hidden, nt, nb):
    """Reverse-time BPTT over grid (batch_tiles, time): time step idx
    processes t = nt-1-idx with the (dh, dc) chain and the dW/dpw
    accumulators living in VMEM — no forward recompute (gates/h/c come
    from the forward kernel).  The chain scratches reset per batch tile;
    dW/dpw accumulate across ALL tiles and write out on the last grid
    step."""
    bi = pl.program_id(0)
    idx = pl.program_id(1)

    @pl.when(jnp.logical_and(bi == 0, idx == 0))
    def _init_acc():
        dw_scr[...] = jnp.zeros_like(dw_scr[...])
        dpw_scr[...] = jnp.zeros_like(dpw_scr[...])

    @pl.when(idx == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr[...])
        dc_scr[...] = jnp.zeros_like(dc_scr[...])

    g = gates_ref[0]                      # [B, 4H] f32 (i, f, cand, o)
    i = g[:, :hidden]
    f = g[:, hidden:2 * hidden]
    cand = g[:, 2 * hidden:3 * hidden]
    o = g[:, 3 * hidden:]
    c_t = c_ref[0].astype(jnp.float32)
    c_p = cprev_ref[0].astype(jnp.float32)
    h_p = hprev_ref[0].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    pw = pw_ref[...].astype(jnp.float32)

    dh = cth_ref[0].astype(jnp.float32) + dh_scr[...]
    tc = jnp.tanh(c_t)
    do = dh * tc
    dgo = do * o * (1.0 - o)
    dc = ctc_ref[0].astype(jnp.float32) + dc_scr[...] + \
        dh * o * (1.0 - tc * tc) + dgo * pw[2:3, :]
    di = dc * cand
    df = dc * c_p
    dcand = dc * i
    dgi = di * i * (1.0 - i)
    dgf = df * f * (1.0 - f)
    dgc = dcand * (1.0 - cand * cand)
    dg = jnp.concatenate([dgi, dgf, dgc, dgo], axis=1)  # [B, 4H]
    dx_ref[0] = dg.astype(dx_ref.dtype)
    dw_scr[...] += jax.lax.dot_general(
        h_p, dg, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dpw_scr[0:1, :] += jnp.sum(dgi * c_p, axis=0, keepdims=True)
    dpw_scr[1:2, :] += jnp.sum(dgf * c_p, axis=0, keepdims=True)
    dpw_scr[2:3, :] += jnp.sum(dgo * c_t, axis=0, keepdims=True)
    dh_scr[...] = jax.lax.dot_general(
        dg, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dc_scr[...] = dc * f + dgi * pw[0:1, :] + dgf * pw[1:2, :]

    @pl.when(jnp.logical_and(bi == nb - 1, idx == nt - 1))
    def _finish():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)
        dpw_ref[...] = dpw_scr[...].astype(dpw_ref.dtype)


def _scan_reference(x_tm, w, pw):
    """The identical recurrence as a lax.scan (the backward path)."""
    hdim = w.shape[0]
    pwf = pw.astype(jnp.float32)

    def step(carry, g_t):
        h_p, c_p = carry
        g = g_t.astype(jnp.float32) + jnp.matmul(
            h_p, w.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        i = jax.nn.sigmoid(g[:, :hdim] + c_p * pwf[0:1, :])
        f = jax.nn.sigmoid(g[:, hdim:2 * hdim] + c_p * pwf[1:2, :])
        cand = jnp.tanh(g[:, 2 * hdim:3 * hdim])
        c = f * c_p + i * cand
        o = jax.nn.sigmoid(g[:, 3 * hdim:] + c * pwf[2:3, :])
        h = o * jnp.tanh(c)
        return (h, c), (h, c)

    b = x_tm.shape[1]
    init = (jnp.zeros((b, hdim), jnp.float32),
            jnp.zeros((b, hdim), jnp.float32))
    _, (hs, cs) = jax.lax.scan(step, init, x_tm)
    return hs.astype(x_tm.dtype), cs.astype(x_tm.dtype)


def lstm_scan(x_tm, w, pw=None, interpret=None):
    """Fused LSTM over time-major gates x_tm [T, B, 4H], recurrent weight
    w [H, 4H], optional peephole weights pw [3, H] (w_ic, w_fc, w_oc);
    zero initial state.  Returns (hs, cs) [T, B, H] each.
    interpret=None auto-selects off the default backend; executor ops
    pass it explicitly so a CPUPlace run on a TPU-attached host doesn't
    compile Mosaic for CPU."""
    if pw is None:
        pw = jnp.zeros((3, w.shape[0]), jnp.float32)
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    return _lstm_scan_core(x_tm, w, pw, bool(interpret))


def _batch_tile(b, hidden, gate_width):
    from ..rnn import _rnn_vmem_budget
    bt = pick_batch_tile(b, hidden, gate_width, _rnn_vmem_budget())
    return bt if bt is not None else b


def _lstm_forward(x_tm, w, pw, with_gates, interpret):
    """with_gates=True also emits the f32 post-activation gates the BPTT
    kernel replays; the primal (no-grad) path skips that HBM write."""
    t, b, four_h = x_tm.shape
    hidden = four_h // 4
    bt = _batch_tile(b, hidden, four_h)
    nb = b // bt
    kernel = functools.partial(_lstm_kernel, hidden=hidden,
                               with_gates=with_gates)
    tm = lambda j, i: (i, j, 0)  # [T, B, X] blocks over (batch, time)
    # the grad path keeps h/c residuals f32 so the BPTT replay sees the
    # exact forward carry (bf16 callers would otherwise replay rounded
    # snapshots); the primal path emits the caller's dtype directly
    hc_dtype = jnp.float32 if with_gates else x_tm.dtype
    out_specs = [
        pl.BlockSpec((1, bt, hidden), tm),
        pl.BlockSpec((1, bt, hidden), tm),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((t, b, hidden), hc_dtype),
        jax.ShapeDtypeStruct((t, b, hidden), hc_dtype),
    ]
    if with_gates:
        out_specs.append(pl.BlockSpec((1, bt, four_h), tm))
        out_shape.append(jax.ShapeDtypeStruct((t, b, four_h),
                                              jnp.float32))
    return pl.pallas_call(
        kernel,
        grid=(nb, t),
        in_specs=[
            pl.BlockSpec((1, bt, four_h), tm),
            pl.BlockSpec((hidden, four_h), lambda j, i: (0, 0)),
            pl.BlockSpec((3, hidden), lambda j, i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bt, hidden), jnp.float32),
            pltpu.VMEM((bt, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(x_tm, w, pw)


def _lstm_backward(w, pw, hs, cs, gates, ct_h, ct_c, interpret):
    t, b, four_h = gates.shape
    hidden = four_h // 4
    bt = _batch_tile(b, hidden, four_h)
    nb = b // bt
    zrow = jnp.zeros((1, b, hidden), hs.dtype)
    h_prev = jnp.concatenate([zrow, hs[:-1]], axis=0)
    c_prev = jnp.concatenate([zrow, cs[:-1]], axis=0)
    rev = lambda j, i: (t - 1 - i, j, 0)
    const = lambda j, i: (0, 0)
    kernel = functools.partial(_lstm_bwd_kernel, hidden=hidden, nt=t,
                               nb=nb)
    dx, dw, dpw = pl.pallas_call(
        kernel,
        grid=(nb, t),
        in_specs=[
            pl.BlockSpec((1, bt, four_h), rev),    # gates
            pl.BlockSpec((1, bt, hidden), rev),    # c_t
            pl.BlockSpec((1, bt, hidden), rev),    # c_{t-1}
            pl.BlockSpec((1, bt, hidden), rev),    # h_{t-1}
            pl.BlockSpec((1, bt, hidden), rev),    # ct_h
            pl.BlockSpec((1, bt, hidden), rev),    # ct_c
            pl.BlockSpec((hidden, four_h), const),
            pl.BlockSpec((3, hidden), const),
        ],
        out_specs=[
            pl.BlockSpec((1, bt, four_h), rev),
            pl.BlockSpec((hidden, four_h), const),
            pl.BlockSpec((3, hidden), const),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, four_h), jnp.float32),
            jax.ShapeDtypeStruct((hidden, four_h), jnp.float32),
            jax.ShapeDtypeStruct((3, hidden), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bt, hidden), jnp.float32),
            pltpu.VMEM((bt, hidden), jnp.float32),
            pltpu.VMEM((hidden, four_h), jnp.float32),
            pltpu.VMEM((3, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(gates, cs, c_prev, h_prev, ct_h, ct_c, w, pw)
    return dx, dw, dpw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _lstm_scan_core(x_tm, w, pw, interpret):
    hs, cs = _lstm_forward(x_tm, w, pw, with_gates=False,
                           interpret=interpret)
    return hs, cs


def _residual_dtype(x_dtype):
    """Saved forward state [T, B, 4H]+[T, B, H]x2 dominates training
    activation HBM; bf16 callers keep bf16 residuals (the saturating
    gate activations bound the replay error), halving that footprint.
    f32 callers keep exact f32.  The backward upcasts before its
    kernel, so compute stays f32 either way."""
    return x_dtype if x_dtype == jnp.bfloat16 else jnp.float32


def _fwd(x_tm, w, pw, interpret):
    hs, cs, gates = _lstm_forward(x_tm, w, pw, with_gates=True,
                                  interpret=interpret)  # h/c f32
    # zero-size token carries x's dtype (residuals must be jax types)
    x_tok = jnp.empty((0,), x_tm.dtype)
    rdt = _residual_dtype(x_tm.dtype)
    return (hs.astype(x_tm.dtype), cs.astype(x_tm.dtype)), \
        (x_tok, w, pw, hs.astype(rdt), cs.astype(rdt), gates.astype(rdt))


def _bwd(interpret, res, cts):
    # hand-written reverse-time kernel over the saved forward state —
    # no recompute pass (cf. the scan path, which re-runs the forward)
    x_tok, w, pw, hs, cs, gates = res
    ct_h, ct_c = cts
    dx, dw, dpw = _lstm_backward(w, pw, hs.astype(jnp.float32),
                                 cs.astype(jnp.float32),
                                 gates.astype(jnp.float32), ct_h, ct_c,
                                 interpret)
    return (dx.astype(x_tok.dtype), dw.astype(w.dtype),
            dpw.astype(pw.dtype))


_lstm_scan_core.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------- GRU
def _gru_kernel(x_ref, w_ref, h0_ref, o_ref, *o_g_and_scr, hidden,
                with_gates):
    o_g_ref = o_g_and_scr[0] if with_gates else None
    h_scr = o_g_and_scr[-1]
    t = pl.program_id(1)  # grid = (batch_tiles, time); time innermost

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(jnp.float32)

    g = x_ref[0].astype(jnp.float32)  # [B, 3H] pre-projected gates
    w = w_ref[...].astype(jnp.float32)  # [H, 3H]
    h_p = h_scr[...]
    rz = g[:, :2 * hidden] + jax.lax.dot_general(
        h_p, w[:, :2 * hidden], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    u = jax.nn.sigmoid(rz[:, :hidden])      # update gate
    r = jax.nn.sigmoid(rz[:, hidden:])      # reset gate
    c = jnp.tanh(g[:, 2 * hidden:] + jax.lax.dot_general(
        r * h_p, w[:, 2 * hidden:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32))
    h = u * h_p + (1.0 - u) * c
    h_scr[...] = h
    o_ref[0] = h.astype(o_ref.dtype)
    if with_gates:
        # post-activation gates saved f32 for the BPTT kernel's replay
        o_g_ref[0] = jnp.concatenate([u, r, c], axis=1)


def _gru_bwd_kernel(gates_ref, hprev_ref, cth_ref, w_ref, dx_ref, dw_ref,
                    dh0_ref, dh_scr, dw_scr, *, hidden, nt, nb):
    """Reverse-time GRU BPTT over grid (batch_tiles, time): time step
    idx processes t = nt-1-idx; the dh chain and dW accumulator live in
    VMEM (no forward recompute).  dh resets per batch tile; dW
    accumulates across all tiles."""
    bi = pl.program_id(0)
    idx = pl.program_id(1)

    @pl.when(jnp.logical_and(bi == 0, idx == 0))
    def _init_acc():
        dw_scr[...] = jnp.zeros_like(dw_scr[...])

    @pl.when(idx == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr[...])

    g = gates_ref[0]                       # [B, 3H] f32 (u, r, c)
    u = g[:, :hidden]
    r = g[:, hidden:2 * hidden]
    c = g[:, 2 * hidden:]
    h_p = hprev_ref[0].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    w_rz = w[:, :2 * hidden]
    w_c = w[:, 2 * hidden:]

    dh = cth_ref[0].astype(jnp.float32) + dh_scr[...]
    du = dh * (h_p - c)
    dc = dh * (1.0 - u)
    dc_pre = dc * (1.0 - c * c)
    drh = jax.lax.dot_general(                 # d(r*h_p) [B, H]
        dc_pre, w_c, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dr = drh * h_p
    du_pre = du * u * (1.0 - u)
    dr_pre = dr * r * (1.0 - r)
    dg_rz = jnp.concatenate([du_pre, dr_pre], axis=1)  # [B, 2H]
    dx_ref[0] = jnp.concatenate([dg_rz, dc_pre],
                                axis=1).astype(dx_ref.dtype)
    dw_scr[:, :2 * hidden] += jax.lax.dot_general(
        h_p, dg_rz, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dw_scr[:, 2 * hidden:] += jax.lax.dot_general(
        r * h_p, dc_pre, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dh_scr[...] = dh * u + drh * r + jax.lax.dot_general(
        dg_rz, w_rz, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(idx == nt - 1)
    def _finish_tile():
        # the final dh chain value IS this tile's d h0
        dh0_ref[...] = dh_scr[...].astype(dh0_ref.dtype)

    @pl.when(jnp.logical_and(bi == nb - 1, idx == nt - 1))
    def _finish():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)


def _gru_scan_reference(x_tm, w):
    """Identical recurrence as lax.scan (ops/rnn.py gru math)."""
    hdim = w.shape[0]
    w_rz = w[:, :2 * hdim].astype(jnp.float32)
    w_c = w[:, 2 * hdim:].astype(jnp.float32)

    def step(h_p, g_t):
        g = g_t.astype(jnp.float32)
        rz = g[:, :2 * hdim] + jnp.matmul(
            h_p, w_rz, preferred_element_type=jnp.float32)
        u = jax.nn.sigmoid(rz[:, :hdim])
        r = jax.nn.sigmoid(rz[:, hdim:])
        c = jnp.tanh(g[:, 2 * hdim:] + jnp.matmul(
            r * h_p, w_c, preferred_element_type=jnp.float32))
        h = u * h_p + (1.0 - u) * c
        return h, h

    b = x_tm.shape[1]
    _, hs = jax.lax.scan(step, jnp.zeros((b, hdim), jnp.float32), x_tm)
    return hs.astype(x_tm.dtype)


def _gru_forward(x_tm, w, h0, with_gates, interpret):
    t, b, three_h = x_tm.shape
    hidden = three_h // 3
    bt = _batch_tile(b, hidden, three_h)
    nb = b // bt
    kernel = functools.partial(_gru_kernel, hidden=hidden,
                               with_gates=with_gates)
    tm = lambda j, i: (i, j, 0)
    h_dtype = jnp.float32 if with_gates else x_tm.dtype  # see LSTM note
    out_specs = [pl.BlockSpec((1, bt, hidden), tm)]
    out_shape = [jax.ShapeDtypeStruct((t, b, hidden), h_dtype)]
    if with_gates:
        out_specs.append(pl.BlockSpec((1, bt, three_h), tm))
        out_shape.append(jax.ShapeDtypeStruct((t, b, three_h),
                                              jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid=(nb, t),
        in_specs=[
            pl.BlockSpec((1, bt, three_h), tm),
            pl.BlockSpec((hidden, three_h), lambda j, i: (0, 0)),
            pl.BlockSpec((bt, hidden), lambda j, i: (j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bt, hidden), jnp.float32)],
        interpret=interpret,
    )(x_tm, w, h0)
    return out if with_gates else (out[0], None)


def _gru_backward(w, h0, hs, gates, ct_h, interpret):
    t, b, three_h = gates.shape
    hidden = three_h // 3
    bt = _batch_tile(b, hidden, three_h)
    nb = b // bt
    h_prev = jnp.concatenate([h0[None].astype(hs.dtype), hs[:-1]],
                             axis=0)
    rev = lambda j, i: (t - 1 - i, j, 0)
    const = lambda j, i: (0, 0)
    kernel = functools.partial(_gru_bwd_kernel, hidden=hidden, nt=t,
                               nb=nb)
    dx, dw, dh0 = pl.pallas_call(
        kernel,
        grid=(nb, t),
        in_specs=[
            pl.BlockSpec((1, bt, three_h), rev),   # gates (u, r, c)
            pl.BlockSpec((1, bt, hidden), rev),    # h_{t-1}
            pl.BlockSpec((1, bt, hidden), rev),    # ct_h
            pl.BlockSpec((hidden, three_h), const),
        ],
        out_specs=[
            pl.BlockSpec((1, bt, three_h), rev),
            pl.BlockSpec((hidden, three_h), const),
            pl.BlockSpec((bt, hidden), lambda j, i: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, three_h), jnp.float32),
            jax.ShapeDtypeStruct((hidden, three_h), jnp.float32),
            jax.ShapeDtypeStruct((b, hidden), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bt, hidden), jnp.float32),
            pltpu.VMEM((hidden, three_h), jnp.float32),
        ],
        interpret=interpret,
    )(gates, h_prev, ct_h, w)
    return dx, dw, dh0


def gru_scan(x_tm, w, h0=None, interpret=None):
    """Fused GRU over time-major gates x_tm [T, B, 3H], recurrent weight
    w [H, 3H] ([:, :2H] update/reset, [:, 2H:] candidate); h0 [B, H]
    initial state (zeros when None — the seq2seq decoder chains its
    encoder summary in).  Returns hs [T, B, H].  interpret: see
    lstm_scan."""
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    if h0 is None:
        h0 = jnp.zeros((x_tm.shape[1], w.shape[0]), jnp.float32)
    return _gru_scan_core(x_tm, w, h0, bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gru_scan_core(x_tm, w, h0, interpret):
    hs, _ = _gru_forward(x_tm, w, h0, with_gates=False,
                         interpret=interpret)
    return hs


def _gru_fwd(x_tm, w, h0, interpret):
    hs, gates = _gru_forward(x_tm, w, h0, with_gates=True,
                             interpret=interpret)  # hs f32
    x_tok = jnp.empty((0,), x_tm.dtype)
    rdt = _residual_dtype(x_tm.dtype)
    return hs.astype(x_tm.dtype), (x_tok, w, h0, hs.astype(rdt),
                                   gates.astype(rdt))


def _gru_bwd(interpret, res, ct):
    # reverse-time BPTT kernel over the saved forward state
    x_tok, w, h0, hs, gates = res
    dx, dw, dh0 = _gru_backward(w, h0.astype(jnp.float32),
                                hs.astype(jnp.float32),
                                gates.astype(jnp.float32), ct, interpret)
    return (dx.astype(x_tok.dtype), dw.astype(w.dtype),
            dh0.astype(h0.dtype))


_gru_scan_core.defvjp(_gru_fwd, _gru_bwd)
