"""Fused dense optimizer applies as Pallas TPU kernels.

Reference parity: the DENSE branches of paddle/operators/{sgd,momentum,
adam}_op — elementwise updates over whole parameters.  The XLA lowering
of today's path (ops/optim_ops.py) is an op soup per parameter: dense
Adam is three multiply-add chains whose intermediates (`m_new`, `v_new`,
the step) round-trip HBM between fusions, so the optimizer apply reads
and writes each state table several times per step.  At ResNet/VGG batch
sizes the roofline says this — not matmul — is where the non-MFU time
lives (PERF.md "MFU accounting", BENCH r05 ~0.15 MFU).

These kernels fuse each rule into ONE grid walk over the flattened
parameter: every block DMAs a [1, T] tile of param + each moment out of
HBM exactly once, applies the update on the VPU, and stores the tile
back through ``input_output_aliases`` — the donated state is updated in
place with no intermediate materialization:

  dense_apply_sgd       param                     (+ optional fused L2
                                                   weight decay)
  dense_apply_momentum  param + velocity, ONE pass (plain and Nesterov)
  dense_apply_adam      param + m1 + m2, ONE pass  (vs 3+ XLA fusions
                                                   with HBM round-trips)

Tiling: the parameter is viewed as [1, N] (any rank, any N — Pallas
masks the ragged last block, so tile-unaligned shapes stay exact) and
walked in [1, T] lane-aligned tiles; `pick_flat_tile` chooses the
largest T whose per-block working set fits the VMEM budget, the same
budget-driven chooser pattern as lstm_cell.pick_batch_tile.

Bitwise parity contract (tier-1 tests/test_pallas_dense_update.py): the
kernel bodies restate the ops/optim_ops.py dense expressions term for
term, so XLA makes the same fma-contraction choices in both lowerings —
the PR-4 subtlety recurs here: a factor pre-rounded outside the kernel
(or an expression reassociated inside it) would change the contraction
rounding and break bitwise parity.  Scalars (lr, mu, lr_t) ride in as
(1, 1) SMEM-class operands; betas/eps/mu are trace-time constants baked
into the kernel exactly as they are baked into the XLA branch.

On non-TPU backends the kernels run with interpret=True — CPU CI
executes the same code path.  The mode switch lives in
`dense_apply_mode()`: PADDLE_TPU_DENSE_APPLY=pallas|xla forces a path,
default is pallas on TPU and xla elsewhere; ops/optim_ops.py routes on
it per trace and the resolved mode is part of the executor's plan cache
key, so a flip retraces instead of silently serving the old lowering.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._compat import CompilerParams as _CompilerParams

__all__ = ['dense_apply_sgd', 'dense_apply_momentum', 'dense_apply_adam',
           'dense_apply_mode', 'pick_flat_tile', 'flat_tile_budget']

# per-block VMEM the flat walk may claim: tables are double-buffered by
# Mosaic (in + aliased out), values single; leave margin for temporaries
_VMEM_BUDGET = 4 * 1024 * 1024

# lane-aligned tile ladder, largest first (f32 lane width 128)
_TILES = (65536, 32768, 16384, 8192, 4096, 2048, 1024, 512, 256, 128)


def dense_apply_mode():
    """Resolved dense-apply path: 'pallas' or 'xla'.

    PADDLE_TPU_DENSE_APPLY=pallas|xla pins it; the default ('auto')
    picks pallas on a TPU backend and xla elsewhere.  Read at trace
    time and part of the executor's plan cache key, so a flip retraces
    instead of silently serving the old path."""
    from ...flags import FLAGS
    mode = FLAGS.dense_apply
    if mode in ('pallas', 'xla'):
        return mode
    return 'pallas' if jax.default_backend() == 'tpu' else 'xla'


def flat_tile_budget():
    """Resolved per-block VMEM budget for :func:`pick_flat_tile`:
    PADDLE_TPU_FLAT_TILE_BUDGET when >0 (the autotuner's hook — a
    registered tunable in tuning/registry.py), the baked-in 4 MiB
    otherwise.  Read at trace time and a component of the composite
    plan-cache key (pass_manager.plan_key), so an override retraces
    instead of serving a plan built at the old tile size."""
    from ...flags import FLAGS
    b = int(FLAGS.flat_tile_budget or 0)
    return b if b > 0 else _VMEM_BUDGET


def pick_flat_tile(n, n_tables, n_vals, budget=None):
    """Largest lane-aligned tile T such that one grid step's working
    set — each table twice (block in + aliased block out) + each value
    block, all f32 — fits `budget` bytes of VMEM.  Also never wider
    than the padded element count (a tiny param takes one ragged
    block).  The floor is one 128-lane tile: the budget can shrink the
    tile, never veto the kernel (same contract as
    lstm_cell.pick_batch_tile returning its smallest divisor)."""
    if budget is None:
        budget = flat_tile_budget()
    bufs = 2 * n_tables + n_vals
    padded = -(-max(int(n), 1) // 128) * 128
    for t in _TILES:
        if t <= padded and bufs * t * 4 <= budget:
            return t
    return 128


def _flat_kernel(*refs, nt, nv, ns, rule):
    """One grid step = one [1, T] tile of every table/value.  refs
    layout: nt table blocks, nv value blocks, ns (1, 1) scalar blocks,
    then the nt aliased out blocks.  Blocks are disjoint (no resident-
    block accumulation like the row-sparse kernels need) — the ragged
    last block is masked by Pallas, so tile-unaligned params are
    exact."""
    tabs = refs[:nt]
    vals = refs[nt:nt + nv]
    scalars = tuple(r[0, 0] for r in refs[nt + nv:nt + nv + ns])
    outs = refs[nt + nv + ns:]
    for o, new in zip(outs, rule(tuple(t[...] for t in tabs),
                                 tuple(v[...] for v in vals),
                                 scalars)):
        o[...] = new


def _flat_call(tables, vals, scalars, rule, interpret):
    """Launch the flat tile walk over same-shaped f32 tables/values of
    any rank: each is viewed [1, N], the grid covers ceil(N / T) tiles,
    and the tables come back input_output_aliased (in place under
    donation) in their original shapes."""
    shape = tables[0].shape
    n = 1
    for d in shape:
        n *= int(d)
    if n == 0:
        return tuple(tables) if len(tables) > 1 else tables[0]
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    nt, nv, ns = len(tables), len(vals), len(scalars)
    tile = pick_flat_tile(n, nt, nv)
    flat = [jnp.reshape(t, (1, n)) for t in tables]
    vflat = [jnp.reshape(v, (1, n)) for v in vals]
    sflat = [jnp.reshape(s, (1, 1)).astype(jnp.float32) for s in scalars]
    spec = pl.BlockSpec((1, tile), lambda i: (0, i))
    outs = pl.pallas_call(
        functools.partial(_flat_kernel, nt=nt, nv=nv, ns=ns, rule=rule),
        grid=(-(-n // tile),),
        in_specs=([spec] * (nt + nv) +
                  [pl.BlockSpec((1, 1), lambda i: (0, 0))] * ns),
        out_specs=[spec] * nt,
        out_shape=[jax.ShapeDtypeStruct((1, n), jnp.float32)
                   for _ in tables],
        # operand t aliases out t: the state updates in place under the
        # executor's donated-carry step
        input_output_aliases={t: t for t in range(nt)},
        # tiles are disjoint; 'arbitrary' (sequential) is always valid
        # and the walk is bandwidth-bound either way
        compiler_params=_CompilerParams(
            dimension_semantics=('arbitrary',)),
        interpret=interpret,
    )(*flat, *vflat, *sflat)
    return tuple(jnp.reshape(o, shape) for o in outs) if nt > 1 \
        else jnp.reshape(outs[0], shape)


def dense_apply_sgd(param, grad, lr, weight_decay=None, interpret=None):
    """param -= lr * grad, one fused pass; with `weight_decay` the
    decoupled-into-the-grad L2 term rides the same pass:
    param -= lr * (grad + wd * param) — exactly the expression the
    append_regularization_ops scale+sum pair feeds today's sgd op, so
    fusing it keeps the update bitwise when the decay coefficient is
    folded into the op instead of woven as separate ops."""
    if weight_decay is None:
        def rule(tabs, vals, scalars):
            (p,), (g,), (lr_s,) = tabs, vals, scalars
            # ops/optim_ops.py _sgd dense branch, verbatim
            return (p - lr_s * g,)
        return _flat_call([param], [grad], [lr], rule, interpret)

    def rule(tabs, vals, scalars):
        (p,), (g,), (lr_s, wd) = tabs, vals, scalars
        return (p - lr_s * (g + wd * p),)
    return _flat_call([param], [grad], [lr, weight_decay], rule,
                      interpret)


def dense_apply_momentum(param, velocity, grad, lr, mu,
                         use_nesterov=False, interpret=None):
    """Fused momentum: velocity accumulate + param step in ONE pass
    (today's XLA path re-reads v_new from HBM for the step).  `mu` is a
    trace-time constant (op attr), `lr` a traced scalar.  Returns
    (param_new, velocity_new)."""
    if use_nesterov:
        def rule(tabs, vals, scalars):
            (p, v), (g,), (lr_s,) = tabs, vals, scalars
            # ops/optim_ops.py _momentum, verbatim (nesterov arm)
            v_new = mu * v + g
            p_new = p - (g + mu * v_new) * lr_s
            return (p_new, v_new)
    else:
        def rule(tabs, vals, scalars):
            (p, v), (g,), (lr_s,) = tabs, vals, scalars
            v_new = mu * v + g
            p_new = p - lr_s * v_new
            return (p_new, v_new)
    return _flat_call([param, velocity], [grad], [lr], rule, interpret)


def dense_apply_adam(param, moment1, moment2, grad, lr_t, beta1, beta2,
                     epsilon, interpret=None):
    """Fused dense Adam: param + both moments in ONE grid walk — one
    read and one aliased write per state table, vs the XLA op soup's
    multiple fusions with `m_new`/`v_new` HBM round-trips.  `lr_t` is
    the bias-corrected rate the caller computed from the pow
    accumulators (a traced scalar); betas/eps are trace-time constants.
    Returns (p, m1, m2)."""
    def rule(tabs, vals, scalars):
        (p, m, v), (g,), (lrt,) = tabs, vals, scalars
        # ops/optim_ops.py _adam dense tail, verbatim — same
        # expressions, same fma-contraction choices (the PR-4 lesson:
        # reassociating any term here breaks bitwise parity)
        m_new = beta1 * m + (1 - beta1) * g
        v_new = beta2 * v + (1 - beta2) * jnp.square(g)
        p_new = p - lrt * m_new / (jnp.sqrt(v_new) + epsilon)
        return (p_new, m_new, v_new)
    return _flat_call([param, moment1, moment2], [grad], [lr_t], rule,
                      interpret)
