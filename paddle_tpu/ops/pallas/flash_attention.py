"""Flash attention as a Pallas TPU kernel.

Reference parity: the reference's attention rides separate matmul/softmax
ops (scaled_dot_product_attention in fluid nets.py) materializing the
[Tq, Tk] score matrix in HBM.  This kernel keeps the online-softmax
running (max, sum, acc) state in VMEM across K blocks — O(block) memory,
one HBM pass — the bandwidth-bound fusion XLA does not do by itself.

Forward is the Pallas kernel (grid = (batch*heads, q blocks, k blocks),
VMEM scratch carries m/l/acc between k iterations).  Backward on TPU is
a pair of Pallas kernels (dk/dv: grid (bh, nk, nq); dq: grid (bh, nq,
nk)) recomputing p from the saved logsumexp in VMEM; off-TPU it falls
back to a jax lax.scan flash recompute.  Causal grids skip fully-masked
tiles.  Env gates (resolved per call, part of the vjp cache key):
PADDLE_TPU_FLASH_BWD_SCAN forces the scan path on TPU,
PADDLE_TPU_FLASH_BWD_PALLAS runs the Pallas backward in interpret mode
off-TPU (how CPU CI exercises the kernel path).

On non-TPU backends the forward kernel runs with interpret=True, so the
same code path is exercised by CPU CI.
"""
import functools
import os

import numpy as _np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ['flash_attention']

_NEG_INF = -1e30


def _env_on(name):
    return os.environ.get(name, '') not in ('', '0')


def _tile_alive(qoff, koff, qi, ki, block_q, block_k):
    """Causal dead-tile predicate shared by fwd/dkv/dq kernels: the tile
    is fully masked when its newest query precedes its oldest key."""
    return (qoff + qi * block_q + block_q - 1) >= (koff + ki * block_k)


def _fa_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
               m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
               nk, tk):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    # causal dead-tile skip: tile fully masked when its newest query
    # precedes its oldest key — costs one predicate, halves causal work
    alive = True
    if causal:
        alive = _tile_alive(qoff_ref[0], koff_ref[0], qi, ki,
                            block_q, block_k)

    @pl.when(alive)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)  # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = kpos < tk  # last block may be padding past the real length
        if causal:
            # global positions: scalar-prefetched offsets shift the local
            # indices, so causal masking works across ring-rotated K blocks
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & ((qoff_ref[0] + qpos) >= (koff_ref[0] + kpos))
        s = jnp.where(valid, s, _NEG_INF)

        m_prev = m_scr[:, 0]  # [bq]
        l_prev = l_scr[:, 0]
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # explicit zero for masked entries: when a whole row is masked,
        # s == m_new == _NEG_INF and bare exp(s - m_new) would be 1
        p = jnp.where(valid, jnp.exp(s - m_new[:, None]), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse broadcast across the 128-lane axis (Mosaic wants the last
        # two block dims (block_q, 128); column 0 is read back outside)
        lse = m_scr[:, 0] + jnp.log(l_safe)
        lse_ref[0] = jnp.broadcast_to(lse[:, None],
                                      lse_ref.shape[1:]).astype(
                                          lse_ref.dtype)


def _sds(shape, dtype):
    """ShapeDtypeStruct annotated as varying over the ambient mapped
    axes.  This clears shard_map's out_shape vma requirement; pallas
    -internal slice ops still trip the strict checker, so callers pass
    check_vma=False on the enclosing shard_map (see
    parallel/ring_attention.ring_attention)."""
    try:
        import jax.core as jc
        vma = frozenset(jc.unsafe_get_axis_names_DO_NOT_USE())
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except Exception:
        pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _fa_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                q_offset=None, k_offset=None):
    """q/k/v: [BH, T, D] -> (o [BH, T, D], lse [BH, T]).  Optional traced
    q_offset/k_offset (int32 scalars, scalar-prefetched into SMEM) shift
    the causal mask's global positions — the hook ring attention uses to
    run causal flash blocks against rotated K/V shards."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    nq = pl.cdiv(tq, block_q)
    nk = pl.cdiv(tk, block_k)
    # pad sequence dims to block multiples: Mosaic requires block shapes
    # that divide (or equal) the array dims; padded K columns are masked
    # in-kernel via `tk`, padded Q rows are sliced off below
    tq_p, tk_p = nq * block_q, nk * block_k
    if tq_p != tq:
        q = jnp.pad(q, ((0, 0), (0, tq_p - tq), (0, 0)))
    if tk_p != tk:
        k = jnp.pad(k, ((0, 0), (0, tk_p - tk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, tk_p - tk), (0, 0)))
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, nk=nk,
                               tk=tk)
    qoff = jnp.asarray([0 if q_offset is None else q_offset], jnp.int32)
    koff = jnp.asarray([0 if k_offset is None else k_offset], jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128),
                         lambda b, i, j, *_: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((bh, tq_p, d), q.dtype),
            _sds((bh, tq_p, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qoff, koff, q, k, v)


def _fa_forward_sliced(q, k, v, causal, scale, block_q, block_k,
                       interpret, q_offset=None, k_offset=None):
    tq = q.shape[1]
    o, lse = _fa_forward(q, k, v, causal, scale, block_q, block_k,
                         interpret, q_offset, k_offset)
    return o[:, :tq], lse[:, :tq, 0]


def _dense_ref(q, k, v, causal, scale):
    s = jnp.einsum('btd,bsd->bts', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[1], s.shape[2]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bts,bsd->btd', p, v.astype(jnp.float32))


def _fa_backward(causal, scale, block_k, res, do, dlse=None):
    """Flash backward: recompute scores per K block against the saved
    logsumexp; never materializes [Tq, Tk].  `dlse` is the cotangent of
    the logsumexp output (d lse/d s = p, so it folds into ds)."""
    q, k, v, q_off, k_off, o, lse = res
    qf = q.astype(jnp.float32)
    do = do.astype(jnp.float32)
    of = o.astype(jnp.float32)
    di = jnp.sum(do * of, axis=-1)  # [BH, T]
    if dlse is not None:
        di = di - dlse.astype(jnp.float32)  # ds += p * dlse
    tk = k.shape[1]
    bk = min(block_k, tk)
    nk = pl.cdiv(tk, bk)
    pad = nk * bk - tk
    kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
    kpos0 = jnp.arange(nk) * bk
    tq = q.shape[1]
    qpos = q_off + jnp.arange(tq)

    def kblock(carry, inp):
        dq_acc = carry
        kb, vb, k0 = inp  # [BH, bk, D], [BH, bk, D], scalar
        kf = kb.astype(jnp.float32)
        vf = vb.astype(jnp.float32)
        s = jnp.einsum('btd,bsd->bts', qf, kf) * scale
        kpos = k0 + jnp.arange(bk)
        valid = (kpos < tk)[None, None, :]
        if causal:
            valid = valid & (qpos[:, None] >=
                             (k_off + kpos)[None, :])[None]
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse[:, :, None])  # [BH, Tq, bk]
        p = jnp.where(valid, p, 0.0)
        dv = jnp.einsum('bts,btd->bsd', p, do)
        dp = jnp.einsum('btd,bsd->bts', do, vf)
        ds = p * (dp - di[:, :, None]) * scale
        dq_acc = dq_acc + jnp.einsum('bts,bsd->btd', ds, kf)
        dk = jnp.einsum('bts,btd->bsd', ds, qf)
        return dq_acc, (dk, dv)

    kb = kp.reshape(kp.shape[0], nk, bk, -1).swapaxes(0, 1)
    vb = vp.reshape(vp.shape[0], nk, bk, -1).swapaxes(0, 1)
    dq, (dks, dvs) = jax.lax.scan(
        kblock, jnp.zeros_like(qf), (kb, vb, kpos0))
    dk = dks.swapaxes(0, 1).reshape(kp.shape)[:, :tk]
    dv = dvs.swapaxes(0, 1).reshape(vp.shape)[:, :tk]
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


def _bwd_common(q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref, *, scale,
                causal, q0, k0, tq, tk, qoff, koff, bq, bk):
    """Shared per-tile flash backward math: returns\n    (q, do, k, p, ds) with p/ds [bq, bk] fp32."""
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]  # [bq, 1] sublane vector
    di = di_ref[0, 0]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    valid = (qpos < tq) & (kpos < tk)  # block padding rows/cols
    if causal:
        valid = valid & ((qoff + qpos) >= (koff + kpos))
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - di) * scale
    return q, do, k, p, ds


def _fa_bwd_dkv_kernel(qoff_ref, koff_ref, q_ref, do_ref, lse_ref, di_ref,
                       k_ref, v_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                       scale, causal, block_q, block_k, nq, tq, tk):
    ki = pl.program_id(1)
    qi = pl.program_id(2)  # innermost: accumulate over q blocks

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    # causal dead-tile skip: the whole tile is masked when its newest
    # query precedes its oldest key
    alive = True
    if causal:
        alive = _tile_alive(qoff_ref[0], koff_ref[0], qi, ki,
                            block_q, block_k)

    @pl.when(alive)
    def _compute():
        q, do, k, p, ds = _bwd_common(
            q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref, scale=scale,
            causal=causal, q0=qi * block_q, k0=ki * block_k, tq=tq, tk=tk,
            qoff=qoff_ref[0], koff=koff_ref[0], bq=block_q, bk=block_k)
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(qoff_ref, koff_ref, q_ref, do_ref, lse_ref, di_ref,
                      k_ref, v_ref, dq_ref, dq_scr, *, scale, causal,
                      block_q, block_k, nk, tq, tk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)  # innermost: accumulate over k blocks

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr[...])

    alive = True
    if causal:
        alive = _tile_alive(qoff_ref[0], koff_ref[0], qi, ki,
                            block_q, block_k)

    @pl.when(alive)
    def _compute():
        _q, _do, k, p, ds = _bwd_common(
            q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref, scale=scale,
            causal=causal, q0=qi * block_q, k0=ki * block_k, tq=tq, tk=tk,
            qoff=qoff_ref[0], koff=koff_ref[0], bq=block_q, bk=block_k)
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _fa_backward_pallas(causal, scale, block_q, block_k, res, do,
                        dlse, interpret):
    """Pallas flash backward: dk/dv kernel (grid bh, nk, nq) and dq
    kernel (grid bh, nq, nk), both recomputing p from the saved lse in
    VMEM — the [Tq, Tk] lattice never touches HBM (the jax-scan fallback
    `_fa_backward` streams [Tq, block_k] slabs through HBM instead)."""
    q, k, v, q_off, k_off, o, lse = res
    bh, tq, d = q.shape
    tk = k.shape[1]
    bq = min(block_q, tq)
    bk = min(block_k, tk)
    nq = pl.cdiv(tq, bq)
    nk = pl.cdiv(tk, bk)
    tq_p, tk_p = nq * bq, nk * bk

    dof = do.astype(jnp.float32)
    di = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # [BH, Tq]
    if dlse is not None:
        di = di - dlse.astype(jnp.float32)

    qp = jnp.pad(q, ((0, 0), (0, tq_p - tq), (0, 0)))
    dop = jnp.pad(do, ((0, 0), (0, tq_p - tq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, tk_p - tk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, tk_p - tk), (0, 0)))
    # lse/di ride as [BH, nq, bq, 1] sublane-vector blocks: 512B per
    # tile visit instead of the 64KB a 128-lane broadcast would re-read
    lse_b = jnp.pad(lse, ((0, 0), (0, tq_p - tq))).reshape(
        bh, nq, bq, 1)
    di_b = jnp.pad(di, ((0, 0), (0, tq_p - tq))).reshape(bh, nq, bq, 1)

    qoff = jnp.asarray([0 if q_off is None else q_off], jnp.int32)
    koff = jnp.asarray([0 if k_off is None else k_off], jnp.int32)

    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i, *_: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i, *_: (b, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, j, i, *_: (b, i, 0, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, j, i, *_: (b, i, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nq=nq, tq=tq, tk=tk),
        grid_spec=dkv_spec,
        out_shape=[_sds((bh, tk_p, d), k.dtype),
                   _sds((bh, tk_p, d), v.dtype)],
        interpret=interpret,
    )(qoff, koff, qp, dop, lse_b, di_b, kp, vp)

    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, i, j, *_: (b, i, 0, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, i, j, *_: (b, i, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j, *_: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j, *_: (b, j, 0)),
        ],
        out_specs=[pl.BlockSpec((1, bq, d), lambda b, i, j, *_: (b, i, 0))],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
    )
    dq, = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nk=nk, tq=tq, tk=tk),
        grid_spec=dq_spec,
        out_shape=[_sds((bh, tq_p, d), q.dtype)],
        interpret=interpret,
    )(qoff, koff, qp, dop, lse_b, di_b, kp, vp)

    return dq[:, :tq], dk[:, :tk], dv[:, :tk]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_with_lse(q, k, v, q_off, k_off, causal, scale, block_q,
                    block_k, interpret, bwd_mode):
    """[BH, T, D] kernel entry returning (o, lse); differentiable —
    the backward folds both cotangents into one flash recompute.
    q_off/k_off are traced int32 scalars shifting the causal mask.
    bwd_mode ('pallas'|'scan') is part of the vjp cache key, so the env
    gates that select it (resolved by the caller) take effect on the
    next call instead of silently needing jax.clear_caches()."""
    return _fa_forward_sliced(q, k, v, causal, scale, block_q, block_k,
                              interpret, q_off, k_off)


def _flash_fwd(q, k, v, q_off, k_off, causal, scale, block_q, block_k,
               interpret, bwd_mode):
    o, lse = _fa_forward_sliced(q, k, v, causal, scale, block_q, block_k,
                                interpret, q_off, k_off)
    return (o, lse), (q, k, v, q_off, k_off, o, lse)


def _bwd_mode_from_env(interpret):
    """PADDLE_TPU_FLASH_BWD_SCAN forces the jax-scan path on TPU (A/B
    numerics); PADDLE_TPU_FLASH_BWD_PALLAS forces the Pallas kernels
    (interpret mode) off-TPU."""
    if _env_on('PADDLE_TPU_FLASH_BWD_PALLAS'):
        return 'pallas'
    if interpret or _env_on('PADDLE_TPU_FLASH_BWD_SCAN'):
        return 'scan'
    return 'pallas'


def _flash_bwd(causal, scale, block_q, block_k, interpret, bwd_mode,
               res, cts):
    do, dlse = cts
    if bwd_mode == 'pallas':
        dq, dk, dv = _fa_backward_pallas(causal, scale, block_q, block_k,
                                         res, do, dlse,
                                         interpret=interpret)
    else:  # CPU: the jax-scan recompute (fast under interpret-free jit)
        dq, dk, dv = _fa_backward(causal, scale, block_k, res, do, dlse)
    f0 = _np.zeros((), jax.dtypes.float0)  # int operands: zero cotangent
    return dq, dk, dv, f0, f0


_flash_with_lse.defvjp(_flash_fwd, _flash_bwd)


def _to_bhtd(q, k, v):
    """[B, T, H, D] (or [BH, T, D] pass-through) -> flattened [B*H, T, D]
    plus the info to restore — the single home of the layout contract."""
    if q.ndim == 3:
        return q, k, v, None
    b, tq, h, d = q.shape
    tk = k.shape[1]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    return qf, kf, vf, (b, h, tq, d)


def attention_with_lse(q, k, v, causal=False, scale=None, block_q=None,
                       block_k=None, q_offset=0, k_offset=0,
                       interpret=None):
    """Fused attention returning (o, lse) for online-softmax merging
    (ring attention's local blocks).  q/k/v [B, T, H, D] -> o same shape,
    lse [B, H, T].  Differentiable.  q_offset/k_offset (traced int ok)
    place the local blocks on the global sequence axis for causal
    masking across ring-rotated K/V shards."""
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    # head-dim-aware default tiles: d<=64 leaves VMEM headroom for 1024
    # (measured ~1.2x over 512 on v5e fwd+bwd); d=128 regresses there
    auto = 1024 if q.shape[-1] <= 64 else 512
    block_q = auto if block_q is None else block_q
    block_k = auto if block_k is None else block_k
    qf, kf, vf, restore = _to_bhtd(q, k, v)
    qo = jnp.asarray(q_offset, jnp.int32)
    ko = jnp.asarray(k_offset, jnp.int32)
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    o, lse = _flash_with_lse(qf, kf, vf, qo, ko, bool(causal),
                             float(scale), int(block_q), int(block_k),
                             bool(interpret),
                             _bwd_mode_from_env(bool(interpret)))
    if restore is None:
        return o, lse
    b, h, tq, d = restore
    o = o.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    return o, lse.reshape(b, h, tq)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None):
    """Fused attention over [B, T, H, D] (or [BH, T, D]) tensors.

    Returns softmax(q k^T * scale [+ causal mask]) v with O(block) live
    memory on-chip.  Differentiable (Pallas backward on TPU, flash
    recompute scan elsewhere).  Default tiles are head-dim aware
    (1024 for d<=64, else 512 — ~4x over the original 128 on v5e
    fwd+bwd; 2048 overflows Mosaic VMEM).
    """
    squeeze = False
    if q.ndim == 3:
        q4, k4, v4 = (x[:, :, None, :] for x in (q, k, v))
        squeeze = True
    else:
        q4, k4, v4 = q, k, v
    o, _lse = attention_with_lse(q4, k4, v4, causal=causal, scale=scale,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret)
    return o[:, :, 0, :] if squeeze else o
