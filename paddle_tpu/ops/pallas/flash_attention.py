"""Flash attention as a Pallas TPU kernel.

Reference parity: the reference's attention rides separate matmul/softmax
ops (scaled_dot_product_attention in fluid nets.py) materializing the
[Tq, Tk] score matrix in HBM.  This kernel keeps the online-softmax
running (max, sum, acc) state in VMEM across K blocks — O(block) memory,
one HBM pass — the bandwidth-bound fusion XLA does not do by itself.

Forward is the Pallas kernel (grid = (batch*heads, q blocks, k blocks),
VMEM scratch carries m/l/acc between k iterations).  Backward on TPU is
a pair of Pallas kernels (dk/dv: grid (bh, nk, nq); dq: grid (bh, nq,
nk)) recomputing p from the saved logsumexp in VMEM; off-TPU it falls
back to a jax lax.scan flash recompute.  Causal grids skip fully-masked
tiles.  Env gates (resolved per call, part of the vjp cache key):
PADDLE_TPU_FLASH_BWD_SCAN forces the scan path on TPU,
PADDLE_TPU_FLASH_BWD_PALLAS runs the Pallas backward in interpret mode
off-TPU (how CPU CI exercises the kernel path).

On non-TPU backends the forward kernel runs with interpret=True, so the
same code path is exercised by CPU CI.
"""
import functools
import math
import os

import numpy as _np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import CompilerParams as _CompilerParams

__all__ = ['flash_attention']

_NEG_INF = -1e30


def _env_on(name):
    return os.environ.get(name, '') not in ('', '0')


def _tile_alive(qoff, koff, qi, ki, block_q, block_k):
    """Causal dead-tile predicate shared by fwd/dkv/dq kernels: the tile
    is fully masked when its newest query precedes its oldest key."""
    return (qoff + qi * block_q + block_q - 1) >= (koff + ki * block_k)


def _tile_interior(qoff, koff, qi, ki, block_q, block_k):
    """Causal all-valid predicate: every (q, k) pair in the tile is
    unmasked when the tile's oldest query is >= its newest key."""
    return (qoff + qi * block_q) >= (koff + ki * block_k + block_k - 1)


def _fa_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
               m_scr, l_scr, acc_scr, *, causal, block_q, block_k,
               nk, tk):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    # causal dead-tile skip: tile fully masked when its newest query
    # precedes its oldest key — costs one predicate, halves causal work
    alive = True
    if causal:
        alive = _tile_alive(qoff_ref[0], koff_ref[0], qi, ki,
                            block_q, block_k)

    @pl.when(alive)
    def _compute():
        # matmul inputs stay in the storage dtype (bf16 on the bench
        # path): the MXU multiplies bf16 at full rate and accumulates
        # fp32 via preferred_element_type — casting to fp32 first would
        # run the matmul at a fraction of peak.  Softmax state (m, l,
        # acc) is fp32 throughout.  q arrives pre-scaled (see
        # _fa_forward), so no per-element scale multiply here.
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]  # [bk, d]
        d = v.shape[-1]
        # l-sum rides the PV matmul when head_dim leaves idle lanes:
        # augmenting v with a ones column turns sum(p, axis=1) — a
        # 1M-element cross-lane VPU reduce per 1024^2 tile — into lane
        # d of the matmul output the MXU was padding to 128 anyway
        mxu_lsum = d % 128 != 0
        if mxu_lsum:
            dx = -(-(d + 1) // 128) * 128 - d  # lanes to fill
            v = jnp.concatenate(
                [v, jnp.full((v.shape[0], 1), 1, v.dtype),
                 jnp.zeros((v.shape[0], dx - 1), v.dtype)], axis=1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)

        def _tail(s, valid):
            m_prev = m_scr[:, 0]  # [bq]
            l_prev = l_scr[:, 0]
            m_cur = jnp.max(s, axis=1)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, None])
            if valid is not None:
                # explicit zero for masked entries: when a whole row is
                # masked, s == m_new == _NEG_INF and exp(0) would be 1
                p = jnp.where(valid, p, 0.0)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if mxu_lsum:
                l_new = l_prev * alpha + pv[:, d]
            else:
                l_new = l_prev * alpha + jnp.sum(p, axis=1)
            acc_scr[...] = acc_scr[...] * alpha[:, None] + pv[:, :d]
            m_scr[...] = m_new[:, None]
            l_scr[...] = l_new[:, None]

        def _masked_tail():
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = kpos < tk  # last block may pad past the real length
            if causal:
                # global positions: scalar-prefetched offsets shift the
                # local indices, so causal masking works across
                # ring-rotated K blocks
                qpos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                valid = valid & ((qoff_ref[0] + qpos) >=
                                 (koff_ref[0] + kpos))
            _tail(jnp.where(valid, s, _NEG_INF), valid)

        # interior fast path: tiles with no padding columns and (if
        # causal) strictly below the diagonal band skip the iota/
        # compare/where masking ops entirely — at bq=bk=1024 that is
        # ~5 of the ~15 VPU ops per element on the T=8192 bench, and
        # interior tiles are the vast majority of alive tiles
        no_pad = True if tk % block_k == 0 else (ki + 1) * block_k <= tk
        if causal:
            interior = _tile_interior(qoff_ref[0], koff_ref[0], qi, ki,
                                      block_q, block_k)
            if no_pad is not True:
                interior = jnp.logical_and(interior, no_pad)
            pl.when(interior)(lambda: _tail(s, None))
            pl.when(jnp.logical_not(interior))(_masked_tail)
        elif tk % block_k == 0:
            _tail(s, None)
        else:
            pl.when(no_pad)(lambda: _tail(s, None))
            pl.when(jnp.logical_not(no_pad))(_masked_tail)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse as a [bq, 1] sublane vector (the same layout the backward
        # reads it in): 4 KB per q-block instead of the 512 KB a
        # 128-lane broadcast would write — over half a GB per step saved
        # at the T=8192 bench shape
        lse = m_scr[:, 0] + jnp.log(l_safe)
        lse_ref[0, 0] = lse[:, None].astype(lse_ref.dtype)


def _sds(shape, dtype):
    """ShapeDtypeStruct annotated as varying over the ambient mapped
    axes.  This clears shard_map's out_shape vma requirement; pallas
    -internal slice ops still trip the strict checker, so callers pass
    check_vma=False on the enclosing shard_map (see
    parallel/ring_attention.ring_attention)."""
    try:
        import jax.core as jc
        vma = frozenset(jc.unsafe_get_axis_names_DO_NOT_USE())
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except Exception:
        pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _dimsem(*sems):
    """Grid dimension semantics: the two outer dims (batch*heads and the
    non-accumulated block axis) are parallel, the innermost accumulation
    axis is arbitrary/sequential — lets Mosaic pipeline DMA + MXU + VPU
    across grid steps instead of treating the whole grid as a chain.
    The scoped-vmem limit is raised from the 16 MB default: the
    interior/masked two-branch tails hold two [bq, bk] fp32 tiles live
    (~18.4 MB at 1024x1024), and v5e has 128 MB of VMEM to spend."""
    return _CompilerParams(dimension_semantics=sems,
                           vmem_limit_bytes=64 * 1024 * 1024)


def _fa_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                q_offset=None, k_offset=None):
    """q/k/v: [BH, T, D] -> (o [BH, T, D], lse [BH, T]).  Optional traced
    q_offset/k_offset (int32 scalars, scalar-prefetched into SMEM) shift
    the causal mask's global positions — the hook ring attention uses to
    run causal flash blocks against rotated K/V shards."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    nq = pl.cdiv(tq, block_q)
    nk = pl.cdiv(tk, block_k)
    # pad sequence dims to block multiples: Mosaic requires block shapes
    # that divide (or equal) the array dims; padded K columns are masked
    # in-kernel via `tk`, padded Q rows are sliced off below
    tq_p, tk_p = nq * block_q, nk * block_k
    if tq_p != tq:
        q = jnp.pad(q, ((0, 0), (0, tq_p - tq), (0, 0)))
    if tk_p != tk:
        k = jnp.pad(k, ((0, 0), (0, tk_p - tk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, tk_p - tk), (0, 0)))
    # fold the softmax scale into q once ([BH, T, D] pass) instead of
    # multiplying every [bq, bk] score tile in-kernel (T/bk times more
    # elements); backward folds it symmetrically (see _fa_backward_pallas)
    q = (q * scale).astype(q.dtype)
    kernel = functools.partial(_fa_kernel, causal=causal,
                               block_q=block_q, block_k=block_k, nk=nk,
                               tk=tk)
    qoff = jnp.asarray([0 if q_offset is None else q_offset], jnp.int32)
    koff = jnp.asarray([0 if k_offset is None else k_offset], jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, i, j, *_: (b, i, 0, 0)),
        ],
        scratch_shapes=[
            # m/l as [bq, 1] sublane vectors: a 128-lane scratch would
            # broadcast-write 512 KB per k-iteration for 4 KB of state
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((bh, tq_p, d), q.dtype),
            _sds((bh, nq, block_q, 1), jnp.float32),
        ],
        compiler_params=_dimsem('parallel', 'parallel', 'arbitrary'),
        interpret=interpret,
    )(qoff, koff, q, k, v)


def _fa_forward_sliced(q, k, v, causal, scale, block_q, block_k,
                       interpret, q_offset=None, k_offset=None):
    tq = q.shape[1]
    o, lse = _fa_forward(q, k, v, causal, scale, block_q, block_k,
                         interpret, q_offset, k_offset)
    bh = lse.shape[0]
    return o[:, :tq], lse.reshape(bh, -1)[:, :tq]


def _dense_ref(q, k, v, causal, scale):
    s = jnp.einsum('btd,bsd->bts', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[1], s.shape[2]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bts,bsd->btd', p, v.astype(jnp.float32))


def _fa_backward(causal, scale, block_k, res, do, dlse=None):
    """Flash backward: recompute scores per K block against the saved
    logsumexp; never materializes [Tq, Tk].  `dlse` is the cotangent of
    the logsumexp output (d lse/d s = p, so it folds into ds)."""
    q, k, v, q_off, k_off, o, lse = res
    qf = q.astype(jnp.float32)
    do = do.astype(jnp.float32)
    of = o.astype(jnp.float32)
    di = jnp.sum(do * of, axis=-1)  # [BH, T]
    if dlse is not None:
        di = di - dlse.astype(jnp.float32)  # ds += p * dlse
    tk = k.shape[1]
    bk = min(block_k, tk)
    nk = pl.cdiv(tk, bk)
    pad = nk * bk - tk
    kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
    kpos0 = jnp.arange(nk) * bk
    tq = q.shape[1]
    qpos = q_off + jnp.arange(tq)

    def kblock(carry, inp):
        dq_acc = carry
        kb, vb, k0 = inp  # [BH, bk, D], [BH, bk, D], scalar
        kf = kb.astype(jnp.float32)
        vf = vb.astype(jnp.float32)
        s = jnp.einsum('btd,bsd->bts', qf, kf) * scale
        kpos = k0 + jnp.arange(bk)
        valid = (kpos < tk)[None, None, :]
        if causal:
            valid = valid & (qpos[:, None] >=
                             (k_off + kpos)[None, :])[None]
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse[:, :, None])  # [BH, Tq, bk]
        p = jnp.where(valid, p, 0.0)
        dv = jnp.einsum('bts,btd->bsd', p, do)
        dp = jnp.einsum('btd,bsd->bts', do, vf)
        ds = p * (dp - di[:, :, None]) * scale
        dq_acc = dq_acc + jnp.einsum('bts,bsd->btd', ds, kf)
        dk = jnp.einsum('bts,btd->bsd', ds, qf)
        return dq_acc, (dk, dv)

    kb = kp.reshape(kp.shape[0], nk, bk, -1).swapaxes(0, 1)
    vb = vp.reshape(vp.shape[0], nk, bk, -1).swapaxes(0, 1)
    dq, (dks, dvs) = jax.lax.scan(
        kblock, jnp.zeros_like(qf), (kb, vb, kpos0))
    dk = dks.swapaxes(0, 1).reshape(kp.shape)[:, :tk]
    dv = dvs.swapaxes(0, 1).reshape(vp.shape)[:, :tk]
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


def _bwd_common(q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref, *,
                causal, q0, k0, qoff, koff, bq, bk, masked):
    """Shared per-tile flash backward math: returns
    (q, do, k, p, ds) with q/do/k in storage dtype (bf16 matmul inputs
    at full MXU rate, fp32 accumulate) and p/ds [bq, bk] fp32.

    q arrives pre-scaled (s and hence p/lse agree with the forward);
    ds therefore carries no scale factor — dk = ds^T q_scaled is exact,
    and the dq kernel multiplies its accumulator by scale once at
    flush.  Padding needs no mask here: padded q/do/lse/di rows are
    zeros (p row = 1 but do/di = 0 ⇒ dv/ds contributions vanish),
    padded k rows zero out dq contributions, and padded dk/dv rows are
    sliced off by the caller — so `masked` (a static flag; the caller
    branches on the tile predicate) is only True on causal
    diagonal-band tiles."""
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0]  # [bq, 1] sublane vector
    di = di_ref[0, 0]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    p = jnp.exp(s - lse)
    if masked:
        qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        p = jnp.where((qoff + qpos) >= (koff + kpos), p, 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - di)
    return q, do, k, p, ds


def _fa_bwd_dkv_kernel(qoff_ref, koff_ref, q_ref, do_ref, lse_ref, di_ref,
                       k_ref, v_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                       causal, block_q, block_k, nq):
    ki = pl.program_id(1)
    qi = pl.program_id(2)  # innermost: accumulate over q blocks

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    # causal dead-tile skip: the whole tile is masked when its newest
    # query precedes its oldest key
    alive = True
    if causal:
        alive = _tile_alive(qoff_ref[0], koff_ref[0], qi, ki,
                            block_q, block_k)

    def _go(masked):
        q, do, k, p, ds = _bwd_common(
            q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref,
            causal=causal, q0=qi * block_q, k0=ki * block_k,
            qoff=qoff_ref[0], koff=koff_ref[0], bq=block_q, bk=block_k,
            masked=masked)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # interior tiles (strictly below the diagonal band) skip the
        # iota/compare/where masking ops — see _bwd_common for why
        # padding never needs a mask in the backward
        interior = _tile_interior(qoff_ref[0], koff_ref[0], qi, ki,
                                  block_q, block_k)
        pl.when(interior)(lambda: _go(False))
        pl.when(jnp.logical_and(alive, jnp.logical_not(interior)))(
            lambda: _go(True))
    else:
        _go(False)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(qoff_ref, koff_ref, q_ref, do_ref, lse_ref, di_ref,
                      k_ref, v_ref, dq_ref, dq_scr, *, scale, causal,
                      block_q, block_k, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)  # innermost: accumulate over k blocks

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr[...])

    alive = True
    if causal:
        alive = _tile_alive(qoff_ref[0], koff_ref[0], qi, ki,
                            block_q, block_k)

    def _go(masked):
        _q, _do, k, p, ds = _bwd_common(
            q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref,
            causal=causal, q0=qi * block_q, k0=ki * block_k,
            qoff=qoff_ref[0], koff=koff_ref[0], bq=block_q, bk=block_k,
            masked=masked)
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        interior = _tile_interior(qoff_ref[0], koff_ref[0], qi, ki,
                                  block_q, block_k)
        pl.when(interior)(lambda: _go(False))
        pl.when(jnp.logical_and(alive, jnp.logical_not(interior)))(
            lambda: _go(True))
    else:
        _go(False)

    @pl.when(ki == nk - 1)
    def _finish():
        # ds carried no scale in-kernel (q was pre-scaled); fold the
        # d(scale*qk)/dq chain factor in once per accumulator flush
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _fa_bwd_fused_kernel(qoff_ref, koff_ref, q_ref, do_ref, lse_ref,
                         di_ref, k_ref, v_ref, dk_ref, dv_ref, dq_ref,
                         dk_scr, dv_scr, dq_acc, *, scale, causal,
                         block_q, block_k, nq, nk):
    """One k-major pass computing dk, dv AND dq: recomputes s/dp once
    per tile instead of once in each of the split kernels — 5 matmuls
    per tile instead of 7 (the split pair's s+dp are exactly the two
    redundant ones).  dq accumulates in a persistent [tq_p, d] fp32
    VMEM scratch across the outer k loop (callers gate the fused path
    on that scratch fitting VMEM; long-T falls back to the split
    kernels).  Grid (bh, nk, nq): k blocks outer, q blocks inner."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    qs = pl.dslice(qi * block_q, block_q)

    @pl.when(qi == 0)
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    @pl.when(ki == 0)
    def _init_dq():
        # unconditional (outside the alive gate): with ring offsets a
        # q block can have no alive k tile at all and must still flush
        # zeros
        dq_acc[qs, :] = jnp.zeros((block_q, dq_acc.shape[-1]),
                                  jnp.float32)

    alive = True
    if causal:
        alive = _tile_alive(qoff_ref[0], koff_ref[0], qi, ki,
                            block_q, block_k)

    def _go(masked):
        q, do, k, p, ds = _bwd_common(
            q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref,
            causal=causal, q0=qi * block_q, k0=ki * block_k,
            qoff=qoff_ref[0], koff=koff_ref[0], bq=block_q, bk=block_k,
            masked=masked)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dsl = ds.astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            dsl, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_acc[qs, :] += jax.lax.dot_general(
            dsl, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        interior = _tile_interior(qoff_ref[0], koff_ref[0], qi, ki,
                                  block_q, block_k)
        pl.when(interior)(lambda: _go(False))
        pl.when(jnp.logical_and(alive, jnp.logical_not(interior)))(
            lambda: _go(True))
    else:
        _go(False)

    @pl.when(qi == nq - 1)
    def _finish_kv():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    @pl.when(ki == nk - 1)
    def _finish_dq():
        # ds carried no scale in-kernel (q was pre-scaled): fold the
        # chain factor in at the single flush
        dq_ref[0, qs, :] = (dq_acc[qs, :] * scale).astype(dq_ref.dtype)


# cap on the fused backward's persistent dq accumulator (fp32 [tq_p, d]
# VMEM scratch); longer sequences fall back to the split kernels
_FUSED_DQ_BYTES = 16 * 1024 * 1024


def _pow2_floor(n):
    """Largest power of two <= n (n >= 1)."""
    return 1 << (int(n).bit_length() - 1)


def _clamp_blocks(b1, b2, t):
    """Clamp the two split kernels' block sizes on one axis so the
    SHARED padding (lcm of the two) stays bounded.  min(block, t) alone
    can hand lcm a non-power-of-two: with the default d<=64 tiles,
    tk=1100 clamps bk1 to 1100 and lcm(1100, 1024) = 281600 — a 256x
    padding blowup in the k/v/dk/dv buffers and grid (ADVICE.md).  When
    the naive clamp's lcm exceeds max(b1, b2), both blocks drop to the
    largest power of two <= min(block, t); powers of two keep
    lcm == max, so padding is bounded by one block.  Exactly-dividing
    cases (t a multiple of both clamps) keep the naive clamp and its
    zero padding."""
    b1, b2 = min(b1, t), min(b2, t)
    if math.lcm(b1, b2) > max(b1, b2):
        b1, b2 = _pow2_floor(b1), _pow2_floor(b2)
    return b1, b2


def _shared_padding(tq, tk, tiles):
    """Per-axis clamped block pairs + the shared padded lengths both
    split backward kernels read from one padded buffer.  Split out of
    _fa_backward_pallas so the padding arithmetic is unit-testable at
    adversarial lengths."""
    (bq1, bk1), (bq2, bk2) = tiles
    bq1, bq2 = _clamp_blocks(bq1, bq2, tq)
    bk1, bk2 = _clamp_blocks(bk1, bk2, tk)
    tq_p = pl.cdiv(tq, math.lcm(bq1, bq2)) * math.lcm(bq1, bq2)
    tk_p = pl.cdiv(tk, math.lcm(bk1, bk2)) * math.lcm(bk1, bk2)
    return (bq1, bk1), (bq2, bk2), tq_p, tk_p


def _fa_backward_pallas(causal, scale, tiles, res, do,
                        dlse, interpret, phases=('dkv', 'dq'),
                        allow_fused=True):
    """Pallas flash backward.  Default is ONE fused k-major kernel
    (grid bh, nk, nq) producing dk, dv and dq with a single s/dp
    recompute per tile — 5 matmuls instead of the split pair's 7.  The
    split kernels (dk/dv: grid (bh, nk, nq); dq: grid (bh, nq, nk))
    remain for long sequences whose [tq, d] dq accumulator would not
    fit VMEM, for per-phase perf runs, and for the
    PADDLE_TPU_FLASH_BWD_SPLIT A/B gate.  All recompute p from the
    saved lse in VMEM — the [Tq, Tk] lattice never touches HBM (the
    jax-scan fallback `_fa_backward` streams [Tq, block_k] slabs
    through HBM instead).
    `tiles` = ((bq_dkv, bk_dkv), (bq_dq, bk_dq)): the two split
    kernels have different best tiles on v5e (dkv likes wide k blocks —
    its accumulators live on the k axis); the fused kernel uses the
    dkv pair.  `phases` lets the perf harness time each split kernel
    alone (skipped grads come back as None)."""
    q, k, v, q_off, k_off, o, lse = res
    bh, tq, d = q.shape
    tk = k.shape[1]
    # one shared padding serves both kernels: pad to the lcm of the two
    # (clamped — see _clamp_blocks) block sizes on each axis
    (bq1, bk1), (bq2, bk2), tq_p, tk_p = _shared_padding(tq, tk, tiles)

    dof = do.astype(jnp.float32)
    di = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # [BH, Tq]
    if dlse is not None:
        di = di - dlse.astype(jnp.float32)

    # pre-scale q (one [BH, T, D] pass) so the kernels never touch the
    # [bq, bk] score tiles with a scale multiply; dq re-applies scale at
    # its accumulator flush (see _fa_bwd_dq_kernel._finish)
    qp = jnp.pad((q * scale).astype(q.dtype),
                 ((0, 0), (0, tq_p - tq), (0, 0)))
    dop = jnp.pad(do, ((0, 0), (0, tq_p - tq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, tk_p - tk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, tk_p - tk), (0, 0)))
    # lse/di ride as [BH, nq, bq, 1] sublane-vector blocks: 512B per
    # tile visit instead of the 64KB a 128-lane broadcast would re-read
    lse_p = jnp.pad(lse, ((0, 0), (0, tq_p - tq)))
    di_p = jnp.pad(di, ((0, 0), (0, tq_p - tq)))

    qoff = jnp.asarray([0 if q_off is None else q_off], jnp.int32)
    koff = jnp.asarray([0 if k_off is None else k_off], jnp.int32)

    dk = dv = dq = None
    if (allow_fused and 'dkv' in phases and 'dq' in phases
            and tq_p * d * 4 <= _FUSED_DQ_BYTES):
        bq, bk = bq1, bk1
        nq, nk = tq_p // bq, tk_p // bk
        fused_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nk, nq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, j, i, *_: (b, i, 0)),
                pl.BlockSpec((1, bq, d), lambda b, j, i, *_: (b, i, 0)),
                pl.BlockSpec((1, 1, bq, 1),
                             lambda b, j, i, *_: (b, i, 0, 0)),
                pl.BlockSpec((1, 1, bq, 1),
                             lambda b, j, i, *_: (b, i, 0, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
                # dq rides one whole-[tq_p, d] block per bh, flushed
                # from the persistent accumulator at the last k block
                pl.BlockSpec((1, tq_p, d), lambda b, j, i, *_: (b, 0, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                            pltpu.VMEM((bk, d), jnp.float32),
                            pltpu.VMEM((tq_p, d), jnp.float32)],
        )
        dk, dv, dq = pl.pallas_call(
            functools.partial(_fa_bwd_fused_kernel, scale=scale,
                              causal=causal, block_q=bq, block_k=bk,
                              nq=nq, nk=nk),
            grid_spec=fused_spec,
            out_shape=[_sds((bh, tk_p, d), k.dtype),
                       _sds((bh, tk_p, d), v.dtype),
                       _sds((bh, tq_p, d), q.dtype)],
            # the k axis carries the dq accumulation -> arbitrary
            compiler_params=_dimsem('parallel', 'arbitrary', 'arbitrary'),
            interpret=interpret,
        )(qoff, koff, qp, dop,
          lse_p.reshape(bh, nq, bq, 1), di_p.reshape(bh, nq, bq, 1),
          kp, vp)
        return dq[:, :tq], dk[:, :tk], dv[:, :tk]

    if 'dkv' in phases:
        bq, bk = bq1, bk1
        nq, nk = tq_p // bq, tk_p // bk
        dkv_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nk, nq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, j, i, *_: (b, i, 0)),
                pl.BlockSpec((1, bq, d), lambda b, j, i, *_: (b, i, 0)),
                pl.BlockSpec((1, 1, bq, 1),
                             lambda b, j, i, *_: (b, i, 0, 0)),
                pl.BlockSpec((1, 1, bq, 1),
                             lambda b, j, i, *_: (b, i, 0, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i, *_: (b, j, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                            pltpu.VMEM((bk, d), jnp.float32)],
        )
        dk, dv = pl.pallas_call(
            functools.partial(_fa_bwd_dkv_kernel,
                              causal=causal, block_q=bq, block_k=bk,
                              nq=nq),
            grid_spec=dkv_spec,
            out_shape=[_sds((bh, tk_p, d), k.dtype),
                       _sds((bh, tk_p, d), v.dtype)],
            compiler_params=_dimsem('parallel', 'parallel', 'arbitrary'),
            interpret=interpret,
        )(qoff, koff, qp, dop,
          lse_p.reshape(bh, nq, bq, 1), di_p.reshape(bh, nq, bq, 1),
          kp, vp)

    if 'dq' in phases:
        bq, bk = bq2, bk2
        nq, nk = tq_p // bq, tk_p // bk
        dq_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, bq, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, 1, bq, 1),
                             lambda b, i, j, *_: (b, i, 0, 0)),
                pl.BlockSpec((1, 1, bq, 1),
                             lambda b, i, j, *_: (b, i, 0, 0)),
                pl.BlockSpec((1, bk, d), lambda b, i, j, *_: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, i, j, *_: (b, j, 0)),
            ],
            out_specs=[pl.BlockSpec((1, bq, d),
                                    lambda b, i, j, *_: (b, i, 0))],
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        )
        dq, = pl.pallas_call(
            functools.partial(_fa_bwd_dq_kernel, scale=scale,
                              causal=causal, block_q=bq, block_k=bk,
                              nk=nk),
            grid_spec=dq_spec,
            out_shape=[_sds((bh, tq_p, d), q.dtype)],
            compiler_params=_dimsem('parallel', 'parallel', 'arbitrary'),
            interpret=interpret,
        )(qoff, koff, qp, dop,
          lse_p.reshape(bh, nq, bq, 1), di_p.reshape(bh, nq, bq, 1),
          kp, vp)

    return (None if dq is None else dq[:, :tq],
            None if dk is None else dk[:, :tk],
            None if dv is None else dv[:, :tk])


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_with_lse(q, k, v, q_off, k_off, causal, scale, tiles,
                    interpret, bwd_mode):
    """[BH, T, D] kernel entry returning (o, lse); differentiable —
    the backward folds both cotangents into one flash recompute.
    q_off/k_off are traced int32 scalars shifting the causal mask.
    tiles = ((bq, bk) for fwd, dkv, dq) — static, per-phase.
    bwd_mode ('pallas'|'scan') is part of the vjp cache key, so the env
    gates that select it (resolved by the caller) take effect on the
    next call instead of silently needing jax.clear_caches()."""
    return _fa_forward_sliced(q, k, v, causal, scale, tiles[0][0],
                              tiles[0][1], interpret, q_off, k_off)


def _flash_fwd(q, k, v, q_off, k_off, causal, scale, tiles,
               interpret, bwd_mode):
    o, lse = _fa_forward_sliced(q, k, v, causal, scale, tiles[0][0],
                                tiles[0][1], interpret, q_off, k_off)
    return (o, lse), (q, k, v, q_off, k_off, o, lse)


def _bwd_mode_from_env(interpret):
    """PADDLE_TPU_FLASH_BWD_SCAN forces the jax-scan path on TPU (A/B
    numerics); PADDLE_TPU_FLASH_BWD_PALLAS forces the Pallas kernels
    (interpret mode) off-TPU; PADDLE_TPU_FLASH_BWD_SPLIT forces the
    split dkv/dq kernel pair instead of the fused k-major kernel."""
    if _env_on('PADDLE_TPU_FLASH_BWD_PALLAS'):
        return ('pallas_split' if _env_on('PADDLE_TPU_FLASH_BWD_SPLIT')
                else 'pallas')
    if interpret or _env_on('PADDLE_TPU_FLASH_BWD_SCAN'):
        return 'scan'
    if _env_on('PADDLE_TPU_FLASH_BWD_SPLIT'):
        return 'pallas_split'
    return 'pallas'


def _flash_bwd(causal, scale, tiles, interpret, bwd_mode,
               res, cts):
    do, dlse = cts
    if bwd_mode in ('pallas', 'pallas_split'):
        dq, dk, dv = _fa_backward_pallas(
            causal, scale, tiles[1:], res, do, dlse,
            interpret=interpret,
            allow_fused=(bwd_mode == 'pallas'))
    else:  # CPU: the jax-scan recompute (fast under interpret-free jit)
        dq, dk, dv = _fa_backward(causal, scale, tiles[1][1], res, do,
                                  dlse)
    f0 = _np.zeros((), jax.dtypes.float0)  # int operands: zero cotangent
    return dq, dk, dv, f0, f0


_flash_with_lse.defvjp(_flash_fwd, _flash_bwd)


def _to_bhtd(q, k, v):
    """[B, T, H, D] (or [BH, T, D] pass-through) -> flattened [B*H, T, D]
    plus the info to restore — the single home of the layout contract."""
    if q.ndim == 3:
        return q, k, v, None
    b, tq, h, d = q.shape
    tk = k.shape[1]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    return qf, kf, vf, (b, h, tq, d)


def attention_with_lse(q, k, v, causal=False, scale=None, block_q=None,
                       block_k=None, q_offset=0, k_offset=0,
                       interpret=None):
    """Fused attention returning (o, lse) for online-softmax merging
    (ring attention's local blocks).  q/k/v [B, T, H, D] -> o same shape,
    lse [B, H, T].  Differentiable.  q_offset/k_offset (traced int ok)
    place the local blocks on the global sequence axis for causal
    masking across ring-rotated K/V shards."""
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    # per-phase default tiles from the v5e sweep (benchmarks/exp_flash,
    # steps=100 chains — short chains are launch-overhead-dominated):
    # fwd 24.4 ms at 2048^2 vs 26.1 at 1024^2; the fused backward
    # (which reads the dkv slot) 48.7 ms at (1024, 2048) vs 50.1 at
    # 1024^2 — its accumulators live on the k axis; d=128 halves
    # everything for VMEM.  Explicit block_q/block_k pin all phases.
    if block_q is None and block_k is None:
        tiles = (((2048, 2048), (1024, 2048), (1024, 1024))
                 if q.shape[-1] <= 64
                 else ((512, 512), (512, 512), (512, 512)))
    else:
        bq = int(block_q if block_q is not None else block_k)
        bk = int(block_k if block_k is not None else block_q)
        tiles = ((bq, bk),) * 3
    qf, kf, vf, restore = _to_bhtd(q, k, v)
    qo = jnp.asarray(q_offset, jnp.int32)
    ko = jnp.asarray(k_offset, jnp.int32)
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    o, lse = _flash_with_lse(qf, kf, vf, qo, ko, bool(causal),
                             float(scale), tiles,
                             bool(interpret),
                             _bwd_mode_from_env(bool(interpret)))
    if restore is None:
        return o, lse
    b, h, tq, d = restore
    o = o.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    return o, lse.reshape(b, h, tq)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None):
    """Fused attention over [B, T, H, D] (or [BH, T, D]) tensors.

    Returns softmax(q k^T * scale [+ causal mask]) v with O(block) live
    memory on-chip.  Differentiable (Pallas backward on TPU, flash
    recompute scan elsewhere).  Default tiles are head-dim-aware and
    per-phase (see attention_with_lse); explicit block_q/block_k pin
    every phase to one tile for testing.
    """
    squeeze = False
    if q.ndim == 3:
        q4, k4, v4 = (x[:, :, None, :] for x in (q, k, v))
        squeeze = True
    else:
        q4, k4, v4 = q, k, v
    o, _lse = attention_with_lse(q4, k4, v4, causal=causal, scale=scale,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret)
    return o[:, :, 0, :] if squeeze else o
