"""Pallas TPU kernels (SURVEY §6.6): hand-fused hot ops XLA won't fuse.

Tests run them with interpret=True on CPU; on a TPU backend the same
kernels compile to Mosaic.
"""
from .dense_update import (dense_apply_adam,  # noqa: F401
                           dense_apply_mode, dense_apply_momentum,
                           dense_apply_sgd)
from .flash_attention import flash_attention  # noqa: F401
from .lstm_cell import gru_scan, lstm_scan  # noqa: F401
from .table_update import (sparse_apply_adagrad,  # noqa: F401
                           sparse_apply_adam, sparse_apply_mode,
                           sparse_apply_sgd)

__all__ = ['flash_attention', 'lstm_scan', 'gru_scan',
           'sparse_apply_sgd', 'sparse_apply_adagrad',
           'sparse_apply_adam', 'sparse_apply_mode',
           'dense_apply_sgd', 'dense_apply_momentum',
           'dense_apply_adam', 'dense_apply_mode']
