"""Pallas API compatibility shims shared by the kernel modules.

jax renamed ``TPUCompilerParams`` -> ``CompilerParams``; the TPU box and
the CPU-CI container sit on opposite sides of the rename, so every
kernel resolves it through this one alias."""
from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, 'CompilerParams',
                         getattr(pltpu, 'TPUCompilerParams', None))
if CompilerParams is None:  # pragma: no cover - future-proofing
    raise ImportError(
        'jax.experimental.pallas.tpu exposes neither CompilerParams nor '
        'TPUCompilerParams; update paddle_tpu/ops/pallas/_compat.py for '
        'this jax version')
