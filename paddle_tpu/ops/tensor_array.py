"""LoDTensorArray ops (O17).

Reference parity: paddle/operators/tensor_array_read_write_op.cc,
lod_tensor_to_array / array_to_lod_tensor, lod_rank_table,
max_sequence_len, shrink_rnn_memory.

TPU-native design: an array is a `TArray` pytree — a preallocated stacked
buffer [N, ...] plus a traced int32 `size` — so reads/writes are
`dynamic_(update_)slice` on static shapes and an array can ride a
`lax.scan`/`while` carry.  Writes past the preallocated capacity are a
trace-time error (capacity comes from the time axis or the While layer's
max_iters), not a silent reallocation: growth is a host concept TPUs
don't have.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out

__all__ = ['TArray']


class TArray(object):
    """Stacked tensor array: data [N, ...], size (traced int32)."""

    def __init__(self, data, size):
        self.data = data
        self.size = size

    def tree_flatten(self):
        return (self.data, self.size), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def capacity(self):
        return self.data.shape[0]


jax.tree_util.register_pytree_node(
    TArray, lambda a: a.tree_flatten(),
    lambda aux, ch: TArray.tree_unflatten(aux, ch))


class EmptyTArray(object):
    """A created-but-never-written array: carries only its dtype.  The
    first write_to_array allocates the real buffer (capacity attr or
    DEFAULT_CAPACITY)."""

    def __init__(self, dtype='float32'):
        self.dtype = dtype


jax.tree_util.register_pytree_node(
    EmptyTArray, lambda a: ((), a.dtype),
    lambda dtype, ch: EmptyTArray(dtype))

DEFAULT_CAPACITY = 128


def _as_index(i):
    i = jnp.asarray(i)
    return i.reshape(()).astype(jnp.int32)


@register_op('create_array')
def _create_array(ctx, ins, attrs):
    """Create an array.  With `capacity` + `elem_shape` attrs the buffer
    is allocated now; otherwise the first write_to_array allocates it."""
    dtype = attrs.get('elem_dtype', 'float32')
    if 'capacity' in attrs and 'elem_shape' in attrs:
        cap = int(attrs['capacity'])
        shape = tuple(int(d) for d in attrs['elem_shape'])
        data = jnp.zeros((cap,) + shape, dtype=dtype)
        return out(TArray(data, jnp.asarray(0, jnp.int32)))
    return out(EmptyTArray(dtype))


@register_op('write_to_array')
def _write_to_array(ctx, ins, attrs):
    arr = first(ins, 'X' if 'X' in ins else 'Array')
    x = first(ins, 'V' if 'V' in ins else 'X')
    i = _as_index(first(ins, 'I'))
    x = jnp.asarray(x)
    if isinstance(arr, EmptyTArray):
        cap = int(attrs.get('capacity', DEFAULT_CAPACITY))
        arr = TArray(jnp.zeros((cap,) + x.shape, dtype=x.dtype),
                     jnp.asarray(0, jnp.int32))
    elif not isinstance(arr, TArray):
        raise TypeError("write_to_array target is not a tensor array")
    if x.shape != arr.data.shape[1:]:
        raise ValueError(
            "write_to_array shape %s != array element shape %s" %
            (x.shape, arr.data.shape[1:]))
    data = jax.lax.dynamic_update_index_in_dim(
        arr.data, x.astype(arr.data.dtype), i, 0)
    size = jnp.maximum(arr.size, i + 1)
    return out(TArray(data, size))


@register_op('read_from_array')
def _read_from_array(ctx, ins, attrs):
    arr = first(ins, 'X' if 'X' in ins else 'Array')
    i = _as_index(first(ins, 'I'))
    return out(jax.lax.dynamic_index_in_dim(arr.data, i, 0,
                                            keepdims=False))


@register_op('array_length')
def _array_length(ctx, ins, attrs):
    arr = first(ins, 'X')
    return out(arr.size.reshape(1).astype(jnp.int32))


@register_op('lod_tensor_to_array')
def _lod_tensor_to_array(ctx, ins, attrs):
    """Split padded [B, T, ...] into a T-entry array of [B, ...] steps.

    The reference splits by LoD rank table (sequences sorted desc by
    length, each entry holding the still-active rows); on TPU we keep the
    batch dense — entry t is simply timestep t for all rows and masking
    handles inactive rows downstream (see DynamicRNN)."""
    x = first(ins, 'X')
    data = jnp.moveaxis(x, 1, 0)  # [T, B, ...]
    return out(TArray(data, jnp.asarray(x.shape[1], jnp.int32)))


@register_op('array_to_lod_tensor')
def _array_to_lod_tensor(ctx, ins, attrs):
    arr = first(ins, 'X')
    return out(jnp.moveaxis(arr.data, 0, 1))  # [B, T, ...]


@register_op('lod_rank_table')
def _lod_rank_table(ctx, ins, attrs):
    """The reference rank table sorts sequences by length for batch
    shrinking.  The TPU representation is just the lengths vector (no
    reordering — masks replace shrinking); ops that consume the table
    (max_sequence_len, shrink_memory) read it directly."""
    x = first(ins, 'X')
    ln = first(ins, 'XLen')
    if ln is None:
        ln = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    return out(ln.astype(jnp.int32))


@register_op('max_sequence_len')
def _max_sequence_len(ctx, ins, attrs):
    table = first(ins, 'RankTable')
    return out(jnp.max(table).reshape(1).astype(jnp.int32))


@register_op('shrink_rnn_memory')
def _shrink_rnn_memory(ctx, ins, attrs):
    """Reference: drops finished sequences' rows at step I.  Dense-batch
    equivalent: zero the memory rows whose sequence already ended (the
    scan carries full batch; masking preserves numerics)."""
    x = first(ins, 'X')
    table = first(ins, 'RankTable')
    i = _as_index(first(ins, 'I'))
    active = (table > i)
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    return out(jnp.where(active.reshape(shape), x, 0))
