"""Normalization ops.

Reference parity: paddle/operators/{batch_norm_op,layer_norm?,lrn_op}.*.
Batch-norm statistics are computed/kept in float32 even for bf16 activations
(TPU mixed-precision recipe); running-stat updates ride the executor's
persistable-state mechanism (MeanOut/VarianceOut alias Mean/Variance).
"""
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first


@register_op('batch_norm')
def _batch_norm(ctx, ins, attrs):
    x = first(ins, 'X')
    scale = first(ins, 'Scale').astype(jnp.float32)
    bias = first(ins, 'Bias').astype(jnp.float32)
    mean = first(ins, 'Mean').astype(jnp.float32)
    var = first(ins, 'Variance').astype(jnp.float32)
    eps = attrs.get('epsilon', 1e-5)
    momentum = attrs.get('momentum', 0.9)
    is_test = attrs.get('is_test', False)
    layout = attrs.get('data_layout', 'NCHW')

    ch_axis = 1 if layout == 'NCHW' else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    xf = x.astype(jnp.float32)
    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_var = var
    else:
        use_mean = jnp.mean(xf, axis=axes)
        use_var = jnp.var(xf, axis=axes)
        mean_out = momentum * mean + (1 - momentum) * use_mean
        var_out = momentum * var + (1 - momentum) * use_var
        saved_mean = use_mean
        saved_var = use_var
    inv = jnp.asarray(1.0, jnp.float32) / jnp.sqrt(use_var + eps)
    y = (xf - use_mean.reshape(bshape)) * inv.reshape(bshape) * \
        scale.reshape(bshape) + bias.reshape(bshape)
    return {
        'Y': [y.astype(x.dtype)],
        'MeanOut': [mean_out],
        'VarianceOut': [var_out],
        'SavedMean': [saved_mean],
        'SavedVariance': [saved_var],
    }


@register_op('layer_norm')
def _layer_norm(ctx, ins, attrs):
    x = first(ins, 'X')
    scale = first(ins, 'Scale')
    bias = first(ins, 'Bias')
    eps = attrs.get('epsilon', 1e-5)
    begin = attrs.get('begin_norm_axis', 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(
            (1,) * begin + x.shape[begin:])
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(
            (1,) * begin + x.shape[begin:])
    return {'Y': [y.astype(x.dtype)], 'Mean': [mean.reshape(x.shape[:begin])],
            'Variance': [var.reshape(x.shape[:begin])]}


@register_op('lrn')
def _lrn(ctx, ins, attrs):
    """Local response normalization across channels (operators/lrn_op.cc):
    Out = X / (k + alpha * sum_{local} X^2)^beta."""
    x = first(ins, 'X')  # NCHW
    n = attrs.get('n', 5)
    k = attrs.get('k', 2.0)
    alpha = attrs.get('alpha', 1e-4)
    beta = attrs.get('beta', 0.75)
    xf = x.astype(jnp.float32)
    sq = jnp.square(xf)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    acc = jnp.zeros_like(xf)
    for i in range(n):
        acc = acc + pad[:, i:i + x.shape[1]]
    mid = k + alpha * acc
    return {'Out': [(xf / jnp.power(mid, beta)).astype(x.dtype)],
            'MidOut': [mid]}
