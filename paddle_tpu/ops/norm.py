"""Normalization ops.

Reference parity: paddle/operators/{batch_norm_op,layer_norm?,lrn_op}.*.
Batch-norm statistics are computed/kept in float32 even for bf16 activations
(TPU mixed-precision recipe); running-stat updates ride the executor's
persistable-state mechanism (MeanOut/VarianceOut alias Mean/Variance).

Training batch_norm carries a hand-written VJP: autodiff through
jnp.mean/var re-reads the full activation several times per BN layer in
backward, and ResNet-50's 53 BN layers made that ~1/3 of the train
step's HBM traffic.  The fused form is two passes: one reduction pass
producing sum(dy) and sum(dy*xhat) (reads stay bf16, accumulation f32),
and one elementwise pass dx = scale*inv*(dy - s1/N - xhat*s2/N) that XLA
fuses into the adjacent conv backward.
"""
import functools

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _bn_train(x, scale, bias, shift, axes, eps, use_shift):
    y, m, v, _inv = _bn_train_fwd_impl(x, scale, bias, shift, axes, eps,
                                       use_shift)
    return y, m, v


def _bn_train_fwd_impl(x, scale, bias, shift, axes, eps, use_shift):
    """use_shift=False: two-pass stats (mean, then E[(x-m)^2]) — exact,
    used off-TPU.  use_shift=True: SINGLE-pass stats shifted by the
    running mean, var = E[(x-s)^2] - (m-s)^2, both reductions reading x
    once (multi-output fusion, bf16 reads, f32 accumulate).  Plain
    E[x^2]-m^2 cancels catastrophically for large-mean activations; the
    running-mean shift keeps |m-s| ~ 0 (it tracks the batch mean), so
    the subtraction is well-conditioned wherever the running stats have
    warmed up, and at init (s=0) it degrades to the centered case that
    fresh nets with near-zero-mean activations occupy anyway."""
    bshape = _bcast_shape(x, axes)
    if not use_shift:
        m = jnp.mean(x, axis=axes, dtype=jnp.float32)
        v = jnp.mean(jnp.square(x.astype(jnp.float32)
                                - m.reshape(bshape)), axis=axes)
    else:
        s = shift.astype(jnp.float32)
        xs = x.astype(jnp.float32) - s.reshape(bshape)
        m_s = jnp.mean(xs, axis=axes)
        msq_s = jnp.mean(jnp.square(xs), axis=axes)
        m = m_s + s
        v = jnp.maximum(msq_s - m_s * m_s, 0.0)
    inv = jax.lax.rsqrt(v + eps)
    y = ((x.astype(jnp.float32) - m.reshape(bshape)) * inv.reshape(bshape)
         * scale.reshape(bshape) + bias.reshape(bshape))
    return y.astype(x.dtype), m, v, inv


def _bcast_shape(x, axes):
    return tuple(1 if i in axes else x.shape[i] for i in range(x.ndim))


def _bn_fwd(x, scale, bias, shift, axes, eps, use_shift):
    y, m, v, inv = _bn_train_fwd_impl(x, scale, bias, shift, axes, eps,
                                      use_shift)
    return (y, m, v), (x, scale, m, inv)


def _bn_bwd(axes, eps, use_shift, res, cts):
    x, scale, m, inv = res
    dy, dm_ct, dv_ct = cts
    n = 1
    for a in axes:
        n *= x.shape[a]
    n = float(n)
    bshape = _bcast_shape(x, axes)
    mb = m.reshape(bshape)
    invb = inv.reshape(bshape)
    dyf = dy.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    xhat = (xf - mb) * invb
    # one fused reduction pass over (dy, x)
    s1 = jnp.sum(dyf, axis=axes)                    # = dbias
    s2 = jnp.sum(dyf * xhat, axis=axes)             # = dscale
    dx = (scale.reshape(bshape) * invb) * (
        dyf - (s1 / n).reshape(bshape) - xhat * (s2 / n).reshape(bshape))
    # cotangents of the returned batch stats — zero constants on the
    # loss path (running-stat updates aren't differentiated), which
    # XLA's algebraic simplifier erases; kept for exactness elsewhere
    dx = dx + (dm_ct / n).reshape(bshape)
    dx = dx + (dv_ct * 2.0 / n).reshape(bshape) * (xf - mb)
    # the shift is running state, not a differentiated input
    return dx.astype(x.dtype), s2, s1, jnp.zeros_like(m)


_bn_train.defvjp(_bn_fwd, _bn_bwd)


@register_op('batch_norm')
def _batch_norm(ctx, ins, attrs):
    x = first(ins, 'X')
    scale = first(ins, 'Scale').astype(jnp.float32)
    bias = first(ins, 'Bias').astype(jnp.float32)
    mean = first(ins, 'Mean').astype(jnp.float32)
    var = first(ins, 'Variance').astype(jnp.float32)
    eps = attrs.get('epsilon', 1e-5)
    momentum = attrs.get('momentum', 0.9)
    is_test = attrs.get('is_test', False)
    layout = attrs.get('data_layout', 'NCHW')

    ch_axis = 1 if layout == 'NCHW' else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    if is_test:
        inv = jnp.asarray(1.0, jnp.float32) / jnp.sqrt(var + eps)
        y = (x.astype(jnp.float32) - mean.reshape(bshape)) * \
            inv.reshape(bshape) * scale.reshape(bshape) + \
            bias.reshape(bshape)
        return {
            'Y': [y.astype(x.dtype)],
            'MeanOut': [mean],
            'VarianceOut': [var],
            'SavedMean': [mean],
            'SavedVariance': [var],
        }
    # single-pass shifted stats on TPU (one read of x); exact two-pass
    # elsewhere (CPU runs double as the numerics oracle)
    use_shift = getattr(ctx, 'backend', None) == 'tpu'
    y, use_mean, use_var = _bn_train(x, scale, bias, mean, axes,
                                     float(eps), use_shift)
    mean_out = momentum * mean + (1 - momentum) * use_mean
    var_out = momentum * var + (1 - momentum) * use_var
    return {
        'Y': [y],
        'MeanOut': [mean_out],
        'VarianceOut': [var_out],
        'SavedMean': [use_mean],
        'SavedVariance': [use_var],
    }


@register_op('layer_norm')
def _layer_norm(ctx, ins, attrs):
    x = first(ins, 'X')
    scale = first(ins, 'Scale')
    bias = first(ins, 'Bias')
    eps = attrs.get('epsilon', 1e-5)
    begin = attrs.get('begin_norm_axis', 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(
            (1,) * begin + x.shape[begin:])
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(
            (1,) * begin + x.shape[begin:])
    return {'Y': [y.astype(x.dtype)], 'Mean': [mean.reshape(x.shape[:begin])],
            'Variance': [var.reshape(x.shape[:begin])]}


@register_op('lrn')
def _lrn(ctx, ins, attrs):
    """Local response normalization across channels (operators/lrn_op.cc):
    Out = X / (k + alpha * sum_{local} X^2)^beta."""
    x = first(ins, 'X')  # NCHW
    n = attrs.get('n', 5)
    k = attrs.get('k', 2.0)
    alpha = attrs.get('alpha', 1e-4)
    beta = attrs.get('beta', 0.75)
    xf = x.astype(jnp.float32)
    sq = jnp.square(xf)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    acc = jnp.zeros_like(xf)
    for i in range(n):
        acc = acc + pad[:, i:i + x.shape[1]]
    mid = k + alpha * acc
    return {'Out': [(xf / jnp.power(mid, beta)).astype(x.dtype)],
            'MidOut': [mid]}
