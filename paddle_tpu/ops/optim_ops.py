"""Optimizer update ops.

Reference parity: paddle/operators/{sgd,momentum,adam,adamax,adagrad,
decayed_adagrad,adadelta,rmsprop,ftrl,proximal_gd,proximal_adagrad}_op.*.
Each is a functional update: reads param/grad/moments, returns new values;
the executor's donated persistable state makes them in-place on device.

Sparse grads arrive as a core/selected_rows.SelectedRows (or a raw
(rows, values) pair): sgd/adagrad/adam apply them ROW-WISE — scatter-adds
into the donated buffers, the vocab-height dense grad never materializes
(parity: sgd_op.cc / adagrad_op.cc sparse branches; adam applies lazily
on the touched rows).  Other optimizers densify via scatter-add.

The ROW-WISE apply itself has two interchangeable lowerings, selected
per trace by ops/pallas/table_update.sparse_apply_mode():

  'xla'    — the `.at[rows].add` scatter path below, verbatim.  Exact,
             but XLA:TPU lowers every scatter as a full pass over the
             table operand (O(table height) per scattered table —
             PERF.md "CTR at Criteo scale").
  'pallas' — ops/pallas/table_update.py: a grid over the touched rows
             updates the donated table in place, O(touched rows), with
             Adagrad's param+moment (and Adam's param+both-moments)
             fused into ONE kernel pass.  Bitwise-identical to the XLA
             path (tier-1 tests/test_pallas_table_update.py).

PADDLE_TPU_SPARSE_APPLY=xla|pallas pins the path (default: pallas on
TPU, xla elsewhere); the resolved mode is part of the executor's plan
cache key, so a flip retraces.

The DENSE applies of sgd/momentum/adam have the same two lowerings,
selected by ops/pallas/dense_update.dense_apply_mode()
(PADDLE_TPU_DENSE_APPLY, same default/cache-key contract):

  'xla'    — the jnp expression chains below, verbatim: several fused
             multiply-adds whose intermediates round-trip HBM between
             fusions (dense Adam reads/writes each state table more
             than once per step).
  'pallas' — ops/pallas/dense_update.py: ONE grid walk over the
             flattened param applies the whole rule — each state table
             is read once and written once through
             input_output_aliases.  Bitwise-identical to the XLA path
             (tier-1 tests/test_pallas_dense_update.py), AMP f32-master
             grads included.
"""
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows, merge_duplicate_rows
from .common import first


def _pallas_rowwise(p, values):
    """True when the Pallas row-walking apply should serve this sparse
    update: mode resolves to pallas and the operand is a rank-2 table
    with matching row width (anything else falls back to the scatter
    path — e.g. rank>2 params the kernels don't block for)."""
    if getattr(p, 'ndim', 0) != 2 or getattr(values, 'ndim', 0) != 2:
        return False
    if p.shape[1] != values.shape[1]:
        return False
    from .pallas.table_update import sparse_apply_mode
    return sparse_apply_mode() == 'pallas'


def _embed_ways(attrs, p, values):
    """Shard count when this sparse apply targets a row-sharded
    embedding table (attrs stamped by the embed_shard pass) AND the
    Pallas row-walk serves it — the engine routes each shard's
    SelectedRows slice onto the kernel over LOCAL rows only.  Under
    PADDLE_TPU_SPARSE_APPLY=xla the global scatter stays (rows < true
    height never touch the sentinel pad rows, so it is equally
    correct, just not shard-local)."""
    ways = int(attrs.get('embed_ways') or 0)
    if ways > 1 and _pallas_rowwise(p, values):
        return ways
    return 0


def _pallas_dense(p, g):
    """True when the fused flat-walk kernel should serve this dense
    update: mode resolves to pallas and grad/param agree in shape (the
    kernels flatten, so any rank qualifies; a broadcasting or empty
    operand falls back to the jnp chain)."""
    if getattr(p, 'shape', None) != getattr(g, 'shape', None):
        return False
    if getattr(p, 'size', 0) == 0:
        return False
    from .pallas.dense_update import dense_apply_mode
    return dense_apply_mode() == 'pallas'


def _p32(x):
    return x.astype(jnp.float32)


def _as_sparse(grad):
    """Normalize a sparse grad to (rows, values) or None if dense."""
    if isinstance(grad, SelectedRows):
        return grad.rows, grad.values
    if isinstance(grad, tuple):
        rows, values = grad
        return rows.astype(jnp.int32).reshape(-1), _p32(values)
    return None


def _sparse_to_update(param, grad):
    """Densify a sparse grad by scatter-add (fallback for optimizers
    without a row-wise sparse rule)."""
    if isinstance(grad, SelectedRows):
        return grad.to_dense().astype(jnp.float32)
    if isinstance(grad, tuple):
        rows, values = grad
        dense = jnp.zeros(param.shape, jnp.float32)
        return dense.at[rows.astype(jnp.int32).reshape(-1)].add(
            _p32(values))
    return _p32(grad)


@register_op('sgd')
def _sgd(ctx, ins, attrs):
    p = first(ins, 'Param')
    grad = first(ins, 'Grad')
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    sp = _as_sparse(grad)
    if sp is not None:
        # row-wise apply: duplicates accumulate (linear update)
        rows, values = sp
        ways = _embed_ways(attrs, p, values)
        if ways:
            from ..distributed.embedding_engine import sharded_apply_sgd
            p_new = sharded_apply_sgd(
                _p32(p), rows, _p32(values), lr, ways,
                height=int(attrs['embed_height']),
                tile=int(attrs.get('embed_tile', 8)))
            return {'ParamOut': [p_new.astype(p.dtype)]}
        if _pallas_rowwise(p, values):
            from .pallas.table_update import sparse_apply_sgd
            p_new = sparse_apply_sgd(_p32(p), rows, _p32(values), lr)
            return {'ParamOut': [p_new.astype(p.dtype)]}
        p_new = _p32(p).at[rows].add(-lr * _p32(values))
        return {'ParamOut': [p_new.astype(p.dtype)]}
    g = _p32(grad)
    # optional fused L2 weight decay (the scale+sum pair
    # append_regularization_ops would otherwise weave as separate ops)
    wd = attrs.get('weight_decay', 0.0)
    if _pallas_dense(p, g):
        from .pallas.dense_update import dense_apply_sgd
        p_new = dense_apply_sgd(
            _p32(p), g, lr,
            weight_decay=jnp.float32(wd) if wd else None)
        return {'ParamOut': [p_new.astype(p.dtype)]}
    if wd:
        return {'ParamOut': [
            (_p32(p) - lr * (g + jnp.float32(wd) * _p32(p))).astype(
                p.dtype)]}
    return {'ParamOut': [(_p32(p) - lr * g).astype(p.dtype)]}


@register_op('momentum')
def _momentum(ctx, ins, attrs):
    p = first(ins, 'Param')
    g = _sparse_to_update(p, first(ins, 'Grad'))
    v = _p32(first(ins, 'Velocity'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    mu = attrs.get('mu', 0.9)
    if _pallas_dense(p, g):
        from .pallas.dense_update import dense_apply_momentum
        p_new, v_new = dense_apply_momentum(
            _p32(p), v, g, lr, mu,
            use_nesterov=attrs.get('use_nesterov', False))
        return {'ParamOut': [p_new.astype(p.dtype)],
                'VelocityOut': [v_new]}
    v_new = mu * v + g
    if attrs.get('use_nesterov', False):
        p_new = _p32(p) - (g + mu * v_new) * lr
    else:
        p_new = _p32(p) - lr * v_new
    return {'ParamOut': [p_new.astype(p.dtype)], 'VelocityOut': [v_new]}


@register_op('adam')
def _adam(ctx, ins, attrs):
    p = first(ins, 'Param')
    grad = first(ins, 'Grad')
    m = _p32(first(ins, 'Moment1'))
    v = _p32(first(ins, 'Moment2'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    b1p = _p32(first(ins, 'Beta1Pow')).reshape(())
    b2p = _p32(first(ins, 'Beta2Pow')).reshape(())
    b1 = attrs.get('beta1', 0.9)
    b2 = attrs.get('beta2', 0.999)
    eps = attrs.get('epsilon', 1e-8)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    sp = _as_sparse(grad)
    if sp is not None:
        # lazy sparse adam: moments decay and the param moves only on
        # touched rows; duplicate rows merge first (nonlinear update)
        rows, values = sp
        ways = _embed_ways(attrs, p, values)
        if ways:
            from ..distributed.embedding_engine import \
                sharded_apply_adam
            p_new, m_new, v_new = sharded_apply_adam(
                _p32(p), m, v, rows, _p32(values), lr_t, b1, b2, eps,
                ways, height=int(attrs['embed_height']),
                tile=int(attrs.get('embed_tile', 8)))
            return {'ParamOut': [p_new.astype(p.dtype)],
                    'Moment1Out': [m_new], 'Moment2Out': [v_new]}
        if _pallas_rowwise(p, values):
            from .pallas.table_update import sparse_apply_adam
            p_new, m_new, v_new = sparse_apply_adam(
                _p32(p), m, v, rows, _p32(values), lr_t, b1, b2, eps)
            return {'ParamOut': [p_new.astype(p.dtype)],
                    'Moment1Out': [m_new], 'Moment2Out': [v_new]}
        rows, g, valid = merge_duplicate_rows(rows, _p32(values))
        vmask = valid[:, None]
        m_row = b1 * m[rows] + (1 - b1) * g
        v_row = b2 * v[rows] + (1 - b2) * jnp.square(g)
        m_new = m.at[rows].add(jnp.where(vmask, m_row - m[rows], 0.0))
        v_new = v.at[rows].add(jnp.where(vmask, v_row - v[rows], 0.0))
        step = -lr_t * m_row / (jnp.sqrt(v_row) + eps)
        p_new = _p32(p).at[rows].add(jnp.where(vmask, step, 0.0))
        return {'ParamOut': [p_new.astype(p.dtype)], 'Moment1Out': [m_new],
                'Moment2Out': [v_new]}
    g = _p32(grad)
    if _pallas_dense(p, g):
        from .pallas.dense_update import dense_apply_adam
        p_new, m_new, v_new = dense_apply_adam(
            _p32(p), m, v, g, lr_t, b1, b2, eps)
        return {'ParamOut': [p_new.astype(p.dtype)],
                'Moment1Out': [m_new], 'Moment2Out': [v_new]}
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    p_new = _p32(p) - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    return {'ParamOut': [p_new.astype(p.dtype)], 'Moment1Out': [m_new],
            'Moment2Out': [v_new]}


@register_op('adamax')
def _adamax(ctx, ins, attrs):
    p = first(ins, 'Param')
    g = _sparse_to_update(p, first(ins, 'Grad'))
    m = _p32(first(ins, 'Moment'))
    u = _p32(first(ins, 'InfNorm'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    b1p = _p32(first(ins, 'Beta1Pow')).reshape(())
    b1 = attrs.get('beta1', 0.9)
    b2 = attrs.get('beta2', 0.999)
    eps = attrs.get('epsilon', 1e-8)
    m_new = b1 * m + (1 - b1) * g
    u_new = jnp.maximum(b2 * u, jnp.abs(g))
    p_new = _p32(p) - (lr / (1 - b1p)) * m_new / (u_new + eps)
    return {'ParamOut': [p_new.astype(p.dtype)], 'MomentOut': [m_new],
            'InfNormOut': [u_new]}


@register_op('adagrad')
def _adagrad(ctx, ins, attrs):
    p = first(ins, 'Param')
    grad = first(ins, 'Grad')
    mom = _p32(first(ins, 'Moment'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    eps = attrs.get('epsilon', 1e-6)
    sp = _as_sparse(grad)
    if sp is not None:
        # reference adagrad_op.cc sparse branch: merge duplicate rows,
        # then accumulate + step on the touched rows only
        rows, values = sp
        ways = _embed_ways(attrs, p, values)
        if ways:
            from ..distributed.embedding_engine import \
                sharded_apply_adagrad
            p_new, mom_new = sharded_apply_adagrad(
                _p32(p), mom, rows, _p32(values), lr, eps, ways,
                height=int(attrs['embed_height']),
                tile=int(attrs.get('embed_tile', 8)))
            return {'ParamOut': [p_new.astype(p.dtype)],
                    'MomentOut': [mom_new]}
        if _pallas_rowwise(p, values):
            from .pallas.table_update import sparse_apply_adagrad
            p_new, mom_new = sparse_apply_adagrad(
                _p32(p), mom, rows, _p32(values), lr, eps)
            return {'ParamOut': [p_new.astype(p.dtype)],
                    'MomentOut': [mom_new]}
        rows, g, valid = merge_duplicate_rows(rows, _p32(values))
        vmask = valid[:, None]
        mom_row = mom[rows] + jnp.square(g)
        mom_new = mom.at[rows].add(jnp.where(vmask, jnp.square(g), 0.0))
        step = -lr * g / (jnp.sqrt(mom_row) + eps)
        p_new = _p32(p).at[rows].add(jnp.where(vmask, step, 0.0))
        return {'ParamOut': [p_new.astype(p.dtype)], 'MomentOut': [mom_new]}
    g = _p32(grad)
    mom_new = mom + jnp.square(g)
    p_new = _p32(p) - lr * g / (jnp.sqrt(mom_new) + eps)
    return {'ParamOut': [p_new.astype(p.dtype)], 'MomentOut': [mom_new]}


@register_op('decayed_adagrad')
def _decayed_adagrad(ctx, ins, attrs):
    p = first(ins, 'Param')
    g = _sparse_to_update(p, first(ins, 'Grad'))
    mom = _p32(first(ins, 'Moment'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    decay = attrs.get('decay', 0.95)
    eps = attrs.get('epsilon', 1e-6)
    mom_new = decay * mom + (1 - decay) * jnp.square(g)
    p_new = _p32(p) - lr * g / (jnp.sqrt(mom_new) + eps)
    return {'ParamOut': [p_new.astype(p.dtype)], 'MomentOut': [mom_new]}


@register_op('adadelta')
def _adadelta(ctx, ins, attrs):
    p = first(ins, 'Param')
    g = _sparse_to_update(p, first(ins, 'Grad'))
    avg_sq_grad = _p32(first(ins, 'AvgSquaredGrad'))
    avg_sq_upd = _p32(first(ins, 'AvgSquaredUpdate'))
    rho = attrs.get('rho', 0.95)
    eps = attrs.get('epsilon', 1e-6)
    asg_new = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_upd + eps) / (asg_new + eps)) * g
    asu_new = rho * avg_sq_upd + (1 - rho) * jnp.square(update)
    return {'ParamOut': [(_p32(p) + update).astype(p.dtype)],
            'AvgSquaredGradOut': [asg_new],
            'AvgSquaredUpdateOut': [asu_new]}


@register_op('rmsprop')
def _rmsprop(ctx, ins, attrs):
    p = first(ins, 'Param')
    g = _sparse_to_update(p, first(ins, 'Grad'))
    ms = _p32(first(ins, 'MeanSquare'))
    mom = _p32(first(ins, 'Moment'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    decay = attrs.get('decay', 0.9)
    mu = attrs.get('momentum', 0.0)
    eps = attrs.get('epsilon', 1e-10)
    ms_new = decay * ms + (1 - decay) * jnp.square(g)
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {'ParamOut': [(_p32(p) - mom_new).astype(p.dtype)],
            'MeanSquareOut': [ms_new], 'MomentOut': [mom_new]}


@register_op('ftrl')
def _ftrl(ctx, ins, attrs):
    p = first(ins, 'Param')
    g = _sparse_to_update(p, first(ins, 'Grad'))
    sq = _p32(first(ins, 'SquaredAccumulator'))
    lin = _p32(first(ins, 'LinearAccumulator'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    l1 = attrs.get('l1', 0.0)
    l2 = attrs.get('l2', 0.0)
    lr_power = attrs.get('lr_power', -0.5)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * _p32(p)
    x = jnp.clip(new_lin, -l1, l1) - new_lin
    y = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    p_new = x / y
    return {'ParamOut': [p_new.astype(p.dtype)],
            'SquaredAccumOut': [new_sq], 'LinearAccumOut': [new_lin]}


@register_op('proximal_gd')
def _proximal_gd(ctx, ins, attrs):
    p = first(ins, 'Param')
    g = _sparse_to_update(p, first(ins, 'Grad'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    l1 = attrs.get('l1', 0.0)
    l2 = attrs.get('l2', 0.0)
    prox = _p32(p) - lr * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / \
        (1.0 + lr * l2)
    return {'ParamOut': [p_new.astype(p.dtype)]}


@register_op('proximal_adagrad')
def _proximal_adagrad(ctx, ins, attrs):
    p = first(ins, 'Param')
    g = _sparse_to_update(p, first(ins, 'Grad'))
    mom = _p32(first(ins, 'Moment'))
    lr = _p32(first(ins, 'LearningRate')).reshape(())
    l1 = attrs.get('l1', 0.0)
    l2 = attrs.get('l2', 0.0)
    mom_new = mom + jnp.square(g)
    lr_t = lr / jnp.sqrt(mom_new)
    prox = _p32(p) - lr_t * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0.0) / \
        (1.0 + lr_t * l2)
    return {'ParamOut': [p_new.astype(p.dtype)], 'MomentOut': [mom_new]}
