"""Recurrent ops.

Reference parity: paddle/operators/{lstm_op,lstm_unit_op,gru_op,
gru_unit_op}.* — the reference reorders sequences by length and runs
batched GEMMs per time step over the packed LoD layout.  TPU-native design:
padded [B, T, D] + lengths, one lax.scan over time whose body is a single
MXU matmul; finished rows freeze their state via masks (no reordering, no
dynamic shapes).
"""
import os

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first

_ACC = dict(preferred_element_type=jnp.float32)


def _gate_act(name):
    return {
        'sigmoid': jax.nn.sigmoid,
        'tanh': jnp.tanh,
        'relu': jax.nn.relu,
        'identity': lambda x: x,
    }[name]


def _maybe_reverse(xf, lengths, is_reverse):
    """Reverse each row's valid prefix (padded tail stays in place).
    Returns (x, rev_idx) with rev_idx None when not reversing — the same
    gather applied to the outputs undoes it."""
    if not is_reverse:
        return xf, None
    b, t = xf.shape[0], xf.shape[1]
    ln = (jnp.full((b,), t, jnp.int32) if lengths is None
          else lengths.astype(jnp.int32).reshape(-1))
    idx = jnp.arange(t)
    rev_idx = jnp.where(idx[None, :] < ln[:, None],
                        ln[:, None] - 1 - idx[None, :], idx[None, :])
    return jnp.take_along_axis(xf, rev_idx[..., None], axis=1), rev_idx


def _unreverse_and_mask(seqs, rev_idx, lengths, t):
    """Shared RNN output epilogue: undo _maybe_reverse's gather and zero
    positions >= length.  seqs: [B, T, H] arrays; returns the list."""
    mask = None
    if lengths is not None:
        mask = (jnp.arange(t)[None, :] <
                lengths.astype(jnp.int32).reshape(-1)[:, None])[..., None]
    out = []
    for v in seqs:
        if rev_idx is not None:
            v = jnp.take_along_axis(v, rev_idx[..., None], axis=1)
        if mask is not None:
            v = jnp.where(mask, v, 0.0)
        out.append(v)
    return out


def _device_vmem_bytes():
    """Per-core VMEM of the attached accelerator, from device_kind:
    16 MB for TPU v2–v5 families, 32 MB starting with the v6
    generation (Trillium), 16 MB when the generation is unparseable."""
    import re
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return 16 * 1024 * 1024
    m = re.search(r'v(\d+)', kind)
    if m and int(m.group(1)) >= 6:
        return 32 * 1024 * 1024
    return 16 * 1024 * 1024


def _rnn_vmem_budget():
    """VMEM bytes the BPTT kernel may claim: 75% of the device's VMEM
    (the rest is margin for Mosaic's own temporaries), derived from the
    attached device generation rather than hardcoded.
    PADDLE_TPU_RNN_VMEM_BUDGET_MB overrides for parts where the margin
    is wrong in either direction."""
    mb = os.environ.get('PADDLE_TPU_RNN_VMEM_BUDGET_MB')
    if mb:
        try:
            return int(float(mb) * 1024 * 1024)
        except ValueError:
            pass
    return int(_device_vmem_bytes() * 0.75)


def _pallas_rnn_fits_vmem(batch, hidden, gate_width):
    """The BPTT kernel keeps the weight block AND an equally-sized f32
    dW accumulator resident in VMEM for the whole grid, plus per-step
    [bt, gate_width] tiles.  The batch dimension TILES (grid =
    (batch_tiles, time)), so a config fits whenever ANY divisor of the
    batch keeps the working set under budget — only a hidden size whose
    resident weight+accumulator alone exceed VMEM falls back to the
    lax.scan path."""
    from .pallas.lstm_cell import pick_batch_tile
    return pick_batch_tile(batch, hidden, gate_width,
                           _rnn_vmem_budget()) is not None


@register_op('lstm')
def _lstm(ctx, ins, attrs):
    """Dynamic LSTM over a padded batch (operators/lstm_op.cc).  Input is
    the pre-projected gates [B, T, 4H] (the reference's `dynamic_lstm`
    layer computes x@W outside the op); Weight [H, 4H] is the recurrent
    projection; gate order i, f, c, o (reference order: i c f o differs —
    we follow the fluid docstring order input/forget/cell/output applied
    consistently with the layer)."""
    x = first(ins, 'Input')  # [B, T, 4H]
    w = first(ins, 'Weight').astype(jnp.float32)  # [H, 4H]
    bias = first(ins, 'Bias')  # [1, 4H] or [1, 7H] with peepholes
    lengths = first(ins, 'XLen')
    h0 = first(ins, 'H0')
    c0 = first(ins, 'C0')
    b, t, fourh = x.shape
    h = fourh // 4
    use_peepholes = attrs.get('use_peepholes', True) and bias is not None \
        and bias.shape[-1] == 7 * h

    xf = x.astype(jnp.float32)
    if bias is not None:
        xf = xf + bias.astype(jnp.float32)[..., :4 * h].reshape(1, 1, -1)

    backend = getattr(ctx, 'backend', None) or jax.default_backend()
    if attrs.get('use_pallas') and h0 is None and c0 is None and \
            attrs.get('gate_activation', 'sigmoid') == 'sigmoid' and \
            attrs.get('cell_activation', 'tanh') == 'tanh' and \
            attrs.get('candidate_activation', 'tanh') == 'tanh' and \
            _pallas_rnn_fits_vmem(b, h, fourh) and \
            (backend == 'tpu' or attrs.get('pallas_interpret', False)):
        # fused Pallas time loop (ops/pallas/lstm_cell.py): carry lives
        # in VMEM across grid steps; backward is the reverse-time BPTT
        # kernel.  TPU-only (interpret mode would unroll all T steps);
        # falls back to the lax.scan path for custom-activation or
        # chained-h0/c0 configs (peepholes ride the kernel via
        # pw = Bias[4H:7H]).  Ragged batches run the kernel UNMASKED:
        # lengths are prefixes, so padded steps can't reach any valid
        # output, and the zero-mask below (whose vjp zeroes the padded
        # cotangents) makes fwd and bwd exactly match the masked scan.
        from .pallas.lstm_cell import lstm_scan
        xin, rev_idx = _maybe_reverse(xf, lengths,
                                      attrs.get('is_reverse', False))
        pw = (bias.astype(jnp.float32).reshape(-1)[4 * h:7 * h]
              .reshape(3, h) if use_peepholes else None)
        # kernel gate order (i, f, cand, o) == this op's (i, f, c, o)
        hs, cs = lstm_scan(jnp.swapaxes(xin, 0, 1), w, pw,
                           interpret=backend != 'tpu')
        hs, cs = _unreverse_and_mask(
            [jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)],
            rev_idx, lengths, t)
        return {'Hidden': [hs.astype(x.dtype)],
                'Cell': [cs.astype(x.dtype)]}
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)
    lengths = lengths.astype(jnp.int32).reshape(-1)
    gate_act = _gate_act(attrs.get('gate_activation', 'sigmoid'))
    cell_act = _gate_act(attrs.get('cell_activation', 'tanh'))
    cand_act = _gate_act(attrs.get('candidate_activation', 'tanh'))
    is_reverse = attrs.get('is_reverse', False)

    if use_peepholes:
        bf = bias.astype(jnp.float32).reshape(-1)
        w_ic, w_fc, w_oc = (bf[4 * h:5 * h], bf[5 * h:6 * h],
                            bf[6 * h:7 * h])
    if is_reverse:
        xf, rev_idx = _maybe_reverse(xf, lengths, True)

    h_prev = (h0.astype(jnp.float32) if h0 is not None
              else jnp.zeros((b, h), jnp.float32))
    c_prev = (c0.astype(jnp.float32) if c0 is not None
              else jnp.zeros((b, h), jnp.float32))

    def step(carry, inputs):
        h_p, c_p = carry
        g_t, t_idx = inputs  # [B, 4H]
        g = g_t + jnp.matmul(h_p, w, **_ACC)
        gi, gf, gc, go = jnp.split(g, 4, axis=1)
        if use_peepholes:
            gi = gi + c_p * w_ic
            gf = gf + c_p * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c = f * c_p + i * cand_act(gc)
        if use_peepholes:
            go = go + c * w_oc
        o = gate_act(go)
        h_t = o * cell_act(c)
        alive = (t_idx < lengths)[:, None]
        h_t = jnp.where(alive, h_t, h_p)
        c = jnp.where(alive, c, c_p)
        return (h_t, c), (h_t, c)

    (_, _), (hs, cs) = jax.lax.scan(
        step, (h_prev, c_prev),
        (jnp.swapaxes(xf, 0, 1), jnp.arange(t)))
    hs, cs = _unreverse_and_mask(
        [jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)],
        rev_idx if is_reverse else None, lengths, t)
    return {'Hidden': [hs.astype(x.dtype)], 'Cell': [cs.astype(x.dtype)]}


@register_op('lstm_unit')
def _lstm_unit(ctx, ins, attrs):
    """Single LSTM cell step (operators/lstm_unit_op): X [B, 4H] gates,
    C_prev [B, H] → (C, H).  Gate order i, f, o, j (parity with the
    reference kernel)."""
    x = first(ins, 'X').astype(jnp.float32)
    c_prev = first(ins, 'C_prev').astype(jnp.float32)
    forget_bias = attrs.get('forget_bias', 0.0)
    i, f, o, j = jnp.split(x, 4, axis=1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + \
        jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    dt = first(ins, 'X').dtype
    return {'C': [c.astype(dt)], 'H': [h.astype(dt)]}


@register_op('gru')
def _gru(ctx, ins, attrs):
    """Dynamic GRU over a padded batch (operators/gru_op.cc).  Input [B, T,
    3H] pre-projected; Weight packs [H, 2H] (update/reset) + [H, H]
    (candidate)."""
    x = first(ins, 'Input')
    w = first(ins, 'Weight').astype(jnp.float32)  # [H, 3H]
    bias = first(ins, 'Bias')
    lengths = first(ins, 'XLen')
    h0 = first(ins, 'H0')
    b, t, threeh = x.shape
    h = threeh // 3

    xf = x.astype(jnp.float32)
    if bias is not None:
        xf = xf + bias.astype(jnp.float32).reshape(1, 1, -1)

    backend = getattr(ctx, 'backend', None) or jax.default_backend()
    if attrs.get('use_pallas') and \
            attrs.get('gate_activation', 'sigmoid') == 'sigmoid' and \
            attrs.get('activation', 'tanh') == 'tanh' and \
            _pallas_rnn_fits_vmem(b, h, threeh) and \
            (backend == 'tpu' or attrs.get('pallas_interpret', False)):
        # fused Pallas time loop (ops/pallas/lstm_cell.gru_scan); ragged
        # batches run unmasked + zero-mask outside (see the lstm branch);
        # a chained h0 (seq2seq decoder) rides the kernel's h0 input
        from .pallas.lstm_cell import gru_scan
        xin, rev_idx = _maybe_reverse(xf, lengths,
                                      attrs.get('is_reverse', False))
        h0f = h0.astype(jnp.float32) if h0 is not None else None
        hs = jnp.swapaxes(gru_scan(jnp.swapaxes(xin, 0, 1), w, h0f,
                                   interpret=backend != 'tpu'), 0, 1)
        hs, = _unreverse_and_mask([hs], rev_idx, lengths, t)
        return {'Hidden': [hs.astype(x.dtype)]}

    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)
    lengths = lengths.astype(jnp.int32).reshape(-1)
    gate_act = _gate_act(attrs.get('gate_activation', 'sigmoid'))
    cand_act = _gate_act(attrs.get('activation', 'tanh'))
    is_reverse = attrs.get('is_reverse', False)
    w_rz = w[:, :2 * h]
    w_c = w[:, 2 * h:]
    if is_reverse:
        xf, rev_idx = _maybe_reverse(xf, lengths, True)

    h_prev = (h0.astype(jnp.float32) if h0 is not None
              else jnp.zeros((b, h), jnp.float32))

    def step(h_p, inputs):
        g_t, t_idx = inputs
        rz = g_t[:, :2 * h] + jnp.matmul(h_p, w_rz, **_ACC)
        u = gate_act(rz[:, :h])      # update gate
        r = gate_act(rz[:, h:])      # reset gate
        c = cand_act(g_t[:, 2 * h:] + jnp.matmul(r * h_p, w_c, **_ACC))
        h_t = u * h_p + (1.0 - u) * c
        alive = (t_idx < lengths)[:, None]
        h_t = jnp.where(alive, h_t, h_p)
        return h_t, h_t

    _, hs = jax.lax.scan(step, h_prev,
                         (jnp.swapaxes(xf, 0, 1), jnp.arange(t)))
    hs, = _unreverse_and_mask([jnp.swapaxes(hs, 0, 1)],
                              rev_idx if is_reverse else None, lengths, t)
    return {'Hidden': [hs.astype(x.dtype)]}


@register_op('gru_unit')
def _gru_unit(ctx, ins, attrs):
    """Single GRU step (operators/gru_unit_op): Input [B, 3H] pre-projected
    gates, HiddenPrev [B, H], Weight [H, 3H]."""
    x = first(ins, 'Input').astype(jnp.float32)
    h_p = first(ins, 'HiddenPrev').astype(jnp.float32)
    w = first(ins, 'Weight').astype(jnp.float32)
    bias = first(ins, 'Bias')
    h = h_p.shape[1]
    if bias is not None:
        x = x + bias.astype(jnp.float32).reshape(1, -1)
    gate_act = _gate_act(
        {0: 'sigmoid', 1: 'sigmoid', 2: 'tanh', 3: 'relu'}.get(
            attrs.get('gate_activation', 0), 'sigmoid')
        if isinstance(attrs.get('gate_activation', 0), int)
        else attrs.get('gate_activation', 'sigmoid'))
    cand_act = _gate_act(
        {0: 'identity', 1: 'sigmoid', 2: 'tanh', 3: 'relu'}.get(
            attrs.get('activation', 2), 'tanh')
        if isinstance(attrs.get('activation', 2), int)
        else attrs.get('activation', 'tanh'))
    rz = x[:, :2 * h] + jnp.matmul(h_p, w[:, :2 * h], **_ACC)
    u = gate_act(rz[:, :h])
    r = gate_act(rz[:, h:])
    c = cand_act(x[:, 2 * h:] + jnp.matmul(r * h_p, w[:, 2 * h:], **_ACC))
    h_t = u * h_p + (1.0 - u) * c
    dt = first(ins, 'Input').dtype
    return {'Hidden': [h_t.astype(dt)], 'ResetHiddenPrev': [(r * h_p).astype(dt)],
            'Gate': [jnp.concatenate([u, r, c], axis=1).astype(dt)]}
