"""Embedding lookup + sparse-gradient assembly.

Reference parity: paddle/operators/lookup_table_op.* — forward gather;
with `is_sparse` the grad kernel emits a SelectedRows instead of a dense
vocab-height tensor (lookup_table_op.cc:52 LookupTableGradKernel).  On TPU
the gather is one HLO gather; the sparse grad path is realised by
core/backward.py diffing w.r.t. the lookup *outputs* and a
`sparse_grad_assemble` op packing (ids, output-cotangents) into a
core/selected_rows.SelectedRows, which the optimizer ops apply row-wise
into the donated parameter buffer.
"""
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows
from .common import first, out


@register_op('lookup_table')
def _lookup_table(ctx, ins, attrs):
    w = first(ins, 'W')
    ids = first(ins, 'Ids').astype(jnp.int32)
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids.squeeze(-1)
    ways = int(attrs.get('embed_ways') or 0)
    if ways > 1 and w.ndim == 2:
        # row-sharded table (stamped by transpiler/sharding.py's
        # embed_shard pass): all-to-all of ids -> per-shard LOCAL
        # gather -> all-to-all of rows back.  Bitwise the jnp.take
        # below, incl. padding_idx against the TRUE height (the stored
        # table may carry sentinel pad rows past it)
        from ..distributed.embedding_engine import sharded_lookup
        y = sharded_lookup(
            w, ids, ways, height=int(attrs['embed_height']),
            tile=int(attrs.get('embed_tile', 8)),
            padding_idx=attrs.get('padding_idx', None))
        return out(y)
    y = jnp.take(w, ids, axis=0)
    pad = attrs.get('padding_idx', None)
    if pad is not None:
        if pad < 0:  # fluid convention: -1 means row vocab_size-1,
            # resolved against the DECLARED height (the staged table
            # may carry sentinel pad rows past it after a sharded
            # plan ran); w.shape[0] is the legacy fallback for
            # hand-built OpDescs without the height attr
            pad = int(attrs.get('height', w.shape[0])) + pad
        mask = (ids != pad)[..., None]
        y = jnp.where(mask, y, jnp.zeros_like(y))
    return out(y)


@register_op('sparse_grad_assemble')
def _sparse_grad_assemble(ctx, ins, attrs):
    """Pack one or more (Ids, OutGrad) pairs — every sparse lookup of one
    shared table — into a single SelectedRows grad.  Rows of a
    `padding_idx` id get zero values (the dense autodiff's where-mask
    blocks those grads; the sparse path must too)."""
    height = int(attrs['height'])
    pad = attrs.get('padding_idx', None)
    rows_list, vals_list = [], []
    for ids, g in zip(ins['Ids'], ins['OutGrad']):
        ids = ids.astype(jnp.int32)
        if ids.ndim >= 2 and ids.shape[-1] == 1:
            ids = ids.squeeze(-1)
        dim = g.shape[-1]
        rows = ids.reshape(-1)
        vals = g.astype(jnp.float32).reshape(-1, dim)
        if pad is not None:
            # zero the values but KEEP rows == pad: lazy sparse optimizers
            # then touch only the always-masked padding row, never a real
            # vocabulary entry
            p = pad if pad >= 0 else height + pad
            vals = jnp.where((rows != p)[:, None], vals,
                             jnp.zeros_like(vals))
        rows_list.append(rows)
        vals_list.append(vals)
    return out(SelectedRows(jnp.concatenate(rows_list),
                            jnp.concatenate(vals_list), height))
