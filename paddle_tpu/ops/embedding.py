"""Embedding lookup.

Reference parity: paddle/operators/lookup_table_op.* (forward gather;
sparse SelectedRows grad).  On TPU the gather is a single HLO gather; the
autodiff grad is a dense scatter-add which XLA handles natively, so
`is_sparse` is a no-op hint here (SelectedRows applies in ops/optim_ops.py
when explicitly fed).
"""
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out


@register_op('lookup_table')
def _lookup_table(ctx, ins, attrs):
    w = first(ins, 'W')
    ids = first(ins, 'Ids').astype(jnp.int32)
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids.squeeze(-1)
    y = jnp.take(w, ids, axis=0)
    pad = attrs.get('padding_idx', None)
    if pad is not None:
        if pad < 0:  # fluid convention: -1 means row vocab_size-1
            pad = w.shape[0] + pad
        mask = (ids != pad)[..., None]
        y = jnp.where(mask, y, jnp.zeros_like(y))
    return out(y)
