"""Activation ops.

Reference parity: paddle/operators/activation_op.{cc,cu,h} — the full list
in fluid/layers/ops.py __activations__.  All are pure jnp element-wise
functions; XLA fuses them into the producing matmul/conv on TPU.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out


def _unary(name, fn):
    @register_op(name)
    def _impl(ctx, ins, attrs, _fn=fn):
        return out(_fn(first(ins, 'X'), attrs))

    return _impl


_unary('sigmoid', lambda x, a: jax.nn.sigmoid(x))
_unary('logsigmoid', lambda x, a: jax.nn.log_sigmoid(x))
_unary('exp', lambda x, a: jnp.exp(x))
_unary('relu', lambda x, a: jax.nn.relu(x))
_unary('tanh', lambda x, a: jnp.tanh(x))
_unary('tanh_shrink', lambda x, a: x - jnp.tanh(x))
_unary('sqrt', lambda x, a: jnp.sqrt(x))
_unary('abs', lambda x, a: jnp.abs(x))
_unary('ceil', lambda x, a: jnp.ceil(x))
_unary('floor', lambda x, a: jnp.floor(x))
_unary('round', lambda x, a: jnp.round(x))
_unary('reciprocal', lambda x, a: 1.0 / x)
_unary('log', lambda x, a: jnp.log(x))
_unary('square', lambda x, a: jnp.square(x))
_unary('softplus', lambda x, a: jax.nn.softplus(x))
_unary('softsign', lambda x, a: jax.nn.soft_sign(x))
_unary('softshrink',
       lambda x, a: jnp.where(x > a.get('lambda', 0.5), x - a.get('lambda', 0.5),
                              jnp.where(x < -a.get('lambda', 0.5),
                                        x + a.get('lambda', 0.5),
                                        jnp.zeros_like(x))))
_unary('hard_shrink',
       lambda x, a: jnp.where(jnp.abs(x) > a.get('threshold', 0.5), x,
                              jnp.zeros_like(x)))
_unary('brelu',
       lambda x, a: jnp.clip(x, a.get('t_min', 0.0), a.get('t_max', 24.0)))
_unary('leaky_relu',
       lambda x, a: jnp.where(x >= 0, x, a.get('alpha', 0.02) * x))
_unary('soft_relu',
       lambda x, a: jnp.log1p(
           jnp.exp(jnp.clip(x, -a.get('threshold', 40.0),
                            a.get('threshold', 40.0)))))
_unary('elu',
       lambda x, a: jnp.where(x >= 0, x,
                              a.get('alpha', 1.0) * (jnp.exp(x) - 1)))
_unary('relu6', lambda x, a: jnp.clip(x, 0.0, a.get('threshold', 6.0)))
_unary('pow', lambda x, a: jnp.power(x, a.get('factor', 1.0)))
_unary('stanh',
       lambda x, a: a.get('scale_b', 1.7159) * jnp.tanh(
           a.get('scale_a', 2.0 / 3.0) * x))
_unary('thresholded_relu',
       lambda x, a: jnp.where(x > a.get('threshold', 1.0), x,
                              jnp.zeros_like(x)))
_unary('hard_sigmoid',
       lambda x, a: jnp.clip(a.get('slope', 0.2) * x + a.get('offset', 0.5),
                             0.0, 1.0))
_unary('swish', lambda x, a: x * jax.nn.sigmoid(a.get('beta', 1.0) * x))
_unary('sign', lambda x, a: jnp.sign(x))


@register_op('prelu')
def _prelu(ctx, ins, attrs):
    x = first(ins, 'X')
    alpha = first(ins, 'Alpha')
    return out(jnp.where(x >= 0, x, alpha.reshape(()) * x
                         if alpha.size == 1 else alpha * x))
