"""DataFeeder — converts python reader minibatches into feed dicts.

Reference parity: python/paddle/v2/fluid/data_feeder.py.  Ragged (lod_level
> 0) slots are padded to a rectangle and paired with an int32 lengths vector
(the TPU-native LoD representation, core/lod.py).
"""
import numpy as np

from .core import datatypes
from .core.lod import LoDTensor
from .core.program import Variable, default_main_program

__all__ = ['DataFeeder']


class DataToLoDTensorConverter(object):
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [d for d in shape]
        self.dtype = datatypes.as_numpy_dtype(dtype)
        if self.dtype == np.int64:
            self.dtype = np.int32
        elif self.dtype == np.float64:
            self.dtype = np.float32
        self.data = []

    def feed(self, data):
        self.data.append(data)

    def done(self):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            # honor the declared per-row rank: scalar label rows must land
            # as [batch, 1] (the fluid LoDTensor contract) — a bare [batch]
            # silently broadcasts against [batch, 1] vars downstream
            shape = [int(d) for d in self.shape if d != -1]
            if shape and list(arr.shape[1:]) != shape and \
                    int(np.prod(arr.shape[1:], dtype=np.int64)) == \
                    int(np.prod(shape)):
                arr = arr.reshape([arr.shape[0]] + shape)
            return arr
        # one LoD level: each row is a sequence
        seqs = [np.asarray(s, dtype=self.dtype) for s in self.data]
        return self._ragged(seqs)

    def _ragged(self, seqs):
        lengths = [len(s) for s in seqs]
        maxlen = max(lengths) if lengths else 0
        trailing = seqs[0].shape[1:] if seqs and seqs[0].ndim > 1 else ()
        out = np.zeros((len(seqs), maxlen) + tuple(trailing),
                       dtype=self.dtype)
        for i, s in enumerate(seqs):
            out[i, :len(s)] = s
        return (out, np.asarray(lengths, dtype=np.int32))


class DataFeeder(object):
    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("Feed list should contain Variables")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            shape = list(each_var.shape)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(shape)
        self.place = place

    def feed(self, iterable):
        converters = [
            DataToLoDTensorConverter(
                place=self.place, lod_level=lod, shape=shape, dtype=dtype)
            for lod, shape, dtype in zip(
                self.feed_lod_level, self.feed_shapes, self.feed_dtypes)
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "The number of fields in data (%d) does not match the "
                "number of feed vars (%d)" %
                (len(each_sample), len(converters)))
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        ret_dict = {}
        for each_name, each_converter in zip(self.feed_names, converters):
            ret_dict[each_name] = each_converter.done()
        return ret_dict
