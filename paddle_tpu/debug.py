"""A3 — failure detection: nan/inf guards.

Reference parity: paddle/framework/executor.cc `check_nan_inf` (per-op
output scan under FLAGS_check_nan_inf) and the fluid debugger.  TPU-native
design: `jax.debug_nans` makes XLA itself fault on the first NaN-producing
op inside the fused step (strictly stronger than the reference's per-op
host scan), plus host-side finite checks on fetched values.
"""
import contextlib

import numpy as np

import jax

from .flags import FLAGS

__all__ = ['has_nan_inf', 'check_nan_inf', 'nan_guard', 'guarded_fetches']


def has_nan_inf(value):
    """True if the array holds any NaN or Inf."""
    arr = np.asarray(value)
    if arr.dtype.kind not in 'fc':
        return False
    return bool(np.any(~np.isfinite(arr)))


def check_nan_inf(value, name='<tensor>'):
    """Raise RuntimeError if `value` has NaN/Inf (executor.cc parity:
    `PADDLE_ENFORCE(!framework::HasInvalidValue(...))`)."""
    if has_nan_inf(value):
        arr = np.asarray(value)
        n_nan = int(np.isnan(arr).sum())
        n_inf = int(np.isinf(arr).sum())
        raise RuntimeError(
            "Tensor %s has %d NaN and %d Inf values" % (name, n_nan, n_inf))
    return value


def guarded_fetches(fetches, names=None):
    """Check every fetched value; returns fetches unchanged when clean."""
    for i, v in enumerate(fetches):
        check_nan_inf(v, names[i] if names else 'fetch[%d]' % i)
    return fetches


@contextlib.contextmanager
def nan_guard():
    """Enable jax.debug_nans for the enclosed region: the first op that
    produces a NaN raises immediately with the offending primitive —
    device-side failure detection the reference scans for on host."""
    prev = jax.config.jax_debug_nans
    jax.config.update('jax_debug_nans', True)
    try:
        yield
    finally:
        jax.config.update('jax_debug_nans', prev)


if FLAGS.check_nan_inf:
    # gflags parity: PADDLE_TPU_CHECK_NAN_INF=1 arms debug_nans globally
    jax.config.update('jax_debug_nans', True)
