"""D5 — long-sequence context parallelism: ring attention over 'sp'.

Reference parity: the reference handles long sequences by LoD chunking on
one device; context parallelism is the TPU-native scale-out: Q stays put,
K/V blocks rotate around the ring (`ppermute` rides ICI) while each member
accumulates its softmax numerator/denominator online (flash-attention
style running max/sum) — exact attention, O(seq/sp) memory per chip,
compute/comm overlapped by XLA's async collective scheduling.

`seq_to_heads`/`heads_to_seq` are the all-to-all layout switches (DeepSpeed
-Ulysses style) for layers that prefer head-sharding.
"""
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['ring_attention', 'seq_to_heads', 'heads_to_seq',
           'local_attention']


def local_attention(q, k, v, scale=None, causal=False, q_offset=0,
                    k_offset=0):
    """Plain blockwise attention returning (out_unnormalised, row_max,
    row_sum) for online-softmax accumulation.  q: [B, Tq, H, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[1])
        ki = k_offset + jnp.arange(k.shape[1])
        s = jnp.where(qi[:, None] >= ki[None, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B, H, Tq]
    # guard fully-masked rows (all -inf) against nan
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B, H, Tq]
    o = jnp.einsum('bhqk,bkhd->bqhd', p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m_safe, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Combine two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1[..., None].swapaxes(1, 2) + o2 * a2[..., None].swapaxes(1, 2)
    l = l1 * a1 + l2 * a2
    return o, m, l


def _flash_local(q, k, v, scale, causal, q_off, k_off):
    """Local block via the fused Pallas kernel (ops/pallas): returns
    online-softmax partials in _merge form — the normalized block output
    with m := lse and l := 1 merges exactly (weights exp(lse_i - lse)).
    Differentiable: attention_with_lse carries a custom flash-recompute
    VJP that folds the lse cotangent from the merge weights back in.
    Causal masking uses the scalar-prefetched global offsets, so it is
    exact against ring-rotated K/V shards; fully-masked rows come back
    with lse=-inf-like values and zero out in the merge."""
    from ..ops.pallas.flash_attention import attention_with_lse
    o, lse = attention_with_lse(q, k, v, scale=scale, causal=causal,
                                q_offset=q_off, k_offset=k_off)
    return o.astype(jnp.float32), lse, jnp.ones_like(lse)


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   use_flash=False):
    """Exact attention with K/V sharded over `axis_name` (inside
    shard_map).  q/k/v: [B, T/sp, H, D] local shards; returns [B, T/sp,
    H, D].

    use_flash=True computes each local block with the Pallas
    online-softmax kernel (causal included — global offsets ride scalar
    prefetch).  NOTE: call the enclosing shard_map with check_vma=False
    — jax's varying-axes checker does not yet see through interpret-mode
    pallas internals (its own error message recommends exactly this
    workaround)."""
    sp = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    chunk = q.shape[1]
    q_off = rank * chunk
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def local(qb, kb, vb, k_off):
        if not use_flash:
            return local_attention(qb, kb, vb, scale=scale, causal=causal,
                                   q_offset=q_off, k_offset=k_off)
        if not causal:
            return _flash_local(qb, kb, vb, scale, False, q_off, k_off)
        # causal ring: a block entirely in the future (k_off past this
        # shard's last query) contributes zero weight — skip its kernel
        # (~half the local compute at large sp) and emit the neutral
        # partials (_merge weight exp(-1e30 - m) = 0) directly
        b_, tq_, h_, _ = qb.shape

        def masked_block(_):
            lse = jnp.full((b_, h_, tq_), -1e30, jnp.float32)
            return (jnp.zeros(qb.shape[:3] + (vb.shape[-1],),
                              jnp.float32), lse, jnp.ones_like(lse))

        return lax.cond(
            k_off > q_off + tq_ - 1, masked_block,
            lambda _: _flash_local(qb, kb, vb, scale, True, q_off,
                                   k_off), None)

    o0, m0, l0 = local(q, k, v, q_off)

    def step(carry, i):
        o, m, l, kr, vr, k_owner = carry
        kr = lax.ppermute(kr, axis_name, perm)
        vr = lax.ppermute(vr, axis_name, perm)
        k_owner = (k_owner - 1) % sp
        k_off = k_owner * chunk
        o2, m2, l2 = local(q, kr, vr, k_off)
        o, m, l = _merge(o, m, l, o2, m2, l2)
        return (o, m, l, kr, vr, k_owner), None

    (o, m, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, rank), jnp.arange(sp - 1))
    l = jnp.maximum(l, 1e-20)
    return (o / l[..., None].swapaxes(1, 2)).astype(q.dtype)


def seq_to_heads(x, axis_name):
    """[B, T/sp, H, D] -> [B, T, H/sp, D]: all_to_all switch so sequence
    -sharded activations become head-sharded for per-head ops."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x, axis_name):
    """[B, T, H/sp, D] -> [B, T/sp, H, D] (inverse of seq_to_heads)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
