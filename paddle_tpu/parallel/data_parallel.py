"""D1/D2 — data parallelism and ZeRO/fsdp sharded state.

Reference parity: ParallelExecutor + operators/nccl_op allreduce (D1) and
the trainer/pserver split (D2).  TPU-native: the batch is sharded over the
'dp' mesh axis and XLA emits one fused gradient psum per step; the pserver
becomes parameter + optimizer-state sharding over 'fsdp'
(reduce_scatter grads, all_gather params) — same math, no extra process.
"""
import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import api

__all__ = ['DataParallel', 'fsdp_shardings']


class DataParallel(object):
    """Wrap an Executor so each run() step is batch-sharded over `axis`.

    Usage:
        mesh = api.make_mesh((8,), ('dp',))
        dp = DataParallel(exe, mesh)
        dp.run(program, feed=..., fetch_list=[...], scope=scope)
    """

    def __init__(self, exe, mesh, axis='dp', fsdp_axis=None):
        self.exe = exe
        self.mesh = mesh
        self.axis = axis
        self.fsdp_axis = fsdp_axis

    def run(self, program=None, feed=None, fetch_list=None, scope=None):
        from ..core.scope import global_scope
        scope = scope or global_scope()
        with api.mesh_guard(self.mesh):
            return api.run_sharded(
                self.exe, program, feed=feed, fetch_list=fetch_list,
                scope=scope, batch_axis=self.axis,
                param_axis=self.fsdp_axis)

    def run_steps(self, program=None, feed=None, fetch_list=None,
                  scope=None, repeat=None):
        """K sharded steps as one lax.scan over the mesh (the SPMD
        counterpart of Executor.run_steps): state stays sharded on the
        mesh between steps — no per-step host dispatch — and numerics
        match K run() calls exactly."""
        from ..core.scope import global_scope
        scope = scope or global_scope()
        with api.mesh_guard(self.mesh):
            return api.run_steps_sharded(
                self.exe, program, feed=feed, fetch_list=fetch_list,
                scope=scope, batch_axis=self.axis,
                param_axis=self.fsdp_axis, repeat=repeat)


def fsdp_shardings(mesh, state, axis='fsdp'):
    """ZeRO-3-style shardings for a {name: array} state dict: every tensor
    with a dim divisible by the axis size is sharded on its LARGEST such
    dim (params, momenta, adam moments alike); scalars replicate."""
    size = mesh.shape[axis]
    out = {}
    for n, v in state.items():
        shape = np.shape(v)
        cand = [d for d in range(len(shape)) if shape[d] % size == 0
                and shape[d] >= size]
        if not cand:
            out[n] = NamedSharding(mesh, P())
            continue
        d = max(cand, key=lambda i: shape[i])
        spec = [None] * len(shape)
        spec[d] = axis
        out[n] = NamedSharding(mesh, P(*spec))
    return out
