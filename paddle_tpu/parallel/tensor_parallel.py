"""D3 — Megatron-style tensor parallelism as shard_map building blocks.

Reference parity: model-parallel fc/embedding layers.  Column-parallel
matmul keeps the activation sharded on features; row-parallel matmul
psums partial products over 'tp' — one ICI allreduce per pair, the same
schedule Megatron-LM uses.
"""
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['column_parallel_matmul', 'row_parallel_matmul',
           'parallel_embedding', 'tp_fc_pair']


def column_parallel_matmul(x, w_shard, b_shard=None):
    """x: [B, D] replicated; w_shard: [D, H/tp] this member's columns.
    Returns [B, H/tp] (feature-sharded); no communication."""
    y = jnp.dot(x, w_shard, preferred_element_type=jnp.float32)
    if b_shard is not None:
        y = y + b_shard
    return y.astype(x.dtype)


def row_parallel_matmul(x_shard, w_shard, axis_name, b=None):
    """x_shard: [B, D/tp]; w_shard: [D/tp, H].  psum over `axis_name`
    completes the contraction; bias adds once (post-reduce)."""
    partial = jnp.dot(x_shard, w_shard, preferred_element_type=jnp.float32)
    y = lax.psum(partial, axis_name)
    if b is not None:
        y = y + b
    return y.astype(x_shard.dtype)


def parallel_embedding(ids, table_shard, axis_name):
    """Vocab-sharded embedding: each member owns rows
    [rank*V/tp, (rank+1)*V/tp); out-of-range ids contribute zeros and the
    psum assembles the full gather."""
    tp = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    vshard = table_shard.shape[0]
    lo = rank * vshard
    local = ids - lo
    in_range = (local >= 0) & (local < vshard)
    safe = jnp.clip(local, 0, vshard - 1)
    emb = table_shard[safe]
    emb = jnp.where(in_range[..., None], emb, 0)
    return lax.psum(emb, axis_name)


def tp_fc_pair(x, w1_shard, w2_shard, axis_name, act=jax.nn.relu):
    """The canonical Megatron block: column-parallel fc + act +
    row-parallel fc = ONE psum for two matmuls."""
    h = act(column_parallel_matmul(x, w1_shard))
    return row_parallel_matmul(h, w2_shard, axis_name)
