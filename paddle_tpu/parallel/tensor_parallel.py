"""D3 — Megatron-style tensor parallelism as shard_map building blocks.

Reference parity: model-parallel fc/embedding layers.  Column-parallel
matmul keeps the activation sharded on features; row-parallel matmul
psums partial products over 'tp' — one ICI allreduce per pair, the same
schedule Megatron-LM uses.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['column_parallel_matmul', 'row_parallel_matmul',
           'parallel_embedding', 'tp_fc_pair',
           'vocab_parallel_cross_entropy']


def column_parallel_matmul(x, w_shard, b_shard=None):
    """x: [B, D] replicated; w_shard: [D, H/tp] this member's columns.
    Returns [B, H/tp] (feature-sharded); no communication."""
    y = jnp.dot(x, w_shard, preferred_element_type=jnp.float32)
    if b_shard is not None:
        y = y + b_shard
    return y.astype(x.dtype)


def row_parallel_matmul(x_shard, w_shard, axis_name, b=None):
    """x_shard: [B, D/tp]; w_shard: [D/tp, H].  psum over `axis_name`
    completes the contraction; bias adds once (post-reduce)."""
    partial = jnp.dot(x_shard, w_shard, preferred_element_type=jnp.float32)
    y = lax.psum(partial, axis_name)
    if b is not None:
        y = y + b
    return y.astype(x_shard.dtype)


def parallel_embedding(ids, table_shard, axis_name):
    """Vocab-sharded embedding: each member owns rows
    [rank*V/tp, (rank+1)*V/tp); out-of-range ids contribute zeros and the
    psum assembles the full gather."""
    tp = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    vshard = table_shard.shape[0]
    lo = rank * vshard
    local = ids - lo
    in_range = (local >= 0) & (local < vshard)
    safe = jnp.clip(local, 0, vshard - 1)
    emb = table_shard[safe]
    emb = jnp.where(in_range[..., None], emb, 0)
    return lax.psum(emb, axis_name)


def tp_fc_pair(x, w1_shard, w2_shard, axis_name, act=jax.nn.relu):
    """The canonical Megatron block: column-parallel fc + act +
    row-parallel fc = ONE psum for two matmuls."""
    h = act(column_parallel_matmul(x, w1_shard))
    return row_parallel_matmul(h, w2_shard, axis_name)


@functools.partial(jax.custom_jvp, nondiff_argnums=(1,))
def _pmax_nodiff(x, axis_name):
    return lax.pmax(x, axis_name)


@_pmax_nodiff.defjvp
def _pmax_nodiff_jvp(axis_name, primals, tangents):
    (x,), _ = primals, tangents
    return lax.pmax(x, axis_name), jnp.zeros_like(x)


def vocab_parallel_cross_entropy(x, w_shard, b_shard, labels, axis_name):
    """Softmax cross-entropy through a VOCAB-SHARDED head: W is split
    [D, V/k] per member along ``axis_name``, so neither the full [D, V]
    head nor the full [N, V] logits ever exist on one chip — the
    multi-chip lever PERF.md names for the seq2seq vocab wall (the
    single-chip fused op is ops/chunked_ce.py).

    Per member: local logits [N, V/k], local max and sum-exp; the
    global logsumexp combines with one pmax + one psum, and the label
    logit is a masked gather psum'd from whichever member owns the
    label's shard.  Backward flows through the psums automatically
    (the stabilizing pmax rides outside differentiation), producing the
    local dW shard and a psum'd dx — call inside shard_map,
    differentiable.

    :param labels: [N] int32 GLOBAL vocab ids (replicated).
    :returns: per-example loss [N] (replicated across the axis).
    """
    rank = lax.axis_index(axis_name)
    vs = w_shard.shape[1]
    logits = jnp.matmul(x, w_shard.astype(x.dtype),
                        preferred_element_type=jnp.float32)
    logits = logits.astype(jnp.float32) + b_shard.astype(jnp.float32)
    # the max is a pure numerical stabilizer (the logsumexp gradient is
    # shift-invariant), so it rides outside differentiation — pmax has
    # no transpose rule and needs none here
    local_max = lax.stop_gradient(jnp.max(logits, axis=1))
    gmax = _pmax_nodiff(local_max, axis_name)
    gsum = lax.psum(jnp.sum(jnp.exp(logits - gmax[:, None]), axis=1),
                    axis_name)
    lse = gmax + jnp.log(gsum)
    local = labels.astype(jnp.int32) - rank * vs
    hit = (local >= 0) & (local < vs)
    lg = jnp.take_along_axis(
        logits, jnp.clip(local, 0, vs - 1)[:, None], axis=1)[:, 0]
    label_logit = lax.psum(jnp.where(hit, lg, 0.0), axis_name)
    return lse - label_logit
