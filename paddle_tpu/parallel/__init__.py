"""Mesh-first distribution (SURVEY.md §2.6).

Every parallel feature of the reference — nccl allreduce data parallelism
(D1), the pserver split (D2), model/tensor parallel (D3), pipeline (D4),
long-sequence context parallel (D5), the NCCL/MPI collective backend (D6)
— is expressed here as a sharding over ONE `jax.sharding.Mesh` with named
axes; XLA lowers the named-axis collectives onto ICI.
"""
from . import api, collective, data_parallel, expert_parallel, pipeline, \
    ring_attention, tensor_parallel
from .api import (current_mesh, make_mesh, mesh_guard, run_sharded,
                  shard_tensor)

__all__ = [
    'api', 'collective', 'data_parallel', 'tensor_parallel', 'pipeline',
    'ring_attention', 'expert_parallel', 'make_mesh', 'mesh_guard',
    'current_mesh', 'shard_tensor', 'run_sharded',
]
