"""Expert parallelism over an 'ep' mesh axis.

The reference predates mixture-of-experts, but the mesh design
(SURVEY §6.5) names 'ep' among the first-class axes: each mesh member
owns one (or E/ep) experts, tokens route to their expert with an
`all_to_all` over ICI, the expert FFN runs local, and a second
`all_to_all` routes results home — the standard TPU MoE dispatch
(GShard/Switch layout), expressed with the same collective backend as
dp/tp/sp.

Static shapes: every member sends exactly `capacity` tokens to every
expert (over-capacity tokens drop, under-capacity slots pad) — the
TPU-friendly fixed-capacity formulation.
"""
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['dispatch', 'combine', 'expert_ffn', 'moe_layer']


def _capacity_gather(x, gates, n_expert, capacity):
    """Select up to `capacity` token indices per expert (top-gate order
    not needed for correctness here: first-come order, parity with
    capacity-dropping MoE).  Returns idx [E, C] and valid [E, C]."""
    t = x.shape[0]
    # rank of each token within its expert's arrivals
    expert = jnp.argmax(gates, axis=-1)  # [T]
    onehot = jax.nn.one_hot(expert, n_expert, dtype=jnp.int32)  # [T, E]
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot  # 1-based
    pos = jnp.sum(pos_in_expert, axis=-1) - 1  # [T], 0-based
    keep = pos < capacity
    # scatter token ids into [E, C] slots
    slot = jnp.where(keep, expert * capacity + pos, n_expert * capacity)
    idx = jnp.full((n_expert * capacity + 1,), t, jnp.int32)
    idx = idx.at[slot].set(jnp.arange(t, dtype=jnp.int32))
    idx = idx[:-1].reshape(n_expert, capacity)
    valid = idx < t
    idx = jnp.minimum(idx, t - 1)
    return idx, valid, expert, keep, pos


def dispatch(x, gates, axis_name, capacity):
    """Route tokens to their expert's mesh member.

    x [T, D] local tokens, gates [T, E] routing scores with E == mesh
    size of `axis_name`.  Returns (expert_in [E*C_local... actually
    [E, C, D] received tokens for THIS member's expert], routing state
    for combine()).
    """
    n_expert = lax.psum(1, axis_name)
    idx, valid, expert, keep, pos = _capacity_gather(x, gates, n_expert,
                                                     capacity)
    send = x[idx] * valid[..., None].astype(x.dtype)  # [E, C, D]
    # all_to_all: member m sends send[e] to member e; receives [E, C, D]
    # where axis 0 now indexes the SOURCE member
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    return recv, (idx, valid, expert, keep, pos)


def combine(y, state, axis_name):
    """Inverse of dispatch: return expert outputs to their home tokens.
    y [E_src, C, D] processed tokens (source-indexed); returns [T, D]
    with dropped tokens zero."""
    idx, valid, expert, keep, pos = state
    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # [E, C, D] expert-indexed again
    d = y.shape[-1]
    flat = back.reshape(-1, d)  # [E*C, D]
    slot = expert * idx.shape[1] + pos  # token's slot if kept
    gathered = flat[jnp.minimum(slot, flat.shape[0] - 1)]
    return jnp.where(keep[:, None], gathered, 0.0).astype(y.dtype)


def expert_ffn(x, w1, b1, w2, b2):
    """The local expert: position-wise FFN on [*, D] tokens."""
    h = jax.nn.relu(jnp.einsum('...d,dh->...h', x, w1) + b1)
    return jnp.einsum('...h,hd->...d', h, w2) + b2


def moe_layer(x, gates, w1, b1, w2, b2, axis_name, capacity):
    """Full fixed-capacity MoE layer inside shard_map over `axis_name`:
    dispatch -> local expert FFN -> combine.  Each member holds ONE
    expert's weights (w1 [D, H] local)."""
    recv, state = dispatch(x, gates, axis_name, capacity)
    y = expert_ffn(recv, w1, b1, w2, b2)
    return combine(y, state, axis_name)
