"""D4 — pipeline parallelism: stage-sharded shard_map + ppermute
microbatch handoff (GPipe schedule).

Reference parity: the reference pipelines via pserver program splits;
TPU-native pipelining keeps all stages in ONE SPMD program: each mesh
member owns one stage's params, microbatches flow through a `lax.scan`
whose carry ppermutes activations to the next stage each tick.  With S
stages and M microbatches the scan runs S+M-1 ticks (bubble included).
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['pipeline_apply', 'pipeline_train_1f1b']


def pipeline_apply(stage_fn, params_shard, microbatches, axis_name,
                   num_stages=None, remat=False):
    """Run a GPipe pipeline inside shard_map.

    stage_fn(params, x) -> y: one stage's compute (same code every stage;
      heterogeneous stages dispatch on params content).
    params_shard: this member's stage params (stacked leading stage dim
      sliced away by shard_map).
    microbatches: [M, mb, ...] — every member sees the full stream; stage
      0 injects microbatch t at tick t, the last stage emits outputs.

    Returns [M, mb, ...] outputs (valid on the last stage; callers psum or
    gather as needed).
    """
    S = num_stages if num_stages is not None else lax.psum(1, axis_name)
    if remat:
        # 1F1B's memory win, compiler-style: store only stage inputs and
        # recompute the stage body in the backward pipeline wave instead
        # of keeping S+M-1 ticks of activations live.
        stage_fn = jax.checkpoint(stage_fn)
    rank = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    total = M + S - 1

    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    def tick(carry, t):
        buf, outs = carry  # buf: this member's current activation
        # stage 0 picks up microbatch t (if any remain); others keep the
        # activation ppermuted from the previous stage
        inject = microbatches[jnp.minimum(t, M - 1)]
        x = jnp.where(rank == 0, jnp.where(t < M, inject, buf), buf)
        y = stage_fn(params_shard, x)
        # last stage records its output at tick t for microbatch t-(S-1)
        out_idx = t - (S - 1)
        record = (rank == S - 1) & (out_idx >= 0)
        idx = jnp.maximum(out_idx, 0)
        outs = outs.at[idx].set(jnp.where(record, y, outs[idx]))
        # hand activations to the next stage
        buf = lax.ppermute(y, axis_name, fwd_perm)
        return (buf, outs), None

    buf0 = jnp.zeros(mb_shape, microbatches.dtype)
    out_shape = jax.eval_shape(stage_fn, params_shard,
                               jax.ShapeDtypeStruct(mb_shape,
                                                    microbatches.dtype))
    outs0 = jnp.zeros((M,) + out_shape.shape, out_shape.dtype)
    # the carry varies per mesh member (each holds its stage's activation)
    if hasattr(lax, 'pcast'):
        buf0 = lax.pcast(buf0, (axis_name,), to='varying')
        outs0 = lax.pcast(outs0, (axis_name,), to='varying')
    elif hasattr(lax, 'pvary'):  # older jax spelling
        buf0 = lax.pvary(buf0, (axis_name,))
        outs0 = lax.pvary(outs0, (axis_name,))
    (buf, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(total))
    return outs


def _varying(x, axis_name):
    """Mark an array as per-member varying for shard_map scan carries."""
    if hasattr(lax, 'pcast'):
        return lax.pcast(x, (axis_name,), to='varying')
    if hasattr(lax, 'pvary'):
        return lax.pvary(x, (axis_name,))
    return x


def pipeline_train_1f1b(stage_fns, params_tuple, feeds, num_microbatches,
                        axis_name, iface_shape, iface_dtype,
                        loss_scale=None):
    """One pipelined fwd+bwd train pass with 1F1B liveness, inside a
    shard_map over ``axis_name`` (one mesh member per stage).

    The GPipe form (pipeline_apply + autodiff through the scan) keeps
    every tick's activations alive until its backward — O(M) stage
    inputs per member.  Here the backward is part of the SAME scan:
    at tick t, member r runs the forward of microbatch ``f = t - r``
    and the backward of ``b = t - 2(S-1) + r`` (the classic
    one-forward-one-backward schedule in closed form).  Stage inputs
    wait in a ring buffer of 2S slots — a microbatch's input lives
    exactly 2(S-1-r) ticks between its forward and its backward — so
    activation liveness is bounded by the pipeline DEPTH, never by the
    microbatch count.  Backward recomputes the stage body from the
    saved input (jax.vjp per tick), cotangents ppermute upstream, and
    per-stage param grads accumulate locally then psum across the axis.

    :param stage_fns: list of S functions ``f(params_tuple, x, mb_feeds,
        m) -> (y, loss_mb)`` — stage s reads its own entry of
        ``params_tuple``; every non-last stage returns a
        ``iface_shape`` activation and 0.0 loss; the LAST stage returns
        a dummy activation and the per-microbatch loss.  Stage 0
        ignores ``x`` and reads ``mb_feeds``.
    :param params_tuple: tuple of per-stage param pytrees, replicated
        across the axis (shard them over an orthogonal fsdp axis for
        param memory; the pipeline axis owns ACTIVATION memory).
    :param feeds: pytree of [M, mb, ...] arrays (replicated) — sliced
        per microbatch inside the scan.
    :param loss_scale: cotangent seed per microbatch (default 1/M —
        the mean over microbatches).
    :returns: (total_loss, grads_tuple) — both replicated across the
        axis after psum.
    """
    S = len(stage_fns)
    M = int(num_microbatches)
    rank = lax.axis_index(axis_name)
    seed = (1.0 / M) if loss_scale is None else loss_scale
    ring_slots = 2 * S
    total_ticks = M + 2 * (S - 1)

    def fwd_all(params_tuple, x, mb_feeds, m, r):
        return lax.switch(r, stage_fns, params_tuple, x, mb_feeds, m)

    zero_grads = jax.tree_util.tree_map(
        lambda a: jnp.zeros(jnp.shape(a), jnp.float32), params_tuple)

    def tick(carry, t):
        fwd_buf, ct_buf, ring, dparams, loss_acc = carry
        f = t - rank
        fwd_on = (f >= 0) & (f < M)
        b = t - 2 * (S - 1) + rank
        bwd_on = (b >= 0) & (b < M)
        fc = jnp.clip(f, 0, M - 1)
        bc = jnp.clip(b, 0, M - 1)
        mbf = jax.tree_util.tree_map(lambda a: a[fc], feeds)
        mbb = jax.tree_util.tree_map(lambda a: a[bc], feeds)

        # ---- forward of microbatch f ----
        y, loss_mb = fwd_all(params_tuple, fwd_buf, mbf, fc, rank)
        loss_acc = loss_acc + jnp.where(
            fwd_on & (rank == S - 1), loss_mb * seed, 0.0)
        ring = ring.at[fc % ring_slots].set(
            jnp.where(fwd_on, fwd_buf, ring[fc % ring_slots]))

        # ---- backward of microbatch b (recompute from the ring) ----
        x_saved = ring[bc % ring_slots]
        _, vjp = jax.vjp(
            lambda P, x: fwd_all(P, x, mbb, bc, rank),
            params_tuple, x_saved)
        ct_y = jnp.where(rank == S - 1, jnp.zeros_like(ct_buf), ct_buf)
        ct_loss = jnp.where(rank == S - 1, jnp.float32(seed), 0.0)
        dP, dx = vjp((ct_y.astype(iface_dtype),
                      ct_loss.astype(jnp.float32)))
        on = bwd_on.astype(jnp.float32)
        dparams = jax.tree_util.tree_map(
            lambda acc, g: acc + on * g.astype(jnp.float32),
            dparams, dP)

        # ---- hand off: activations downstream, cotangents upstream ----
        fwd_buf = lax.ppermute(y, axis_name,
                               [(i, i + 1) for i in range(S - 1)])
        dx_send = jnp.where(bwd_on, dx, jnp.zeros_like(dx))
        ct_buf = lax.ppermute(dx_send, axis_name,
                              [(i + 1, i) for i in range(S - 1)])
        return (fwd_buf, ct_buf, ring, dparams, loss_acc), None

    # cotangents carry the primal's dtype (bf16 activations get bf16
    # cotangents, like any jax vjp)
    fwd0 = _varying(jnp.zeros(iface_shape, iface_dtype), axis_name)
    ct0 = _varying(jnp.zeros(iface_shape, iface_dtype), axis_name)
    ring0 = _varying(jnp.zeros((ring_slots,) + tuple(iface_shape),
                               iface_dtype), axis_name)
    dparams0 = jax.tree_util.tree_map(
        lambda a: _varying(a, axis_name), zero_grads)
    loss0 = _varying(jnp.float32(0.0), axis_name)

    carry, _ = lax.scan(tick, (fwd0, ct0, ring0, dparams0, loss0),
                        jnp.arange(total_ticks))
    _fwd, _ct, _ring, dparams, loss_acc = carry
    # loss lives on the last stage, each member holds only its own
    # stage's grads — one psum each replicates both across the axis
    loss = lax.psum(loss_acc, axis_name)
    grads = jax.tree_util.tree_map(
        lambda g: lax.psum(g, axis_name), dparams)
    return loss, grads
