"""D4 — pipeline parallelism: stage-sharded shard_map + ppermute
microbatch handoff (GPipe schedule).

Reference parity: the reference pipelines via pserver program splits;
TPU-native pipelining keeps all stages in ONE SPMD program: each mesh
member owns one stage's params, microbatches flow through a `lax.scan`
whose carry ppermutes activations to the next stage each tick.  With S
stages and M microbatches the scan runs S+M-1 ticks (bubble included).
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['pipeline_apply']


def pipeline_apply(stage_fn, params_shard, microbatches, axis_name,
                   num_stages=None, remat=False):
    """Run a GPipe pipeline inside shard_map.

    stage_fn(params, x) -> y: one stage's compute (same code every stage;
      heterogeneous stages dispatch on params content).
    params_shard: this member's stage params (stacked leading stage dim
      sliced away by shard_map).
    microbatches: [M, mb, ...] — every member sees the full stream; stage
      0 injects microbatch t at tick t, the last stage emits outputs.

    Returns [M, mb, ...] outputs (valid on the last stage; callers psum or
    gather as needed).
    """
    S = num_stages if num_stages is not None else lax.psum(1, axis_name)
    if remat:
        # 1F1B's memory win, compiler-style: store only stage inputs and
        # recompute the stage body in the backward pipeline wave instead
        # of keeping S+M-1 ticks of activations live.
        stage_fn = jax.checkpoint(stage_fn)
    rank = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    total = M + S - 1

    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    def tick(carry, t):
        buf, outs = carry  # buf: this member's current activation
        # stage 0 picks up microbatch t (if any remain); others keep the
        # activation ppermuted from the previous stage
        inject = microbatches[jnp.minimum(t, M - 1)]
        x = jnp.where(rank == 0, jnp.where(t < M, inject, buf), buf)
        y = stage_fn(params_shard, x)
        # last stage records its output at tick t for microbatch t-(S-1)
        out_idx = t - (S - 1)
        record = (rank == S - 1) & (out_idx >= 0)
        idx = jnp.maximum(out_idx, 0)
        outs = outs.at[idx].set(jnp.where(record, y, outs[idx]))
        # hand activations to the next stage
        buf = lax.ppermute(y, axis_name, fwd_perm)
        return (buf, outs), None

    buf0 = jnp.zeros(mb_shape, microbatches.dtype)
    out_shape = jax.eval_shape(stage_fn, params_shard,
                               jax.ShapeDtypeStruct(mb_shape,
                                                    microbatches.dtype))
    outs0 = jnp.zeros((M,) + out_shape.shape, out_shape.dtype)
    # the carry varies per mesh member (each holds its stage's activation)
    if hasattr(lax, 'pcast'):
        buf0 = lax.pcast(buf0, (axis_name,), to='varying')
        outs0 = lax.pcast(outs0, (axis_name,), to='varying')
    elif hasattr(lax, 'pvary'):  # older jax spelling
        buf0 = lax.pvary(buf0, (axis_name,))
        outs0 = lax.pvary(outs0, (axis_name,))
    (buf, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(total))
    return outs
