"""Mesh context + sharding annotations + sharded program execution.

Reference parity: D9 (sharding propagation/config) and the glue that turns
a Fluid Program's jitted step into an SPMD program.  The reference
distributes by rewriting the program (distribute_transpiler inserts
send/recv); here the SAME single-block program is partitioned by GSPMD:
we annotate the feed/state args with NamedShardings and XLA inserts the
collectives.
"""
import contextlib
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ['make_mesh', 'mesh_guard', 'current_mesh', 'shard_tensor',
           'replicate', 'batch_sharding', 'param_sharding', 'run_sharded',
           'run_steps_sharded', 'P']

_state = threading.local()


def make_mesh(shape, axis_names, devices=None):
    """Build a Mesh from the first prod(shape) devices (row-major)."""
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(shape))
    if len(devices) < n:
        raise ValueError("mesh %s needs %d devices, have %d" %
                         (tuple(shape), n, len(devices)))
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, axis_names)


@contextlib.contextmanager
def mesh_guard(mesh):
    prev = getattr(_state, 'mesh', None)
    _state.mesh = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _state.mesh = prev


def current_mesh():
    return getattr(_state, 'mesh', None)


def replicate(mesh):
    return NamedSharding(mesh, P())


def shard_tensor(x, mesh, spec):
    """Place x with a PartitionSpec (tuple/None) on the mesh."""
    if not isinstance(spec, P):
        spec = P(*spec) if isinstance(spec, (list, tuple)) else P(spec)
    return jax.device_put(x, NamedSharding(mesh, spec))


def batch_sharding(mesh, axis, ndim):
    """Shard dim0 (batch) over `axis`, rest replicated."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def param_sharding(mesh, axis, shape):
    """Megatron-style parameter sharding: split the largest divisible dim
    over `axis` (column-parallel on [in, out] weights picks `out` when
    both divide).  Falls back to replication."""
    if axis is None:
        return replicate(mesh)
    size = mesh.shape[axis]
    if size == 1:
        return replicate(mesh)
    best = None
    for d in range(len(shape) - 1, -1, -1):  # prefer trailing (output) dims
        if shape[d] % size == 0 and shape[d] >= 2 * size:
            best = d
            break
    if best is None:
        return replicate(mesh)
    spec = [None] * len(shape)
    spec[best] = axis
    return NamedSharding(mesh, P(*spec))


def _multiprocess(mesh):
    """True when the mesh spans devices of more than one OS process
    (multi-host / multi-controller run via distributed.launch)."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def _place(v, sh):
    """Put a (host or device) value onto the mesh with sharding `sh`.

    Single-process: plain device_put.  Multi-process: every process holds
    the same GLOBAL value (the launch protocol feeds each process the
    full batch deterministically) and materializes only its addressable
    shards via make_array_from_callback — device_put cannot target
    non-addressable devices.  Values already sharded correctly pass
    through untouched."""
    if isinstance(v, jax.Array) and v.sharding == sh:
        return v
    if _multiprocess(sh.mesh):
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            return v  # already global; jit reshards if needed
        host = np.asarray(v)
        return jax.make_array_from_callback(host.shape, sh,
                                            lambda idx: host[idx])
    return jax.device_put(v, sh)


def _fetch_np(v):
    """Fetched value -> numpy, tolerating multi-process global arrays:
    replicated fetches read a local shard; sharded fetches allgather."""
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        if v.sharding.is_fully_replicated:
            return np.asarray(v.addressable_data(0))
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(v, tiled=True))
    return np.asarray(v)


def _state_sharding(mesh, name, value, param_axis, shard_plan):
    """Sharding for one persistable: an explicit per-name PartitionSpec
    from `shard_plan` (tensor parallelism) wins; otherwise the uniform
    `param_axis` heuristic (fsdp) or replication."""
    if shard_plan and name in shard_plan:
        return NamedSharding(mesh, shard_plan[name])
    return param_sharding(mesh, param_axis, np.shape(value))


def _plan_key(shard_plan):
    return tuple(sorted((n, str(s)) for n, s in (shard_plan or {}).items()))


def run_sharded(exe, program, feed, fetch_list, scope, batch_axis='dp',
                param_axis=None, donate=True, shard_plan=None):
    """Execute one step of `program` SPMD over the current mesh.

    The executor's traced step function is re-jitted with NamedSharding
    constraints: feeds batch-sharded over `batch_axis` (None replicates),
    persistable state sharded over `param_axis` where divisible
    (replicated otherwise), with `shard_plan` ({name: PartitionSpec})
    overriding per-parameter — the tensor-parallel head/embedding plan.
    GSPMD propagates the rest; gradient psums over dp and activation
    collectives over tp appear in the lowered HLO automatically.
    """
    mesh = current_mesh()
    if mesh is None:
        raise RuntimeError("run_sharded requires a mesh_guard")
    if program is None:
        from ..core.program import default_main_program
        program = default_main_program()
    raw_fn, args = exe.compile_raw(program, feed=feed,
                                   fetch_list=fetch_list, scope=scope)
    feed_arrays, state_rw, state_ro, rng_key = args

    feed_sh = {n: batch_sharding(mesh, batch_axis, np.ndim(v))
               for n, v in feed_arrays.items()}
    rw_sh = {n: _state_sharding(mesh, n, v, param_axis, shard_plan)
             for n, v in state_rw.items()}
    ro_sh = {n: _state_sharding(mesh, n, v, param_axis, shard_plan)
             for n, v in state_ro.items()}
    key_sh = replicate(mesh)

    # one sharded jit per (program version, mesh, axes, arg signature) —
    # multi-step training reuses the compiled executable instead of
    # re-jitting (and thus recompiling) every call
    cache = getattr(exe, '_sharded_cache', None)
    if cache is None:
        cache = exe._sharded_cache = {}
    sig = tuple((n, np.shape(v), str(np.asarray(v).dtype) if not
                 hasattr(v, 'dtype') else str(v.dtype))
                for d in (feed_arrays, state_rw, state_ro)
                for n, v in sorted(d.items()))
    key = (program._uid, program.version, mesh, batch_axis, param_axis,
           _plan_key(shard_plan),
           tuple(getattr(f, 'name', str(f)) for f in fetch_list), donate,
           sig)
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(
            raw_fn,
            in_shardings=(feed_sh, rw_sh, ro_sh, key_sh),
            donate_argnums=(1,) if donate else ())
        cache[key] = fn

    # stage args onto the mesh explicitly: jit refuses committed
    # single-device arrays whose placement disagrees with in_shardings
    feed_arrays = {n: _place(v, feed_sh[n])
                   for n, v in feed_arrays.items()}
    state_rw = {n: _place(v, rw_sh[n])
                for n, v in state_rw.items()}
    state_ro = {n: _place(v, ro_sh[n])
                for n, v in state_ro.items()}
    rng_key = _place(rng_key, key_sh)
    # write staged read-only state back so later steps find it already on
    # the mesh and the device_puts above become no-ops
    for n, v in state_ro.items():
        scope.set(n, v)

    fetches, new_state = fn(feed_arrays, state_rw, state_ro, rng_key)
    exe._step += 1  # advance the PRNG chain (dropout etc.) across steps
    for n, v in new_state.items():
        scope.set(n, v)
    return [_fetch_np(v) for v in fetches]


def run_steps_sharded(exe, program, feed, fetch_list, scope,
                      batch_axis='dp', param_axis=None, repeat=None,
                      shard_plan=None):
    """K SPMD train steps as ONE sharded lax.scan over the mesh — the
    run_sharded counterpart of Executor.run_steps: persistable state is
    the donated carry (it never leaves the mesh between steps) and the
    per-step PRNG folds (seed, global_step) exactly like K run_sharded
    calls.  `feed` is a list of K feed dicts (stacked host-side, batch
    dim sharded over `batch_axis`) or one dict with repeat=K.  Fetches
    return [K, ...]-stacked numpy."""
    mesh = current_mesh()
    if mesh is None:
        raise RuntimeError("run_steps_sharded requires a mesh_guard")
    if program is None:
        from ..core.program import default_main_program
        program = default_main_program()
    if isinstance(feed, dict):
        if not repeat:
            raise ValueError("single feed dict needs repeat=K")
        feeds, k = [feed], int(repeat)
    else:
        feeds, k = list(feed), len(feed)
        if repeat:
            raise ValueError("repeat= only combines with a single dict")
        if k == 0:
            return []
    stacked = len(feeds) > 1

    raw_fn, args = exe.compile_raw(program, feed=feeds[0],
                                   fetch_list=fetch_list, scope=scope)
    feed_arrays, state_rw, state_ro, _rng_key = args

    feed_sh = {n: batch_sharding(mesh, batch_axis, np.ndim(v))
               for n, v in feed_arrays.items()}
    xs_sh = {n: NamedSharding(mesh, P(None, *s.spec))
             for n, s in feed_sh.items()}
    rw_sh = {n: _state_sharding(mesh, n, v, param_axis, shard_plan)
             for n, v in state_rw.items()}
    ro_sh = {n: _state_sharding(mesh, n, v, param_axis, shard_plan)
             for n, v in state_ro.items()}
    key_sh = replicate(mesh)

    cache = getattr(exe, '_sharded_cache', None)
    if cache is None:
        cache = exe._sharded_cache = {}
    sig = tuple((n, np.shape(v), str(np.asarray(v).dtype) if not
                 hasattr(v, 'dtype') else str(v.dtype))
                for d in (feed_arrays, state_rw, state_ro)
                for n, v in sorted(d.items()))
    mkey = ('multi', program._uid, program.version, mesh, batch_axis,
            param_axis, _plan_key(shard_plan), k, stacked,
            tuple(getattr(f, 'name', str(f)) for f in fetch_list), sig)
    fn = cache.get(mkey)
    if fn is None:
        from ..core.executor import make_multi_step_fn
        fn = jax.jit(
            make_multi_step_fn(raw_fn, stacked, k),
            in_shardings=(feed_sh, xs_sh if stacked else None, rw_sh,
                          ro_sh, key_sh, key_sh),
            donate_argnums=(2,))
        cache[mkey] = fn

    feed0 = {n: _place(v, feed_sh[n]) for n, v in feed_arrays.items()}
    xs = None
    if stacked:
        from ..core.executor import _to_feed_arrays
        block = program.global_block()
        cols = {}
        for f in feeds:
            fa = {}
            for name, value in f.items():
                fa.update(_to_feed_arrays(name, value,
                                          block.vars.get(name)))
            for n, v in fa.items():
                cols.setdefault(n, []).append(np.asarray(v))
        from ..core.executor import _stack_feed_col
        xs = {n: _place(_stack_feed_col(n, vs), xs_sh[n])
              for n, vs in cols.items()}
    state_rw = {n: _place(v, rw_sh[n]) for n, v in state_rw.items()}
    state_ro = {n: _place(v, ro_sh[n]) for n, v in state_ro.items()}
    for n, v in state_ro.items():
        scope.set(n, v)
    key0 = _place(jax.random.PRNGKey(exe._base_seed(program)), key_sh)
    t0 = _place(jnp.asarray(exe._step, jnp.int32), key_sh)

    ys, rw_f, last_extra = fn(feed0, xs, state_rw, state_ro, key0, t0)
    exe._step += k
    for n, v in rw_f.items():
        scope.set(n, v)
    for n, v in last_extra.items():
        scope.set(n, v)
    return [_fetch_np(y) for y in ys]
