"""Collective communication backend (D6).

Reference parity: paddle/operators/nccl_op.cc (allreduce/bcast/reduce) and
the MPI/NCCL backend — rebuilt as XLA named-axis collectives usable inside
`shard_map` over a Mesh axis; on TPU these lower onto ICI rings.  Multi
-host process bring-up (the reference's trainer_id/trainer_count env
protocol) maps to jax.distributed in distributed/launch.py.
"""
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['allreduce', 'allgather', 'reduce_scatter', 'broadcast',
           'ppermute', 'all_to_all', 'psum', 'pmean', 'pmax', 'pmin',
           'axis_index', 'axis_size', 'barrier', 'shard_map']

import jax as _jax


def shard_map(f, mesh, in_specs, out_specs, **kwargs):
    """jax.shard_map with the familiar positional signature.  Strict
    replication (vma) checking is on by default; mapped functions that
    call pallas kernels (e.g. ring_attention(use_flash=True)) must pass
    check_vma=False — jax's vma checker does not yet see through
    pallas-internal ops (its own error recommends that workaround)."""
    return _jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)


def psum(x, axis_name):
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)


def pmax(x, axis_name):
    return lax.pmax(x, axis_name)


def pmin(x, axis_name):
    return lax.pmin(x, axis_name)


def allreduce(x, axis_name, op='sum'):
    """nccl_op AllReduce parity (reduction=ncclSum/Prod/Min/Max)."""
    if op == 'sum':
        return lax.psum(x, axis_name)
    if op == 'mean':
        return lax.pmean(x, axis_name)
    if op == 'max':
        return lax.pmax(x, axis_name)
    if op == 'min':
        return lax.pmin(x, axis_name)
    if op == 'prod':
        # Exact for negatives and zeros: combine sign via parity of the
        # negative count, magnitude via sum of log|x| with zeros masked.
        is_zero = (x == 0)
        neg = lax.psum((x < 0).astype(jnp.int32), axis_name)
        any_zero = lax.pmax(is_zero.astype(jnp.int32), axis_name)
        logmag = lax.psum(jnp.where(is_zero, 0.0, jnp.log(jnp.abs(
            jnp.where(is_zero, 1.0, x)))), axis_name)
        sign = jnp.where(neg % 2 == 1, -1.0, 1.0)
        return jnp.where(any_zero > 0, 0.0,
                         sign * jnp.exp(logmag)).astype(x.dtype)
    raise ValueError("unsupported allreduce op %r" % op)


def allgather(x, axis_name, axis=0, tiled=True):
    """nccl AllGather parity: concatenate shards along `axis`."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    """ReduceScatter: sum over the axis group, then scatter along `axis` —
    the fsdp/pserver gradient path (D2)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                            tiled=True)


def broadcast(x, axis_name, root=0):
    """nccl Bcast parity: every member takes root's value."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute(x, axis_name, perm):
    """Point-to-point ring shift (ICI neighbour exchange) — the building
    block of pipeline microbatch handoff (D4) and ring attention (D5)."""
    return lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name, shift=1):
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    """MPI_Alltoall parity: re-shard between sequence- and head-sharded
    layouts (D5 sequence parallelism switch)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.psum(1, axis_name)


def barrier(axis_name):
    """Synchronisation point: a trivial psum forces a collective (the
    XLA analogue of ncclGroupEnd+cudaStreamSynchronize)."""
    return lax.psum(jnp.zeros((), jnp.int32), axis_name)
