"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (Fluid era).

This is not a port: the user-facing Program/Block/Operator IR matches
fluid (so reference model scripts run with an import change), but execution
is jit-compiled whole-block XLA (one HLO per block, donated device state),
autodiff is functional (jax.value_and_grad) rather than grad-op weaving, and
distribution is mesh/sharding-based rather than pserver/NCCL.  See
SURVEY.md for the capability map.

Typical use (parity with `import paddle.v2.fluid as fluid`):

    import paddle_tpu as fluid
    x = fluid.layers.data(name='x', shape=[13])
    y_ = fluid.layers.fc(input=x, size=1)
    ...
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
"""
from . import core
from .core import (Block, CPUPlace, CUDAPlace, LoDTensor, Operator,  # noqa
                   Parameter, Program, Scope, TPUPlace, Variable, XLAPlace,
                   create_lod_tensor, default_main_program,
                   default_startup_program, global_scope, grad_var_name,
                   name_scope, program_guard, scope_guard, switch_scope,
                   switch_main_program, switch_startup_program, unique_name, default_place)
from .core.executor import Executor
from .core import backward
from .core.backward import append_backward, calc_gradient  # noqa: F401

from . import ops  # registers the op library  # noqa: F401
from . import layers
from . import initializer
from . import learning_rate_decay
from . import nets
from . import optimizer
from . import regularizer
from . import clip
from . import evaluator
from . import io
from .data_feeder import DataFeeder
from .param_attr import ParamAttr
from . import profiler
from . import reader
from . import datasets
from .reader.minibatch import batch
dataset = datasets  # parity alias: paddle.v2.dataset
from . import parallel
from . import distributed
from .distributed import DistributeTranspiler, SimpleDistributeTranspiler
from . import highlevel  # v2 trainer/event/parameters/inference (V5-V7)
from . import plot  # v2 notebook training-curve Ploter
from . import flags  # A5 env-var config registry
from .flags import FLAGS
from . import observability  # metrics registry + /metrics exposition
from . import debug  # A3 nan/inf guards
from . import transpiler  # P14 memory_optimize -> remat
from .transpiler import memory_optimize, release_memory
from . import utils  # P17 net_drawer
from . import adversarial  # M12 FGSM toolkit

Tensor = LoDTensor

__version__ = '0.1.0'

__all__ = [
    'core', 'layers', 'nets', 'optimizer', 'initializer', 'backward',
    'regularizer', 'learning_rate_decay', 'clip', 'evaluator', 'io',
    'profiler', 'reader', 'datasets', 'dataset', 'batch',
    'observability',
    'parallel', 'distributed', 'DistributeTranspiler',
    'SimpleDistributeTranspiler',
    'Executor', 'Program', 'Block', 'Operator', 'Variable', 'Parameter',
    'Scope', 'LoDTensor', 'Tensor', 'ParamAttr', 'DataFeeder',
    'CPUPlace', 'CUDAPlace', 'TPUPlace', 'XLAPlace', 'default_place',
    'default_main_program', 'default_startup_program', 'program_guard',
    'scope_guard', 'switch_scope', 'global_scope', 'append_backward',
    'unique_name',
]
