"""Functional autodiff over a program block.

Reference parity: paddle/framework/backward.cc and fluid/backward.py — the
reference weaves one hand-written grad op per forward op into the block.
TPU-native design: we append a single `autodiff` op whose interpretation is
`jax.value_and_grad` over the forward op range (core/executor.py
_run_autodiff).  XLA sees one differentiated computation and fuses
forward+backward; there are no per-op grad kernels to maintain.
"""
from .program import Parameter, Variable, default_main_program, grad_var_name

__all__ = ['append_backward', 'calc_gradient']


def _collect_trainable_params(block, loss, parameter_list=None,
                              no_grad_set=None):
    no_grad = set(no_grad_set or [])
    if parameter_list is not None:
        names = [p.name if isinstance(p, Variable) else p
                 for p in parameter_list]
    else:
        params = block.program.all_parameters()
        names = [p.name for p in params
                 if getattr(p, 'trainable', True)]
    return [n for n in names if n not in no_grad]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Append an `autodiff` op producing `<param>@GRAD` for every trainable
    parameter, and return [(param, grad_var)] like fluid's append_backward.
    """
    assert isinstance(loss, Variable)
    program = loss.block.program
    block = program.global_block()
    param_names = _collect_trainable_params(block, loss, parameter_list,
                                            no_grad_set)

    grad_names = [grad_var_name(n) for n in param_names]
    params_and_grads = []
    for pn, gn in zip(param_names, grad_names):
        p = block.var(pn)
        if not block.has_var(gn):
            g = block.create_var(name=gn, shape=p.shape, dtype=p.dtype,
                                 persistable=False)
            g.stop_gradient = True
        else:
            g = block.var(gn)
        params_and_grads.append((p, g))

    block.append_op(
        type='autodiff',
        inputs={'Loss': [loss]},
        outputs={'Grads': grad_names},
        attrs={
            'loss_name': loss.name,
            'param_names': param_names,
            'grad_names': grad_names,
            'loss_scale': 1.0,
            'op_role': 'backward',
        })
    # Note: fluid's error_clip is applied here via callbacks weaving clip ops
    # into the grad-op chain.  In this framework a var's `error_clip` is read
    # directly by the executor, which wraps the var's forward value in a
    # clip-cotangent identity inside the autodiff closure (executor._run_one)
    # — same semantics, no grad-op weaving.  Custom callbacks still fire once
    # per (param, grad) for API parity.
    if callbacks:
        from ..clip import error_clip_callback
        for cb in (callbacks if isinstance(callbacks, (list, tuple))
                   else [callbacks]):
            if cb is error_clip_callback:
                continue  # handled natively (see note above)
            with program.op_role_guard('backward'):
                for p, g in params_and_grads:
                    cb(block, {'param': p, 'grad': g})
    return params_and_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradient of `targets` w.r.t. arbitrary `inputs` (not only
    Parameters).  Parity with fluid.backward.calc_gradient."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert len(targets) == 1, "calc_gradient supports a single target"
    loss = targets[0]
    block = loss.block.program.global_block()
    in_names = [v.name if isinstance(v, Variable) else v for v in inputs]
    grad_names = [grad_var_name(n) for n in in_names]
    grads = []
    for n, gn in zip(in_names, grad_names):
        v = block.var(n)
        if not block.has_var(gn):
            g = block.create_var(name=gn, shape=v.shape, dtype=v.dtype)
            g.stop_gradient = True
        else:
            g = block.var(gn)
        grads.append(g)
    block.append_op(
        type='autodiff',
        inputs={'Loss': [loss]},
        outputs={'Grads': grad_names},
        attrs={
            'loss_name': loss.name,
            'param_names': in_names,
            'grad_names': grad_names,
            'loss_scale': 1.0,
            'op_role': 'backward',
        })
    return grads
