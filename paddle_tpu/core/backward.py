"""Functional autodiff over a program block.

Reference parity: paddle/framework/backward.cc and fluid/backward.py — the
reference weaves one hand-written grad op per forward op into the block.
TPU-native design: we append a single `autodiff` op whose interpretation is
`jax.value_and_grad` over the forward op range (core/executor.py
_run_autodiff).  XLA sees one differentiated computation and fuses
forward+backward; there are no per-op grad kernels to maintain.
"""
from .program import Parameter, Variable, default_main_program, grad_var_name

__all__ = ['append_backward', 'calc_gradient']


def _collect_trainable_params(block, loss, parameter_list=None,
                              no_grad_set=None):
    no_grad = set(no_grad_set or [])
    if parameter_list is not None:
        names = [p.name if isinstance(p, Variable) else p
                 for p in parameter_list]
    else:
        params = block.program.all_parameters()
        names = [p.name for p in params
                 if getattr(p, 'trainable', True)]
    return [n for n in names if n not in no_grad]


def _find_sparse_params(block, param_names):
    """Params eligible for the SelectedRows grad path: every op reading
    the param is a GLOBAL-block lookup_table with is_sparse=True (parity:
    lookup_table_op.cc SelectedRows grad applies per-table).  Params with
    a regularizer or gradient clip fall back to dense — those append
    elementwise ops over the grad var, which must stay an array.  Returns
    {param_name: (height, padding_idx, [(ids_name, out_name), ...])}."""
    from ..clip import current_gradient_clip
    lookups = {}  # wname -> (padding_idx set, [(ids, out)])
    readers = {}  # var name -> [ops reading it, any block]
    global_ops = set()
    for b in block.program.blocks:
        for op in b.ops:
            for n in op.input_arg_names:
                readers.setdefault(n, []).append(op)
            if b is block:
                global_ops.add(id(op))
    sparse = {}
    for b in block.program.blocks:
        for op in b.ops:
            if op.type == 'lookup_table' and op.attrs.get('is_sparse'):
                w = op.inputs['W'][0]
                pads, pairs = lookups.setdefault(w, (set(), []))
                pads.add(op.attrs.get('padding_idx', None))
                pairs.append((op.inputs['Ids'][0], op.outputs['Out'][0],
                              id(op)))
    for pn in param_names:
        if pn not in lookups:
            continue
        if any(op.type != 'lookup_table' or not op.attrs.get('is_sparse')
               for op in readers.get(pn, [])):
            continue  # param also read densely — keep the dense grad
        pads, pairs = lookups[pn]
        if any(oid not in global_ops for _, _, oid in pairs):
            continue  # lookup inside a sub-block: dense fallback
        if len(pads) != 1:
            continue  # conflicting padding_idx across lookups: play safe
        p = block.var(pn)
        if getattr(p, 'regularizer', None) is not None or \
                getattr(p, 'gradient_clip_attr', None) is not None or \
                current_gradient_clip() is not None:
            continue  # clip/regularizer ops need a dense grad array
        sparse[pn] = (p.shape[0], next(iter(pads)),
                      [(ids, out) for ids, out, _ in pairs])
    return sparse


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Append an `autodiff` op producing `<param>@GRAD` for every trainable
    parameter, and return [(param, grad_var)] like fluid's append_backward.

    Params only read by `is_sparse` lookup_table ops take the SelectedRows
    path: the autodiff differentiates w.r.t. the lookup *outputs* and a
    `sparse_grad_assemble` op packs (ids, out-grads) into a SelectedRows —
    the vocab-height dense grad never exists (reference
    lookup_table_op.cc:52 + sgd_op.cc sparse branch).
    """
    assert isinstance(loss, Variable)
    program = loss.block.program
    block = program.global_block()
    param_names = _collect_trainable_params(block, loss, parameter_list,
                                            no_grad_set)
    sparse = _find_sparse_params(block, param_names)

    grad_names = [grad_var_name(n) for n in param_names]
    params_and_grads = []
    for pn, gn in zip(param_names, grad_names):
        p = block.var(pn)
        if not block.has_var(gn):
            g = block.create_var(name=gn, shape=p.shape, dtype=p.dtype,
                                 persistable=False)
            g.stop_gradient = True
        else:
            g = block.var(gn)
        params_and_grads.append((p, g))

    # autodiff diff-targets: dense params as-is; sparse params swap in
    # their lookup-output vars (deduped, program order)
    ad_params, ad_grads = [], []
    for pn in param_names:
        if pn in sparse:
            for _ids, out_name in sparse[pn][2]:
                if out_name not in ad_params:
                    ad_params.append(out_name)
                    ad_grads.append(grad_var_name(out_name))
        else:
            ad_params.append(pn)
            ad_grads.append(grad_var_name(pn))
    for n, gn in zip(ad_params, ad_grads):
        if not block.has_var(gn):
            v = block.var(n)
            g = block.create_var(name=gn, shape=v.shape, dtype=v.dtype,
                                 persistable=False)
            g.stop_gradient = True

    block.append_op(
        type='autodiff',
        inputs={'Loss': [loss]},
        outputs={'Grads': ad_grads},
        attrs={
            'loss_name': loss.name,
            'param_names': ad_params,
            'grad_names': ad_grads,
            'loss_scale': 1.0,
            'op_role': 'backward',
        })
    for pn, (height, pad, pairs) in sparse.items():
        attrs = {'height': height, 'op_role': 'backward'}
        if pad is not None:
            attrs['padding_idx'] = pad
        block.append_op(
            type='sparse_grad_assemble',
            inputs={'Ids': [ids for ids, _ in pairs],
                    'OutGrad': [grad_var_name(o) for _, o in pairs]},
            outputs={'Out': [grad_var_name(pn)]},
            attrs=attrs)
    # Note: fluid's error_clip is applied here via callbacks weaving clip ops
    # into the grad-op chain.  In this framework a var's `error_clip` is read
    # directly by the executor, which wraps the var's forward value in a
    # clip-cotangent identity inside the autodiff closure (executor._run_one)
    # — same semantics, no grad-op weaving.  Custom callbacks still fire once
    # per (param, grad) for API parity.
    if callbacks:
        from ..clip import error_clip_callback
        for cb in (callbacks if isinstance(callbacks, (list, tuple))
                   else [callbacks]):
            if cb is error_clip_callback:
                continue  # handled natively (see note above)
            with program.op_role_guard('backward'):
                for p, g in params_and_grads:
                    cb(block, {'param': p, 'grad': g})
    return params_and_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradient of `targets` w.r.t. arbitrary `inputs` (not only
    Parameters).  Parity with fluid.backward.calc_gradient."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert len(targets) == 1, "calc_gradient supports a single target"
    loss = targets[0]
    block = loss.block.program.global_block()
    in_names = [v.name if isinstance(v, Variable) else v for v in inputs]
    grad_names = [grad_var_name(n) for n in in_names]
    grads = []
    for n, gn in zip(in_names, grad_names):
        v = block.var(n)
        if not block.has_var(gn):
            g = block.create_var(name=gn, shape=v.shape, dtype=v.dtype)
            g.stop_gradient = True
        else:
            g = block.var(gn)
        grads.append(g)
    block.append_op(
        type='autodiff',
        inputs={'Loss': [loss]},
        outputs={'Grads': grad_names},
        attrs={
            'loss_name': loss.name,
            'param_names': in_names,
            'grad_names': grad_names,
            'loss_scale': 1.0,
            'op_role': 'backward',
        })
    return grads
