"""Core IR + runtime (parity with paddle/framework; see SURVEY.md §2.1)."""
from .datatypes import convert_dtype  # noqa: F401
from .lod import LoDTensor, create_lod_tensor  # noqa: F401
from .place import (CPUPlace, CUDAPlace, Place, TPUPlace,  # noqa: F401
                    XLAPlace, default_place)
from .program import (Block, Operator, Parameter, Program,  # noqa: F401
                      Variable, default_main_program,
                      default_startup_program, grad_var_name, name_scope,
                      program_guard, switch_main_program,
                      switch_startup_program, unique_name)
from .registry import register_op, registered_ops  # noqa: F401
from .scope import (Scope, global_scope, scope_guard,  # noqa: F401
                    switch_scope)
