"""Define-then-run program IR.

Reference parity: paddle/framework/{program_desc,block_desc,op_desc,var_desc}
and python/paddle/v2/fluid/framework.py.  Users build a Program of Blocks of
Operators over symbolic Variables; the Executor (core/executor.py) lowers a
whole block into ONE jit-compiled XLA computation — the TPU-native replacement
for the reference's per-op kernel dispatch loop (paddle/framework/executor.cc).
"""
import collections
import contextlib
import copy
import itertools
import json

import numpy as np

from . import datatypes

__all__ = [
    'Variable', 'Parameter', 'Operator', 'Block', 'Program',
    'default_main_program', 'default_startup_program', 'program_guard',
    'switch_main_program', 'switch_startup_program', 'unique_name',
    'grad_var_name', 'name_scope',
]

GRAD_SUFFIX = '@GRAD'
LEN_SUFFIX = '@LEN'  # companion int32 [batch] sequence-length array for
# variables with lod_level > 0 (TPU-native padded ragged representation;
# replaces the reference's offset-based LoD in framework/lod_tensor.h)


def grad_var_name(name):
    return name + GRAD_SUFFIX


class _UniqueNameGenerator(object):
    def __init__(self):
        self.ids = collections.defaultdict(int)

    def __call__(self, key):
        self.ids[key] += 1
        return "%s_%d" % (key, self.ids[key] - 1)


_name_generator = _UniqueNameGenerator()
_name_scope_stack = []


def unique_name(key):
    prefix = "/".join(_name_scope_stack)
    name = _name_generator(key)
    return prefix + "/" + name if prefix else name


@contextlib.contextmanager
def name_scope(prefix):
    _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        _name_scope_stack.pop()


@contextlib.contextmanager
def reset_unique_name_guard():
    """Fresh name counter (used by tests for reproducible program text)."""
    global _name_generator
    old = _name_generator
    _name_generator = _UniqueNameGenerator()
    try:
        yield
    finally:
        _name_generator = old


class Variable(object):
    """Symbolic tensor in a Block.

    Shape may contain -1 (unknown / batch dimension).  `persistable`
    variables live in the Scope across Executor.run calls (parameters,
    optimizer state, global step...).
    """

    def __init__(self,
                 block,
                 name=None,
                 shape=None,
                 dtype='float32',
                 lod_level=0,
                 persistable=False,
                 stop_gradient=False,
                 is_data=False,
                 initializer=None):
        self.block = block
        self.name = name if name is not None else unique_name('_generated_var')
        self.shape = tuple(int(d) for d in shape) if shape is not None else ()
        self.dtype = datatypes.convert_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.initializer = initializer
        block._add_var(self)

    @property
    def program(self):
        return self.block.program

    def astype(self, dtype):
        from .. import layers
        return layers.cast(x=self, dtype=dtype)

    # -- operator sugar (parity with fluid Variable math ops) --------------
    def _elementwise(self, other, op):
        from .. import layers
        if not isinstance(other, Variable):
            other = _scalar_to_var(self.block, other, self.dtype)
        return getattr(layers, 'elementwise_' + op)(x=self, y=other)

    def __add__(self, other):
        return self._elementwise(other, 'add')

    __radd__ = __add__

    def __sub__(self, other):
        return self._elementwise(other, 'sub')

    def __rsub__(self, other):
        lhs = _scalar_to_var(self.block, other, self.dtype)
        return lhs._elementwise(self, 'sub')

    def __mul__(self, other):
        return self._elementwise(other, 'mul')

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._elementwise(other, 'div')

    def __rtruediv__(self, other):
        lhs = _scalar_to_var(self.block, other, self.dtype)
        return lhs._elementwise(self, 'div')

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s, lod_level=%d%s)" % (
            self.name, self.shape, self.dtype, self.lod_level,
            ", persistable" if self.persistable else "")

    __str__ = __repr__

    def to_dict(self):
        return dict(name=self.name, shape=list(self.shape), dtype=self.dtype,
                    lod_level=self.lod_level, persistable=self.persistable,
                    stop_gradient=self.stop_gradient, is_data=self.is_data,
                    trainable=getattr(self, 'trainable', False),
                    is_parameter=isinstance(self, Parameter))


def _scalar_to_var(block, value, dtype):
    from .. import layers
    with program_guard(block.program):
        return layers.fill_constant(shape=[1], dtype=dtype,
                                    value=float(value))


class Parameter(Variable):
    """A trainable persistable Variable.

    Reference parity: python/paddle/v2/fluid/framework.py Parameter.
    """

    def __init__(self, block, shape, dtype, **kwargs):
        self.trainable = kwargs.pop('trainable', True)
        self.optimize_attr = kwargs.pop('optimize_attr',
                                        {'learning_rate': 1.0})
        self.regularizer = kwargs.pop('regularizer', None)
        self.gradient_clip_attr = kwargs.pop('gradient_clip_attr', None)
        self.error_clip = kwargs.pop('error_clip', None)
        if any(d <= 0 for d in shape):
            raise ValueError("parameter shape must be fully static, got %s" %
                             (shape,))
        super(Parameter, self).__init__(
            block, shape=shape, dtype=dtype, persistable=True, **kwargs)


class Operator(object):
    """One op in a block: type + named input/output slots (lists of var
    names) + attrs.  Attrs must be JSON-serialisable."""

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {
            k: [v.name if isinstance(v, Variable) else v for v in _as_list(vs)]
            for k, vs in (inputs or {}).items()
        }
        self.outputs = {
            k: [v.name if isinstance(v, Variable) else v for v in _as_list(vs)]
            for k, vs in (outputs or {}).items()
        }
        self.attrs = dict(attrs or {})

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    @property
    def output_arg_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def has_attr(self, name):
        return name in self.attrs

    def attr(self, name):
        return self.attrs[name]

    def set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def __repr__(self):
        return "{%s: (%s) -> (%s)}" % (self.type, dict(self.inputs),
                                       dict(self.outputs))

    def to_dict(self):
        return dict(type=self.type, inputs=self.inputs, outputs=self.outputs,
                    attrs=_jsonable_attrs(self.attrs))


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _jsonable_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {'__ndarray__': v.tolist(), 'dtype': str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


class Block(object):
    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = collections.OrderedDict()
        self.ops = []

    @property
    def parent(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def _add_var(self, var):
        self.vars[var.name] = var
        self.program._bump_version()

    def create_var(self, **kwargs):
        return Variable(self, **kwargs)

    def create_parameter(self, **kwargs):
        return Parameter(self, **kwargs)

    def has_var(self, name):
        return name in self.vars

    def has_var_recursive(self, name):
        b = self
        while b is not None:
            if name in b.vars:
                return True
            b = b.parent
        return False

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise KeyError("variable %r not in block %d" % (name, self.idx))
        return v

    def var_recursive(self, name):
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        raise KeyError("variable %r not found up the block chain" % name)

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def append_op(self, type, inputs=None, outputs=None, attrs=None,
                  index=None):
        attrs = dict(attrs or {})
        # Stamp the current role (forward/backward/optimize) so the executor
        # can tell model ops from grad/update machinery — parity with the
        # reference's OpRole attr (framework/op_proto_maker.h).
        attrs.setdefault('op_role', self.program._current_role)
        op = Operator(self, type, inputs, outputs, attrs)
        if index is None:
            self.ops.append(op)
        else:
            self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def prepend_op(self, **kwargs):
        kwargs['index'] = 0
        return self.append_op(**kwargs)

    def remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def __repr__(self):
        lines = ["block[%d] parent=%d" % (self.idx, self.parent_idx)]
        for v in self.vars.values():
            lines.append("  " + repr(v))
        for op in self.ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)


class Program(object):
    _uid_counter = itertools.count()

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._seed_counter = 0
        self._current_role = 'forward'
        # process-unique identity: unlike id(), never reused after GC, so
        # caches keyed on it can't serve a stale entry to a new Program
        self._uid = next(Program._uid_counter)

    @contextlib.contextmanager
    def op_role_guard(self, role):
        """Ops appended inside the guard are stamped with `role`
        ('forward' | 'backward' | 'optimize')."""
        old, self._current_role = self._current_role, role
        try:
            yield
        finally:
            self._current_role = old

    # executor cache invalidation -----------------------------------------
    def _bump_version(self):
        self._version += 1

    @property
    def version(self):
        return self._version

    # block management -----------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        parent = (self.current_block_idx
                  if parent_idx is None else parent_idx)
        self.blocks.append(Block(self, len(self.blocks), parent))
        self.current_block_idx = len(self.blocks) - 1
        self._bump_version()
        return self.current_block()

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # parity helpers --------------------------------------------------------
    def list_vars(self):
        for b in self.blocks:
            for v in b.vars.values():
                yield v

    def all_parameters(self):
        return [v for v in self.list_vars() if isinstance(v, Parameter)]

    def clone(self, for_test=False):
        """Deep-copy the program.  With for_test=True, flip every op's
        `is_test` attr (dropout scales by keep-prob, batch_norm uses running
        stats) — parity with fluid Program.clone + inference_optimize."""
        p = copy.deepcopy(self)
        p._uid = next(Program._uid_counter)  # a clone is a new identity
        if for_test:
            for block in p.blocks:
                for op in block.ops:
                    if 'is_test' in op.attrs:
                        op.attrs['is_test'] = True
        p._bump_version()
        return p

    def prune(self, targets, feeds=()):
        """Drop ops not needed to compute `targets` (names or Variables).

        Reference parity: paddle/framework/prune.cc.  Backward reachability
        from the fetch set over the def-use graph; feed names are treated as
        produced.
        """
        target_names = set(
            t.name if isinstance(t, Variable) else t for t in _as_list(targets))
        feed_names = set(
            f.name if isinstance(f, Variable) else f for f in _as_list(feeds))
        p = copy.deepcopy(self)
        p._uid = next(Program._uid_counter)  # a pruned copy is a new identity
        for block in p.blocks:
            needed = set(target_names)
            kept = []
            for op in reversed(block.ops):
                out_names = set(op.output_arg_names)
                if out_names & needed:
                    kept.append(op)
                    needed -= out_names
                    for n in op.input_arg_names:
                        if n not in feed_names:
                            needed.add(n)
                    # sub-block ops depend on everything their block reads
                    for attr in ('sub_block', 'sub_block_idx'):
                        if attr in op.attrs:
                            sub = p.blocks[op.attrs[attr]]
                            for sop in sub.ops:
                                needed.update(sop.input_arg_names)
            kept.reverse()
            block.ops = kept
        p._bump_version()
        return p

    def inference_optimize(self):
        return self.clone(for_test=True)

    # serialization ---------------------------------------------------------
    def to_dict(self):
        return dict(
            random_seed=self.random_seed,
            blocks=[
                dict(idx=b.idx, parent_idx=b.parent_idx,
                     vars=[v.to_dict() for v in b.vars.values()],
                     ops=[op.to_dict() for op in b.ops])
                for b in self.blocks
            ])

    def to_json(self):
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d):
        p = Program()
        p.random_seed = d.get('random_seed', 0)
        p.blocks = []
        for bd in d['blocks']:
            b = Block(p, bd['idx'], bd['parent_idx'])
            p.blocks.append(b)
            for vd in bd['vars']:
                vd = dict(vd)
                is_param = vd.pop('is_parameter', False)
                trainable = vd.pop('trainable', False)
                if is_param:
                    vd.pop('persistable', None)
                    Parameter(b, trainable=trainable, **vd)
                else:
                    Variable(b, **vd)
            for od in bd['ops']:
                attrs = {}
                for k, v in od['attrs'].items():
                    if isinstance(v, dict) and '__ndarray__' in v:
                        attrs[k] = np.array(v['__ndarray__'],
                                            dtype=v['dtype'])
                    else:
                        attrs[k] = v
                b.append_op(od['type'], od['inputs'], od['outputs'], attrs)
        p.current_block_idx = 0
        return p

    @staticmethod
    def from_json(s):
        return Program.from_dict(json.loads(s))

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)

    __str__ = __repr__


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    old = _main_program
    _main_program = program
    return old


def switch_startup_program(program):
    global _startup_program
    old = _startup_program
    _startup_program = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)
