"""Scope: name -> device array store for persistable state.

Reference parity: paddle/framework/scope.{h,cc}.  Values are jax.Arrays that
stay resident on device between Executor.run calls (parameters, optimizer
moments, batch-norm running stats, global step, RNG state).
"""
import itertools

import numpy as np

# Monotonic scope identity for plan-cache keys: id(scope) can be reused by
# the allocator after a scope is garbage-collected, silently aliasing a new
# scope's compiled plans (and donated-state signatures) with a dead one's.
_scope_uid = itertools.count()


class Scope(object):
    def __init__(self, parent=None):
        self._uid = next(_scope_uid)
        self._vars = {}
        self.parent = parent
        self._kids = []
        if parent is not None:
            parent._kids.append(self)

    def var(self, name):
        """Create-or-get (parity with Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return name

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has(self, name):
        s = self
        while s is not None:
            if name in s._vars and s._vars[name] is not None:
                return True
            s = s.parent
        return False

    def set(self, name, value):
        self._vars[name] = value

    def get(self, name):
        v = self.find_var(name)
        if v is None:
            raise KeyError("variable %r has no value in scope (did you run "
                           "the startup program?)" % name)
        return v

    def get_numpy(self, name):
        return np.asarray(self.get(name))

    def new_scope(self):
        return Scope(self)

    def drop_kids(self):
        self._kids = []

    def erase(self, name):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars)


_global_scope = Scope()


def global_scope():
    return _global_scope


def switch_scope(scope):
    """Swap the global scope, returning the previous one (reference
    executor.py:switch_scope)."""
    global _global_scope
    old = _global_scope
    _global_scope = scope
    return old


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        global _global_scope
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old

    return _guard()
