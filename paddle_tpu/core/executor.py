"""Executor: lowers a whole Program block into ONE jit-compiled XLA
computation.

Reference parity: paddle/framework/executor.{h,cc} + python fluid
executor.py.  The reference interprets a block op-by-op, dispatching a CUDA
kernel per op.  TPU-native design: the same block is *traced* op-by-op in
Python exactly once, producing a single fused HLO program that XLA compiles
for the MXU; parameters stay device-resident in the Scope and are donated
across steps, so a full train step (forward + backward + optimizer update)
is one device launch with zero host round-trips.
"""
import contextlib
import os
import re
import time
import warnings

import numpy as np

import jax
import jax.numpy as jnp

from .. import observability as _obs
from ..observability import timeline as _tlm
from . import datatypes
from .lod import LoDTensor
from .place import default_place
from .program import (LEN_SUFFIX, Program, Variable, default_main_program)
from .registry import get_op_impl
from .scope import Scope, global_scope

__all__ = ['Executor', 'global_scope', 'scope_guard']

from .scope import scope_guard  # re-export (parity with fluid.executor)

_compilation_cache_dir = None  # last dir applied to jax.config
_compilation_cache_resolved = False  # any resolve happened (late-apply)


def _maybe_enable_compilation_cache():
    """Opt-in persistent XLA compilation cache
    (PADDLE_TPU_COMPILATION_CACHE_DIR): every jit compile — Executor
    plans, serving warmup buckets — lands in this directory and survives
    process restarts, so a restarted server skips straight to cache hits.
    Re-reads the flag each call (cheap) so tests and long-lived drivers
    can flip it; thresholds drop to 0 so even fast CPU-smoke compiles
    persist (the default 1s floor would skip them silently).

    Called from executor/server construction AND from every plan-cache
    miss, so a dir set after first executor use applies on the next
    plan build (with a one-line warning) instead of silently waiting
    for reset_cache()."""
    global _compilation_cache_dir, _compilation_cache_resolved
    from ..flags import FLAGS
    d = FLAGS.compilation_cache_dir or None
    if d == _compilation_cache_dir:
        _compilation_cache_resolved = True
        return
    late = _compilation_cache_resolved and d is not None
    try:
        jax.config.update('jax_compilation_cache_dir', d)
        if d:
            jax.config.update('jax_persistent_cache_min_compile_time_secs',
                              0.0)
            jax.config.update('jax_persistent_cache_min_entry_size_bytes',
                              0)
        # jax latches the cache backend at its first compile; flipping
        # the dir after that is silently ignored unless the cache is
        # reset, so a long-lived process (or test) can opt in late
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:  # pragma: no cover - older jax without the knobs
        return
    _compilation_cache_dir = d
    _compilation_cache_resolved = True
    if late:
        import logging
        logging.getLogger(__name__).warning(
            'PADDLE_TPU_COMPILATION_CACHE_DIR=%r was set after first '
            'executor use; applied now — plans built from here on '
            'compile into the persistent cache', d)


def _maybe_apply_tuned(program, place):
    """PADDLE_TPU_TUNE=cached: apply persisted autotuner winners for
    this program (tuning/runtime.py) BEFORE the mesh resolves and the
    plan key is computed — the applied env overrides are plan-cache-key
    components, so the tuned plan builds exactly as a fresh pre-tuned
    process would build it.  With tuning off (the default) this is one
    dict lookup: no import, no flag object, bitwise-identical paths."""
    if os.environ.get('PADDLE_TPU_TUNE') != 'cached':
        return
    try:
        from ..tuning import runtime as _trt
        _trt.maybe_apply_cached(program, place)
    except Exception:  # never let tuning break an untunable run
        import logging
        logging.getLogger(__name__).warning(
            'tuning cache apply failed; running untuned', exc_info=True)


class _ExecutorMetrics(object):
    """Handles into the observability registry for the executor layer.

    Created lazily on the first *enabled* use — with
    PADDLE_TPU_METRICS_ENABLED=0 nothing here is ever allocated, which
    is the zero-overhead contract the hot path relies on.  All metrics
    are host-side: they bracket the calls *into* compiled code, never
    run under a trace.
    """

    def __init__(self):
        r = _obs.registry()
        # .child() handles: one lock per event on the hot path, vs the
        # metric-level conveniences' label lookup + two locks per event
        self.plan_cache_hits = r.counter(
            'paddle_tpu_executor_plan_cache_hits_total',
            'Executor plan-cache lookups served from cache').child()
        self.plan_cache_misses = r.counter(
            'paddle_tpu_executor_plan_cache_misses_total',
            'Executor plan-cache lookups that built (traced) a new '
            'plan').child()
        self.compiles = r.counter(
            'paddle_tpu_executor_compiles_total',
            'first invocations of freshly built plans (each pays the '
            'XLA compile)').child()
        self.compile_seconds = r.histogram(
            'paddle_tpu_executor_compile_seconds',
            'wall time of the first invocation of a fresh plan '
            '(trace + XLA compile + dispatch)',
            buckets=_obs.DEFAULT_COMPILE_BUCKETS).child()
        self.runs = r.counter(
            'paddle_tpu_executor_runs_total',
            'Executor.run() calls').child()
        self.steps = r.counter(
            'paddle_tpu_executor_steps_total',
            'train/eval steps executed (run() counts one, '
            'run_steps(K) counts K)').child()
        self.feed_bytes = r.counter(
            'paddle_tpu_executor_feed_bytes_total',
            'bytes of feed data staged to the device').child()
        self.donated_state_bytes = r.counter(
            'paddle_tpu_executor_donated_state_bytes_total',
            'bytes of persistable state donated into compiled '
            'steps').child()
        self.graph_opt_ops_eliminated = r.counter(
            'paddle_tpu_graph_opt_ops_eliminated_total',
            'ops removed from traced programs by the graph-opt pass '
            'pipeline (DCE + constant folding + CSE), summed over '
            'plan builds').child()
        self.graph_opt_seconds = r.histogram(
            'paddle_tpu_graph_opt_seconds',
            'wall time of one graph-opt pipeline run (per plan-cache '
            'miss)', buckets=_obs.DEFAULT_COMPILE_BUCKETS).child()
        self.amp_ops_lowered = r.counter(
            'paddle_tpu_amp_ops_lowered_total',
            'ops rewritten to low-precision compute by the AMP pass '
            '(PADDLE_TPU_AMP), summed over plan builds').child()
        self.amp_skipped_steps = r.counter(
            'paddle_tpu_amp_skipped_steps_total',
            'training steps skipped by dynamic loss scaling '
            '(non-finite gradients; f16 mode only)').child()
        self.donated_feed_bytes = r.counter(
            'paddle_tpu_executor_donated_feed_bytes_total',
            'bytes of executor-staged feed buffers donated into '
            'compiled steps (XLA reuses them for the short-lived '
            'intermediates the donation analysis reports)').child()
        self.feed_blocking_puts = r.counter(
            'paddle_tpu_executor_feed_blocking_puts_total',
            'per-step feed staging operations on the run_steps '
            'critical path (device idle while the host stacks/'
            'transfers); with PADDLE_TPU_DEVICE_PREFETCH only the '
            'pipeline-priming chunk counts here').child()
        self.feed_prefetched_puts = r.counter(
            'paddle_tpu_executor_feed_prefetched_puts_total',
            'per-step feed chunks staged by the device-prefetch '
            'pipeline while a previous chunk was executing '
            '(overlapped, off the critical path)').child()
        self.feed_prefetched_bytes = r.counter(
            'paddle_tpu_executor_feed_prefetched_bytes_total',
            'bytes staged by the device-prefetch pipeline while a '
            'previous chunk was executing').child()
        self.ir_verify_failures = r.counter(
            'paddle_tpu_ir_verify_failures_total',
            'plan builds rejected by the static IR verifier '
            '(PADDLE_TPU_VERIFY_IR, transpiler/verify.py) — each one '
            'is a pass bug or a malformed program caught before '
            'tracing').child()
        self.collective_modeled_bytes = r.counter(
            'paddle_tpu_executor_collective_modeled_bytes_total',
            'modeled per-device ICI bytes moved by the collectives of '
            'executed SPMD steps (PADDLE_TPU_MESH; ring closed forms '
            'from the sharding pass + cost model), summed over steps '
            '— the communication half of the roofline').child()
        self.collectives_modeled = r.counter(
            'paddle_tpu_executor_collectives_modeled_total',
            'modeled collective operations (gradient allreduce, fsdp '
            'reduce-scatter/all-gather) executed inside SPMD steps, '
            'summed over steps').child()
        self.collective_exposed_bytes = r.counter(
            'paddle_tpu_executor_collective_exposed_bytes_total',
            'modeled ICI bytes NOT hidden behind compute: the exposed '
            'remainder of the overlap schedule (gradient-bucket '
            'allreduces past the backward+update window, pipeline '
            'ppermute sends past their stage tick), summed over steps '
            '— the serial communication tax the overlap pass could '
            'not remove').child()
        self.collective_overlapped_bytes = r.counter(
            'paddle_tpu_executor_collective_overlapped_bytes_total',
            'modeled ICI bytes hidden behind concurrent compute by '
            'the collective-overlap schedule '
            '(PADDLE_TPU_OVERLAP / transpiler/overlap.py), summed '
            'over steps').child()


_exec_metrics = None


def _em():
    global _exec_metrics
    if _exec_metrics is None:
        _exec_metrics = _ExecutorMetrics()
    return _exec_metrics


def _nbytes(arrays):
    """Total nbytes over a {name: array} dict (jax and numpy arrays both
    expose .nbytes; anything else counts 0)."""
    return sum(getattr(v, 'nbytes', 0) for v in arrays.values())


def _feed_aval_strs(feed_arrays):
    """The jax donation warning names each unusable buffer as
    ShapedArray(<dtype>[<d0>,<d1>,...]); precompute those strings for
    the donated feed buffers so _quiet_unused_donation can tell an
    expected feed-donation miss apart from a state-donation one."""
    out = set()
    for v in feed_arrays.values():
        dt = np.dtype(v.dtype).name
        out.add('ShapedArray(%s[%s])'
                % (dt, ','.join(str(d) for d in v.shape)))
    return out


@contextlib.contextmanager
def _quiet_unused_donation(feed_arrays=None):
    """Silence jax's "Some donated buffers were not usable" warning for
    one compiling invocation of a FEED-donating plan.  Donated feed
    buffers are executor-staged host data that is dead after the step —
    donating them is an ownership statement (and free aliasing headroom
    where an output happens to match); a feed shape rarely matches an
    output, so the warning is expected there and would fire on every
    fresh compile.  The warning is swallowed ONLY when every buffer it
    names matches a donated feed aval (best-effort: a state table that
    shares a feed's shape+dtype is indistinguishable in the message);
    anything else re-emits, because an unusable STATE donation is a
    real peak-HBM regression worth hearing about.  State-donating-only
    plans (feed_arrays falsy) are never filtered."""
    if not feed_arrays:
        yield
        return
    allowed = _feed_aval_strs(feed_arrays)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter('always')
        yield
    for w in caught:
        msg = str(w.message)
        if msg.startswith('Some donated buffers were not usable'):
            named = set(re.findall(r'ShapedArray\([^)]*\)',
                                   msg.split('\n', 1)[0]))
            if named and named <= allowed:
                continue
        warnings.warn_explicit(w.message, w.category, w.filename,
                               w.lineno)


def _shard_put(v, sh):
    """Place one value with a NamedSharding, passing through values
    already holding it (the steady-state no-op for device-resident
    state under a stable mesh)."""
    if isinstance(v, jax.Array) and getattr(v, 'sharding', None) == sh:
        return v
    return jax.device_put(v, sh)


def _pass_plan_key(program):
    """The composite pass-configuration component of every plan cache
    key — graph-opt level (with the memory_optimize floor), AMP mode
    (+ loss-scale knobs), verify mode, and the sparse/dense apply
    lowerings, all re-read per build so a flag flip is never served a
    stale trace.  ONE code path (transpiler/pass_manager.plan_key)
    feeds both the run and run_steps keys."""
    from ..transpiler import pass_manager
    return pass_manager.plan_key(program)


class ExecutionContext(object):
    """Per-trace context handed to op compute functions: PRNG derivation,
    access to the interpreter for ops that carry sub-blocks, and the
    enclosing program/block."""

    def __init__(self, program, block, rng_key, uid_prefix=0,
                 backend=None):
        self.program = program
        self.block = block
        self.rng_key = rng_key
        self.uid_prefix = uid_prefix
        self.op_index = 0
        # platform the enclosing jit targets ('tpu'/'cpu'): ops that pick
        # between a Pallas kernel and a lax fallback must key off THIS,
        # not jax.default_backend() — a CPUPlace run on a TPU-attached
        # host would otherwise compile Pallas kernels for CPU
        self.backend = backend or jax.default_backend()

    def rng(self, extra=0):
        """Deterministic per-op PRNG key: stable under the autodiff replay
        of forward ops (keys derive from op position, not call order)."""
        k = jax.random.fold_in(self.rng_key, self.uid_prefix)
        k = jax.random.fold_in(k, self.block.idx)
        k = jax.random.fold_in(k, self.op_index)
        if extra:
            k = jax.random.fold_in(k, extra)
        return k

    def sub_context(self, block):
        sub = ExecutionContext(self.program, block, self.rng_key,
                               self.uid_prefix + 1000,
                               backend=self.backend)
        return sub

    def run_block(self, block_idx, env):
        """Interpret a sub-block in-place over `env` (used by control-flow
        ops like conditional_block)."""
        block = self.program.blocks[block_idx]
        ctx = self.sub_context(block)
        _run_ops(block.ops, env, ctx)
        return env


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _clip_cotangent(x, lo, hi):
    """Identity whose backward clips the incoming gradient — the TPU-native
    realisation of fluid's ErrorClipByValue (clip.py error_clip_callback):
    instead of weaving a clip op into the grad-op chain, the clip rides the
    VJP of the var it guards."""
    return x


def _cc_fwd(x, lo, hi):
    return x, None


def _cc_bwd(lo, hi, _res, g):
    return (jnp.clip(g, lo, hi),)


_clip_cotangent.defvjp(_cc_fwd, _cc_bwd)


# optimizers with a true row-wise SelectedRows rule (ops/optim_ops.py
# sparse branches): a sentinel-gated grad row-set leaves their outputs
# bitwise-unchanged, so AMP skip-step can gate on the ids alone
_ROWWISE_SPARSE_OPS = frozenset({'sgd', 'adagrad', 'adam'})


def _run_one(op, env, ctx, op_index, frozen=()):
    impl = get_op_impl(op.type)
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n not in env:
                raise KeyError(
                    "op %s reads %r which has no value; feed it, run the "
                    "startup program, or check op ordering" % (op.type, n))
            vals.append(env[n])
        ins[slot] = vals
    if impl.needs_env:
        ins['__env__'] = [env]
    # AMP f16 skip-step: an optimize-role op stamped with `amp_gate_var`
    # (transpiler/amp.py) keeps every output's OLD value when the
    # gradients of this step were non-finite — params, moments, and
    # counters all stand still, the textbook loss-scaling skip.
    # Dense updates gate on the outputs (jnp.where fuses into the
    # elementwise update for free).  SelectedRows grads gate on the IDS
    # instead: rows swap to the >=height sentinel on overflow (the PR-4
    # ragged-padding contract — the Pallas kernel skips them, XLA drops
    # the oob scatter), so no touched row exists and the donated
    # in-place table update stays in place; a full-table output where
    # would force XLA to keep the pre-update table live (copy + select,
    # O(table height)) on EVERY step, reverting the row-sparse win.
    gate = op.attrs.get('amp_gate_var')
    gate_val = olds = None
    if gate is not None and gate in env:
        from .selected_rows import SelectedRows
        gate_val = jnp.reshape(env[gate], ()).astype(bool)
        sparse_gated = False
        for slot, vals in list(ins.items()):
            if slot == '__env__':
                continue
            gated_vals = []
            for v in vals:
                if isinstance(v, SelectedRows):
                    v = SelectedRows(
                        jnp.where(gate_val, v.height, v.rows),
                        v.values, v.height)
                    sparse_gated = True
                gated_vals.append(v)
            ins[slot] = gated_vals
        if not (sparse_gated and op.type in _ROWWISE_SPARSE_OPS):
            olds = {n: env[n] for n in op.output_arg_names if n in env}
        # row-wise sparse ops need no output where: with every row at
        # the sentinel, the kernel/scatter writes nothing and the
        # outputs already equal the old state bitwise.  Optimizers that
        # DENSIFY sparse grads (momentum & co) still decay their state
        # on a zero grad, so they keep the output where — they pay the
        # O(height) pass either way.
    # per-op PRNG keys derive from the op's position; an op that survived
    # the graph-opt pipeline carries its PRE-pass position as `op_seq`,
    # so eliminating ops never shifts another op's RNG stream (dropout
    # masks are bitwise-identical with and without optimization)
    ctx.op_index = op.attrs.get('op_seq', op_index)
    outs = impl.compute(ctx, ins, op.attrs) or {}
    if '__env_update__' in outs:
        env.update(outs.pop('__env_update__')[0])
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for n, v in zip(names, vals):
            if v is None:
                continue
            if n in frozen:
                # `n` is a differentiation point (calc_gradient wrt an
                # intermediate var): keep the injected leaf value so grads
                # attach to it rather than to its producer.
                continue
            if olds is not None and n in olds:
                v = jnp.where(gate_val, olds[n], v)
            try:
                var = ctx.block.var_recursive(n)
                if var.stop_gradient and not var.is_data:
                    v = jax.lax.stop_gradient(v)
                ec = getattr(var, 'error_clip', None)
                if ec is not None:
                    v = _clip_cotangent(v, float(ec.min), float(ec.max))
            except KeyError:
                pass
            env[n] = v


def _op_role(op):
    return op.attrs.get('op_role', 'forward')


def _tainted_slice(ops, k, param_names, ad_idxs):
    """Forward-role ops before index k on the dependency path from
    `param_names` to anything downstream (forward taint propagation)."""
    tainted = set(param_names)
    picked = []
    for j in range(k):
        if j in ad_idxs or _op_role(ops[j]) != 'forward':
            continue
        if set(ops[j].input_arg_names) & tainted:
            picked.append((j, ops[j]))
            tainted.update(ops[j].output_arg_names)
    return picked


def _run_ops(ops, env, ctx):
    """Interpret a list of ops with fluid program-order semantics.

    `autodiff` ops (appended by core/backward.py) replace the reference's
    per-op grad weaving (framework/backward.cc) with jax.value_and_grad:

    - The FIRST autodiff executes every preceding forward-role op inside its
      closure (one fused fwd+bwd HLO — the hot path for normal training) and
      publishes their outputs.  Exact, because no optimizer update precedes
      it.
    - LATER autodiff ops (multi-minimize programs: GAN, multi-loss) re-run
      only the subgraph tainted by their params, from a snapshot in which
      any already-applied optimizer updates are rolled back — so every
      gradient is taken at the values the single program-order forward saw,
      matching the reference executor exactly.
    - backward/optimize-role ops (grad clip, regularizers, sgd/adam, LR
      schedules) run at top level in program order.
    """
    ad_idxs = [i for i, op in enumerate(ops) if op.type == 'autodiff']
    first_ad = ad_idxs[0] if ad_idxs else None
    c1 = set()
    if first_ad is not None:
        c1 = {j for j in range(first_ad)
              if j not in ad_idxs and _op_role(ops[j]) == 'forward'}
    pre_update_vals = {}  # param name -> value before its first update
    for i, op in enumerate(ops):
        if op.type == 'autodiff':
            if i == first_ad:
                fwd = [(j, ops[j]) for j in sorted(c1)]
                _run_autodiff(op, fwd, env, ctx, {}, publish=True)
            else:
                fwd = _tainted_slice(ops, i, op.attrs['param_names'],
                                     set(ad_idxs))
                _run_autodiff(op, fwd, env, ctx, pre_update_vals,
                              publish=False)
        elif i in c1:
            continue  # runs inside the first autodiff closure
        else:
            if _op_role(op) == 'optimize':
                for n in op.output_arg_names:
                    if n in env and n not in pre_update_vals:
                        # (pre-update value, program index of the update):
                        # a later autodiff rolls `n` back only for forward
                        # ops that originally ran before this index
                        pre_update_vals[n] = (env[n], i)
            _run_one(op, env, ctx, i)


def _run_autodiff(ad_op, fwd_ops, env, ctx, pre_update_vals, publish):
    """fwd_ops: [(original_index, op)] forward slice for this autodiff."""
    param_names = list(ad_op.attrs['param_names'])
    grad_names = list(ad_op.attrs['grad_names'])
    loss_name = ad_op.attrs['loss_name']
    loss_scale = ad_op.attrs.get('loss_scale', 1.0)
    # AMP dynamic loss scaling (transpiler/amp.py f16 mode): the scale
    # is a persistable var, so it updates per step and rides the
    # run_steps scan carry; check_finite_and_unscale divides it back out
    # of the grads downstream.
    ls_var = ad_op.attrs.get('loss_scale_var')

    captured = dict(env)
    # Keep the POST-update value only when every forward op in this slice
    # that reads the var originally ran after its update (ops built after
    # a minimize() see the updated value in the reference executor too).
    # A slice whose reads straddle the update has no single consistent
    # value; we choose the pre-update one so gradients attach to the
    # values the pre-update forward saw (the common multi-loss pattern).
    for n, (val, upd_idx) in pre_update_vals.items():
        read_idxs = [j for j, op in fwd_ops if n in op.input_arg_names]
        if not read_idxs or min(read_idxs) < upd_idx:
            captured[n] = val
    written = set()
    for _, op in fwd_ops:
        written.update(op.output_arg_names)
    frozen = frozenset(set(param_names) & written)

    if any(n not in captured for n in param_names):
        # calc_gradient wrt an intermediate var: materialise its value with
        # one plain forward pass (XLA CSEs this against the grad pass).
        env_pre = dict(captured)
        for j, op in fwd_ops:
            _run_one(op, env_pre, ctx, j)
        for n in param_names:
            if n not in captured:
                captured[n] = env_pre[n]
                env[n] = env_pre[n]
    params = {n: captured[n] for n in param_names}

    def f(ps):
        env2 = dict(captured)
        env2.update(ps)
        # fluid's error_clip also guards leaf vars (fed data / Parameters):
        # they enter the VJP here as leaves, so the clip must ride their
        # injected value, not a producing op's output (there is none).
        for n in param_names:
            try:
                var = ctx.block.var_recursive(n)
            except KeyError:
                continue
            ec = getattr(var, 'error_clip', None)
            if ec is not None:
                env2[n] = _clip_cotangent(env2[n], float(ec.min),
                                          float(ec.max))
        for j, op in fwd_ops:
            _run_one(op, env2, ctx, j, frozen)
        loss = env2[loss_name]
        loss = jnp.sum(loss.astype(jnp.float32)) * loss_scale
        if ls_var is not None and ls_var in env2:
            loss = loss * jnp.reshape(
                jnp.asarray(env2[ls_var]).astype(jnp.float32), ())
        return loss, env2

    from ..transpiler.memory_optimize import get_remat_policy
    remat = get_remat_policy(ctx.program)
    if remat is not None:
        # P14 memory_optimize: backward recomputes activations instead of
        # keeping them live across the fused fwd+bwd
        f = remat(f)
    (_, env_fwd), grads = jax.value_and_grad(f, has_aux=True)(params)
    if publish:
        for n in written:
            if n in env_fwd:
                env[n] = env_fwd[n]
        if loss_name not in written and loss_name in env_fwd:
            env[loss_name] = env_fwd[loss_name]
    # overlap_collectives lowering: tie each bucket's gradients together
    # with one optimization_barrier — an identity (bitwise-same values,
    # donation-safe) that hands XLA's latency-hiding scheduler a
    # per-bucket dependency cut, so the bucket's allreduce/
    # reduce-scatter issues when ITS grads retire instead of after the
    # whole backward.  No attr (pass off / no mesh) -> path untouched.
    buckets = ad_op.attrs.get('overlap_buckets')
    if buckets:
        grad_to_param = dict(zip(grad_names, param_names))
        for bucket in buckets:
            pns = [grad_to_param[gn] for gn in bucket
                   if grad_to_param.get(gn) in grads]
            if not pns:
                continue
            vals = jax.lax.optimization_barrier(
                tuple(grads[pn] for pn in pns))
            for pn, v in zip(pns, vals):
                grads[pn] = v
    for pn, gn in zip(param_names, grad_names):
        g = grads[pn]
        env[gn] = g.astype(params[pn].dtype) if hasattr(g, 'astype') else g


def _to_feed_arrays(name, value, var):
    """Convert one feed entry to {name: array} (+ companion lengths for
    ragged feeds)."""
    out = {}
    if isinstance(value, jax.Array):
        # Already device-resident (staged by the caller or a prefetch
        # reader): pass through untouched — np.asarray here would drag it
        # back to host and re-upload it every step.
        out[name] = value
        return out
    if isinstance(value, LoDTensor):
        out[name] = _np_to_device_dtype(value.padded(), var)
        if value.is_ragged():
            out[name + LEN_SUFFIX] = np.asarray(value.lengths(),
                                                dtype=np.int32)
        return out
    if isinstance(value, tuple) and len(value) == 2 and var is not None \
            and var.lod_level > 0:
        data, lengths = value
        out[name] = _np_to_device_dtype(np.asarray(data), var)
        out[name + LEN_SUFFIX] = np.asarray(lengths, dtype=np.int32)
        return out
    out[name] = _np_to_device_dtype(np.asarray(value), var)
    return out


def _np_to_device_dtype(arr, var):
    """Narrow 64-bit host arrays to the 32-bit types TPUs run (x64 is
    disabled); honour the declared var dtype otherwise."""
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    elif arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    elif arr.dtype == np.uint64:
        arr = arr.astype(np.uint32)
    if var is not None and datatypes.is_float_dtype(var.dtype) and \
            arr.dtype.kind in 'fiu':
        want = datatypes.as_numpy_dtype(var.dtype)
        if want in (np.float64,):
            want = np.float32
        arr = arr.astype(want)
    return arr


def _convert_feed(block, feed):
    """One feed dict → {column name: array} through _to_feed_arrays
    (which may add companion columns like the LEN_SUFFIX lengths).
    The single home of that expansion for run(), run_steps and the
    chunked prefetch pre-validation — the paths must agree on the
    column set or a feed accepted by one is rejected by another."""
    fa = {}
    for name, value in feed.items():
        fa.update(_to_feed_arrays(name, value, block.vars.get(name)))
    return fa


def _feed_shape_error(name, shapes):
    """The run_steps shape contract, stated once for both the one-shot
    stack and the chunked pre-validation."""
    return ValueError(
        "run_steps feeds must agree in shape across steps (static "
        "shapes — one compiled scan), but %r varies: %s.  Pad "
        "batches to a common shape or fall back to per-step run()"
        % (name, sorted(shapes)))


def _feed_column_error(step, got, want):
    """The run_steps column-set contract (e.g. a LEN_SUFFIX companion
    fed in only SOME steps), stated once for both the one-shot stack
    and the chunked pre-validation."""
    return ValueError(
        "run_steps feeds must produce one column set across steps; "
        "step %d yields %s vs %s" % (step, sorted(got), sorted(want)))


def _stack_feed_col(name, vals):
    """Stack one feed column across K steps; the scan needs identical
    shapes per step (XLA static shapes), so say which feed broke the
    contract instead of letting np.stack fail opaquely."""
    shapes = {np.shape(v) for v in vals}
    if len(shapes) > 1:
        raise _feed_shape_error(name, shapes)
    return np.stack(vals)


def make_multi_step_fn(raw_fn, stacked, k):
    """The K-step lax.scan over a traced step function — the single home
    of the multi-step semantics shared by Executor.run_steps and
    parallel.api.run_steps_sharded: persistable state is the carry, the
    per-step PRNG folds (key0, global_step) exactly like K single runs,
    fetches stack along a leading K axis, and out-only state (written,
    not carried in) surfaces as its last-step value.  Out-only vars ride
    the carry too — seeded from zeros placeholders discovered with
    eval_shape at trace time — so each holds ONE buffer on device rather
    than a [K, ...] stack that keeps K-1 dead copies live in HBM."""
    def multi_fn(feed_one, xs_feeds, state_rw, state_ro, key0, t0):
        f0 = (jax.tree_util.tree_map(lambda a: a[0], xs_feeds)
              if stacked else feed_one)
        _, state_shape = jax.eval_shape(raw_fn, f0, state_rw, state_ro,
                                        key0)
        extra0 = {n: jnp.zeros(s.shape, s.dtype)
                  for n, s in state_shape.items() if n not in state_rw}

        def body(carry, xs_t):
            rw, extra, t = carry
            f_t = xs_t if stacked else feed_one
            key = jax.random.fold_in(key0, t)
            fetches, new_state = raw_fn(f_t, rw, state_ro, key)
            new_rw = {n: new_state[n] for n in rw if n in new_state}
            new_extra = {n: v for n, v in new_state.items()
                         if n not in new_rw}
            return (new_rw, new_extra, t + 1), tuple(fetches)

        (rw_f, extra_f, _), ys = jax.lax.scan(
            body, (state_rw, extra0, t0), xs_feeds,
            length=None if stacked else k)
        return ys, rw_f, extra_f

    return multi_fn


class Executor(object):
    def __init__(self, place=None):
        if isinstance(place, (list, tuple)):
            place = place[0]
        self.place = place if place is not None else default_place()
        _maybe_enable_compilation_cache()
        self._cache = {}
        self._plan_reports = {}  # plan key -> graph-opt report
        self._mesh_op_cache = {}
        self._step = 0
        self._plan_fresh = False  # set by _get_plan, read by run()
        # graph-opt report of the most recently looked-up plan (tracked
        # per plan key so cache hits restore the right one; None when
        # that plan was built with the pipeline off) — see
        # transpiler/passes.run_pipeline
        self.last_graph_opt_report = None
        # unified step report of the most recent run_steps call: the
        # measured phase walls (feed_s / feed_overlap_s / update_s /
        # compute_s residual, summing to ~wall_s) joined with the
        # static cost model's per-phase FLOPs/bytes under 'phases' —
        # the numbers behind benchmarks/common.py's
        # where-did-the-time-go table and every bench row's MFU
        self.last_step_report = None

    @property
    def last_run_steps_report(self):
        """Deprecated alias (one release): the run_steps breakdown now
        lives in ``last_step_report`` with the same keys (feed_s /
        feed_overlap_s / update_s / chunks) plus the timeline-derived
        wall/compute residuals and the cost-model phase annotations."""
        return self.last_step_report

    # ------------------------------------------------------------------
    def run(self,
            program=None,
            feed=None,
            fetch_list=None,
            feed_var_name='feed',
            fetch_var_name='fetch',
            scope=None,
            return_numpy=True,
            use_program_cache=True):
        try:
            return self._run_impl(program, feed, fetch_list,
                                  feed_var_name, fetch_var_name, scope,
                                  return_numpy, use_program_cache)
        except BaseException:
            # flight-recorder forensics (PADDLE_TPU_TRACE_DUMP_ON_ERROR):
            # flush the last-N-steps timeline ring before re-raising —
            # maybe_dump_on_error never raises and is a cached-bool
            # no-op when disarmed
            _tlm.maybe_dump_on_error()
            raise

    def _run_impl(self, program, feed, fetch_list, feed_var_name,
                  fetch_var_name, scope, return_numpy,
                  use_program_cache):
        if program is None:
            program = default_main_program()
        if not isinstance(program, Program):
            raise TypeError("Executor requires a Program, got %r" %
                            type(program))
        if scope is None:
            scope = global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]

        block = program.global_block()

        # PADDLE_TPU_TUNE=cached: persisted tuner winners apply here,
        # before mesh resolution and plan-key computation (one dict
        # lookup when tuning is off)
        _maybe_apply_tuned(program, self.place)

        # flight recorder (observability/timeline.py): one cached-bool
        # check when disarmed, phase events on the shared ring when
        # PADDLE_TPU_TRACE_DIR / _TRACE_DUMP_ON_ERROR armed it
        tl = _tlm.ring_if_armed()
        mesh, dev = self._mesh_and_dev(program)
        spmd = self._spmd_mesh(program) if mesh is None else None
        if tl is not None:
            tl.set_step(self._step)
            t_f0 = time.perf_counter()
        feed_arrays = _convert_feed(block, feed)
        # every buffer the executor stages itself this call (host data
        # in, device_put here) is dead the moment the step consumes it
        # — donate it so XLA reuses the memory for step intermediates.
        # This holds under a mesh too (the staging device_put below
        # creates executor-owned replicated/sharded buffers); only a
        # caller-staged jax.Array (where re-placement may alias the
        # caller's buffer) stays caller-owned and must NOT be donated.
        feed_donate = (bool(feed_arrays) and
                       not any(isinstance(v, jax.Array)
                               for v in feed_arrays.values()))
        if spmd is None:
            feed_arrays = self._stage_feed(feed_arrays, mesh, dev)
        # host-side feed work so far (convert + non-mesh staging);
        # the timeline event must NOT swallow the _get_plan call below
        # (trace + XLA compile) into the feed phase.  Clock reads stay
        # behind the armed guard (the disarmed zero-cost contract)
        t_conv = (time.perf_counter() - t_f0) if tl is not None else 0.0

        plan = self._get_plan(program, block, scope, feed_arrays,
                              tuple(fetch_names), use_program_cache,
                              mesh=mesh, feed_donate=feed_donate,
                              spmd_mesh=spmd)
        (fn, _raw, state_rw_names, state_ro_names, smeta) = plan

        t_s0 = time.perf_counter() if tl is not None else 0.0
        if smeta is not None:
            # sharded feed staging: each column lands on the mesh
            # already split per the propagated plan (batch over dp/
            # fsdp), so the pjit-lowered step starts from ICI-resident
            # shards instead of re-scattering a replicated copy
            feed_arrays = {n: _shard_put(v, smeta['feed_sh'][n])
                           for n, v in feed_arrays.items()}
        if tl is not None and feed_arrays:
            tl.record('executor.feed_stage', 'feed', t0=t_f0,
                      dur=t_conv + (time.perf_counter() - t_s0),
                      args={'bytes': _nbytes(feed_arrays),
                            'donated': feed_donate})

        if smeta is not None:
            state_rw = self._stage_state_spmd(scope, state_rw_names,
                                              smeta['rw_sh'],
                                              smeta.get('pads'))
            state_ro = self._stage_state_spmd(scope, state_ro_names,
                                              smeta['ro_sh'],
                                              smeta.get('pads'))
            rng_key = jax.device_put(self._rng_key(program),
                                     smeta['key_sh'])
        else:
            state_rw = self._stage_state(
                {n: scope.get(n) for n in state_rw_names}, mesh, dev)
            state_ro = self._stage_state(
                {n: scope.get(n) for n in state_ro_names}, mesh, dev)
            rng_key = jax.device_put(self._rng_key(program), dev)
        self._step += 1

        em = _em() if _obs.enabled() else None
        if em is not None:
            em.runs.inc()
            em.steps.inc()
            em.feed_bytes.inc(_nbytes(feed_arrays))
            em.donated_state_bytes.inc(_nbytes(state_rw))
            if feed_donate:
                em.donated_feed_bytes.inc(_nbytes(feed_arrays))

        # the span covers dispatch + scope update + (for return_numpy)
        # the host sync, so its histogram reads as per-call latency.
        # The donation-warning filter only arms on the compiling
        # invocation — the warning can only fire there, and
        # warnings.catch_warnings mutates process-global state, which
        # the cached steady-state dispatches must stay clear of
        fresh = self._plan_fresh
        self._plan_fresh = False
        with _obs.span('executor.run'), \
                _quiet_unused_donation(
                    feed_arrays if (feed_donate and fresh) else None):
            if tl is not None:
                t_d0 = time.perf_counter()
            if em is not None and fresh:
                # first invocation of a fresh plan: jit compiles
                # synchronously inside this call.  The inner span also
                # lands "executor.compile" on any running XLA trace
                with _obs.span('executor.compile'):
                    t0 = time.perf_counter()
                    fetches, new_state = fn(feed_arrays, state_rw,
                                            state_ro, rng_key)
                    em.compile_seconds.observe(time.perf_counter() - t0)
                em.compiles.inc()
            else:
                fetches, new_state = fn(feed_arrays, state_rw,
                                        state_ro, rng_key)
            if tl is not None:
                tl.record('executor.compile' if fresh
                          else 'executor.dispatch',
                          'compile' if fresh else 'compute', t0=t_d0,
                          dur=time.perf_counter() - t_d0,
                          args={'donated_state_bytes':
                                _nbytes(state_rw)})
                ms = _tlm.device_memory_stats(self._memory_device())
                if ms and ms.get('bytes_in_use') is not None:
                    tl.counter_sample('paddle_tpu.device_bytes_in_use',
                                      ms['bytes_in_use'])
            if smeta is not None:
                self._note_collectives(tl, 1)
            for n, v in new_state.items():
                scope.set(n, v)
            if return_numpy:
                fetches = [np.asarray(v) for v in fetches]
                if em is not None:
                    self._note_amp_skips(new_state, scope)
        return fetches

    def _note_amp_skips(self, new_state, scope):
        """Surface the on-device cumulative AMP skip counter (f16
        dynamic loss scaling) as a host-side metric.  Called only on
        return_numpy paths — the step already synced, so the [1] scalar
        read is a copy of a ready buffer, never a pipeline stall; async
        (return_numpy=False) callers catch up on their next synced call
        because the counter is cumulative.  The seen-watermark lives ON
        the scope (the counter is scope state): it dies with the scope,
        and two executors draining the same scope — e.g. one recreated
        after a checkpoint reload — share it instead of each re-adding
        the full historical count to the process-global metric."""
        from ..transpiler.amp import SKIPPED_STEPS_VAR
        v = new_state.get(SKIPPED_STEPS_VAR)
        if v is None:
            return
        cur = int(np.asarray(v).reshape(-1)[0])
        seen = getattr(scope, '_amp_skip_seen', 0)
        if cur > seen:
            _em().amp_skipped_steps.inc(cur - seen)
        scope._amp_skip_seen = cur

    # ------------------------------------------------------------------
    def _mesh_and_dev(self, program):
        """(mesh, placement) for a program: a program with a parallel_do
        op lowers to a shard_map over the active mesh; its jit then
        spans the mesh's devices, so every argument must stage
        replicated on the mesh (the reference analogue: the host drives
        the program, only parallel_do fans out to places).  The single
        home of the mesh-staging rule shared by run() and run_steps()."""
        mesh = self._active_mesh(program)
        dev = self.place.jax_device()
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            dev = NamedSharding(mesh, PartitionSpec())
        return mesh, dev

    @staticmethod
    def _stage_feed(feed_arrays, mesh, dev):
        """Commit feeds explicitly: an async device_put is ~10x faster
        than letting jit transfer numpy args in-line, and committed
        inputs pin the computation to the place without a
        jax.default_device context (which defeats jit's C++ fast-path
        dispatch — measured 9.7s/step vs 60ms on a tunneled v5e).
        Already-staged jax.Arrays pass through untouched unless a mesh
        requires re-placement."""
        return {k: (v if isinstance(v, jax.Array) and mesh is None
                    else jax.device_put(v, dev))
                for k, v in feed_arrays.items()}

    @staticmethod
    def _stage_state(state, mesh, dev):
        if mesh is None:
            return state
        return {n: jax.device_put(v, dev) for n, v in state.items()}

    @staticmethod
    def _stage_state_spmd(scope, names, shardings, pads=None):
        """Stage persistable state per the plan's NamedShardings — the
        ONE staging rule all three SPMD call sites (run, run_steps,
        the prefetch path) share; steady-state re-stages are no-ops
        via the _shard_put pass-through.  ``pads`` (embed plans) maps a
        row-sharded table/accumulator to its sentinel-padded height:
        the first stage pads the stored [V, D] value to [V_pad, D]
        with zero rows (never gathered, never updated — the engine's
        buckets stop at the TRUE height), after which the padded
        buffer round-trips through the donated carry untouched."""
        out = {}
        for n in names:
            v = scope.get(n)
            padded = (pads or {}).get(n)
            if padded and getattr(v, 'ndim', 0) >= 1 and \
                    int(v.shape[0]) < int(padded):
                v = jnp.asarray(v)
                fill = jnp.zeros((int(padded) - int(v.shape[0]),)
                                 + tuple(v.shape[1:]), v.dtype)
                v = jnp.concatenate([v, fill])
            out[n] = _shard_put(v, shardings[n])
        return out

    def _spmd_mesh(self, program):
        """The PADDLE_TPU_MESH mesh for SPMD-lowering this program's
        whole train step, or None: the flag must parse to axes, and a
        program carrying its own parallel_do distribution keeps the
        explicit shard_map path (one distribution mechanism per
        program).  Mesh construction/caching lives in
        distributed/_compat.py; the Mesh object participates in plan
        keys (its identity is stable per normalized spec)."""
        from ..distributed import _compat
        axes = _compat.mesh_axes_from_flag()
        if axes is None:
            return None
        pp_size = int(dict(axes).get('pp', 1))
        if pp_size > 1:
            # pp shards TIME, not tensors: a pipeline axis cannot be
            # lowered as one pjit program — it needs the 1F1B
            # schedule's per-stage branches and ppermute transfers.
            # Only TRAIN steps (programs carrying an autodiff op) are
            # refused; startup init and plain forwards run replicated
            # over the pipeline, i.e. with the time axis dropped
            if any(op.type == 'autodiff'
                   for b in program.blocks for op in b.ops):
                raise RuntimeError(
                    'PADDLE_TPU_MESH declares a pipeline axis '
                    '(pp=%d), which the single-program SPMD executor '
                    'cannot lower for a train step.  Route the '
                    'program through the 1F1B engine instead: '
                    'paddle_tpu.distributed.pipeline.from_mesh('
                    'program, ...) cuts stages at annotate_pp_cut() '
                    'boundaries and schedules microbatches — or drop '
                    'the pp axis (e.g. PADDLE_TPU_MESH=dp%d) to stay '
                    'on the plain SPMD path.' % (pp_size, pp_size))
            axes = tuple((n, s) for n, s in axes if n != 'pp')
            if not any(int(s) > 1 for _, s in axes):
                return None
        key = (program._uid, program.version)
        has_pdo = self._mesh_op_cache.get(key)
        if has_pdo is None:
            has_pdo = any(op.type == 'parallel_do'
                          for b in program.blocks for op in b.ops)
            self._mesh_op_cache[key] = has_pdo
        if has_pdo:
            return None
        return _compat.mesh_for(axes)

    def _build_shard_meta(self, prog, mesh, feed_names, rw_names,
                          ro_names):
        """NamedShardings for one plan's jit boundary, from the
        sharding-propagation pass's plan (``prog._sharding_plan``):
        feeds per the propagated feed table (batch over dp/fsdp),
        persistable state per the param plan (fsdp shards params AND
        optimizer accumulators; tp follows the transpiler plan),
        everything unplanned replicated.  A pipeline fallback that
        left no plan degrades to all-replicated — correct, just
        unsharded."""
        from ..distributed import _compat
        plan = getattr(prog, '_sharding_plan', None) or {}
        feeds = plan.get('feeds') or {}
        params = dict(plan.get('params') or {})
        # row-sharded embedding tables with a NON-divisible height:
        # stage sentinel-padded to the engine's shard-divisible height
        # (pads map state name -> padded rows).  Only when the embed
        # lowering actually rewrote the ops — an unlowered plan (pass
        # crash, flag off) must not feed padded tables to a plain
        # lookup, so those names degrade to replicated staging instead
        pads = {}
        embed = plan.get('embed') or {}
        for e in embed.values():
            if int(e['padded']) == int(e['height']):
                continue
            for n in e.get('state', ()):
                if plan.get('embed_lowered'):
                    pads[n] = int(e['padded'])
                else:
                    params.pop(n, None)
        return {
            'mesh': mesh,
            'plan': plan,
            'pads': pads,
            'feed_sh': {n: _compat.named_sharding(mesh, feeds.get(n))
                        for n in feed_names},
            'rw_sh': {n: _compat.named_sharding(mesh, params.get(n))
                      for n in rw_names},
            'ro_sh': {n: _compat.named_sharding(mesh, params.get(n))
                      for n in ro_names},
            'key_sh': _compat.named_sharding(mesh, None),
        }

    def _xs_shardings(self, smeta, names):
        """Per-column shardings for the [K, ...]-stacked run_steps
        feed: the per-step spec shifted one dim right (dim0 is the
        scan axis, never sharded)."""
        from ..distributed import _compat
        feeds = smeta['plan'].get('feeds') or {}
        return {n: _compat.named_sharding(
                    smeta['mesh'], (None,) + tuple(feeds.get(n) or ()))
                for n in names}

    def _note_collectives(self, tl, steps, compute_s=None):
        """Attribute the modeled ICI collectives of ``steps`` executed
        SPMD steps: counters (modeled bytes + collective ops) and one
        ``collective``-category timeline event, with an estimated wall
        when PADDLE_TPU_ICI_GBPS names a link bandwidth.  The numbers
        come from the cost model's pricing of the sharding pass's
        collective table, cached per plan in last_graph_opt_report.

        ``compute_s`` is the MEASURED compute wall for the ``steps``
        steps, when the caller has a synced one (run_steps does; the
        async single-step dispatch does not).  The overlap schedule the
        cost model priced at roofline-floor walls is pure arithmetic
        over the stamped bucket descriptors, so it is re-run here with
        every wall scaled by measured/modeled compute — same buckets,
        same serial-channel model, real time base — and the reported
        overlap fraction then describes the step that actually ran
        instead of the optimistic floor.  The fraction lands as a
        Chrome-trace counter series
        (``paddle_tpu.collective_overlap_pct``, 0-100) next to the
        collective event."""
        cost = (self.last_graph_opt_report or {}).get('cost') or {}
        coll = cost.get('collectives')
        if not coll or not coll.get('ici_bytes'):
            return None
        nbytes = int(coll['ici_bytes']) * int(steps)
        nops = len(coll.get('items') or ()) * int(steps)
        sched = coll.get('overlap')
        split = dict(coll.get('bytes') or {})
        frac = sched.get('overlap_fraction') if sched else None
        basis = 'modeled-roofline'
        if sched and sched.get('buckets') and compute_s \
                and compute_s > 0.0:
            modeled = float(coll.get('modeled_compute_s') or 0.0)
            if modeled > 0.0:
                from ..transpiler import cost_model as _cmod
                scale = (float(compute_s) / int(steps)) / modeled
                rerun = _cmod.overlap_schedule(
                    sched['buckets'],
                    float(sched['backward_s']) * scale,
                    float(sched['window_s']) * scale,
                    float(sched['ici_gbps']) * 1e9)
                frac = rerun['overlap_fraction']
                # only the gradient-bucket term is re-priced; every
                # other exposed byte (pp sends, unbucketed items)
                # keeps its static verdict
                exposed = max(0, int(split.get('exposed') or 0)
                              - int(sched.get('exposed_bytes') or 0)
                              + int(rerun['exposed_bytes']))
                split['exposed'] = min(exposed,
                                       int(split.get('total') or 0))
                split['overlapped'] = (int(split.get('total') or 0)
                                       - split['exposed'])
                basis = 'measured-compute'
        if _obs.enabled():
            em = _em()
            em.collective_modeled_bytes.inc(nbytes)
            em.collectives_modeled.inc(nops)
            if split:
                em.collective_exposed_bytes.inc(
                    int(split.get('exposed') or 0) * int(steps))
                em.collective_overlapped_bytes.inc(
                    int(split.get('overlapped') or 0) * int(steps))
        est = None
        from ..flags import FLAGS
        gbps = float(FLAGS.ici_gbps or 0.0)
        if gbps > 0:
            est = nbytes / (gbps * 1e9)
        out = {'ici_bytes': nbytes, 'collectives': nops,
               'est_wall_s': est, 'by_kind': coll.get('by_kind')}
        if frac is not None:
            mgbps = float(sched.get('ici_gbps') or 0.0)
            out['overlap_fraction'] = frac
            out['overlap_basis'] = basis
            out['exposed_bytes_per_step'] = int(split.get('exposed')
                                                or 0)
            out['overlapped_bytes_per_step'] = \
                int(split.get('overlapped') or 0)
            if mgbps > 0:
                out['exposed_est_wall_s'] = \
                    out['exposed_bytes_per_step'] / (mgbps * 1e9)
        if coll.get('pp'):
            out['pp'] = dict(coll['pp'])
        if tl is not None:
            args = {'modeled_ici_bytes': nbytes,
                    'collectives': nops,
                    'by_kind': dict(coll.get('by_kind') or {}),
                    'est_wall_s': est}
            if frac is not None:
                args['overlap_fraction'] = frac
                args['overlap_basis'] = basis
                args['exposed_bytes_per_step'] = \
                    out['exposed_bytes_per_step']
            if frac is not None:
                # counter samples are integer-valued (args['bytes']):
                # the fraction rides as a 0-100 percent series.
                # Sampled BEFORE the record event so the category's
                # latest event stays the attribution record
                tl.counter_sample(
                    'paddle_tpu.collective_overlap_pct',
                    round(frac * 100.0), cat='collective')
            tl.record('executor.collective', 'collective',
                      dur=est or 0.0, args=args)
        return out

    def _active_mesh(self, program):
        """The current mesh_guard mesh, when `program` contains an op
        that fans out over it (parallel_do) and the mesh is >1 device."""
        key = (program._uid, program.version)
        has = self._mesh_op_cache.get(key)
        if has is None:
            has = any(op.type == 'parallel_do'
                      for b in program.blocks for op in b.ops)
            self._mesh_op_cache[key] = has
        if not has:
            return None
        from ..parallel import api as _papi
        mesh = _papi.current_mesh()
        if mesh is None or mesh.devices.size <= 1:
            return None
        return mesh

    def _base_seed(self, program):
        seed = program.random_seed
        return seed if seed else id(self) % (2**31)

    def _rng_key(self, program):
        return jax.random.fold_in(
            jax.random.PRNGKey(self._base_seed(program)), self._step)

    def _analyze_state(self, program, scope, feed_names):
        """Classify persistable vars: `rw` (existing value, written → passed
        in and donated), `ro` (existing value, only read), `out` (written by
        the block — includes first-time writes, e.g. the startup program)."""
        written = set()
        read = set()
        for b in program.blocks:
            for op in b.ops:
                written.update(op.output_arg_names)
                read.update(op.input_arg_names)
        rw, ro, out = [], [], []
        for v in program.list_vars():
            if not v.persistable or v.name in feed_names:
                continue
            if v.name in written:
                out.append(v.name)
            if not scope.has(v.name):
                if v.name in read and v.name not in written:
                    raise RuntimeError(
                        "persistable var %r is read but has no value in "
                        "scope; run the startup program first" % v.name)
                continue
            if v.name in written:
                rw.append(v.name)
            elif v.name in read:
                ro.append(v.name)
        return tuple(sorted(rw)), tuple(sorted(ro)), tuple(sorted(out))

    def _get_plan(self, program, block, scope, feed_arrays, fetch_names,
                  use_cache, mesh=None, feed_donate=False,
                  spmd_mesh=None, mesh_off=False):
        feed_sig = tuple(
            (n, feed_arrays[n].shape, str(feed_arrays[n].dtype))
            for n in sorted(feed_arrays))
        state_rw_names, state_ro_names, state_out_names = \
            self._analyze_state(program, scope, set(feed_arrays))
        # mesh participates: a parallel_do program traced under a mesh
        # embeds that mesh's shard_map in the compiled step, and an
        # SPMD mesh (PADDLE_TPU_MESH) bakes its NamedShardings into the
        # jit boundary.  Scope
        # identity is its monotonic _uid, never id(): ids recycle after
        # gc and would alias a fresh scope's plans with a dead one's.
        # The pass configuration participates as ONE composite component
        # (pass_manager.plan_key): graph-opt level, AMP mode, verify
        # mode, sparse/dense apply lowerings, mesh spec — a flip of any
        # must not be
        # served a plan built under the old configuration.
        # feed_donate keys the donation variant: a plan jitted with the
        # feed argument donated must never serve a call whose feed
        # buffers the caller still owns.
        pm_key = _pass_plan_key(program)
        key = (program._uid, program.version, feed_sig, fetch_names,
               state_rw_names, state_ro_names, state_out_names,
               scope._uid, mesh, spmd_mesh, mesh_off, pm_key,
               feed_donate)
        if use_cache and key in self._cache:
            self._plan_fresh = False
            # keep the report describing THIS plan, not whichever plan
            # happened to miss last (one executor can serve many programs)
            self.last_graph_opt_report = self._plan_reports.get(key)
            if _obs.enabled():
                _em().plan_cache_hits.inc()
            return self._cache[key]
        # the caller (run) reads this flag to time the plan's first
        # invocation — the call that pays the XLA compile.  The jitted
        # fn itself stays a bare jax.jit object: wrapping it would break
        # the AOT consumers of compile() (fn.lower().compile()), and the
        # export path would fire a wrapper's timer mid-trace
        self._plan_fresh = True
        if _obs.enabled():
            _em().plan_cache_misses.inc()
        # a compilation-cache dir set after construction applies to THIS
        # build (one-line warning inside) instead of silently waiting
        # for reset_cache()
        _maybe_enable_compilation_cache()

        known = set()
        for b in program.blocks:
            known.update(b.vars)
            for op in b.ops:
                known.update(op.output_arg_names)
        for n in fetch_names:
            if n not in known and n not in feed_arrays:
                raise KeyError(
                    "fetch var %r is not produced by any op in the program "
                    "and is not fed" % n)

        # The managed pass pipeline (transpiler/pass_manager.py): graph
        # opt -> AMP -> donation analysis over a COPY of the block,
        # statically verified per PADDLE_TPU_VERIFY_IR.  A crashing pass
        # is skipped inside the manager (per-pass fallback, reported in
        # last_graph_opt_report['passes']); a manager-level failure
        # falls back to tracing the unrewritten program; a VERIFIER
        # rejection propagates — a program the checker proves broken
        # must not be traced into a worse error downstream.
        from ..transpiler import pass_manager
        from ..transpiler.verify import IRVerificationError
        prog, report = program, None
        try:
            prog, report = pass_manager.run_pipeline(
                program, fetch_names=fetch_names,
                feed_names=tuple(sorted(feed_arrays)),
                # concrete feed shapes seed the cost model's shape
                # propagation (declared -1 batch dims resolve to the
                # real batch, so FLOPs/bytes are exact per step).
                # mesh_off pins the sharding pass OFF for plans that
                # will jit WITHOUT in_shardings (compile()/compile_raw
                # AOT + serving consumers): a sharded analysis report
                # over an unsharded executable would under-state
                # per-device residency by the shard count
                feed_specs={n: (tuple(v.shape), str(v.dtype))
                            for n, v in feed_arrays.items()},
                **({'mesh': ''} if mesh_off else {}))
        except IRVerificationError:
            if _obs.enabled():
                _em().ir_verify_failures.inc()
            raise
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "pass pipeline failed; tracing the unrewritten program",
                exc_info=True)
        if report is not None and report['level'] <= 0 and \
                'amp' not in report:
            report = None  # nothing rewrote: legacy bypass contract
        self.last_graph_opt_report = report
        if report is not None:
            if report['ops_before'] is not None and _obs.enabled():
                em = _em()
                # count what the graph-opt passes actually removed, not
                # the before/after op delta — AMP weaves casts in after
                # the eliminations and would mask them
                em.graph_opt_seconds.observe(sum(
                    e['wall_s'] for e in report['passes']
                    if e['name'] != 'amp'))
                em.graph_opt_ops_eliminated.inc(
                    max(0, sum(report['eliminated'].values())))
            amp_report = report.get('amp')
            if amp_report is not None:
                # seed the dynamic-loss-scale state (f16 mode) so the
                # state analysis below sees live values — the user never
                # runs a startup program for pass-created vars
                for n, v in amp_report['state_defaults'].items():
                    if not scope.has(n):
                        scope.set(n, jnp.asarray(v))
                # the rewrite can add persistable state: re-derive the
                # rw/ro/out sets from the program that will actually
                # trace (the pre-rewrite sets only keyed the cache)
                state_rw_names, state_ro_names, state_out_names = \
                    self._analyze_state(prog, scope, set(feed_arrays))
                if _obs.enabled():
                    _em().amp_ops_lowered.inc(amp_report['ops_lowered'])
        backend = self.place.jax_device().platform

        def step_fn(feed_vals, state_rw, state_ro, rng_key):
            env = {}
            env.update(state_ro)
            env.update(state_rw)
            env.update(feed_vals)
            ctx = ExecutionContext(prog, prog.global_block(), rng_key,
                                   backend=backend)
            _run_ops(prog.global_block().ops, env, ctx)
            fetches = []
            for n in fetch_names:
                if n not in env:
                    raise KeyError("fetch var %r was never computed" % n)
                fetches.append(env[n])
            new_state = {n: env[n] for n in state_out_names if n in env}
            return fetches, new_state

        # state is always donated; the feed argument joins it when the
        # caller (run) proved this plan only ever sees executor-staged
        # feed buffers — the donated feeds are exactly the extra reuse
        # headroom the PR-3 donation analysis reports (short-lived
        # intermediates can land in the dead feed buffers instead of
        # growing peak HBM).  Under an SPMD mesh the same donation
        # applies THROUGH the pjit boundary (sharded feed and state
        # buffers are executor-staged too — run() proved ownership
        # before asking for the donating variant).
        smeta = None
        jit_kw = {}
        if spmd_mesh is not None:
            smeta = self._build_shard_meta(
                prog, spmd_mesh, set(feed_arrays), state_rw_names,
                state_ro_names)
            jit_kw['in_shardings'] = (smeta['feed_sh'], smeta['rw_sh'],
                                      smeta['ro_sh'], smeta['key_sh'])
        fn = jax.jit(step_fn,
                     donate_argnums=(0, 1) if feed_donate else (1,),
                     **jit_kw)
        plan = (fn, step_fn, state_rw_names, state_ro_names, smeta)
        if use_cache:
            self._cache[key] = plan
            self._plan_reports[key] = self.last_graph_opt_report
        return plan

    def run_steps(self, program=None, feed=None, fetch_list=None,
                  scope=None, repeat=None, return_numpy=True):
        """Run K training steps as ONE compiled XLA computation — a
        lax.scan over the step function with the persistable state as
        donated carry.  Populates ``last_step_report`` (measured phase
        walls × cost-model FLOPs/bytes) and, when the flight recorder
        is armed, exports the timeline ring to PADDLE_TPU_TRACE_DIR.

        TPU-native executor extension (no reference counterpart): over a
        network-attached accelerator each run() costs a host dispatch
        round trip; scanning K steps on-device amortizes it to one.  The
        per-step PRNG chain folds (seed, global_step) exactly like run(),
        so K calls of run() and one run_steps(K) produce identical
        numerics, dropout streams included.

        :param feed: list of K feed dicts (stacked on the device), or a
            single feed dict with ``repeat=K`` to reuse one device-staged
            batch for every step (benchmark mode — no re-staging).
        :param fetch_list: fetched per step; returns [K, ...]-stacked
            arrays, one per fetch.
        """
        try:
            return self._run_steps_impl(program, feed, fetch_list,
                                        scope, repeat, return_numpy)
        except BaseException:
            _tlm.maybe_dump_on_error()
            raise

    def _run_steps_impl(self, program, feed, fetch_list, scope, repeat,
                        return_numpy):
        t_call = time.perf_counter()
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        fetch_names = tuple(
            f.name if isinstance(f, Variable) else str(f)
            for f in (fetch_list or []))
        block = program.global_block()

        if isinstance(feed, dict):
            if not repeat:
                raise ValueError("run_steps with a single feed dict "
                                 "needs repeat=K")
            feeds, k = [feed], int(repeat)
        else:
            feeds, k = list(feed), len(feed)
            if repeat:
                raise ValueError("repeat= only combines with a single "
                                 "feed dict")
            if k == 0:
                return []
        stacked = len(feeds) > 1
        names0 = set(feeds[0])
        for i, f in enumerate(feeds[1:], start=1):
            if set(f) != names0:
                missing = sorted(names0 - set(f))
                extra = sorted(set(f) - names0)
                raise ValueError(
                    "run_steps feeds must use one key set across steps "
                    "(one compiled scan); step %d %s" % (i, '; '.join(
                        filter(None,
                               ["is missing %s" % missing if missing
                                else '',
                                "adds %s" % extra if extra else '']))))

        # tuned winners, like run(): before mesh and plan key resolve
        _maybe_apply_tuned(program, self.place)

        mesh, dev = self._mesh_and_dev(program)
        spmd = self._spmd_mesh(program) if mesh is None else None
        feed0 = _convert_feed(block, feeds[0])
        if spmd is None:
            feed0 = self._stage_feed(feed0, mesh, dev)

        fn_plan = self._get_plan(program, block, scope, feed0,
                                 fetch_names, True, mesh=mesh,
                                 spmd_mesh=spmd)
        _fn, raw_fn, rw_names, ro_names, smeta = fn_plan
        if smeta is not None:
            feed0 = {n: _shard_put(v, smeta['feed_sh'][n])
                     for n, v in feed0.items()}

        from ..flags import FLAGS
        prefetch = bool(FLAGS.device_prefetch) and stacked
        # per-call step-time breakdown (benchmarks/common.py reads it):
        # feed_s = host feed staging on the critical path (device
        # idle), feed_overlap_s = staging done while a previous chunk
        # was executing, update_s = scope write-back.  _finalize_step_
        # report joins these with the cost model under 'phases'.
        report = {'k': k, 'device_prefetch': prefetch,
                  'chunks': 1, 'chunk_steps': k,
                  'feed_s': 0.0, 'feed_overlap_s': 0.0,
                  'update_s': 0.0, 'feed_bytes': 0}
        self.last_step_report = report
        em = _em() if _obs.enabled() else None
        tl = _tlm.ring_if_armed()
        if tl is not None:
            tl.set_step(self._step)

        if prefetch:
            return self._run_steps_prefetch(
                program, block, scope, feeds, k, feed0, fetch_names,
                rw_names, ro_names, raw_fn, mesh, dev, em, report,
                return_numpy, t_call, smeta=smeta)

        multi, multi_fresh = self._multi_plan(
            program, scope, feed0, fetch_names, rw_names, ro_names,
            mesh if smeta is None else smeta['mesh'], raw_fn, k,
            stacked, smeta=smeta)

        xs = None
        if stacked:
            tf = time.perf_counter()
            xs = self._stack_chunk(feeds, 0, k, block,
                                   self._xs_placement(smeta, dev))
            report['feed_s'] = time.perf_counter() - tf
            report['feed_bytes'] = _nbytes(xs)
            if tl is not None:
                tl.record('executor.feed_stack', 'feed', t0=tf,
                          dur=report['feed_s'],
                          args={'bytes': report['feed_bytes'],
                                'steps': k})

        if smeta is not None:
            state_rw = self._stage_state_spmd(scope, rw_names,
                                              smeta['rw_sh'],
                                              smeta.get('pads'))
            state_ro = self._stage_state_spmd(scope, ro_names,
                                              smeta['ro_sh'],
                                              smeta.get('pads'))
            key0 = jax.device_put(
                jax.random.PRNGKey(self._base_seed(program)),
                smeta['key_sh'])
        else:
            state_rw = self._stage_state(
                {n: scope.get(n) for n in rw_names}, mesh, dev)
            state_ro = self._stage_state(
                {n: scope.get(n) for n in ro_names}, mesh, dev)
            key0 = jax.device_put(
                jax.random.PRNGKey(self._base_seed(program)), dev)
        t0 = jnp.asarray(self._step, jnp.int32)

        if em is not None:
            em.steps.inc(k)
            em.feed_bytes.inc(_nbytes(feed0) + (_nbytes(xs) if xs else 0))
            em.donated_state_bytes.inc(_nbytes(state_rw))
            if xs:
                # the whole [K, ...] stack is staged in one put before
                # the dispatch — the critical-path event the
                # device-prefetch pipeline exists to hide
                em.feed_blocking_puts.inc()
                em.donated_feed_bytes.inc(_nbytes(xs))

        with _obs.span('executor.run_steps'):
            ys, rw_f, last_extra = self._dispatch_multi(
                multi, multi_fresh, em, feed0, xs, state_rw, state_ro,
                key0, t0)
            self._step += k
            tu = time.perf_counter()
            for n, v in rw_f.items():
                scope.set(n, v)
            for n, v in last_extra.items():
                scope.set(n, v)
            report['update_s'] = time.perf_counter() - tu
            if tl is not None:
                tl.record('executor.scope_update', 'update', t0=tu,
                          dur=report['update_s'])
            if em is not None and return_numpy:
                self._note_amp_skips(rw_f, scope)
            if return_numpy:
                ts = time.perf_counter()
                outs = [np.asarray(y) for y in ys]
                if tl is not None:
                    tl.record('executor.fetch_sync', 'compute', t0=ts,
                              dur=time.perf_counter() - ts,
                              args={'steps': k})
            else:
                outs = list(ys)
            self._finalize_step_report(
                report, t_call,
                synced=return_numpy and bool(fetch_names))
            return outs

    def _multi_plan(self, program, scope, feed0, fetch_names, rw_names,
                    ro_names, mesh, raw_fn, k, stacked, smeta=None):
        """Get-or-build the jitted K-step scan plan for one scan length.

        The composite pass-configuration key (_pass_plan_key — the same
        single code path the run() key uses) keys the multi plan too:
        the scan closes over raw_fn, which traces the (un)rewritten
        program — a flag flip must not be served a scan over the old
        one.  The stacked feed argument (xs)
        is donated along with the state: run_steps always builds the
        stack itself from host copies, so the buffer is executor-owned
        and dead once the scan consumed it — XLA gets the whole stack
        back for intermediates instead of holding K dead batches.
        Under an SPMD mesh (``smeta``) the scan jits with the plan's
        NamedShardings — per-step feeds batch-sharded (scan dim 0
        replicated), state per the param plan — and the same xs+state
        donation flows through the pjit boundary."""
        mkey = ('multi', program._uid, program.version, k, stacked,
                fetch_names,
                tuple((n, feed0[n].shape, str(feed0[n].dtype))
                      for n in sorted(feed0)), scope._uid,
                rw_names, ro_names, mesh, _pass_plan_key(program))
        multi = self._cache.get(mkey)
        fresh = multi is None
        if fresh:
            if _obs.enabled():
                _em().plan_cache_misses.inc()
            jit_kw = {}
            if smeta is not None:
                jit_kw['in_shardings'] = (
                    smeta['feed_sh'],
                    self._xs_shardings(smeta, set(feed0))
                    if stacked else None,
                    smeta['rw_sh'], smeta['ro_sh'],
                    smeta['key_sh'], smeta['key_sh'])
            multi = jax.jit(make_multi_step_fn(raw_fn, stacked, k),
                            donate_argnums=(1, 2) if stacked else (2,),
                            **jit_kw)
            self._cache[mkey] = multi
        elif _obs.enabled():
            _em().plan_cache_hits.inc()
        return multi, fresh

    def _dispatch_multi(self, multi, fresh, em, feed0, xs, state_rw,
                        state_ro, key0, t0):
        """Invoke a multi-step plan, timing the first (compiling)
        invocation of a fresh plan under the executor.compile span.
        The donation-warning filter arms only on that compiling call —
        steady-state dispatches must not touch the process-global
        warnings state."""
        tl = _tlm.ring_if_armed()
        td = time.perf_counter() if tl is not None else None
        with _quiet_unused_donation(
                xs if (xs is not None and fresh) else None):
            if em is not None and fresh:
                with _obs.span('executor.compile'):
                    tc = time.perf_counter()
                    out = multi(feed0, xs, state_rw, state_ro, key0, t0)
                    em.compile_seconds.observe(time.perf_counter() - tc)
                em.compiles.inc()
            else:
                out = multi(feed0, xs, state_rw, state_ro, key0, t0)
        if tl is not None:
            # compile is synchronous inside the fresh call; cached
            # dispatches return before the device finishes (jax async) —
            # the event times the host-side dispatch, the device work
            # shows under executor.fetch_sync / the jax profiler trace
            tl.record('executor.compile' if fresh
                      else 'executor.dispatch',
                      'compile' if fresh else 'compute', t0=td,
                      dur=time.perf_counter() - td,
                      args={'donated_state_bytes': _nbytes(state_rw)})
        return out

    def _xs_placement(self, smeta, dev):
        """Placement argument for staging stacked feed columns: the
        per-column NamedShardings under an SPMD mesh (each chunk lands
        pre-sharded over the batch axis), the single device/sharding
        otherwise — consumed by runtime/prefetch.stage_columns."""
        if smeta is None:
            return dev
        return self._xs_shardings(
            smeta, set(smeta['feed_sh']))

    def _stack_chunk(self, feeds, lo, hi, block, placement):
        """Stack feeds[lo:hi] into device-staged [hi-lo, ...] columns
        (the one-shot path; the chunked path pre-converts and validates
        every feed before its first dispatch instead)."""
        from ..runtime.prefetch import stage_columns
        cols = {}
        want = None
        for i, f in enumerate(feeds[lo:hi]):
            fa = _convert_feed(block, f)
            if want is None:
                want = set(fa)
            elif set(fa) != want:
                # must fail here, not as an opaque scan-length
                # mismatch after state staging
                raise _feed_column_error(lo + i, set(fa), want)
            for n, v in fa.items():
                cols.setdefault(n, []).append(np.asarray(v))
        return stage_columns(
            {n: _stack_feed_col(n, vs) for n, vs in cols.items()},
            placement)

    def _run_steps_prefetch(self, program, block, scope, feeds, k,
                            feed0, fetch_names, rw_names, ro_names,
                            raw_fn, mesh, dev, em, report,
                            return_numpy, t_call, smeta=None):
        """Device-resident run_steps (PADDLE_TPU_DEVICE_PREFETCH): the
        K-step feed stack is staged in chunks through a double-buffered
        pipeline — the host stacks and device_puts chunk c+1 while the
        device scans chunk c — so steady-state steps never wait on a
        host transfer, and only ~2 chunks of feed are resident instead
        of the whole [K, ...] stack.  Bitwise-identical to the one-shot
        path: the scan body folds the PRNG key with the ABSOLUTE step
        index (key0, t), so chunk boundaries don't exist numerically,
        and the donated state chains from each chunk's output into the
        next chunk's input without a host round trip."""
        from ..flags import FLAGS
        from ..runtime.prefetch import device_prefetch
        cs = int(FLAGS.device_prefetch_chunk) or max(1, -(-k // 4))
        cs = max(1, min(cs, k))
        bounds = [(lo, min(lo + cs, k)) for lo in range(0, k, cs)]
        report['chunks'] = len(bounds)
        report['chunk_steps'] = cs
        started = [False]  # has any chunk been dispatched yet?

        # Convert + validate EVERY feed before the first dispatch: the
        # one-shot path fails atomically on a shape mismatch, and the
        # chunked path must too — chunk 0 donates the scope's state
        # buffers, so raising mid-stream would leave the scope holding
        # deleted arrays with half the steps applied.  Conversion is
        # host-side and copy-free for already-conforming ndarray feeds
        # (np.asarray is a view), but dtype coercion (int64→int32 &
        # co) copies — it happens on the critical path, so it counts
        # toward feed_s, not silently toward compute.  The per-chunk
        # np.stack + device_put — the bulk copy and transfer — still
        # runs overlapped in the thunks.
        tv = time.perf_counter()
        col_shapes = {}
        col_dtypes = {}
        conv = []
        for f in feeds:
            fa = _convert_feed(block, f)
            if conv and set(fa) != set(conv[0]):
                # e.g. one step fed (data, lengths) where another fed a
                # plain array: the LEN_SUFFIX companion appears in only
                # one of them
                raise _feed_column_error(len(conv), set(fa), set(conv[0]))
            for n in sorted(fa):
                v = np.asarray(fa[n])
                fa[n] = v
                want = col_shapes.setdefault(n, v.shape)
                if v.shape != want:
                    raise _feed_shape_error(n, {want, v.shape})
                # join the column dtype across ALL steps: the one-shot
                # path's single np.stack over K steps promotes every
                # step to the column's result_type, so each chunk must
                # stack to that same dtype — both for bitwise parity
                # and so every chunk shares ONE jit signature (a dtype
                # drift would otherwise force a fresh trace mid-stream,
                # after the scope state was donated)
                have = col_dtypes.get(n)
                col_dtypes[n] = (v.dtype if have is None
                                 else np.result_type(have, v.dtype))
            conv.append(fa)
        report['feed_s'] += time.perf_counter() - tv

        from ..runtime.prefetch import stage_columns
        xs_placement = self._xs_placement(smeta, dev)

        def make_thunk(lo, hi):
            def thunk():
                ts = time.perf_counter()
                xs = stage_columns(
                    {n: np.stack([conv[i][n] for i in range(lo, hi)])
                        .astype(col_dtypes[n], copy=False)
                     for n in col_shapes},
                    xs_placement)
                dt = time.perf_counter() - ts
                nb = _nbytes(xs)
                if started[0]:
                    report['feed_overlap_s'] += dt
                    if em is not None:
                        em.feed_prefetched_puts.inc()
                        em.feed_prefetched_bytes.inc(nb)
                else:
                    # pipeline prime: the only staging the device ever
                    # waits for
                    report['feed_s'] += dt
                    if em is not None:
                        em.feed_blocking_puts.inc()
                if em is not None:
                    em.feed_bytes.inc(nb)
                    em.donated_feed_bytes.inc(nb)
                report['feed_bytes'] += nb
                return lo, hi, xs
            return thunk

        if smeta is not None:
            state_rw = self._stage_state_spmd(scope, rw_names,
                                              smeta['rw_sh'],
                                              smeta.get('pads'))
            state_ro = self._stage_state_spmd(scope, ro_names,
                                              smeta['ro_sh'],
                                              smeta.get('pads'))
            key0 = jax.device_put(
                jax.random.PRNGKey(self._base_seed(program)),
                smeta['key_sh'])
        else:
            state_rw = self._stage_state(
                {n: scope.get(n) for n in rw_names}, mesh, dev)
            state_ro = self._stage_state(
                {n: scope.get(n) for n in ro_names}, mesh, dev)
            key0 = jax.device_put(
                jax.random.PRNGKey(self._base_seed(program)), dev)
        base = self._step
        if em is not None:
            # steps_total counts per COMPLETED chunk below, not k
            # up-front: a mid-stream failure lands the boundary state
            # and advances self._step by `done`, and the metric must
            # agree with that resumable step count
            em.feed_bytes.inc(_nbytes(feed0))
            em.donated_state_bytes.inc(_nbytes(state_rw))
        ys_parts = []
        last_extra = {}
        done = 0  # steps landed by completed chunks
        with _obs.span('executor.run_steps'):
            try:
                for lo, hi, xs in device_prefetch(
                        make_thunk(lo, hi) for lo, hi in bounds):
                    tl0 = _tlm.ring_if_armed()
                    if tl0 is not None:
                        tl0.set_step(base + lo)
                    multi, fresh = self._multi_plan(
                        program, scope, feed0, fetch_names, rw_names,
                        ro_names,
                        mesh if smeta is None else smeta['mesh'],
                        raw_fn, hi - lo, True, smeta=smeta)
                    ys, state_rw, last_extra = self._dispatch_multi(
                        multi, fresh, em, feed0, xs, state_rw, state_ro,
                        key0, jnp.asarray(base + lo, jnp.int32))
                    started[0] = True
                    if em is not None:
                        em.steps.inc(hi - done)
                    done = hi
                    ys_parts.append(ys)
                    if tl0 is not None:
                        # measured device memory, one sample per chunk
                        # (None on backends without memory_stats)
                        ms = _tlm.device_memory_stats(
                            self._memory_device())
                        if ms and ms.get('bytes_in_use') is not None:
                            tl0.counter_sample(
                                'paddle_tpu.device_bytes_in_use',
                                ms['bytes_in_use'])
            except BaseException as e:
                # BaseException: a Ctrl-C during the seconds-wide
                # multi-chunk host loop must land the boundary state
                # too, or the scope keeps referencing donated buffers
                if not started[0]:
                    raise
                # A completed chunk donated the scope's original state
                # buffers, so "unwind to before the call" no longer
                # exists.  On a mid-stream compile/staging failure
                # (feed errors never get here — every feed validated
                # above) the last completed chunk's OUTPUT state is
                # alive: land it and advance the step counter so the
                # scope reads as exactly "first `done` steps applied"
                # (a consistent, resumable boundary) instead of
                # holding references to deleted arrays.  But if the
                # failing chunk's EXECUTION already consumed that
                # carry before raising (e.g. a debug-nans abort fires
                # after donation), there is nothing consistent to land
                # — surface the original error unwrapped rather than
                # publish deleted arrays under a resumability claim.
                if any(getattr(v, 'is_deleted', lambda: False)()
                       for v in state_rw.values()):
                    raise
                for n, v in state_rw.items():
                    scope.set(n, v)
                for n, v in last_extra.items():
                    scope.set(n, v)
                self._step += done
                if not isinstance(e, Exception):
                    raise  # KeyboardInterrupt & co propagate as-is
                raise RuntimeError(
                    "run_steps(device_prefetch) failed mid-stream "
                    "after %d of %d steps; the scope holds the state "
                    "of the %d completed steps" % (done, k, done)) \
                    from e
            self._step += k
            tu = time.perf_counter()
            for n, v in state_rw.items():
                scope.set(n, v)
            for n, v in last_extra.items():
                scope.set(n, v)
            report['update_s'] = time.perf_counter() - tu
            tl = _tlm.ring_if_armed()
            if tl is not None:
                tl.record('executor.scope_update', 'update', t0=tu,
                          dur=report['update_s'])
            if em is not None and return_numpy:
                self._note_amp_skips(state_rw, scope)
            ts = time.perf_counter()
            outs = []
            for i in range(len(fetch_names)):
                parts = [p[i] for p in ys_parts]
                if return_numpy:
                    outs.append(np.concatenate(
                        [np.asarray(x) for x in parts]))
                else:
                    outs.append(parts[0] if len(parts) == 1
                                else jnp.concatenate(parts))
            if tl is not None and return_numpy and fetch_names:
                tl.record('executor.fetch_sync', 'compute', t0=ts,
                          dur=time.perf_counter() - ts,
                          args={'steps': k})
            self._finalize_step_report(
                report, t_call,
                synced=return_numpy and bool(fetch_names))
            return outs

    def _finalize_step_report(self, report, t_call, synced=False):
        """Join the measured run_steps phase walls with the static
        cost-model report (transpiler/cost_model.py, cached per plan in
        last_graph_opt_report['cost']) into ``last_step_report``:

        - ``wall_s`` = whole-call wall; ``compute_s`` = the residual
          after feed_s + update_s, i.e. device scan + fetch sync — the
          three phases sum to ~wall by construction.
        - ``phases`` = {feed, compute, update}, each with its wall and
          the modeled bytes/FLOPs that phase moves per step; compute
          carries per-role FLOPs and arithmetic intensity, plus
          achieved FLOP/s and — when PADDLE_TPU_PEAK_TFLOPS is set —
          MFU, but ONLY when ``synced`` (the fetch conversion forced
          the device scan to completion inside the measured window).
          A return_numpy=False call returns before the device
          finishes, so its residual measures host dispatch only —
          publishing a rate from it would overstate MFU by the
          device-time/dispatch-time ratio.  Callers that sync
          externally (benchmarks/common.py _step_breakdown) derive
          MFU from their own synced wall and the modeled
          flops_per_step instead.

        Also flushes the timeline ring to PADDLE_TPU_TRACE_DIR when the
        flight recorder is armed (one atomic trace_<pid>.json per
        run_steps call)."""
        import os as _os
        wall = time.perf_counter() - t_call
        k = max(int(report.get('k', 1)), 1)
        compute = max(wall - report['feed_s'] - report['update_s'], 0.0)
        report['wall_s'] = wall
        report['compute_s'] = compute
        report['synced'] = bool(synced)
        cost = (self.last_graph_opt_report or {}).get('cost')
        feed_phase = {'wall_s': report['feed_s'],
                      'overlap_s': report['feed_overlap_s'],
                      'bytes': report.get('feed_bytes', 0)}
        compute_phase = {'wall_s': compute}
        update_phase = {'wall_s': report['update_s']}
        if cost is not None and cost.get('total') is not None:
            total = cost['total']
            compute_phase.update({
                'flops': total['flops'] * k,
                'bytes': total['bytes'] * k,
                'flops_per_step': total['flops'],
                'bytes_per_step': total['bytes'],
                'intensity': total['intensity'],
                'per_role_flops': {r: v['flops']
                                   for r, v in cost['per_role'].items()},
            })
            if synced and compute > 0.0 and total['flops']:
                compute_phase['flops_per_s'] = total['flops'] * k / \
                    compute
                peak = _os.environ.get('PADDLE_TPU_PEAK_TFLOPS')
                if peak:
                    compute_phase['mfu'] = (
                        compute_phase['flops_per_s'] /
                        (float(peak) * 1e12))
            if cost.get('feed_bytes') is not None:
                feed_phase['modeled_bytes_per_step'] = cost['feed_bytes']
            update_phase['state_bytes'] = cost.get('state_bytes', 0)
        report['phases'] = {'feed': feed_phase,
                            'compute': compute_phase,
                            'update': update_phase}
        # comm attribution (SPMD plans): the modeled ICI bytes the
        # k steps' collectives moved, priced by the cost model from
        # the sharding pass's table — attributed like feed/compute/
        # update, with a wall estimate when PADDLE_TPU_ICI_GBPS is set
        noted = self._note_collectives(
            _tlm.ring_if_armed(), k,
            compute_s=compute if (synced and compute > 0.0) else None)
        if noted is not None:
            report['phases']['collective'] = {
                'modeled_ici_bytes': noted['ici_bytes'],
                'modeled_ici_bytes_per_step': noted['ici_bytes'] // k,
                'collectives': noted['collectives'],
                'by_kind': dict(noted.get('by_kind') or {}),
                'est_wall_s': noted['est_wall_s'],
            }
            for fld in ('overlap_fraction', 'overlap_basis',
                        'exposed_bytes_per_step',
                        'overlapped_bytes_per_step',
                        'exposed_est_wall_s', 'pp'):
                if fld in noted:
                    report['phases']['collective'][fld] = noted[fld]
        report['cost'] = cost
        measured = _tlm.device_memory_stats(self._memory_device())
        report['memory'] = self._memory_report(cost, measured)
        tl = _tlm.ring_if_armed()
        if tl is not None:
            self._emit_memory_counters(
                tl, (cost or {}).get('memory'),
                t_call + report['feed_s'], compute, measured=measured)
        _tlm.maybe_flush()
        return report

    def _memory_device(self):
        """The device whose memory_stats() this executor's measured
        numbers describe — the executor's PLACE, not local_devices()[0]
        (on a multi-device host they differ, and the modeled-vs-
        measured comparison must read one device)."""
        try:
            return self.place.jax_device()
        except Exception:
            return None

    def _memory_report(self, cost, measured):
        """The memory block of ``last_step_report``: the modeled peak
        (liveness walk, transpiler/memory_model.py) joined with the
        MEASURED device stats when the backend provides them —
        ``measured`` is honestly None on CPU backends, never a made-up
        zero — plus a headroom ratio against PADDLE_TPU_PEAK_HBM_BYTES
        when set, so model-vs-measured divergence is a first-class
        printed quantity."""
        from ..flags import FLAGS
        mem = (cost or {}).get('memory') if isinstance(cost, dict) \
            else None
        entry = {
            'modeled_peak_bytes': (mem or {}).get('peak_bytes'),
            'modeled_persistable_bytes':
                (mem or {}).get('persistable_bytes'),
            'watermark_op': ((mem or {}).get('watermark') or [None])[0],
            'remat_level': (mem or {}).get('remat_level'),
            'measured': measured,
        }
        if measured is not None:
            entry['measured_peak_bytes'] = measured.get(
                'peak_bytes_in_use')
        budget = int(FLAGS.peak_hbm_bytes or 0)
        if budget > 0:
            head = {'budget_bytes': budget}
            if entry['modeled_peak_bytes']:
                head['modeled_ratio'] = (
                    entry['modeled_peak_bytes'] / budget)
            if measured is not None and \
                    measured.get('peak_bytes_in_use'):
                head['measured_ratio'] = (
                    measured['peak_bytes_in_use'] / budget)
            entry['headroom'] = head
        return entry

    @staticmethod
    def _emit_memory_counters(tl, mem, t0, span, measured=None):
        """Render the modeled live-bytes sawtooth as a Chrome counter
        track (``ph:"C"``): samples step along op_seq, mapped linearly
        onto the measured compute window so the track lines up with the
        dispatch it models.  Downsampled to a bounded point count with
        the peak sample always kept — a 1000-op program must not eat
        the event ring.  ``measured`` is the device_memory_stats()
        dict the caller already captured (one query serves both the
        report and the counter track), sampled alongside."""
        timeline = (mem or {}).get('timeline') or ()
        if timeline:
            pts = list(timeline)
            cap = 96
            if len(pts) > cap:
                peak_i = max(range(len(pts)),
                             key=lambda i: pts[i]['live_bytes'])
                stride = -(-len(pts) // cap)
                keep = sorted({0, peak_i, len(pts) - 1}
                              | set(range(0, len(pts), stride)))
                pts = [pts[i] for i in keep]
            span = max(span, 1e-6)
            n = max(len(pts) - 1, 1)
            for i, p in enumerate(pts):
                tl.counter_sample('paddle_tpu.modeled_live_bytes',
                                  p['live_bytes'],
                                  t0=t0 + span * (i / n))
        if measured and measured.get('bytes_in_use') is not None:
            tl.counter_sample('paddle_tpu.device_bytes_in_use',
                              measured['bytes_in_use'])

    def _compile_common(self, program, feed, fetch_list, scope):
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        feed = feed or {}
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f)
            for f in (fetch_list or [])
        ]
        block = program.global_block()
        feed_arrays = {}
        for name, value in feed.items():
            var = block.vars.get(name)
            feed_arrays.update(_to_feed_arrays(name, value, var))
        # compile()/compile_raw() hand their fn to AOT/export/serving
        # consumers (and run_sharded re-jits with its OWN shard plan):
        # the flag mesh is pinned off so the plan — and its cost/memory
        # report — describes the single-logical-device executable these
        # callers actually get
        fn, raw, rw_names, ro_names, _smeta = self._get_plan(
            program, block, scope, feed_arrays, tuple(fetch_names),
            True, mesh_off=True)
        state_rw = {n: scope.get(n) for n in rw_names}
        state_ro = {n: scope.get(n) for n in ro_names}
        rng_key = self._rng_key(program)
        return fn, raw, (feed_arrays, state_rw, state_ro, rng_key)

    def compile(self, program=None, feed=None, fetch_list=None, scope=None):
        """Build (but do not run) the jitted step function for a program.

        Returns (fn, example_args) where ``fn(feed, state_rw, state_ro,
        rng_key) -> (fetches, new_state)`` is the whole-block XLA
        computation — the hook used by __graft_entry__ and jax.export.
        """
        fn, _raw, args = self._compile_common(program, feed, fetch_list,
                                              scope)
        return fn, args

    def compile_raw(self, program=None, feed=None, fetch_list=None,
                    scope=None):
        """Like compile(), but returns the UN-jitted python step function —
        the hook for re-jitting with explicit shardings (parallel/api.py)
        or custom transforms."""
        _fn, raw, args = self._compile_common(program, feed, fetch_list,
                                              scope)
        return raw, args

    def reset_cache(self):
        """Drop every cached plan and re-read late-bound flags: the
        persistent-compile-cache dir (PADDLE_TPU_COMPILATION_CACHE_DIR)
        is re-applied, and the next plan build re-reads
        PADDLE_TPU_GRAPH_OPT_LEVEL, PADDLE_TPU_SPARSE_APPLY,
        PADDLE_TPU_DENSE_APPLY, PADDLE_TPU_AMP, and
        PADDLE_TPU_VERIFY_IR (all folded into the composite
        pass-configuration component of every plan key, so flips
        invalidate naturally — this just frees the old plans).  PADDLE_TPU_DEVICE_PREFETCH is re-read on every
        run_steps call and its chunking keys the scan plans by length,
        so it needs no special handling here either."""
        self.close()
        _maybe_enable_compilation_cache()

    def close(self):
        self._cache.clear()
        self._plan_reports.clear()
        self.last_graph_opt_report = None
        self.last_step_report = None
        self._mesh_op_cache.clear()
        if hasattr(self, '_sharded_cache'):
            self._sharded_cache.clear()
