"""Executor: lowers a whole Program block into ONE jit-compiled XLA
computation.

Reference parity: paddle/framework/executor.{h,cc} + python fluid
executor.py.  The reference interprets a block op-by-op, dispatching a CUDA
kernel per op.  TPU-native design: the same block is *traced* op-by-op in
Python exactly once, producing a single fused HLO program that XLA compiles
for the MXU; parameters stay device-resident in the Scope and are donated
across steps, so a full train step (forward + backward + optimizer update)
is one device launch with zero host round-trips.
"""
import numpy as np

import jax
import jax.numpy as jnp

from . import datatypes
from .lod import LoDTensor
from .place import default_place
from .program import (LEN_SUFFIX, Program, Variable, default_main_program)
from .registry import get_op_impl
from .scope import Scope, global_scope

__all__ = ['Executor', 'global_scope', 'scope_guard']

from .scope import scope_guard  # re-export (parity with fluid.executor)


class ExecutionContext(object):
    """Per-trace context handed to op compute functions: PRNG derivation,
    access to the interpreter for ops that carry sub-blocks, and the
    enclosing program/block."""

    def __init__(self, program, block, rng_key, uid_prefix=0):
        self.program = program
        self.block = block
        self.rng_key = rng_key
        self.uid_prefix = uid_prefix
        self.op_index = 0

    def rng(self, extra=0):
        """Deterministic per-op PRNG key: stable under the autodiff replay
        of forward ops (keys derive from op position, not call order)."""
        k = jax.random.fold_in(self.rng_key, self.uid_prefix)
        k = jax.random.fold_in(k, self.block.idx)
        k = jax.random.fold_in(k, self.op_index)
        if extra:
            k = jax.random.fold_in(k, extra)
        return k

    def sub_context(self, block):
        sub = ExecutionContext(self.program, block, self.rng_key,
                               self.uid_prefix + 1000)
        return sub

    def run_block(self, block_idx, env):
        """Interpret a sub-block in-place over `env` (used by control-flow
        ops like conditional_block)."""
        block = self.program.blocks[block_idx]
        ctx = self.sub_context(block)
        _run_ops(block.ops, env, ctx)
        return env


def _run_one(op, env, ctx, op_index):
    impl = get_op_impl(op.type)
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n not in env:
                raise KeyError(
                    "op %s reads %r which has no value; feed it, run the "
                    "startup program, or check op ordering" % (op.type, n))
            vals.append(env[n])
        ins[slot] = vals
    ctx.op_index = op_index
    outs = impl.compute(ctx, ins, op.attrs) or {}
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for n, v in zip(names, vals):
            if v is None:
                continue
            try:
                var = ctx.block.var_recursive(n)
                if var.stop_gradient and not var.is_data:
                    v = jax.lax.stop_gradient(v)
            except KeyError:
                pass
            env[n] = v


def _run_ops(ops, env, ctx):
    """Interpret a list of ops.  `autodiff` ops (appended by
    core/backward.py) are handled here: the forward range they cover is
    executed exactly once, inside jax.value_and_grad — functional autodiff
    replacing the reference's per-op grad kernels (framework/backward.cc)."""
    ad_idxs = [i for i, op in enumerate(ops) if op.type == 'autodiff']
    cursor = 0
    for k in ad_idxs:
        ad_op = ops[k]
        s = ad_op.attrs['forward_start']
        for i in range(cursor, s):
            _run_one(ops[i], env, ctx, i)
        _run_autodiff(ad_op, ops[s:k], env, ctx, base_index=s)
        cursor = k + 1
    for i in range(cursor, len(ops)):
        _run_one(ops[i], env, ctx, i)


def _run_autodiff(ad_op, fwd_ops, env, ctx, base_index):
    param_names = list(ad_op.attrs['param_names'])
    grad_names = list(ad_op.attrs['grad_names'])
    loss_name = ad_op.attrs['loss_name']
    loss_scale = ad_op.attrs.get('loss_scale', 1.0)

    params = {n: env[n] for n in param_names}
    captured = dict(env)

    def f(ps):
        env2 = dict(captured)
        env2.update(ps)
        for j, op in enumerate(fwd_ops):
            _run_one(op, env2, ctx, base_index + j)
        loss = env2[loss_name]
        loss = jnp.sum(loss.astype(jnp.float32)) * loss_scale
        return loss, env2

    (_, env_fwd), grads = jax.value_and_grad(f, has_aux=True)(params)
    env.update(env_fwd)
    for pn, gn in zip(param_names, grad_names):
        g = grads[pn]
        env[gn] = g.astype(env[pn].dtype) if hasattr(g, 'astype') else g


def _to_feed_arrays(name, value, var):
    """Convert one feed entry to {name: array} (+ companion lengths for
    ragged feeds)."""
    out = {}
    if isinstance(value, LoDTensor):
        out[name] = _np_to_device_dtype(value.padded(), var)
        if value.is_ragged():
            out[name + LEN_SUFFIX] = np.asarray(value.lengths(),
                                                dtype=np.int32)
        return out
    if isinstance(value, tuple) and len(value) == 2 and var is not None \
            and var.lod_level > 0:
        data, lengths = value
        out[name] = _np_to_device_dtype(np.asarray(data), var)
        out[name + LEN_SUFFIX] = np.asarray(lengths, dtype=np.int32)
        return out
    out[name] = _np_to_device_dtype(np.asarray(value), var)
    return out


def _np_to_device_dtype(arr, var):
    """Narrow 64-bit host arrays to the 32-bit types TPUs run (x64 is
    disabled); honour the declared var dtype otherwise."""
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    elif arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    elif arr.dtype == np.uint64:
        arr = arr.astype(np.uint32)
    if var is not None and datatypes.is_float_dtype(var.dtype) and \
            arr.dtype.kind in 'fiu':
        want = datatypes.as_numpy_dtype(var.dtype)
        if want in (np.float64,):
            want = np.float32
        arr = arr.astype(want)
    return arr


class Executor(object):
    def __init__(self, place=None):
        if isinstance(place, (list, tuple)):
            place = place[0]
        self.place = place if place is not None else default_place()
        self._cache = {}
        self._step = 0

    # ------------------------------------------------------------------
    def run(self,
            program=None,
            feed=None,
            fetch_list=None,
            feed_var_name='feed',
            fetch_var_name='fetch',
            scope=None,
            return_numpy=True,
            use_program_cache=True):
        if program is None:
            program = default_main_program()
        if not isinstance(program, Program):
            raise TypeError("Executor requires a Program, got %r" %
                            type(program))
        if scope is None:
            scope = global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]

        block = program.global_block()

        feed_arrays = {}
        for name, value in feed.items():
            var = block.vars.get(name)
            feed_arrays.update(_to_feed_arrays(name, value, var))

        plan = self._get_plan(program, block, scope, feed_arrays,
                              tuple(fetch_names), use_program_cache)
        (fn, state_rw_names, state_ro_names) = plan

        state_rw = {n: scope.get(n) for n in state_rw_names}
        state_ro = {n: scope.get(n) for n in state_ro_names}
        rng_key = self._rng_key(program)
        self._step += 1

        with jax.default_device(self.place.jax_device()):
            fetches, new_state = fn(feed_arrays, state_rw, state_ro, rng_key)

        for n, v in new_state.items():
            scope.set(n, v)
        if return_numpy:
            fetches = [np.asarray(v) for v in fetches]
        return fetches

    # ------------------------------------------------------------------
    def _rng_key(self, program):
        seed = program.random_seed
        if seed == 0:
            seed = id(self) % (2**31)
        return jax.random.fold_in(jax.random.PRNGKey(seed), self._step)

    def _analyze_state(self, program, scope, feed_names):
        """Classify persistable vars: `rw` (existing value, written → passed
        in and donated), `ro` (existing value, only read), `out` (written by
        the block — includes first-time writes, e.g. the startup program)."""
        written = set()
        read = set()
        for b in program.blocks:
            for op in b.ops:
                written.update(op.output_arg_names)
                read.update(op.input_arg_names)
        rw, ro, out = [], [], []
        for v in program.list_vars():
            if not v.persistable or v.name in feed_names:
                continue
            if v.name in written:
                out.append(v.name)
            if not scope.has(v.name):
                if v.name in read and v.name not in written:
                    raise RuntimeError(
                        "persistable var %r is read but has no value in "
                        "scope; run the startup program first" % v.name)
                continue
            if v.name in written:
                rw.append(v.name)
            elif v.name in read:
                ro.append(v.name)
        return tuple(sorted(rw)), tuple(sorted(ro)), tuple(sorted(out))

    def _get_plan(self, program, block, scope, feed_arrays, fetch_names,
                  use_cache):
        feed_sig = tuple(
            (n, feed_arrays[n].shape, str(feed_arrays[n].dtype))
            for n in sorted(feed_arrays))
        state_rw_names, state_ro_names, state_out_names = \
            self._analyze_state(program, scope, set(feed_arrays))
        key = (id(program), program.version, feed_sig, fetch_names,
               state_rw_names, state_ro_names, state_out_names, id(scope))
        if use_cache and key in self._cache:
            return self._cache[key]

        prog = program

        def step_fn(feed_vals, state_rw, state_ro, rng_key):
            env = {}
            env.update(state_ro)
            env.update(state_rw)
            env.update(feed_vals)
            ctx = ExecutionContext(prog, prog.global_block(), rng_key)
            _run_ops(prog.global_block().ops, env, ctx)
            fetches = []
            for n in fetch_names:
                if n not in env:
                    raise KeyError("fetch var %r was never computed" % n)
                fetches.append(env[n])
            new_state = {n: env[n] for n in state_out_names if n in env}
            return fetches, new_state

        fn = jax.jit(step_fn, donate_argnums=(1,))
        plan = (fn, state_rw_names, state_ro_names)
        if use_cache:
            self._cache[key] = plan
        return plan

    def close(self):
        self._cache.clear()
