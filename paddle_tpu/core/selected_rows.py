"""SelectedRows: sparse row-set gradients (C5/O11).

Reference parity: paddle/framework/selected_rows.{h,cc} — a (rows, value)
pair standing in for a mostly-zero dense tensor, produced by
lookup_table's grad and consumed by the sparse branches of
sgd/adagrad/adam (paddle/operators/sgd_op.cc, adagrad_op.cc).

TPU-native design: a registered pytree of (rows int32 [K], values [K, D])
with a static `height` (the dense row count), so a SelectedRows can flow
through a jitted step like any array.  K is static (= number of looked-up
ids per step), which is exactly the TPU-friendly property: the *dense*
vocab-height grad never materializes; optimizers scatter row updates into
the donated parameter buffer in place.
"""
import jax
import jax.numpy as jnp

__all__ = ['SelectedRows', 'merge_duplicate_rows']


class SelectedRows(object):
    """rows: int32 [K] dense-row indices (may repeat); values: [K, ...]
    per-row data; height: static dense row count."""

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def to_dense(self):
        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def __repr__(self):
        return 'SelectedRows(rows=%s, values=%s, height=%d)' % (
            self.rows.shape, self.values.shape, self.height)


jax.tree_util.register_pytree_node(
    SelectedRows,
    lambda s: ((s.rows, s.values), s.height),
    lambda height, ch: SelectedRows(ch[0], ch[1], height))


def merge_duplicate_rows(rows, values):
    """Sum values of duplicate rows (reference
    operators/math/selected_rows_functor MergeAdd) with static shapes:
    sort by row, segment-sum runs of equal rows.  Returns (rows', values')
    of the SAME length K — unused tail slots point at row0 with zero
    values, so scatter-consumers can apply them as harmless no-ops ONLY
    when the per-row update of a zero gradient is zero (sgd/adagrad-style
    g-scaled updates).  Callers needing true no-ops must mask on
    `valid` = slot < number of unique rows (third return value)."""
    rows = rows.astype(jnp.int32).reshape(-1)
    k = rows.shape[0]
    order = jnp.argsort(rows)
    srows = rows[order]
    svals = values[order]
    is_new = jnp.concatenate([jnp.ones((1,), bool),
                              srows[1:] != srows[:-1]])
    seg = jnp.cumsum(is_new) - 1  # [K] segment id per sorted slot
    merged_vals = jax.ops.segment_sum(svals, seg, num_segments=k)
    merged_rows = jnp.zeros((k,), jnp.int32).at[seg].set(srows)
    n_unique = seg[-1] + 1
    valid = jnp.arange(k) < n_unique
    return merged_rows, merged_vals, valid
