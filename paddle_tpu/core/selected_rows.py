"""SelectedRows: sparse row-set gradients (C5/O11).

Reference parity: paddle/framework/selected_rows.{h,cc} — a (rows, value)
pair standing in for a mostly-zero dense tensor, produced by
lookup_table's grad and consumed by the sparse branches of
sgd/adagrad/adam (paddle/operators/sgd_op.cc, adagrad_op.cc).

TPU-native design: a registered pytree of (rows int32 [K], values [K, D])
with a static `height` (the dense row count), so a SelectedRows can flow
through a jitted step like any array.  K is static (= number of looked-up
ids per step), which is exactly the TPU-friendly property: the *dense*
vocab-height grad never materializes; optimizers scatter row updates into
the donated parameter buffer in place.
"""
import jax
import jax.numpy as jnp

__all__ = ['SelectedRows', 'merge_duplicate_rows', 'merge_rows_sentinel']


class SelectedRows(object):
    """rows: int32 [K] dense-row indices (may repeat); values: [K, ...]
    per-row data; height: static dense row count."""

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def to_dense(self):
        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def __repr__(self):
        return 'SelectedRows(rows=%s, values=%s, height=%d)' % (
            self.rows.shape, self.values.shape, self.height)


jax.tree_util.register_pytree_node(
    SelectedRows,
    lambda s: ((s.rows, s.values), s.height),
    lambda height, ch: SelectedRows(ch[0], ch[1], height))


def merge_duplicate_rows(rows, values):
    """Sum values of duplicate rows (reference
    operators/math/selected_rows_functor MergeAdd) with static shapes:
    sort by row, segment-sum runs of equal rows.  Returns (rows', values')
    of the SAME length K — unused tail slots point at row0 with zero
    values, so scatter-consumers can apply them as harmless no-ops ONLY
    when the per-row update of a zero gradient is zero (sgd/adagrad-style
    g-scaled updates).  Callers needing true no-ops must mask on
    `valid` = slot < number of unique rows (third return value)."""
    rows = rows.astype(jnp.int32).reshape(-1)
    k = rows.shape[0]
    order = jnp.argsort(rows)
    srows = rows[order]
    svals = values[order]
    is_new = jnp.concatenate([jnp.ones((1,), bool),
                              srows[1:] != srows[:-1]])
    seg = jnp.cumsum(is_new) - 1  # [K] segment id per sorted slot
    merged_vals = jax.ops.segment_sum(svals, seg, num_segments=k)
    merged_rows = jnp.zeros((k,), jnp.int32).at[seg].set(srows)
    n_unique = seg[-1] + 1
    valid = jnp.arange(k) < n_unique
    return merged_rows, merged_vals, valid


def merge_rows_sentinel(rows, values, height, pad_to=None):
    """merge_duplicate_rows with the SENTINEL slot convention the Pallas
    table-update kernels (ops/pallas/table_update.py) consume: every
    non-real output slot carries row index ``height`` — out of range, so
    an XLA scatter consumer DROPS it (out-of-bounds updates are dropped)
    and the kernel skips it; no `valid` masking of the values is needed
    on either path.  Incoming ids outside [0, height) are treated as
    padding and land in the sentinel tail too, which is what makes
    RAGGED touched-row counts bucket-friendly: pad the id vector with
    ``height`` up to a bucket size and the padding is exact no-ops.

    ``pad_to`` right-pads the OUTPUT to a multiple of that many slots
    (sentinel rows, zero values) — tile-aligned output, so a consumer
    whose grid/blocking wants K % tile == 0 compiles one shape per
    bucket instead of one per batch.

    Returns (rows [K'], values [K', ...], valid [K'] bool)."""
    rows = rows.astype(jnp.int32).reshape(-1)
    k = rows.shape[0]
    height = int(height)
    if k == 0:
        return rows, values, jnp.zeros((0,), bool)
    in_range = (rows >= 0) & (rows < height)
    rows_in = jnp.where(in_range, rows, height)
    order = jnp.argsort(rows_in, stable=True)
    srows = rows_in[order]
    svals = values[order]
    is_new = jnp.concatenate([jnp.ones((1,), bool),
                              srows[1:] != srows[:-1]])
    seg = jnp.cumsum(is_new) - 1
    merged_vals = jax.ops.segment_sum(svals, seg, num_segments=k)
    # unassigned tail segments keep the sentinel fill; the (single)
    # sentinel segment, if any, writes `height` over it — same value
    merged_rows = jnp.full((k,), height, jnp.int32).at[seg].set(srows)
    n_valid = jnp.sum(is_new & (srows < height))
    valid = jnp.arange(k) < n_valid
    # sentinel slots may hold garbage segment sums (summed padding
    # values); both consumers drop them by row id, so zeroing would be
    # wasted work
    if pad_to and k % int(pad_to):
        pad = int(pad_to) - k % int(pad_to)
        merged_rows = jnp.concatenate(
            [merged_rows, jnp.full((pad,), height, jnp.int32)])
        merged_vals = jnp.concatenate(
            [merged_vals,
             jnp.zeros((pad,) + merged_vals.shape[1:], merged_vals.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
    return merged_rows, merged_vals, valid
