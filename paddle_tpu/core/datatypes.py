"""Dtype registry.

Reference parity: paddle/framework/data_type.h and
python/paddle/v2/fluid/data_feeder.py dtype strings.  TPU-native addition:
bfloat16 is a first-class dtype (the MXU native matmul type).
"""
import numpy as np

try:
    import ml_dtypes

    bfloat16 = ml_dtypes.bfloat16
except Exception:  # pragma: no cover
    bfloat16 = np.float32

_STR2NP = {
    'float16': np.float16,
    'bfloat16': bfloat16,
    'float32': np.float32,
    'float64': np.float64,
    'int8': np.int8,
    'uint8': np.uint8,
    'int16': np.int16,
    'int32': np.int32,
    'int64': np.int64,
    'bool': np.bool_,
}

_ALIASES = {
    'float': 'float32',
    'double': 'float64',
    'int': 'int32',
    'fp16': 'float16',
    'bf16': 'bfloat16',
    'fp32': 'float32',
    'fp64': 'float64',
}


def convert_dtype(dtype):
    """Normalise a dtype spec (string / numpy dtype / jax dtype) to a
    canonical string name."""
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _STR2NP:
            raise ValueError("unsupported dtype: %r" % (dtype,))
        return name
    name = np.dtype(dtype).name
    if name == 'bfloat16' or 'bfloat16' in str(dtype):
        return 'bfloat16'
    return convert_dtype(name)


def as_numpy_dtype(dtype):
    return _STR2NP[convert_dtype(dtype)]


def is_float_dtype(dtype):
    return convert_dtype(dtype) in ('float16', 'bfloat16', 'float32',
                                    'float64')


def is_integer_dtype(dtype):
    return convert_dtype(dtype) in ('int8', 'uint8', 'int16', 'int32',
                                    'int64')
