"""Dtype registry.

Reference parity: paddle/framework/data_type.h and
python/paddle/v2/fluid/data_feeder.py dtype strings.  TPU-native addition:
bfloat16 is a first-class dtype (the MXU native matmul type).
"""
import numpy as np

try:
    import ml_dtypes

    bfloat16 = ml_dtypes.bfloat16
except Exception:  # pragma: no cover
    bfloat16 = np.float32

_STR2NP = {
    'float16': np.float16,
    'bfloat16': bfloat16,
    'float32': np.float32,
    'float64': np.float64,
    'int8': np.int8,
    'uint8': np.uint8,
    'int16': np.int16,
    'int32': np.int32,
    'int64': np.int64,
    'bool': np.bool_,
}

_ALIASES = {
    'float': 'float32',
    'double': 'float64',
    'int': 'int32',
    'fp16': 'float16',
    'bf16': 'bfloat16',
    'fp32': 'float32',
    'fp64': 'float64',
}


def convert_dtype(dtype):
    """Normalise a dtype spec (string / numpy dtype / jax dtype) to a
    canonical string name."""
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _STR2NP:
            raise ValueError("unsupported dtype: %r" % (dtype,))
        return name
    name = np.dtype(dtype).name
    if name == 'bfloat16' or 'bfloat16' in str(dtype):
        return 'bfloat16'
    return convert_dtype(name)


def as_numpy_dtype(dtype):
    return _STR2NP[convert_dtype(dtype)]


def is_float_dtype(dtype):
    return convert_dtype(dtype) in ('float16', 'bfloat16', 'float32',
                                    'float64')


def is_low_precision(dtype):
    """True for the 16-bit float dtypes AMP lowers compute into."""
    return convert_dtype(dtype) in ('float16', 'bfloat16')


# widest-wins float lattice for AMP's grey-op "follow the inputs" rule:
# f64 > f32 > {bf16, f16}.  bf16 and f16 don't order against each other
# (8-bit exponent vs 10-bit mantissa) — mixing them promotes to f32.
_FLOAT_RANK = {'float64': 3, 'float32': 2, 'bfloat16': 1, 'float16': 1}


def promote_float_dtype(a, b):
    """The dtype a grey (follow-the-inputs) op runs in when fed `a` and
    `b`: the wider of the two; bf16 + f16 (unordered) promotes to f32."""
    a = convert_dtype(a)
    b = convert_dtype(b)
    ra, rb = _FLOAT_RANK.get(a), _FLOAT_RANK.get(b)
    if ra is None or rb is None:
        raise ValueError("promote_float_dtype needs float dtypes, got "
                         "%r and %r" % (a, b))
    if ra == rb:
        return a if a == b else 'float32'
    return a if ra > rb else b


def is_integer_dtype(dtype):
    return convert_dtype(dtype) in ('int8', 'uint8', 'int16', 'int32',
                                    'int64')
