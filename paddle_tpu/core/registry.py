"""Op registry.

Reference parity: paddle/framework/op_registry.h.  Each op type maps to a
single pure-jax compute function (instead of per-device kernel families —
XLA owns device lowering).  Signature:

    def compute(ctx, ins, attrs) -> {slot: [jax.Array, ...]}

where `ins` is {slot: [arrays]} and ctx is an ExecutionContext giving access
to PRNG keys and the interpreter (for ops with sub-blocks).

``op_signature()`` recovers each op's *declared-slot contract* statically —
the reference's OpProto (op_proto_maker.h) rebuilt by AST introspection of
the compute function instead of a hand-maintained proto: which input slots
the function can read, which output slots it can produce, and which attrs
it requires.  The IR verifier (transpiler/verify.py) checks every OpDesc
against it, so a layer passing a slot the kernel never reads fails at plan
build with an op-precise message instead of silently dropping the tensor.
"""
import ast
import collections
import inspect
import textwrap

_OP_REGISTRY = {}
_CALLED = set()  # op types fetched for execution (coverage meta-test)

# ---------------------------------------------------------------------------
# AMP (automatic mixed precision) op classification — consumed by the
# transpiler/amp.py cast-insertion pass and reported through op_traits().
#
# AMP_WHITE: matmul-shaped ops whose FLOPs land on the MXU — these run in
# the low precision (bf16/f16) under PADDLE_TPU_AMP; the win is ~2x matmul
# throughput plus halved activation bandwidth.
#
# AMP_BLACK: ops that must stay f32 — losses and softmaxes (dynamic
# range), normalization statistics, wide accumulations (sum/mean),
# range-sensitive elementwise math (exp/log/pow/square), metrics, the
# optimizer updates (f32 master weights), and the AMP machinery itself.
#
# Everything else is GREY: precision follows the inputs (an elementwise op
# between two bf16 values runs in bf16; one fed a f32 value stays f32).
# A newly registered op is grey by default, which is always SAFE — it can
# never force a value into low precision on its own — and
# tests/test_zz_op_coverage.py asserts every registered op lands in
# exactly one class so list rot is caught structurally.
AMP_WHITE = frozenset({
    'matmul', 'mul',
    'conv2d', 'conv2d_transpose', 'conv3d', 'conv3d_transpose',
    'sequence_conv', 'conv_shift', 'row_conv',
    'bilinear_tensor_product', 'flash_attention', 'paged_attention',
    'chunked_prefill_attention',
    'lstm', 'lstm_unit', 'gru', 'gru_unit',
    # fused vocab-head CE ops: dominated by the [N,D]x[D,V] matmul and
    # internally f32-safe (preferred_element_type accumulation + f32
    # softmax state), so their INPUTS lower; their loss outputs are
    # always f32 (amp.py WHITE_F32_OUTPUT_OPS)
    'fused_linear_softmax_ce', 'vocab_parallel_ce',
})

AMP_BLACK = frozenset({
    # softmax family + losses (dynamic range / reductions over logits)
    'softmax', 'sequence_softmax',
    'cross_entropy', 'softmax_with_cross_entropy',
    'sigmoid_cross_entropy_with_logits', 'square_error_cost',
    'smooth_l1', 'smooth_l1_loss', 'hinge_loss', 'huber_loss',
    'log_loss', 'margin_rank_loss', 'modified_huber_loss', 'rank_loss',
    'warpctc', 'nce', 'linear_chain_crf', 'crf_decoding',
    # normalization / statistics
    'batch_norm', 'layer_norm', 'norm', 'lrn', 'l1_norm',
    'squared_l2_norm', 'squared_l2_distance', 'cos_sim', 'clip_by_norm',
    # wide accumulations
    'sum', 'mean', 'reduce_sum', 'reduce_mean', 'reduce_prod',
    # range-sensitive elementwise math
    'exp', 'log', 'pow', 'square',
    # metrics
    'accuracy', 'auc', 'precision_recall', 'positive_negative_pair',
    'chunk_eval', 'edit_distance', 'detection_output',
    # optimizer updates apply to the f32 masters
    'sgd', 'momentum', 'adam', 'adamax', 'adagrad', 'decayed_adagrad',
    'adadelta', 'rmsprop', 'ftrl', 'proximal_gd', 'proximal_adagrad',
    # grad machinery + the AMP ops themselves
    'sparse_grad_assemble', 'check_finite_and_unscale',
    'update_loss_scale',
})


def amp_class(type):
    """'white' | 'black' | 'grey' AMP classification for an op type.
    Unregistered/unknown types are grey (the safe default: grey can
    never lower a value's precision on its own)."""
    if type in AMP_WHITE:
        return 'white'
    if type in AMP_BLACK:
        return 'black'
    return 'grey'


# ---------------------------------------------------------------------------
# Cost-model op classification — consumed by transpiler/cost_model.py
# (the static per-op FLOPs/bytes analysis pass) and reported through
# op_traits().cost.
#
# COST_MAC: ops whose dominant cost is multiply-accumulates on the MXU —
# each has an exact closed-form MAC formula in
# transpiler/cost_model.MAC_FORMULAS (shape-derived, no sampling).  This
# is deliberately the AMP_WHITE set: "FLOPs land on the MXU" is the same
# property both classifications name, and keeping them equal means a new
# matmul-shaped op registered WHITE without a MAC formula fails the
# cost-coverage sweep instead of silently costing zero.
#
# Everything else registered is COST class 'bytes': the roofline cost of
# an elementwise/reduction/reshape op is the memory traffic it moves
# (inputs read + outputs written), not its ALU count — its FLOPs column
# reads 0 by convention and its bytes column is exact from shapes.
# Ops with no per-op dense-tensor cost at all (control flow whose cost
# is its body's, SelectedRows plumbing) carry explicit waivers in
# transpiler/cost_model.WAIVED_OPS.
COST_MAC = frozenset(AMP_WHITE)


def cost_class(type):
    """'mac' | 'bytes' cost classification for an op type (see COST_MAC
    above; transpiler/cost_model.py holds the formulas and the
    explicit no-verdict waivers)."""
    return 'mac' if type in COST_MAC else 'bytes'


OpTraits = collections.namedtuple(
    'OpTraits', ['registered', 'stateful_rng', 'needs_env', 'amp',
                 'cost'])


class OpImpl(object):
    def __init__(self, type, compute, stateful_rng=False, needs_env=False):
        self.type = type
        self.compute = compute
        # ops that consume PRNG (dropout, *_random) — executor threads keys
        self.stateful_rng = stateful_rng
        # control-flow ops that interpret sub-blocks get the live env dict
        # as ins['__env__'] and may return {'__env_update__': [dict]}
        self.needs_env = needs_env


def register_op(type, stateful_rng=False, needs_env=False):
    def deco(fn):
        if type in _OP_REGISTRY:
            raise ValueError("op %r already registered" % type)
        _OP_REGISTRY[type] = OpImpl(type, fn, stateful_rng, needs_env)
        return fn

    return deco


def get_op_impl(type):
    impl = _OP_REGISTRY.get(type)
    if impl is None:
        raise NotImplementedError(
            "no TPU implementation registered for op %r" % type)
    _CALLED.add(type)
    return impl


def has_op(type):
    return type in _OP_REGISTRY


def op_traits(type):
    """OpTraits(registered, stateful_rng, needs_env, amp, cost) for an
    op type WITHOUT marking it as executed — the graph-opt, AMP, and
    cost-model pipelines classify every op in a block, and routing that
    through get_op_impl would make the coverage meta-test (called_ops)
    see phantom executions.  `amp` is 'white' | 'black' | 'grey' (see
    AMP_WHITE / AMP_BLACK above; grey = follow-the-inputs default);
    `cost` is 'mac' | 'bytes' (see COST_MAC)."""
    impl = _OP_REGISTRY.get(type)
    if impl is None:
        return OpTraits(False, False, False, amp_class(type),
                        cost_class(type))
    return OpTraits(True, impl.stateful_rng, impl.needs_env,
                    amp_class(type), cost_class(type))


# ---------------------------------------------------------------------------
# Static op signatures (OpProto parity, recovered by introspection).
#
# A signature dimension is *closed* when the AST walk accounted for every
# use of the corresponding parameter (`ins` / `attrs` / the return value);
# it is *open* when the function does something the walk cannot name (e.g.
# iterates ins.items(), builds slot names dynamically, returns a dict
# assembled elsewhere).  Open dimensions are simply not checkable — the
# verifier skips them instead of guessing.

OpSignature = collections.namedtuple('OpSignature', [
    'in_slots',        # frozenset: input slot names the fn can read
    'in_open',         # True -> in_slots is incomplete, don't enforce
    'out_slots',       # frozenset: output slot names the fn can return
    'out_open',        # True -> out_slots is incomplete, don't enforce
    'attr_keys',       # frozenset: every attr key the fn reads
    'required_attrs',  # frozenset: keys read unconditionally via attrs[k]
])

_OPEN_SIGNATURE = OpSignature(frozenset(), True, frozenset(), True,
                              frozenset(), frozenset())
_SIG_CACHE = {}

# dict methods whose use keeps the slot set knowable (.get with a literal
# key) vs. ones that make it open (whole-dict iteration/copy)
_OPEN_DICT_METHODS = ('items', 'values', 'keys', 'pop', 'update', 'copy',
                      'setdefault')


class _SigVisitor(ast.NodeVisitor):
    """Collect literal-keyed accesses of one dict-shaped parameter.

    Tracks whether each access is control-flow-conditional (inside
    If/IfExp/Try/loop bodies, boolop tails, or nested defs/lambdas) so
    ``attrs['k']`` counts as *required* only when it runs on every call.
    """

    def __init__(self, param):
        self.param = param
        self.keys = set()
        self.required = set()     # unconditional [k] subscripts
        self.guarded = set()      # keys seen via .get()/`in` (optional)
        self.open = False
        self._covered = set()     # id()s of Name nodes already explained
        self._cond = 0

    # -- helpers -----------------------------------------------------------
    def _is_param(self, node):
        return isinstance(node, ast.Name) and node.id == self.param

    def _const_str(self, node):
        return node.value if (isinstance(node, ast.Constant)
                              and isinstance(node.value, str)) else None

    # -- conditional-context scaffolding -----------------------------------
    def _visit_cond(self, node):
        self._cond += 1
        try:
            self.generic_visit(node)
        finally:
            self._cond -= 1

    def visit_IfExp(self, node):
        self.visit(node.test)
        self._cond += 1
        try:
            self.visit(node.body)
            self.visit(node.orelse)
        finally:
            self._cond -= 1

    def visit_If(self, node):
        self.visit(node.test)
        self._cond += 1
        try:
            for n in node.body + node.orelse:
                self.visit(n)
        finally:
            self._cond -= 1

    def visit_Try(self, node):
        self._visit_cond(node)

    def visit_While(self, node):
        self._visit_cond(node)

    def visit_For(self, node):
        self._visit_cond(node)

    def visit_BoolOp(self, node):
        self.visit(node.values[0])
        self._cond += 1
        try:
            for v in node.values[1:]:
                self.visit(v)
        finally:
            self._cond -= 1

    def visit_FunctionDef(self, node):
        self._visit_cond(node)  # inner defs may never run

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._visit_cond(node)

    # -- the accesses ------------------------------------------------------
    def visit_Subscript(self, node):
        if self._is_param(node.value):
            self._covered.add(id(node.value))
            key = self._const_str(node.slice)
            if key is None:
                self.open = True
            else:
                self.keys.add(key)
                if self._cond == 0:
                    self.required.add(key)
        self.generic_visit(node)

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute) and self._is_param(func.value):
            self._covered.add(id(func.value))
            if func.attr == 'get':
                key = (self._const_str(node.args[0])
                       if node.args else None)
                if key is None:
                    self.open = True
                else:
                    self.keys.add(key)
                    self.guarded.add(key)
            elif func.attr in _OPEN_DICT_METHODS:
                self.open = True
        elif isinstance(func, ast.Name) and func.id == 'first' and \
                any(self._is_param(a) for a in node.args):
            # ops/common.py first(ins, 'X') — the dominant idiom
            for a in node.args:
                if self._is_param(a):
                    self._covered.add(id(a))
            key = next((self._const_str(a) for a in node.args
                        if self._const_str(a) is not None), None)
            if key is None:
                self.open = True
            else:
                self.keys.add(key)
        self.generic_visit(node)

    def visit_Compare(self, node):
        # `'k' in attrs` proves the fn handles absence -> optional
        if len(node.ops) == 1 and isinstance(node.ops[0],
                                             (ast.In, ast.NotIn)) and \
                self._is_param(node.comparators[0]):
            self._covered.add(id(node.comparators[0]))
            key = self._const_str(node.left)
            if key is not None:
                self.guarded.add(key)
            else:
                self.open = True
        self.generic_visit(node)

    def visit_Name(self, node):
        if node.id == self.param and id(node) not in self._covered:
            # the param escapes (passed whole to a helper, aliased,
            # len()'d...): the walk can no longer claim completeness
            self.open = True


def _return_slots(fn_node):
    """Output slot names derivable from the function's return statements.
    Returns (slots, open)."""
    slots, open_ = set(), False

    def analyze(value):
        nonlocal open_
        if value is None or (isinstance(value, ast.Constant)
                             and value.value is None):
            return
        if isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Name) and \
                value.func.id == 'out':
            slots.add('Out')  # ops/common.py out(x) -> {'Out': [x]}
            return
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    if k.value != '__env_update__':
                        slots.add(k.value)
                else:
                    open_ = True
            return
        if isinstance(value, ast.IfExp):
            analyze(value.body)
            analyze(value.orelse)
            return
        open_ = True

    for node in ast.walk(fn_node):
        if isinstance(node, ast.Return):
            analyze(node.value)
    return slots, open_


_MODULE_FN_INDEX = {}  # filename -> [FunctionDef]


def _find_fn_node(compute):
    """The FunctionDef AST node of a compute function, via a per-module
    parse (inspect.getsource per function re-tokenizes the file each
    time — across ~30 op types that is the whole cold-verify budget)."""
    code = getattr(compute, '__code__', None)
    if code is None:
        return None
    fname = code.co_filename
    nodes = _MODULE_FN_INDEX.get(fname)
    if nodes is None:
        try:
            with open(fname) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError, ValueError):
            nodes = []
        else:
            nodes = [n for n in ast.walk(tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
        _MODULE_FN_INDEX[fname] = nodes
    want = code.co_firstlineno
    for n in nodes:
        lines = [n.lineno] + [d.lineno for d in n.decorator_list]
        if want in lines and n.name == compute.__name__:
            return n
    return None


def _introspect_signature(compute):
    fn = _find_fn_node(compute)
    if fn is None:
        try:
            src = textwrap.dedent(inspect.getsource(compute))
            tree = ast.parse(src)
        except (OSError, TypeError, SyntaxError, IndentationError):
            return _OPEN_SIGNATURE
        fn = next((n for n in tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))), None)
    if fn is None or len(fn.args.args) < 3:
        return _OPEN_SIGNATURE
    ins_param = fn.args.args[1].arg
    attrs_param = fn.args.args[2].arg

    ins_v = _SigVisitor(ins_param)
    attrs_v = _SigVisitor(attrs_param)
    for stmt in fn.body:
        ins_v.visit(stmt)
        attrs_v.visit(stmt)
    out_slots, out_open = _return_slots(fn)
    return OpSignature(
        in_slots=frozenset(ins_v.keys - {'__env__'}),
        in_open=ins_v.open,
        out_slots=frozenset(out_slots),
        out_open=out_open,
        attr_keys=frozenset(attrs_v.keys),
        required_attrs=frozenset(attrs_v.required - attrs_v.guarded),
    )


def op_signature(type):
    """OpSignature for a registered op type (None when unregistered).
    Introspected once per process and cached — the verifier calls this
    for every op of every plan build."""
    impl = _OP_REGISTRY.get(type)
    if impl is None:
        return None
    sig = _SIG_CACHE.get(type)
    if sig is None:
        sig = _introspect_signature(impl.compute)
        _SIG_CACHE[type] = sig
    return sig


def registered_ops():
    return sorted(_OP_REGISTRY)


def called_ops():
    """Op types actually fetched for execution in this process — the
    registry-coverage meta-test (tests/test_zz_op_coverage.py) diffs this
    against registered_ops() at the end of a full suite run, so a newly
    registered op with no test fails CI instead of rotting silently."""
    return set(_CALLED)
