"""Op registry.

Reference parity: paddle/framework/op_registry.h.  Each op type maps to a
single pure-jax compute function (instead of per-device kernel families —
XLA owns device lowering).  Signature:

    def compute(ctx, ins, attrs) -> {slot: [jax.Array, ...]}

where `ins` is {slot: [arrays]} and ctx is an ExecutionContext giving access
to PRNG keys and the interpreter (for ops with sub-blocks).
"""

_OP_REGISTRY = {}
_CALLED = set()  # op types fetched for execution (coverage meta-test)


class OpImpl(object):
    def __init__(self, type, compute, stateful_rng=False, needs_env=False):
        self.type = type
        self.compute = compute
        # ops that consume PRNG (dropout, *_random) — executor threads keys
        self.stateful_rng = stateful_rng
        # control-flow ops that interpret sub-blocks get the live env dict
        # as ins['__env__'] and may return {'__env_update__': [dict]}
        self.needs_env = needs_env


def register_op(type, stateful_rng=False, needs_env=False):
    def deco(fn):
        if type in _OP_REGISTRY:
            raise ValueError("op %r already registered" % type)
        _OP_REGISTRY[type] = OpImpl(type, fn, stateful_rng, needs_env)
        return fn

    return deco


def get_op_impl(type):
    impl = _OP_REGISTRY.get(type)
    if impl is None:
        raise NotImplementedError(
            "no TPU implementation registered for op %r" % type)
    _CALLED.add(type)
    return impl


def has_op(type):
    return type in _OP_REGISTRY


def op_traits(type):
    """(registered, stateful_rng, needs_env) for an op type WITHOUT
    marking it as executed — the graph-opt pipeline classifies every op
    in a block, and routing that through get_op_impl would make the
    coverage meta-test (called_ops) see phantom executions."""
    impl = _OP_REGISTRY.get(type)
    if impl is None:
        return (False, False, False)
    return (True, impl.stateful_rng, impl.needs_env)


def registered_ops():
    return sorted(_OP_REGISTRY)


def called_ops():
    """Op types actually fetched for execution in this process — the
    registry-coverage meta-test (tests/test_zz_op_coverage.py) diffs this
    against registered_ops() at the end of a full suite run, so a newly
    registered op with no test fails CI instead of rotting silently."""
    return set(_CALLED)
