"""Op registry.

Reference parity: paddle/framework/op_registry.h.  Each op type maps to a
single pure-jax compute function (instead of per-device kernel families —
XLA owns device lowering).  Signature:

    def compute(ctx, ins, attrs) -> {slot: [jax.Array, ...]}

where `ins` is {slot: [arrays]} and ctx is an ExecutionContext giving access
to PRNG keys and the interpreter (for ops with sub-blocks).
"""
import collections

_OP_REGISTRY = {}
_CALLED = set()  # op types fetched for execution (coverage meta-test)

# ---------------------------------------------------------------------------
# AMP (automatic mixed precision) op classification — consumed by the
# transpiler/amp.py cast-insertion pass and reported through op_traits().
#
# AMP_WHITE: matmul-shaped ops whose FLOPs land on the MXU — these run in
# the low precision (bf16/f16) under PADDLE_TPU_AMP; the win is ~2x matmul
# throughput plus halved activation bandwidth.
#
# AMP_BLACK: ops that must stay f32 — losses and softmaxes (dynamic
# range), normalization statistics, wide accumulations (sum/mean),
# range-sensitive elementwise math (exp/log/pow/square), metrics, the
# optimizer updates (f32 master weights), and the AMP machinery itself.
#
# Everything else is GREY: precision follows the inputs (an elementwise op
# between two bf16 values runs in bf16; one fed a f32 value stays f32).
# A newly registered op is grey by default, which is always SAFE — it can
# never force a value into low precision on its own — and
# tests/test_zz_op_coverage.py asserts every registered op lands in
# exactly one class so list rot is caught structurally.
AMP_WHITE = frozenset({
    'matmul', 'mul',
    'conv2d', 'conv2d_transpose', 'conv3d', 'conv3d_transpose',
    'sequence_conv', 'conv_shift', 'row_conv',
    'bilinear_tensor_product', 'flash_attention',
    'lstm', 'lstm_unit', 'gru', 'gru_unit',
    # fused vocab-head CE ops: dominated by the [N,D]x[D,V] matmul and
    # internally f32-safe (preferred_element_type accumulation + f32
    # softmax state), so their INPUTS lower; their loss outputs are
    # always f32 (amp.py WHITE_F32_OUTPUT_OPS)
    'fused_linear_softmax_ce', 'vocab_parallel_ce',
})

AMP_BLACK = frozenset({
    # softmax family + losses (dynamic range / reductions over logits)
    'softmax', 'sequence_softmax',
    'cross_entropy', 'softmax_with_cross_entropy',
    'sigmoid_cross_entropy_with_logits', 'square_error_cost',
    'smooth_l1', 'smooth_l1_loss', 'hinge_loss', 'huber_loss',
    'log_loss', 'margin_rank_loss', 'modified_huber_loss', 'rank_loss',
    'warpctc', 'nce', 'linear_chain_crf', 'crf_decoding',
    # normalization / statistics
    'batch_norm', 'layer_norm', 'norm', 'lrn', 'l1_norm',
    'squared_l2_norm', 'squared_l2_distance', 'cos_sim', 'clip_by_norm',
    # wide accumulations
    'sum', 'mean', 'reduce_sum', 'reduce_mean', 'reduce_prod',
    # range-sensitive elementwise math
    'exp', 'log', 'pow', 'square',
    # metrics
    'accuracy', 'auc', 'precision_recall', 'positive_negative_pair',
    'chunk_eval', 'edit_distance', 'detection_output',
    # optimizer updates apply to the f32 masters
    'sgd', 'momentum', 'adam', 'adamax', 'adagrad', 'decayed_adagrad',
    'adadelta', 'rmsprop', 'ftrl', 'proximal_gd', 'proximal_adagrad',
    # grad machinery + the AMP ops themselves
    'sparse_grad_assemble', 'check_finite_and_unscale',
    'update_loss_scale',
})


def amp_class(type):
    """'white' | 'black' | 'grey' AMP classification for an op type.
    Unregistered/unknown types are grey (the safe default: grey can
    never lower a value's precision on its own)."""
    if type in AMP_WHITE:
        return 'white'
    if type in AMP_BLACK:
        return 'black'
    return 'grey'


OpTraits = collections.namedtuple(
    'OpTraits', ['registered', 'stateful_rng', 'needs_env', 'amp'])


class OpImpl(object):
    def __init__(self, type, compute, stateful_rng=False, needs_env=False):
        self.type = type
        self.compute = compute
        # ops that consume PRNG (dropout, *_random) — executor threads keys
        self.stateful_rng = stateful_rng
        # control-flow ops that interpret sub-blocks get the live env dict
        # as ins['__env__'] and may return {'__env_update__': [dict]}
        self.needs_env = needs_env


def register_op(type, stateful_rng=False, needs_env=False):
    def deco(fn):
        if type in _OP_REGISTRY:
            raise ValueError("op %r already registered" % type)
        _OP_REGISTRY[type] = OpImpl(type, fn, stateful_rng, needs_env)
        return fn

    return deco


def get_op_impl(type):
    impl = _OP_REGISTRY.get(type)
    if impl is None:
        raise NotImplementedError(
            "no TPU implementation registered for op %r" % type)
    _CALLED.add(type)
    return impl


def has_op(type):
    return type in _OP_REGISTRY


def op_traits(type):
    """OpTraits(registered, stateful_rng, needs_env, amp) for an op type
    WITHOUT marking it as executed — the graph-opt and AMP pipelines
    classify every op in a block, and routing that through get_op_impl
    would make the coverage meta-test (called_ops) see phantom
    executions.  `amp` is 'white' | 'black' | 'grey' (see AMP_WHITE /
    AMP_BLACK above; grey = follow-the-inputs default)."""
    impl = _OP_REGISTRY.get(type)
    if impl is None:
        return OpTraits(False, False, False, amp_class(type))
    return OpTraits(True, impl.stateful_rng, impl.needs_env,
                    amp_class(type))


def registered_ops():
    return sorted(_OP_REGISTRY)


def called_ops():
    """Op types actually fetched for execution in this process — the
    registry-coverage meta-test (tests/test_zz_op_coverage.py) diffs this
    against registered_ops() at the end of a full suite run, so a newly
    registered op with no test fails CI instead of rotting silently."""
    return set(_CALLED)
