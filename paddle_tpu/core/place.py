"""Device places.

Reference parity: paddle/platform/place.h (CPUPlace / CUDAPlace).  The
TPU-native framework adds TPUPlace; every place resolves to a jax.Device.
"""
import jax


class Place(object):
    _platform = None

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def jax_device(self):
        """Resolve to a concrete LOCAL jax.Device, falling back to the
        default backend when the requested platform is absent (e.g.
        asking for TPUPlace on a CPU-only host during tests).  Local
        devices only: in a multi-process (distributed.launch) run,
        jax.devices() leads with process 0's devices, which other
        processes cannot place data on."""
        if self._platform is not None:
            try:
                devs = jax.local_devices(backend=self._platform)
            except RuntimeError:
                devs = jax.local_devices()
        else:
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    _platform = 'cpu'

    def __init__(self):
        super(CPUPlace, self).__init__(0)


class TPUPlace(Place):
    """A single TPU chip.  Parity with the reference's CUDAPlace(id)."""
    _platform = 'tpu'


# CUDAPlace is accepted as an alias so reference scripts run unchanged: on a
# TPU host it resolves to the TPU chip with the same ordinal.
class CUDAPlace(TPUPlace):
    pass


class XLAPlace(Place):
    """Whatever jax's default backend is (tpu > gpu > cpu)."""
    _platform = None


def default_place():
    platform = jax.default_backend()
    if platform == 'cpu':
        return CPUPlace()
    return XLAPlace(0)
