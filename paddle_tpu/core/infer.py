"""Build-time shape/dtype inference.

Reference parity: paddle/framework/shape_inference.h + each op's InferShape.
TPU-native twist: there is ONE source of truth — the op's jax compute
function — abstractly evaluated with jax.eval_shape.  The unknown batch
dimension (-1) is substituted with a sentinel prime and mapped back in the
result, so layers never duplicate shape logic.
"""
import jax
import jax.numpy as jnp
import numpy as np

from . import datatypes
from .registry import get_op_impl

_BATCH_SENTINEL = 509  # prime, unlikely to collide with real dims


class _InferCtx(object):
    """Stand-in ExecutionContext for abstract evaluation."""

    def __init__(self):
        self.op_index = 0
        self.block = None

    def rng(self, extra=0):
        return jax.random.PRNGKey(0)


def infer_outputs(op_type, input_specs, attrs, out_slots):
    """input_specs: {slot: [(shape, dtype) or None]}.  Returns
    {slot: [(shape, dtype)]} with -1 restored where the sentinel appears.
    """
    impl = get_op_impl(op_type)
    had_unknown = False
    ins = {}
    for slot, specs in input_specs.items():
        vals = []
        for spec in specs:
            if spec is None:
                vals.append(None)
                continue
            shape, dtype = spec
            shape2 = []
            for d in shape:
                if d == -1:
                    had_unknown = True
                    shape2.append(_BATCH_SENTINEL)
                else:
                    shape2.append(int(d))
            np_dtype = datatypes.as_numpy_dtype(dtype)
            if np_dtype == np.int64:
                np_dtype = np.int32
            elif np_dtype == np.float64:
                np_dtype = np.float32
            vals.append(jax.ShapeDtypeStruct(tuple(shape2), np_dtype))
        ins[slot] = vals

    ctx = _InferCtx()

    def f(ins_):
        return impl.compute(ctx, ins_, attrs)

    outs = jax.eval_shape(f, ins)
    result = {}
    for slot in out_slots:
        specs = []
        for o in (outs or {}).get(slot, []):
            if o is None:
                specs.append(None)
                continue
            shape = tuple(-1 if (had_unknown and d == _BATCH_SENTINEL) else d
                          for d in o.shape)
            specs.append((shape, datatypes.convert_dtype(o.dtype)))
        result[slot] = specs
    return result
