"""Build-time shape/dtype inference.

Reference parity: paddle/framework/shape_inference.h + each op's InferShape.
TPU-native twist: there is ONE source of truth — the op's jax compute
function — abstractly evaluated with jax.eval_shape.  The unknown batch
dimension (-1) is substituted with a sentinel prime and mapped back in the
result, so layers never duplicate shape logic.
"""
import jax
import jax.numpy as jnp
import numpy as np

from . import datatypes
from .registry import get_op_impl

_BATCH_SENTINEL = 509  # prime, unlikely to collide with real dims


class _InferCtx(object):
    """Stand-in ExecutionContext for abstract evaluation."""

    def __init__(self):
        self.op_index = 0
        self.block = None

    def rng(self, extra=0):
        return jax.random.PRNGKey(0)


def _encode_ins(input_specs):
    """{slot: [(shape, dtype) | None]} -> ({slot: [ShapeDtypeStruct]},
    had_unknown) with -1 dims mapped to the batch sentinel."""
    had_unknown = False
    ins = {}
    for slot, specs in input_specs.items():
        vals = []
        for spec in specs:
            if spec is None:
                vals.append(None)
                continue
            shape, dtype = spec
            shape2 = []
            for d in shape:
                if d == -1:
                    had_unknown = True
                    shape2.append(_BATCH_SENTINEL)
                else:
                    shape2.append(int(d))
            np_dtype = datatypes.as_numpy_dtype(dtype)
            if np_dtype == np.int64:
                np_dtype = np.int32
            elif np_dtype == np.float64:
                np_dtype = np.float32
            vals.append(jax.ShapeDtypeStruct(tuple(shape2), np_dtype))
        ins[slot] = vals
    return ins, had_unknown


def _decode_outs(outs, out_slots, had_unknown):
    result = {}
    for slot in out_slots:
        specs = []
        for o in (outs or {}).get(slot, []):
            if o is None or not (hasattr(o, 'shape')
                                 and hasattr(o, 'dtype')):
                # non-tensor abstract outputs (SelectedRows,
                # LoDTensorArray handles) carry no (shape, dtype)
                # verdict — report "unknown", don't fail the whole op
                specs.append(None)
                continue
            shape = tuple(-1 if (had_unknown and d == _BATCH_SENTINEL) else d
                          for d in o.shape)
            specs.append((shape, datatypes.convert_dtype(o.dtype)))
        result[slot] = specs
    return result


def infer_outputs(op_type, input_specs, attrs, out_slots):
    """input_specs: {slot: [(shape, dtype) or None]}.  Returns
    {slot: [(shape, dtype)]} with -1 restored where the sentinel appears.
    """
    impl = get_op_impl(op_type)
    ins, had_unknown = _encode_ins(input_specs)
    ctx = _InferCtx()

    def f(ins_):
        return impl.compute(ctx, ins_, attrs)

    outs = jax.eval_shape(f, ins)
    return _decode_outs(outs, out_slots, had_unknown)


# ---------------------------------------------------------------------------
# Memoized re-inference (the IR verifier's entry point).
#
# The verifier re-infers every checkable op of every plan build; one
# eval_shape is a fresh jax trace, so identical (op, input specs, attrs)
# triples — CSE'd programs, run/run_steps plan pairs, repeated builds —
# must share one trace.  The cache is process-global and bounded: entries
# key on hashable spec/attr tuples, odd attr values fall back to uncached.

_INFER_CACHE = {}
_INFER_CACHE_CAP = 4096
_FAILED = object()  # negative-cache sentinel: this triple cannot infer


class InferenceFailedError(RuntimeError):
    """Raised on a negative-cache hit: this exact (op, specs, attrs)
    triple already failed abstract evaluation once."""


class _Uncacheable(Exception):
    pass


def _hashable(v):
    if isinstance(v, np.ndarray):
        return ('nd', str(v.dtype), v.shape, v.tobytes())
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple((k, _hashable(v[k])) for k in sorted(v))
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return v
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return v.item()
    raise _Uncacheable(type(v).__name__)


def infer_outputs_cached(op_type, input_specs, attrs, out_slots):
    """infer_outputs with a process-global memo.  Raises whatever
    eval_shape raises — callers decide whether that is an error."""
    try:
        key = _cache_key(op_type, input_specs, attrs, out_slots)
    except (_Uncacheable, TypeError):
        return infer_outputs(op_type, input_specs, attrs, out_slots)
    hit = _INFER_CACHE.get(key)
    if hit is _FAILED:
        # negative cache: un-evaluable triples (e.g. SelectedRows-only
        # ops fed dense specs) would otherwise re-pay a failing jax
        # trace on every verifier run
        raise InferenceFailedError(op_type)
    if hit is not None:
        return hit
    if len(_INFER_CACHE) >= _INFER_CACHE_CAP:
        _INFER_CACHE.clear()  # simple bound; refill is cheap
    try:
        result = infer_outputs(op_type, input_specs, attrs, out_slots)
    except Exception:
        _INFER_CACHE[key] = _FAILED
        raise
    _INFER_CACHE[key] = result
    return result


# attrs that never affect the computed shapes/dtypes: pass bookkeeping
# (op_seq position stamps, role tags, AMP gating) — excluding them from
# the key lets a build-time inference (layer_helper, pre-stamp) serve
# the verifier's post-pass lookup of the same op
_NON_SEMANTIC_ATTRS = frozenset({'op_seq', 'op_role', 'amp_gate_var'})


def _cache_key(op_type, input_specs, attrs, out_slots):
    return (op_type,
            tuple((slot,
                   tuple(None if s is None else (tuple(s[0]), str(s[1]))
                         for s in specs))
                  for slot, specs in sorted(input_specs.items())),
            tuple((k, _hashable(attrs[k])) for k in sorted(attrs)
                  if k not in _NON_SEMANTIC_ATTRS),
            tuple(out_slots))


def _eval_batch(tasks):
    """Abstractly evaluate many (impl, ins, attrs) triples in ONE
    eval_shape trace — per-call pjit overhead (~2 ms) is paid once for
    the whole batch instead of once per op."""
    ctx = _InferCtx()

    def f(all_ins):
        return [impl.compute(ctx, ins_, attrs)
                for (impl, _ins, attrs), ins_ in zip(tasks, all_ins)]

    return jax.eval_shape(f, [ins for _impl, ins, _attrs in tasks])


def prime_infer_cache(requests):
    """Warm the memo for many (op_type, input_specs, attrs, out_slots)
    requests at once — the IR verifier's cold-start path.  Uncached
    requests are abstractly evaluated in one batched trace; a failing
    batch bisects until the individually un-evaluable requests are
    isolated and negative-cached.  Requests that cannot be keyed are
    skipped (the per-op path handles them uncached)."""
    pending = []  # (key, impl, ins, attrs, out_slots, had_unknown)
    seen = set()
    for op_type, input_specs, attrs, out_slots in requests:
        try:
            key = _cache_key(op_type, input_specs, attrs, out_slots)
        except (_Uncacheable, TypeError):
            continue
        if key in _INFER_CACHE or key in seen:
            continue
        seen.add(key)
        try:
            impl = get_op_impl(op_type)
            ins, had_unknown = _encode_ins(input_specs)
        except Exception:
            _INFER_CACHE[key] = _FAILED
            continue
        pending.append((key, impl, ins, attrs, tuple(out_slots),
                        had_unknown))

    def solve(chunk):
        if not chunk:
            return
        try:
            outs = _eval_batch([(impl, ins, attrs)
                                for _k, impl, ins, attrs, _o, _u
                                in chunk])
        except Exception:
            if len(chunk) == 1:
                _INFER_CACHE[chunk[0][0]] = _FAILED
                return
            mid = len(chunk) // 2
            solve(chunk[:mid])
            solve(chunk[mid:])
            return
        for (key, _impl, _ins, _attrs, out_slots, had_unknown), o in \
                zip(chunk, outs):
            try:
                _INFER_CACHE[key] = _decode_outs(o, out_slots,
                                                 had_unknown)
            except Exception:
                # non-tensor abstract outputs (e.g. SelectedRows) have
                # no (shape, dtype) reading — no verdict for this op
                _INFER_CACHE[key] = _FAILED

    if len(_INFER_CACHE) + len(pending) >= _INFER_CACHE_CAP:
        _INFER_CACHE.clear()
    solve(pending)
