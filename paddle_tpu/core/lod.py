"""Host-side ragged tensor container.

Reference parity: paddle/framework/lod_tensor.{h,cc} (offset-based LoD).
TPU-native representation: sequences are padded to a rectangle and carried
with an int32 lengths vector — static shapes for XLA; on-device sequence ops
use masks/segment ids (paddle_tpu/ops/sequence.py).  This class is the host
bridge: it accepts the reference's recursive_sequence_lengths / offset LoD
and produces (padded, lengths).
"""
import numpy as np

__all__ = ['LoDTensor', 'create_lod_tensor']


def _offsets_to_lengths(offsets):
    return [int(offsets[i + 1]) - int(offsets[i])
            for i in range(len(offsets) - 1)]


class LoDTensor(object):
    def __init__(self, data=None, recursive_seq_lens=None):
        """`data` is either a dense np array, or a list of per-sequence
        arrays/lists (ragged).  `recursive_seq_lens` follows the fluid
        convention: a list of lod levels, each a list of lengths."""
        self._lengths = None
        self._padded = None
        if recursive_seq_lens:
            # only the innermost level determines padding; outer levels are
            # kept for API parity.
            self._rec_lens = [list(l) for l in recursive_seq_lens]
            self._lengths = list(self._rec_lens[-1])
            total = sum(self._lengths)
            if isinstance(data, (list, tuple)) and len(data) and \
                    not np.isscalar(data[0]) and len(data) != total and \
                    sum(len(s) for s in data) == total:
                # list of per-sequence lists (ragged or equal-length):
                # concatenate to flat [sum(lengths), ...] form
                data = np.concatenate([np.asarray(s) for s in data], axis=0)
            self._flat = np.asarray(data)
        else:
            self._rec_lens = []
            if isinstance(data, (list, tuple)) and len(data) and \
                    not np.isscalar(data[0]) and \
                    _is_ragged_list(data):
                seqs = [np.asarray(s) for s in data]
                self._lengths = [len(s) for s in seqs]
                self._flat = (np.concatenate(seqs, axis=0)
                              if len(seqs) else np.zeros((0,)))
                self._rec_lens = [list(self._lengths)]
            else:
                self._padded = np.asarray(data)

    # -- fluid parity ------------------------------------------------------
    def set(self, data, place=None):
        self._padded = np.asarray(data)
        return self

    def set_recursive_sequence_lengths(self, rec_lens):
        self._rec_lens = [list(l) for l in rec_lens]
        self._lengths = list(self._rec_lens[-1])
        if self._padded is not None and self._lengths is not None and \
                self._padded.ndim >= 1 and \
                self._padded.shape[0] == sum(self._lengths):
            self._flat = self._padded
            self._padded = None
        return self

    def recursive_sequence_lengths(self):
        return self._rec_lens

    def set_lod(self, lod):
        """Offset-based LoD (old API)."""
        return self.set_recursive_sequence_lengths(
            [_offsets_to_lengths(l) for l in lod])

    def lod(self):
        out = []
        for lens in self._rec_lens:
            off = [0]
            for l in lens:
                off.append(off[-1] + l)
            out.append(off)
        return out

    # -- TPU bridge --------------------------------------------------------
    def is_ragged(self):
        return self._lengths is not None

    def lengths(self):
        if self._lengths is None:
            n = self._padded.shape[0] if self._padded.ndim else 0
            return [1] * n
        return self._lengths

    def padded(self, pad_value=0):
        if self._padded is not None:
            return self._padded
        lens = self._lengths
        batch = len(lens)
        maxlen = max(lens) if lens else 0
        flat = self._flat
        trailing = flat.shape[1:]
        out = np.full((batch, maxlen) + trailing, pad_value,
                      dtype=flat.dtype)
        pos = 0
        for i, l in enumerate(lens):
            out[i, :l] = flat[pos:pos + l]
            pos += l
        return out

    def flat(self):
        if self._padded is not None and self._lengths is None:
            return self._padded
        if getattr(self, '_flat', None) is not None:
            return self._flat
        lens = self._lengths
        return np.concatenate(
            [self.padded()[i, :l] for i, l in enumerate(lens)], axis=0)

    def __array__(self, dtype=None):
        arr = self.padded() if self.is_ragged() else self._padded
        return arr.astype(dtype) if dtype is not None else arr

    def shape(self):
        return tuple(np.asarray(self).shape)

    def __repr__(self):
        return "LoDTensor(shape=%s, rec_lens=%s)" % (
            np.asarray(self).shape, self._rec_lens)


def _is_ragged_list(data):
    try:
        first = len(data[0])
    except TypeError:
        return False
    return any(len(s) != first for s in data)


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Parity with fluid.create_lod_tensor."""
    return LoDTensor(data, recursive_seq_lens)
