"""Gradient and error clipping.

Reference parity: python/paddle/v2/fluid/clip.py (GradientClipByValue,
ByNorm, ByGlobalNorm, ErrorClipByValue).
"""
import functools

from .core.program import grad_var_name

__all__ = [
    'BaseErrorClipAttr', 'ErrorClipByValue', 'error_clip_callback',
    'BaseGradientClipAttr', 'NullGradientClipAttr', 'GradientClipByValue',
    'GradientClipByNorm', 'GradientClipByGlobalNorm',
    'append_gradient_clip_ops', 'set_gradient_clip',
]


class BaseErrorClipAttr(object):
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        min = float(min) if min is not None else -max
        self.max = max
        self.min = min

    def append_clip_op(self, block, grad_name):
        block.append_op(
            type='clip',
            inputs={'X': [grad_name]},
            outputs={'Out': [grad_name]},
            attrs={'min': self.min, 'max': self.max})


def error_clip_callback(block, context):
    for var_name, var in list(block.vars.items()):
        error_clip = getattr(var, 'error_clip', None)
        if error_clip is not None:
            error_clip.append_clip_op(block, grad_var_name(var_name))


class BaseGradientClipAttr(object):
    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        min = float(min) if min is not None else -max
        self.max = max
        self.min = min

    def create_operators(self, param, grad):
        from .layers import ops as layer_ops
        new_grad = layer_ops.clip(x=grad, min=self.min, max=self.max)
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def create_operators(self, param, grad):
        from .layers import ops as layer_ops
        new_grad = layer_ops.clip_by_norm(x=grad, max_norm=self.clip_norm)
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name
        self.context = None

    def process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        from .layers import nn as layer_nn
        sq = layer_nn.reduce_sum(
            input=_square(grad))
        context[self.group_name].append(sq)
        self.context = context

    def create_operators(self, param, grad):
        from .layers import nn as layer_nn
        from .layers import ops as layer_ops
        from .layers import tensor as layer_tensor
        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self.context:
            group_norm = layer_tensor.sums(self.context[self.group_name])
            group_norm = layer_ops.sqrt(x=group_norm)
            clip_var = layer_tensor.fill_constant(
                shape=[1], dtype='float32', value=self.clip_norm)
            scale = layer_ops.elementwise_div(
                x=clip_var,
                y=layer_ops.elementwise_max(x=clip_var, y=group_norm))
            self.context[group_scale_name] = scale
        new_grad = layer_ops.elementwise_mul(
            x=grad, y=self.context[group_scale_name])
        return param, new_grad


def _square(v):
    from .layers import ops as layer_ops
    return layer_ops.square(x=v)


_gradient_clip_attr = None


def set_gradient_clip(clip, param_list=None, program=None):
    global _gradient_clip_attr
    if param_list:
        for p in param_list:
            p.gradient_clip_attr = clip
    else:
        _gradient_clip_attr = clip


def current_gradient_clip():
    """The program-wide clip set via set_gradient_clip (or None)."""
    return _gradient_clip_attr


def append_gradient_clip_ops(param_grad):
    context = {}
    create_op_callbacks = []
    for p, g in param_grad:
        clip_attr = getattr(p, 'gradient_clip_attr', None) or \
            _gradient_clip_attr or NullGradientClipAttr()
        clip_attr.process_context(context=context, param=p, grad=g)
        create_op_callbacks.append(
            functools.partial(clip_attr.create_operators, param=p, grad=g))
    return [each_callback() for each_callback in create_op_callbacks]
