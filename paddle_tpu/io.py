"""Model save/load and inference-model serialization.

Reference parity: python/paddle/v2/fluid/io.py.  Variables serialize as .npy
files (one per var, like the reference's one-file-per-var layout); the
inference program serializes as JSON (core/program.py), playing the role of
the reference's ProgramDesc protobuf `__model__` file.
"""
import os

import numpy as np

from .core.program import Parameter, Program, Variable, default_main_program
from .core.scope import global_scope

__all__ = [
    'save_vars', 'save_params', 'save_persistables', 'load_vars',
    'load_params', 'load_persistables', 'save_inference_model',
    'load_inference_model', 'get_inference_program',
    'get_parameter_value', 'get_parameter_value_by_name', 'is_parameter',
    'is_persistable', 'save_checkpoint', 'load_checkpoint',
]


def is_parameter(var):
    return isinstance(var, Parameter)


def is_persistable(var):
    return var.persistable


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None):
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    for var in vars:
        name = var.name if isinstance(var, Variable) else var
        value = scope.find_var(name)
        if value is None:
            continue
        np.save(os.path.join(dirname, _safe(name) + '.npy'),
                np.asarray(value))


def save_params(executor, dirname, main_program=None):
    save_vars(executor, dirname, main_program, vars=None,
              predicate=is_parameter)


def save_persistables(executor, dirname, main_program=None):
    save_vars(executor, dirname, main_program, vars=None,
              predicate=is_persistable)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None):
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    for var in vars:
        name = var.name if isinstance(var, Variable) else var
        path = os.path.join(dirname, _safe(name) + '.npy')
        if os.path.exists(path):
            scope.set(name, np.load(path))


def load_params(executor, dirname, main_program=None):
    load_vars(executor, dirname, main_program, predicate=is_parameter)


def load_persistables(executor, dirname, main_program=None):
    load_vars(executor, dirname, main_program, predicate=is_persistable)


def load_persistables_if_exist(executor, dirname, main_program=None):
    if os.path.isdir(dirname):
        load_persistables(executor, dirname, main_program)


def _safe(name):
    return name.replace('/', '%2F')


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program.prune(targets=target_vars)
    return pruned.inference_optimize()


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None):
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    if main_program is None:
        main_program = default_main_program()
    os.makedirs(dirname, exist_ok=True)
    pruned = main_program.prune(targets=target_vars,
                                feeds=feeded_var_names)
    inference_program = pruned.inference_optimize()
    fetch_var_names = [v.name for v in target_vars]
    meta = dict(program=inference_program.to_dict(),
                feed_var_names=list(feeded_var_names),
                fetch_var_names=fetch_var_names)
    import json
    with open(os.path.join(dirname, '__model__'), 'w') as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, inference_program)
    return inference_program


def load_inference_model(dirname, executor):
    import json
    with open(os.path.join(dirname, '__model__')) as f:
        meta = json.load(f)
    program = Program.from_dict(meta['program'])
    load_persistables(executor, dirname, program)
    fetch_vars = [program.global_block().var(n)
                  for n in meta['fetch_var_names']]
    return program, meta['feed_var_names'], fetch_vars


def get_parameter_value(para, executor=None):
    assert is_parameter(para)
    return global_scope().get_numpy(para.name)


def get_parameter_value_by_name(name, executor=None, program=None):
    if program is None:
        program = default_main_program()
    var = program.global_block().var(name)
    return get_parameter_value(var, executor)


# -- checkpoint/resume (SURVEY.md A2) ------------------------------------
def save_checkpoint(executor, dirname, main_program=None, step=None):
    """Full training state: every persistable (params + optimizer moments +
    bn stats + counters)."""
    save_persistables(executor, dirname, main_program)
    if step is not None:
        with open(os.path.join(dirname, 'STEP'), 'w') as f:
            f.write(str(int(step)))


def load_checkpoint(executor, dirname, main_program=None):
    load_persistables(executor, dirname, main_program)
    step_file = os.path.join(dirname, 'STEP')
    if os.path.exists(step_file):
        with open(step_file) as f:
            return int(f.read().strip())
    return None
