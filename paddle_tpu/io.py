"""Model save/load and inference-model serialization.

Reference parity: python/paddle/v2/fluid/io.py.  Variables serialize as .npy
files (one per var, like the reference's one-file-per-var layout); the
inference program serializes as JSON (core/program.py), playing the role of
the reference's ProgramDesc protobuf `__model__` file.

Sharding-aware checkpointing (reference io.py:191 save_persistables +
the pserver owning param shards): a var whose scope value is a jax.Array
with a non-replicated NamedSharding is saved as one file PER UNIQUE SHARD
(each host writes only its addressable shards — no host-gather of the
full tensor), with the PartitionSpec recorded in `__manifest__.json`.
Loading under a live mesh_guard reassembles the array directly onto the
mesh via jax.make_array_from_callback with the saved spec; loading with
no mesh yields the assembled numpy array.  The manifest also records
shape/dtype for every var, checked at load time so restoring into a
changed program fails loudly instead of corrupting the scope.
"""
import json
import os

import numpy as np

from .core.datatypes import as_numpy_dtype
from .core.program import Parameter, Program, Variable, default_main_program
from .core.scope import global_scope

_MANIFEST = '__manifest__.json'
_FORMAT_VERSION = 1

__all__ = [
    'save_vars', 'save_params', 'save_persistables', 'load_vars',
    'load_params', 'load_persistables', 'save_inference_model',
    'load_inference_model', 'get_inference_program',
    'get_parameter_value', 'get_parameter_value_by_name', 'is_parameter',
    'is_persistable', 'save_checkpoint', 'load_checkpoint',
]


def is_parameter(var):
    return isinstance(var, Parameter)


def is_persistable(var):
    return var.persistable


def _sharding_of(value):
    """(PartitionSpec-as-list, mesh) if value is a mesh-sharded jax.Array,
    else (None, None)."""
    import jax
    from jax.sharding import NamedSharding
    if not isinstance(value, jax.Array):
        return None, None
    sh = getattr(value, 'sharding', None)
    if not isinstance(sh, NamedSharding) or sh.is_fully_replicated:
        return None, None
    spec = [list(s) if isinstance(s, tuple) else s for s in sh.spec]
    return spec, sh.mesh


def _save_sharded(dirname, name, value):
    """One .npy per unique shard (dedup replicated copies by index);
    returns the manifest shard records.  Indices are normalized to
    concrete (start, stop) bounds — jax yields slice(None) for unsharded
    dims — so the load-time lookup matches exactly."""
    seen = {}
    shape = value.shape
    for shard in value.addressable_shards:
        idx = tuple((sl.start if sl.start is not None else 0,
                     sl.stop if sl.stop is not None else shape[d])
                    for d, sl in enumerate(shard.index))
        if idx in seen:
            continue
        k = len(seen)
        np.save(os.path.join(dirname, '%s.shard%d.npy' % (_safe(name), k)),
                np.asarray(shard.data))
        seen[idx] = k
    return [{'index': [list(p) for p in idx], 'file': k}
            for idx, k in seen.items()]


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None):
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    manifest = _read_manifest(dirname) or {
        'format_version': _FORMAT_VERSION, 'vars': {}}
    for var in vars:
        name = var.name if isinstance(var, Variable) else var
        value = scope.find_var(name)
        if value is None:
            continue
        rec = {'shape': [int(d) for d in np.shape(value)],
               'dtype': str(np.asarray(value).dtype
                            if not hasattr(value, 'dtype')
                            else value.dtype)}
        spec, _mesh = _sharding_of(value)
        if spec is not None:
            rec['spec'] = spec
            rec['shards'] = _save_sharded(dirname, name, value)
        else:
            np.save(os.path.join(dirname, _safe(name) + '.npy'),
                    np.asarray(value))
        manifest['vars'][name] = rec
    with open(os.path.join(dirname, _MANIFEST), 'w') as f:
        json.dump(manifest, f)


def _read_manifest(dirname):
    path = os.path.join(dirname, _MANIFEST)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        m = json.load(f)
    if m.get('format_version', 0) > _FORMAT_VERSION:
        raise ValueError(
            "checkpoint %s was written by a newer format (version %s > %s)"
            % (dirname, m.get('format_version'), _FORMAT_VERSION))
    return m


def save_params(executor, dirname, main_program=None):
    save_vars(executor, dirname, main_program, vars=None,
              predicate=is_parameter)


def save_persistables(executor, dirname, main_program=None):
    save_vars(executor, dirname, main_program, vars=None,
              predicate=is_persistable)


def _check_against_program(name, var, shape, dtype):
    """Fail loudly when a checkpoint value disagrees with the program's
    declaration (Variable shape may use -1/None for the batch dim)."""
    if not isinstance(var, Variable):
        return
    decl = getattr(var, 'shape', None)
    if decl:
        decl = tuple(int(d) for d in decl)
        got = tuple(shape)
        ok = len(decl) == len(got) and all(
            d in (-1, 0) or d == g for d, g in zip(decl, got))
        if not ok:
            raise ValueError(
                "checkpoint var '%s' has shape %s but the program declares "
                "%s — the model changed since this checkpoint was saved" %
                (name, got, decl))
    vdt = getattr(var, 'dtype', None)
    if vdt is not None:
        want = np.dtype(as_numpy_dtype(vdt))
        if np.dtype(dtype) != want:
            raise ValueError(
                "checkpoint var '%s' has dtype %s but the program declares "
                "%s" % (name, dtype, want))


def _load_sharded(dirname, name, rec):
    """Reassemble a sharded var.  Under a live mesh_guard the result is
    built directly onto the mesh with the saved PartitionSpec (each host
    reads only the shards it needs); otherwise the full numpy array."""
    shape = tuple(rec['shape'])
    dtype = np.dtype(rec['dtype'])
    shard_files = {
        tuple(tuple(p) for p in s['index']):
            os.path.join(dirname, '%s.shard%d.npy' % (_safe(name),
                                                      s['file']))
        for s in rec['shards']}

    def piece(index):
        idx = tuple((sl.start if sl.start is not None else 0,
                     sl.stop if sl.stop is not None else shape[d])
                    for d, sl in enumerate(index))
        if idx in shard_files:
            return _np_load(shard_files[idx], dtype)
        # requested block differs from the saved tiling (different mesh
        # size): assemble the full array once and slice
        return _assemble(shape, dtype, shard_files)[index]

    from .parallel import api
    mesh = api.current_mesh()
    spec = rec.get('spec')
    if mesh is not None and spec is not None and all(
            a in mesh.axis_names for part in spec if part
            for a in (part if isinstance(part, list) else [part])):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        parts = [tuple(p) if isinstance(p, list) else p for p in spec]
        sharding = NamedSharding(mesh, PartitionSpec(*parts))
        return jax.make_array_from_callback(shape, sharding, piece)
    return _assemble(shape, dtype, shard_files)


def _np_load(path, dtype):
    """np.load with an ml_dtypes repair: numpy serializes bfloat16 as a
    raw void dtype (|V2), so reinterpret the buffer as the manifest's
    dtype when they disagree."""
    arr = np.load(path)
    dtype = np.dtype(dtype)
    if arr.dtype != dtype and arr.dtype.itemsize == dtype.itemsize:
        arr = arr.view(dtype)
    return arr


def _assemble(shape, dtype, shard_files):
    full = np.empty(shape, dtype=dtype)
    for idx, path in shard_files.items():
        sl = tuple(slice(a, b) for a, b in idx)
        full[sl] = _np_load(path, dtype)
    return full


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None):
    """Returns the number of vars actually restored (a var absent from
    the directory is skipped — partial checkpoints are legal for
    fine-tuning — but callers like load_checkpoint can detect a total
    miss, e.g. a program whose auto-generated names don't line up)."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    manifest = _read_manifest(dirname)
    records = manifest['vars'] if manifest else {}
    loaded = 0
    for var in vars:
        name = var.name if isinstance(var, Variable) else var
        rec = records.get(name)
        if rec is not None and rec.get('shards'):
            value = _load_sharded(dirname, name, rec)
        else:
            path = os.path.join(dirname, _safe(name) + '.npy')
            if not os.path.exists(path):
                continue
            value = (_np_load(path, rec['dtype']) if rec is not None
                     else np.load(path))
        if rec is not None:
            _check_against_program(name, var, rec['shape'], rec['dtype'])
        scope.set(name, value)
        loaded += 1
    return loaded


def load_params(executor, dirname, main_program=None):
    load_vars(executor, dirname, main_program, predicate=is_parameter)


def load_persistables(executor, dirname, main_program=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_persistable)


def load_persistables_if_exist(executor, dirname, main_program=None):
    if os.path.isdir(dirname):
        load_persistables(executor, dirname, main_program)


def _safe(name):
    return name.replace('/', '%2F')


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program.prune(targets=target_vars)
    return pruned.inference_optimize()


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None):
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    if main_program is None:
        main_program = default_main_program()
    os.makedirs(dirname, exist_ok=True)
    pruned = main_program.prune(targets=target_vars,
                                feeds=feeded_var_names)
    inference_program = pruned.inference_optimize()
    fetch_var_names = [v.name for v in target_vars]
    meta = dict(program=inference_program.to_dict(),
                feed_var_names=list(feeded_var_names),
                fetch_var_names=fetch_var_names)
    import json
    with open(os.path.join(dirname, '__model__'), 'w') as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, inference_program)
    return inference_program


def load_inference_model(dirname, executor):
    import json
    with open(os.path.join(dirname, '__model__')) as f:
        meta = json.load(f)
    program = Program.from_dict(meta['program'])
    load_persistables(executor, dirname, program)
    fetch_vars = [program.global_block().var(n)
                  for n in meta['fetch_var_names']]
    return program, meta['feed_var_names'], fetch_vars


def get_parameter_value(para, executor=None):
    assert is_parameter(para)
    return global_scope().get_numpy(para.name)


def get_parameter_value_by_name(name, executor=None, program=None):
    if program is None:
        program = default_main_program()
    var = program.global_block().var(name)
    return get_parameter_value(var, executor)


# -- checkpoint/resume (SURVEY.md A2) ------------------------------------
def save_checkpoint(executor, dirname, main_program=None, step=None):
    """Full training state: every persistable (params + optimizer moments +
    bn stats + counters)."""
    save_persistables(executor, dirname, main_program)
    if step is not None:
        with open(os.path.join(dirname, 'STEP'), 'w') as f:
            f.write(str(int(step)))


def load_checkpoint(executor, dirname, main_program=None):
    n = load_persistables(executor, dirname, main_program)
    if n == 0:
        raise ValueError(
            "checkpoint %s restored nothing — no persistable var of the "
            "program matches a saved name (was the program rebuilt with "
            "different auto-generated names? build it under "
            "reset_unique_name_guard() for stable names)" % dirname)
    step_file = os.path.join(dirname, 'STEP')
    if os.path.exists(step_file):
        with open(step_file) as f:
            return int(f.read().strip())
    return None
