"""Model save/load and inference-model serialization.

Reference parity: python/paddle/v2/fluid/io.py.  Variables serialize as .npy
files (one per var, like the reference's one-file-per-var layout); the
inference program serializes as JSON (core/program.py), playing the role of
the reference's ProgramDesc protobuf `__model__` file.

Sharding-aware checkpointing (reference io.py:191 save_persistables +
the pserver owning param shards): a var whose scope value is a jax.Array
with a non-replicated NamedSharding is saved as one file PER UNIQUE SHARD
(each host writes only its addressable shards — no host-gather of the
full tensor), with the PartitionSpec recorded in `__manifest__.json`.
Loading under a live mesh_guard reassembles the array directly onto the
mesh via jax.make_array_from_callback with the saved spec; loading with
no mesh yields the assembled numpy array.  The manifest also records
shape/dtype for every var, checked at load time so restoring into a
changed program fails loudly instead of corrupting the scope.
"""
import json
import os
import re
import time

import numpy as np

from .core.datatypes import as_numpy_dtype
from .core.program import Parameter, Program, Variable, default_main_program
from .core.scope import global_scope

_MANIFEST = '__manifest__.json'
# v2: shard records carry index-derived filenames (strings, not counters)
# and multi-host saves write per-process __manifest__.p<K>.json files
# v3: data filenames carry the save generation (``w.shard.g5.0_4x0_8.npy``,
# ``w.g5.npy``) so a crash between the data writes and the manifest write
# can never tear an older checkpoint's files in place; generations older
# than the newest two are garbage-collected after the manifest lands
_FORMAT_VERSION = 3

__all__ = [
    'save_vars', 'save_params', 'save_persistables', 'load_vars',
    'load_params', 'load_persistables', 'save_inference_model',
    'load_inference_model', 'get_inference_program',
    'get_parameter_value', 'get_parameter_value_by_name', 'is_parameter',
    'is_persistable', 'save_checkpoint', 'load_checkpoint',
    'rollback_checkpoint', 'bucket_artifacts', 'resolve_version_dir',
    'write_rollback_json', 'read_rollback_json', 'gc_versions',
]


def is_parameter(var):
    return isinstance(var, Parameter)


def is_persistable(var):
    return var.persistable


def _sharding_of(value):
    """(PartitionSpec-as-list, mesh) if value is a mesh-sharded jax.Array,
    else (None, None)."""
    import jax
    from jax.sharding import NamedSharding
    if not isinstance(value, jax.Array):
        return None, None
    sh = getattr(value, 'sharding', None)
    if not isinstance(sh, NamedSharding) or sh.is_fully_replicated:
        return None, None
    spec = [list(s) if isinstance(s, tuple) else s for s in sh.spec]
    return spec, sh.mesh


def _shard_filename(name, idx, gen=None):
    """Deterministic shard filename derived from the save generation and
    the global index bounds (``v.shard.g5.0_4x8_16.npy`` = generation 5,
    rows [0,4) × cols [8,16)): concurrent hosts writing their own shards
    of the same var never collide, replicas of one block within a
    generation overwrite in place (benign — identical content, atomic
    rename), and a NEWER save never touches an older generation's files,
    so a crash before the manifest write leaves the previous checkpoint
    fully intact."""
    span = 'x'.join('%d_%d' % (a, b) for a, b in idx)
    g = '' if gen is None else 'g%d.' % gen
    return '%s.shard.%s%s.npy' % (_safe(name), g, span or 'scalar')


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def _atomic_save(path, arr):
    """np.save via tmp+rename so a concurrent reader — or a replica of
    the same block written by another host at the same moment — never
    sees a torn .npy.  The tmp name carries (process_index, pid): pid
    alone is not unique across hosts on a shared filesystem."""
    tmp = '%s.tmp.p%d.%d' % (path, _process_index(), os.getpid())
    with open(tmp, 'wb') as f:
        np.save(f, np.asarray(arr))
    os.replace(tmp, path)


def _blocks_overlap(idx, jdx):
    """True when two (start, stop)-bound blocks intersect in every dim —
    the single overlap predicate shared by the manifest merge and the
    _assemble disjointness check (they must agree: a block the merge
    keeps as non-superseded must not collide in _assemble)."""
    return all(a < d and c < b for (a, b), (c, d) in zip(idx, jdx))


def _save_sharded(dirname, name, value, gen=None):
    """One .npy per unique addressable shard (dedup replicated copies by
    index); returns the manifest shard records.  Indices are normalized
    to concrete (start, stop) bounds — jax yields slice(None) for
    unsharded dims — so the load-time lookup matches exactly.  Only
    addressable shards are written: on multi-host each host contributes
    its own blocks and its own manifest (see _write_manifest)."""
    seen = set()
    shape = value.shape
    records = []
    for shard in value.addressable_shards:
        idx = tuple((sl.start if sl.start is not None else 0,
                     sl.stop if sl.stop is not None else shape[d])
                    for d, sl in enumerate(shard.index))
        if idx in seen:
            continue
        seen.add(idx)
        fname = _shard_filename(name, idx, gen)
        _atomic_save(os.path.join(dirname, fname), shard.data)
        records.append({'index': [list(p) for p in idx], 'file': fname})
    return records


def _merge_var_record(old, new):
    """Merge two manifest records for the same var.

    Records carry a save-generation counter (``gen``): differing gens
    resolve wholesale to the higher one, so a torn re-save — host 0
    wrote generation N, host 1 crashed still holding generation N-1
    blocks under the SAME filenames/tiling — drops the stale record and
    fails loudly in _assemble's coverage check rather than silently
    stitching two generations.  Equal gens (hosts of one save, or
    records predating the counter) union shard lists when
    shape/dtype/spec agree — old blocks overlapping any new block are
    superseded (a re-tiling) — and resolve to ``new`` wholesale when the
    metadata differs."""
    if old is None:
        return new
    og, ng = old.get('gen'), new.get('gen')
    if og is not None and ng is not None and og != ng:
        return new if ng > og else old
    if 'shards' not in old or 'shards' not in new:
        return new
    if any(old.get(k) != new.get(k) for k in ('shape', 'dtype', 'spec')):
        return new
    new_indices = [tuple(tuple(p) for p in s['index'])
                   for s in new['shards']]

    def superseded(jdx):
        return any(jdx != idx and _blocks_overlap(idx, jdx)
                   for idx in new_indices)

    by_index = {}
    for s in old['shards']:
        jdx = tuple(tuple(p) for p in s['index'])
        if not superseded(jdx):
            by_index[jdx] = s
    for s, idx in zip(new['shards'], new_indices):
        by_index[idx] = s
    merged = dict(new)
    merged['shards'] = list(by_index.values())
    return merged


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, generation=None, scope=None):
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    os.makedirs(dirname, exist_ok=True)
    scope = scope or global_scope()
    # Seed var records from THIS process's previous manifest only —
    # copying siblings' shard records into our manifest would let a torn
    # later checkpoint (another host crashing mid-save) pass the
    # load-time completeness check on our stale copy of its records.
    manifest = _read_manifest(dirname, own_only=True) or {'vars': {}}
    # re-stamp: a manifest seeded from an older-format dir now carries
    # v3 records — a v2 reader must hit the format gate, not silently
    # fall back to the stale legacy files v3 saves never update
    manifest['format_version'] = _FORMAT_VERSION
    if generation is None:
        # Save generation: one past the newest in the WHOLE directory
        # (all manifests — a process's own history alone diverges when
        # the host count changes between runs, and a stale higher-gen
        # sibling record would then shadow this save at load).  Hosts of
        # one synchronized save read the same history and agree.  On
        # multi-host, UNsynchronized saves can race this read (a host
        # arriving after a sibling finished seeds gen+1 and the load
        # fails LOUDLY as incomplete): pass `generation` — or use
        # save_checkpoint(step=...), whose step is the race-free
        # logical clock.
        try:
            import jax
            if jax.process_count() > 1:
                import warnings
                warnings.warn(
                    "multi-host save_vars without generation=: hosts "
                    "must save in lockstep or the manifest merge may "
                    "reject the checkpoint; prefer "
                    "save_checkpoint(step=...)")
        except Exception:
            pass
        merged = _read_manifest(dirname)
        recs = merged['vars'].values() if merged else []
        generation = 1 + max([r.get('gen', 0) for r in recs] + [0])
    gen = int(generation)
    for var in vars:
        name = var.name if isinstance(var, Variable) else var
        value = scope.find_var(name)
        if value is None:
            continue
        rec = {'shape': [int(d) for d in np.shape(value)],
               'dtype': str(np.asarray(value).dtype
                            if not hasattr(value, 'dtype')
                            else value.dtype),
               'gen': gen}
        spec, _mesh = _sharding_of(value)
        if spec is not None:
            rec['spec'] = spec
            # the record replaces this process's previous one wholesale:
            # the current addressable set IS this host's complete view,
            # and unioning with stale own records would let an old block
            # survive a shard-ownership change (mixing generations)
            rec['shards'] = _save_sharded(dirname, name, value, gen)
        else:
            # replicated vars: every host writes the same generation file
            # with identical content; atomicity makes the race benign
            fname = '%s.g%d.npy' % (_safe(name), gen)
            rec['file'] = fname
            _atomic_save(os.path.join(dirname, fname), value)
        manifest['vars'][name] = rec
    _write_manifest(dirname, manifest)
    _gc_stale_generations(
        dirname,
        [var.name if isinstance(var, Variable) else var for var in vars],
        floor_gen=gen)


def _referenced_generations(dirname):
    """Set of save generations referenced by ANY manifest in the
    directory — live per-process manifests and their ``.prev``
    archives.  GC never deletes a file belonging to one of these, so a
    lagging sibling's live checkpoint and the archived rollback stay
    loadable regardless of how generation numbers are spaced."""
    import glob
    gens = set()
    esc = glob.escape(dirname)
    paths = (glob.glob(os.path.join(esc, '__manifest__*.json')) +
             glob.glob(os.path.join(esc, '__manifest__*.json.prev')))
    for path in paths:
        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, ValueError):
            continue
        for rec in m.get('vars', {}).values():
            g = rec.get('gen')
            if g is not None:
                gens.add(int(g))
    return gens


def _gc_stale_generations(dirname, names, floor_gen):
    """Delete a var's generation-suffixed data files whose generation is
    (a) below ``floor_gen - 1`` — the save that just completed is
    ``floor_gen``; gens at or above it may belong to a synchronized
    sibling still mid-write, and gen ``floor_gen - 1`` is spared too so
    a sibling lagging one full checkpoint behind is never swept — and
    (b) referenced by no manifest in the directory (live or ``.prev``
    archive, see _referenced_generations).  This sweeps torn generations
    (data files whose save crashed before its manifest) without ever
    widowing the archived rollback checkpoint or a lagging sibling's
    files.  Runs AFTER the manifest write, so a crash-interrupted sweep
    only leaves unreferenced files behind — restartable.  Legacy
    un-suffixed files are never touched.  One pass over the directory:
    each filename is parsed once, matched against the saved-var set, and
    deleted iff its generation is both below the floor and
    unreferenced."""
    import re
    try:
        entries = os.listdir(dirname)
    except OSError:
        return
    keep_gens = _referenced_generations(dirname)
    # non-greedy name + backtracking splits the gen suffix correctly
    # even for var names that themselves contain dots
    pat = re.compile(
        r'^(.+?)\.(?:shard\.g(\d+)\.(?:[0-9_x]+|scalar)|g(\d+))\.npy$')
    wanted = {_safe(n) for n in names}
    # a var whose NAME itself ends in '.g<digits>' (e.g. 'w.g5') saves
    # the legacy un-suffixed file 'w.g5.npy', which the pattern above
    # would misparse as generation 5 of var 'w' — exact legacy names of
    # saved vars are never GC candidates
    legacy = {_safe(n) + '.npy' for n in names}
    # never sweep the immediately-previous generation either: a
    # synchronized sibling host can lag a FULL checkpoint behind (still
    # writing gen N-1 data, its manifest not yet on disk) and gen N-1
    # would otherwise be unreferenced from this host's point of view
    floor_gen = floor_gen - 1
    for fname in entries:
        if fname in legacy:
            continue
        m = pat.match(fname)
        if not m or m.group(1) not in wanted:
            continue
        g = int(m.group(2) or m.group(3))
        if g < floor_gen and g not in keep_gens:
            try:
                os.remove(os.path.join(dirname, fname))
            except OSError:
                pass


def _own_manifest_name():
    """This process's manifest filename: ``__manifest__.json`` on a single
    process, ``__manifest__.p<K>.json`` per process on multi-host."""
    try:
        import jax
        if jax.process_count() > 1:
            return '__manifest__.p%d.json' % jax.process_index()
    except Exception:
        pass
    return _MANIFEST


def _write_manifest(dirname, manifest):
    """Each JAX process writes only its own manifest file (no cross-host
    write collision); _read_manifest merges them, unioning the shard
    lists, so the checkpoint is complete once every host has written —
    without any barrier or designated writer.  The write is tmp+rename so
    a concurrent reader (another host seeding its own save) never sees a
    truncated JSON.  A single-process save claims the directory: stale
    per-process manifests from an earlier multi-host run into the same
    dirname are removed, so their shard records can't shadow the fresh
    save at load time."""
    import glob
    fname = _own_manifest_name()
    path = os.path.join(dirname, fname)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(manifest, f)
    # archive the manifest being superseded as <fname>.prev (hardlink:
    # no window with zero manifests) — together with _gc_stale_generations
    # keeping its referenced data files and write_step_file archiving
    # STEP.prev, renaming the .prev files back restores the previous
    # checkpoint.  Archived only when this write CHANGES the newest
    # generation (advance = new checkpoint, regress = rollback re-save;
    # see _advances_generation): a checkpoint composed of several
    # save_vars calls into one manifest (per-member saves) archives
    # once, at the first write of the new generation, so .prev is always
    # the last COMPLETE previous checkpoint, never a mid-checkpoint
    # intermediate.
    # .prev does not match the __manifest__*.json read glob, so loads
    # never see it.
    if os.path.exists(path) and _advances_generation(path, manifest):
        _archive_prev(path)
    os.replace(tmp, path)
    if fname == _MANIFEST:
        # .p*.json AND their .prev/.tmp leftovers: a surviving archive
        # would pin its generations against GC forever
        for stale in glob.glob(os.path.join(glob.escape(dirname),
                                            '__manifest__.p*.json*')):
            try:
                os.remove(stale)
            except OSError:
                pass  # a straggler's os.replace can race .tmp names away


def _archive_prev(path):
    """Snapshot ``path`` as ``path.prev`` — hardlink when the filesystem
    supports it (atomic, no extra IO), tmp+rename copy otherwise (NFS/
    FUSE mounts without link): the rollback the .prev protocol promises
    must not silently vanish on such filesystems."""
    prev = path + '.prev'
    try:
        if os.path.exists(prev + '.tmp'):
            os.remove(prev + '.tmp')  # crashed earlier attempt
        try:
            os.link(path, prev + '.tmp')
        except OSError:
            import shutil
            shutil.copyfile(path, prev + '.tmp')
        os.replace(prev + '.tmp', prev)
    except OSError:
        pass


def _advances_generation(path, manifest):
    """True when ``manifest`` carries a DIFFERENT newest save generation
    than the manifest file at ``path`` (unreadable/legacy files count as
    gen 0).  Forward moves are new checkpoints; a BACKWARD move is a
    rollback re-save claiming the directory, and it archives too — the
    superseded higher-generation checkpoint becomes ``.prev``, keeping
    the archived (params, step) pair consistent with write_step_file's
    matching both-directions gate (a STEP.prev pointing at a step whose
    params archive was never taken is exactly the downgrade desync
    ADVICE.md flags).  Only an equal generation — a re-save of the same
    checkpoint, e.g. per-member saves composing one generation — leaves
    the archive alone."""
    try:
        with open(path) as f:
            on_disk = json.load(f)
    except (OSError, ValueError):
        return True
    return (_newest_generation(manifest)
            != _newest_generation(on_disk))


def _newest_generation(manifest):
    """The highest save generation any var record carries (0 for legacy
    / empty manifests) — the value the step->generation binding in
    load_checkpoint and the archive gate both compare."""
    if not manifest:
        return 0
    return max([r.get('gen', 0) or 0
                for r in manifest.get('vars', {}).values()] + [0])


def _read_manifest(dirname, own_only=False):
    """Read and merge every manifest in the directory: the single-process
    ``__manifest__.json`` plus any per-process ``__manifest__.p<K>.json``
    from a multi-host save.  Per-var conflicts resolve by the records'
    save-generation counter (higher gen wins wholesale; equal gens union
    shard lists — see _merge_var_record); mtime ordering is only the
    fallback for gen ties and legacy records.  Nothing raises here; an
    incomplete winner still fails loudly in _assemble.  ``own_only``
    restricts to this process's own file (save-time seeding)."""
    import glob
    if own_only:
        paths = [os.path.join(dirname, _own_manifest_name())]
        paths = [p for p in paths if os.path.exists(p)]
    else:
        paths = sorted(
            glob.glob(os.path.join(glob.escape(dirname),
                                   '__manifest__*.json')),
            key=lambda p: (os.path.getmtime(p), p))
    merged = None
    for path in paths:
        with open(path) as f:
            m = json.load(f)
        if m.get('format_version', 0) > _FORMAT_VERSION:
            raise ValueError(
                "checkpoint %s was written by a newer format "
                "(version %s > %s)"
                % (dirname, m.get('format_version'), _FORMAT_VERSION))
        if merged is None:
            merged = m
            continue
        for name, rec in m.get('vars', {}).items():
            merged['vars'][name] = _merge_var_record(
                merged['vars'].get(name), rec)
    return merged


def save_params(executor, dirname, main_program=None, generation=None,
                scope=None):
    save_vars(executor, dirname, main_program, vars=None,
              predicate=is_parameter, generation=generation, scope=scope)


def save_persistables(executor, dirname, main_program=None,
                      generation=None, scope=None):
    save_vars(executor, dirname, main_program, vars=None,
              predicate=is_persistable, generation=generation,
              scope=scope)


def _check_against_program(name, var, shape, dtype):
    """Fail loudly when a checkpoint value disagrees with the program's
    declaration (Variable shape may use -1/None for the batch dim)."""
    if not isinstance(var, Variable):
        return
    decl = getattr(var, 'shape', None)
    if decl:
        decl = tuple(int(d) for d in decl)
        got = tuple(shape)
        ok = len(decl) == len(got) and all(
            d in (-1, 0) or d == g for d, g in zip(decl, got))
        if not ok:
            raise ValueError(
                "checkpoint var '%s' has shape %s but the program declares "
                "%s — the model changed since this checkpoint was saved" %
                (name, got, decl))
    vdt = getattr(var, 'dtype', None)
    if vdt is not None:
        want = np.dtype(as_numpy_dtype(vdt))
        if np.dtype(dtype) != want:
            raise ValueError(
                "checkpoint var '%s' has dtype %s but the program declares "
                "%s" % (name, dtype, want))


def _load_sharded(dirname, name, rec):
    """Reassemble a sharded var.  Under a live mesh_guard the result is
    built directly onto the mesh with the saved PartitionSpec (each host
    reads only the shards it needs); otherwise the full numpy array."""
    shape = tuple(rec['shape'])
    dtype = np.dtype(rec['dtype'])
    def _shard_path(s):
        # format v1 wrote integer counters ('x.shard3.npy'); current
        # format records the index-derived filename directly.
        if isinstance(s['file'], int):
            return os.path.join(
                dirname, '%s.shard%d.npy' % (_safe(name), s['file']))
        return os.path.join(dirname, s['file'])

    shard_files = {
        tuple(tuple(p) for p in s['index']): _shard_path(s)
        for s in rec['shards']}

    def piece(index):
        idx = tuple((sl.start if sl.start is not None else 0,
                     sl.stop if sl.stop is not None else shape[d])
                    for d, sl in enumerate(index))
        if idx in shard_files:
            return _np_load(shard_files[idx], dtype)
        # requested block differs from the saved tiling (different mesh
        # size): assemble the full array once and slice
        return _assemble(shape, dtype, shard_files)[index]

    from .parallel import api
    mesh = api.current_mesh()
    spec = rec.get('spec')
    if mesh is not None and spec is not None and all(
            a in mesh.axis_names for part in spec if part
            for a in (part if isinstance(part, list) else [part])):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        parts = [tuple(p) if isinstance(p, list) else p for p in spec]
        sharding = NamedSharding(mesh, PartitionSpec(*parts))
        return jax.make_array_from_callback(shape, sharding, piece)
    return _assemble(shape, dtype, shard_files)


def _np_load(path, dtype):
    """np.load with an ml_dtypes repair: numpy serializes bfloat16 as a
    raw void dtype (|V2), so reinterpret the buffer as the manifest's
    dtype when they disagree."""
    arr = np.load(path)
    dtype = np.dtype(dtype)
    if arr.dtype != dtype and arr.dtype.itemsize == dtype.itemsize:
        arr = arr.view(dtype)
    return arr


def _assemble(shape, dtype, shard_files):
    """Stitch shard blocks into the full array, verifying they tile it
    exactly: in-bounds, pairwise disjoint, and total volume == the full
    volume (blocks within bounds + disjoint + volumes summing to the
    whole is equivalent to gap-free coverage).  A partial checkpoint —
    e.g. one host of a multi-host save missing — raises instead of
    returning uninitialized memory."""
    full = np.empty(shape, dtype=dtype)
    covered = 0
    blocks = list(shard_files.items())
    for i, (idx, path) in enumerate(blocks):
        if len(idx) != len(shape) or any(
                not (0 <= a <= b <= dim)
                for (a, b), dim in zip(idx, shape)):
            raise ValueError(
                "checkpoint shard %s has index %s outside shape %s"
                % (os.path.basename(path), idx, shape))
        for jdx, other in blocks[:i]:
            if _blocks_overlap(idx, jdx):
                raise ValueError(
                    "checkpoint shards %s and %s overlap (indices %s, %s)"
                    % (os.path.basename(path), os.path.basename(other),
                       idx, jdx))
        block = _np_load(path, dtype)
        want = tuple(b - a for a, b in idx)
        if block.shape != want:
            raise ValueError(
                "checkpoint shard %s has shape %s but its index %s spans "
                "%s" % (os.path.basename(path), block.shape, idx, want))
        full[tuple(slice(a, b) for a, b in idx)] = block
        covered += int(np.prod(want))
    total = int(np.prod(shape))
    if covered != total:
        raise ValueError(
            "checkpoint shards cover %d of %d elements — the checkpoint "
            "is incomplete (a host's shards or manifest are missing)"
            % (covered, total))
    return full


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, scope=None, manifest=None):
    """Returns the number of vars actually restored (a var absent from
    the directory is skipped — partial checkpoints are legal for
    fine-tuning — but callers like load_checkpoint can detect a total
    miss, e.g. a program whose auto-generated names don't line up).

    ``manifest`` lets a caller pin the exact manifest the load resolves
    against (load_checkpoint's consistency loop reads it once, loads,
    then re-validates — a second internal read here would reopen the
    race it closes); None reads the directory as before."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = scope or global_scope()
    if manifest is None:
        manifest = _read_manifest(dirname)
    records = manifest['vars'] if manifest else {}
    loaded = 0
    for var in vars:
        name = var.name if isinstance(var, Variable) else var
        rec = records.get(name)
        if rec is not None and rec.get('shards'):
            value = _load_sharded(dirname, name, rec)
        else:
            # generation-suffixed filename from the record (format v3);
            # the legacy un-suffixed name serves ONLY records that never
            # carried a filename (v2 checkpoints, manifest-less dirs) —
            # when a v3 record names a file that is missing, the var is
            # skipped rather than silently restored from a stale legacy
            # copy the v3 saves never updated
            if rec is not None and rec.get('file'):
                path = os.path.join(dirname, rec['file'])
            else:
                path = os.path.join(dirname, _safe(name) + '.npy')
            if not os.path.exists(path):
                continue
            value = (_np_load(path, rec['dtype']) if rec is not None
                     else np.load(path))
        if rec is not None:
            _check_against_program(name, var, rec['shape'], rec['dtype'])
        scope.set(name, value)
        loaded += 1
    return loaded


def load_params(executor, dirname, main_program=None, scope=None):
    load_vars(executor, dirname, main_program, predicate=is_parameter,
              scope=scope)


def load_persistables(executor, dirname, main_program=None, scope=None,
                      manifest=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_persistable, scope=scope,
                     manifest=manifest)


def load_persistables_if_exist(executor, dirname, main_program=None):
    if os.path.isdir(dirname):
        load_persistables(executor, dirname, main_program)


def _safe(name):
    return name.replace('/', '%2F')


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program.prune(targets=target_vars)
    return pruned.inference_optimize()


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None):
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    if main_program is None:
        main_program = default_main_program()
    os.makedirs(dirname, exist_ok=True)
    pruned = main_program.prune(targets=target_vars,
                                feeds=feeded_var_names)
    inference_program = pruned.inference_optimize()
    fetch_var_names = [v.name for v in target_vars]
    meta = dict(program=inference_program.to_dict(),
                feed_var_names=list(feeded_var_names),
                fetch_var_names=fetch_var_names)
    import json
    with open(os.path.join(dirname, '__model__'), 'w') as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, inference_program)
    return inference_program


def load_inference_model(dirname, executor):
    import json
    with open(os.path.join(dirname, '__model__')) as f:
        meta = json.load(f)
    program = Program.from_dict(meta['program'])
    load_persistables(executor, dirname, program)
    fetch_vars = [program.global_block().var(n)
                  for n in meta['fetch_var_names']]
    return program, meta['feed_var_names'], fetch_vars


def get_parameter_value(para, executor=None):
    assert is_parameter(para)
    return global_scope().get_numpy(para.name)


def get_parameter_value_by_name(name, executor=None, program=None):
    if program is None:
        program = default_main_program()
    var = program.global_block().var(name)
    return get_parameter_value(var, executor)


# -- checkpoint/resume (SURVEY.md A2) ------------------------------------
def step_generation(step):
    """The save-generation logical clock a training step maps to — the
    ONE place the step->generation protocol lives (save_checkpoint and
    the per-member distributed saves must agree)."""
    return None if step is None else int(step) + 1


def write_step_file(dirname, step):
    """Record the checkpoint's step, archiving the previous STEP as
    STEP.prev so the .prev rollback (rename both archives back) restores
    a CONSISTENT (params, step) pair — params alone would resume the
    data/LR-schedule position against older weights."""
    path = os.path.join(dirname, 'STEP')
    if os.path.exists(path):
        # archive when the step CHANGES, in either direction (mirrors
        # the manifest's _advances_generation gate): re-saving the SAME
        # step must not overwrite STEP.prev with the current step, but a
        # rollback re-save of an EARLIER step must archive the
        # superseded higher step right alongside the manifest archive —
        # otherwise STEP.prev keeps a step whose params .prev no longer
        # matches (the downgrade desync ADVICE.md flags)
        try:
            with open(path) as f:
                on_disk = int(f.read().strip())
        except (OSError, ValueError):
            on_disk = None
        if on_disk is None or int(step) != on_disk:
            _archive_prev(path)
    # tmp+rename, NOT in-place: the archive may be a hardlink to the
    # current file's inode, and an in-place truncate-and-write would
    # update STEP.prev right along with STEP
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        f.write(str(int(step)))
    os.replace(tmp, path)


def save_checkpoint(executor, dirname, main_program=None, step=None,
                    scope=None):
    """Full training state: every persistable (params + optimizer moments +
    bn stats + counters).  ``step`` doubles as the save-generation logical
    clock: every host of a synchronized save passes the same step, so the
    manifest merge is race-free even across host-count changes."""
    save_persistables(executor, dirname, main_program,
                      generation=step_generation(step), scope=scope)
    if step is not None:
        write_step_file(dirname, step)


def _read_step_file(dirname, prev=False):
    path = os.path.join(dirname, 'STEP' + ('.prev' if prev else ''))
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def load_checkpoint(executor, dirname, main_program=None, scope=None):
    """Restore every persistable and return the checkpoint's step.

    Consistency under a live writer: the manifest and the STEP file are
    two files, so a reader racing a concurrent ``save_checkpoint`` or
    :func:`rollback_checkpoint` could naively pair one save's params
    with another's step.  The step IS the save-generation clock
    (:func:`step_generation`), which every var record carries — so this
    loads against one pinned manifest read, then accepts the result
    only when ``step_generation(STEP)`` equals that manifest's newest
    generation, retrying on a mismatch (a torn window mid-rename).
    Checkpoints saved without ``step`` (no STEP file, or legacy
    manifests without generation counters) load exactly as before —
    there is nothing to bind."""
    last_err = None
    for _attempt in range(8):
        manifest = _read_manifest(dirname)
        try:
            n = load_persistables(executor, dirname, main_program,
                                  scope=scope, manifest=manifest)
        except OSError as e:
            # a concurrent writer swept this manifest's generation files
            # mid-read: re-read and retry against the newer manifest.
            # (ValueError — program mismatch, format gate, torn
            # multi-host coverage — propagates loudly, as before.)
            last_err = e
            time.sleep(0.005)
            continue
        if n == 0:
            last_err = ValueError(
                "checkpoint %s restored nothing — no persistable var of "
                "the program matches a saved name (was the program "
                "rebuilt with different auto-generated names? build it "
                "under reset_unique_name_guard() for stable names)"
                % dirname)
            if not os.path.exists(os.path.join(dirname, _MANIFEST)):
                raise last_err  # no manifest at all: not a race
            time.sleep(0.005)
            continue
        step = _read_step_file(dirname)
        gen = _newest_generation(manifest)
        if step is None or gen == 0:
            return step  # nothing to bind (legacy / step-less save)
        if step_generation(step) == gen:
            return step
        last_err = RuntimeError(
            "checkpoint %s is mid-update: STEP %d does not match the "
            "manifest generation %d" % (dirname, step, gen))
        time.sleep(0.005)
    if isinstance(last_err, ValueError):
        raise last_err  # steady-state miss, not a race: original error
    raise RuntimeError(
        "checkpoint %s kept changing under the reader — could not "
        "observe a consistent (params, step) pair in 8 attempts "
        "(last: %s)" % (dirname, last_err))


def rollback_checkpoint(dirname):
    """Restore the archived previous checkpoint in place: rename the
    ``__manifest__.json.prev`` / ``STEP.prev`` pair (written by
    :func:`_write_manifest` / :func:`write_step_file` when a save
    supersedes a checkpoint) back over the live files.  The archived
    generation's data files are still on disk — the generation GC
    never sweeps manifest-referenced generations — so the result is the
    complete previous (params, step) checkpoint.  Returns the restored
    step (None when the archive predates step tracking).  Raises when
    there is no archive to roll back to.  Concurrent readers using
    :func:`load_checkpoint` observe either the old or the new pair,
    never a mix (the generation binding there retries the torn
    window)."""
    man = os.path.join(dirname, _MANIFEST)
    prev = man + '.prev'
    if not os.path.exists(prev):
        raise ValueError(
            "no %s.prev archive in %s — nothing to roll back to (only "
            "a save that SUPERSEDED a checkpoint leaves an archive)"
            % (_MANIFEST, dirname))
    # manifest first, STEP second — the same order save_checkpoint
    # writes them, so load_checkpoint's gen<->step binding sees the
    # same torn-window shapes either way and retries through both
    os.replace(prev, man)
    step_prev = os.path.join(dirname, 'STEP.prev')
    step_live = os.path.join(dirname, 'STEP')
    if os.path.exists(step_prev):
        os.replace(step_prev, step_live)
    else:
        # no archived step: the checkpoint being restored predates
        # step tracking (or was saved step-less), so any live STEP
        # belongs to the save we just rolled back — leaving it would
        # pair the restored params with the superseded step, the exact
        # desync this protocol exists to prevent
        try:
            os.remove(step_live)
        except OSError:
            pass
    return _read_step_file(dirname)


# -- serving version directories (inference/fleet.py) ---------------------
_BUCKET_RE = re.compile(r'^bucket_(\d+)\.stablehlo$')


def bucket_artifacts(dirname):
    """{bucket_size: path} for the ``export_bucketed`` artifacts in a
    directory (``bucket_<N>.stablehlo``) — the on-disk shape of one
    servable model version.  Empty dict when the directory holds none
    (callers use that as the is-this-a-version-dir predicate)."""
    out = {}
    try:
        entries = os.listdir(dirname)
    except OSError:
        return out
    for fname in entries:
        m = _BUCKET_RE.match(fname)
        if m:
            out[int(m.group(1))] = os.path.join(dirname, fname)
    return out


def resolve_version_dir(path, version=None):
    """Resolve a servable version directory, TF-Serving style.

    ``path`` either IS an ``export_bucketed`` artifact directory, or a
    base directory of versioned subdirectories (``base/1``, ``base/2``,
    ... — numeric names are versions; the HIGHEST number is the newest).
    Returns ``(version_dir, version_name)``:

    - ``version`` given: that subdirectory, loudly checked.
    - ``path`` holds bucket artifacts directly: ``path`` itself, named
      by its basename.
    - otherwise: the numerically-highest subdirectory that holds bucket
      artifacts (non-numeric subdirs are considered last,
      lexicographically, so a ``canary/`` next to ``1..N`` never wins
      by accident).
    """
    if version is not None:
        d = os.path.join(path, str(version))
        if not bucket_artifacts(d):
            raise ValueError(
                "version %r under %s has no bucket_<N>.stablehlo "
                "artifacts (export_bucketed writes them)"
                % (version, path))
        return d, str(version)
    if bucket_artifacts(path):
        name = os.path.basename(os.path.abspath(path).rstrip(os.sep))
        return path, name
    try:
        entries = sorted(os.listdir(path))
    except OSError:
        raise ValueError("version path %s is not a directory" % (path,))
    candidates = []
    for e in entries:
        d = os.path.join(path, e)
        if os.path.isdir(d) and bucket_artifacts(d):
            candidates.append(e)
    if not candidates:
        raise ValueError(
            "%s holds neither bucket_<N>.stablehlo artifacts nor "
            "versioned subdirectories containing them — point the "
            "fleet at an export_bucketed output dir or a base dir of "
            "numbered versions" % (path,))
    # non-digit names sort first (they only win when no numbered
    # version exists, and then lexicographically-last of them does)
    candidates.sort(key=lambda e: (1, int(e)) if e.isdigit()
                    else (0, e))
    best = candidates[-1]
    return os.path.join(path, best), best


def gc_versions(base_dir, keep=4, protect=()):
    """Retention for a base directory of numbered servable versions
    (the ``export_bucketed`` layout ``base/1``, ``base/2``, ...): keep
    the ``keep`` numerically-newest version dirs, delete the rest.
    Returns the list of version names removed.

    A continuously-promoting online pipeline mints a new version every
    promoted round; without GC the export dir grows one full artifact
    set per round forever.  Three dirs are NEVER candidates, because a
    serving fleet may be holding or about to resolve them:

    - anything named in ``protect`` (version names like ``'7'`` or
      directory paths — callers pass the fleet's live version dir and
      the ``.prev`` rollback target from its deploy record, so an
      auto-``rollback()`` always finds its artifacts on disk; a
      multi-tenant fleet's ``protected_version_dirs()`` enumerates
      every tenant's set at once.  Protecting a version dir also keeps
      its AOT executable-cache entries meaningful —
      ``inference.aot_cache.AotCache.sweep_orphans`` removes entries
      whose source artifact this GC deleted, the callers' matching
      post-GC step);
    - the numerically-highest version, regardless of ``keep`` (a
      concurrent ``deploy(base_dir)`` resolves the highest number
      *before* loading it — ``keep`` is floored at 1 for the same
      reason);
    - non-version entries: non-numeric names (``canary/``) and dirs
      without ``bucket_<N>.stablehlo`` artifacts (e.g. a version a
      concurrent exporter is still writing — it has no artifacts yet,
      so it is invisible here exactly like it is to
      :func:`resolve_version_dir`).

    Deletion is rename-then-remove: the dir is atomically renamed to a
    non-numeric ``.gc.<pid>`` name first, so a concurrent
    ``resolve_version_dir`` either sees the intact version dir or does
    not see it at all — never a half-deleted dir that resolves but
    whose artifact files vanish mid-load (the deploy->promote->gc race
    the tests pin)."""
    import shutil
    keep = max(1, int(keep))
    prot_names, prot_paths = set(), set()
    for p in protect:
        if p is None:
            continue
        p = str(p)
        if os.sep in p or p == '.':
            prot_paths.add(os.path.abspath(p.rstrip(os.sep)))
            prot_names.add(os.path.basename(p.rstrip(os.sep)))
        else:
            prot_names.add(p)
    try:
        entries = os.listdir(base_dir)
    except OSError:
        return []
    versions = []
    tomb = re.compile(r'^\d+\.gc\.\d+$')
    for e in entries:
        d = os.path.join(base_dir, e)
        if e.isdigit() and os.path.isdir(d) and bucket_artifacts(d):
            versions.append((int(e), e, d))
        elif tomb.match(e) and os.path.isdir(d):
            # a half-deleted victim from an earlier GC that crashed
            # between its rename and rmtree (or whose rmtree failed):
            # finish the job, or the leak is permanent — tombstone
            # names are non-numeric and would never be candidates
            shutil.rmtree(d, ignore_errors=True)
    versions.sort()
    removed = []
    for _num, name, d in versions[:-keep]:
        if name in prot_names or os.path.abspath(d) in prot_paths:
            continue
        tomb = '%s.gc.%d' % (d, os.getpid())
        try:
            os.rename(d, tomb)
        except OSError:
            continue  # a concurrent GC (or deploy machinery) won it
        shutil.rmtree(tomb, ignore_errors=True)
        removed.append(name)
    return removed


# -- .prev-protocol JSON records (fleet deploy/rollback state) ------------
def write_rollback_json(path, obj):
    """Write a small JSON state file under the STEP-file ``.prev``
    protocol: when the on-disk content CHANGES, the superseded file is
    archived as ``<path>.prev`` first (hardlink or copy —
    :func:`_archive_prev`), then the new content lands via tmp+rename,
    so a crash mid-write never tears the record and a rollback always
    has the superseded state to return to.  Re-writing identical
    content leaves the archive alone (mirrors write_step_file)."""
    changed = True
    if os.path.exists(path):
        try:
            with open(path) as f:
                changed = json.load(f) != obj
        except (OSError, ValueError):
            changed = True  # unreadable counts as a change
        if changed:
            _archive_prev(path)
    tmp = '%s.tmp.%d' % (path, os.getpid())
    with open(tmp, 'w') as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def read_rollback_json(path, prev=False):
    """Read a :func:`write_rollback_json` record; ``prev=True`` reads
    the ``.prev`` archive (the state the newest write superseded).
    Returns None when the requested file does not exist."""
    p = path + '.prev' if prev else path
    try:
        with open(p) as f:
            return json.load(f)
    except OSError:
        return None
