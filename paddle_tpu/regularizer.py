"""Weight-decay regularizers.

Reference parity: python/paddle/v2/fluid/regularizer.py — append ops that
add the regularization gradient to each parameter's gradient before the
optimizer op consumes it.
"""
from .core.program import grad_var_name

__all__ = ['append_regularization_ops', 'WeightDecayRegularizer',
           'L1DecayRegularizer', 'L2DecayRegularizer', 'L1Decay', 'L2Decay']


class WeightDecayRegularizer(object):
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op(
            type='scale',
            inputs={'X': [param]},
            outputs={'Out': [decay]},
            attrs={'scale': self._regularization_coeff})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op(type='sign', inputs={'X': [param]},
                        outputs={'Out': [sign]})
        decay = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op(
            type='scale', inputs={'X': [sign]}, outputs={'Out': [decay]},
            attrs={'scale': self._regularization_coeff})
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    params_and_grads = []
    for param, grad in parameters_and_grads:
        regularization_term = None
        if getattr(param, 'regularizer', None) is not None:
            regularization_term = param.regularizer(param, grad,
                                                    grad.block)
        elif regularization is not None:
            regularization_term = regularization(param, grad, grad.block)
        if grad is None or regularization_term is None:
            params_and_grads.append((param, grad))
            continue
        block = grad.block
        new_grad = block.create_var(
            name=grad_var_name(param.name) + '_reg', shape=param.shape,
            dtype=param.dtype)
        new_grad.stop_gradient = True
        block.append_op(
            type='sum',
            inputs={'X': [grad, regularization_term]},
            outputs={'Out': [new_grad]})
        params_and_grads.append((param, new_grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
