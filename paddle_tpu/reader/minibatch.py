"""Minibatching.  Reference parity: python/paddle/v2/minibatch.py."""

__all__ = ['batch']


def batch(reader, batch_size, drop_last=False):
    """Group a sample reader into a minibatch reader (lists of samples).

    On TPU, fixed batch shapes avoid re-jitting the step program, so
    ``drop_last=True`` is the recommended setting for training loops (the
    executor still handles a ragged tail batch — it just compiles a second
    program for the tail shape).
    """

    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
