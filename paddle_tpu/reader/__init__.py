"""Reader pipelines — composable python iterator factories.

Reference parity: python/paddle/v2/reader (decorator.py) and
python/paddle/v2/minibatch.py.  A *reader creator* is a zero-arg callable
returning an iterator over samples; decorators wrap creators.  On TPU the
hot path is fed by the native C++ prefetcher (paddle_tpu/runtime/native.py)
behind `xmap_readers`/`buffered`; these decorators remain pure-python
fallbacks with identical semantics.
"""
from .decorator import (map_readers, buffered, compose, chain, shuffle,
                        firstn, xmap_readers, cache, metered, PipeReader,
                        ComposeNotAligned)
from .minibatch import batch
from . import creator

__all__ = [
    'map_readers', 'buffered', 'compose', 'chain', 'shuffle', 'firstn',
    'xmap_readers', 'cache', 'metered', 'PipeReader', 'ComposeNotAligned',
    'batch', 'creator',
]
