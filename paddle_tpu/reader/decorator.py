"""Reader decorators.

Reference parity: python/paddle/v2/reader/decorator.py (map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers, PipeReader).
Same contracts; implementation is plain python threading — the heavy
multi-process machinery the reference needs for CPU-bound python feeds is
replaced by the native C++ prefetcher for the TPU input pipeline (see
paddle_tpu/runtime/native.py), with these as the portable fallback.
"""
import itertools
import random
import subprocess
import threading
import time
import queue as _queue

from .. import observability as _obs

__all__ = ['map_readers', 'buffered', 'compose', 'chain', 'shuffle',
           'firstn', 'xmap_readers', 'cache', 'metered', 'PipeReader',
           'ComposeNotAligned']


class _ReaderMetrics(object):
    """Registry handles for one reader pipeline stage, labeled by name
    (``reader="buffered"``, or the user's ``metered`` name)."""

    _cache = {}
    _cache_lock = threading.Lock()

    def __init__(self, name):
        r = _obs.registry()
        L = ('reader',)
        sl = {'reader': name}
        self.samples = r.counter(
            'paddle_tpu_reader_samples_total',
            'samples yielded by instrumented reader stages', L
            ).labels(**sl)
        self.rate = r.gauge(
            'paddle_tpu_reader_samples_per_second',
            'recent sample rate of instrumented reader stages '
            '(updated every rate-window samples)', L).labels(**sl)
        self.buffer_depth = r.gauge(
            'paddle_tpu_reader_buffer_depth',
            'samples sitting in the prefetch buffer', L).labels(**sl)

    @classmethod
    def get(cls, name):
        with cls._cache_lock:
            m = cls._cache.get(name)
            if m is None:
                m = cls._cache[name] = cls(name)
            return m


_RATE_WINDOW = 256  # samples between rate-gauge refreshes
_DEPTH_WINDOW = 64  # samples between buffer-depth/count flushes


class _SampleWindow(object):
    """Amortized per-sample accounting shared by the instrumented reader
    stages: ``hit()`` per delivered sample, locked metric updates only
    once per ``window`` (counter inc, samples/sec gauge, and — when a
    queue is given — its depth gauge).  ``flush()`` from a ``finally``
    delivers the partial window so a consumer that stops early (firstn,
    break, exception) never under-counts delivered samples."""
    __slots__ = ('_m', '_window', '_n', '_t0')

    def __init__(self, m, window):
        self._m = m
        self._window = window
        self._n = 0
        self._t0 = time.perf_counter()

    def hit(self, q=None):
        self._n += 1
        if self._n >= self._window:
            n, self._n = self._n, 0
            self._m.samples.inc(n)
            if q is not None:
                self._m.buffer_depth.set(q.qsize())
            t1 = time.perf_counter()
            if t1 > self._t0:
                self._m.rate.set(n / (t1 - self._t0))
            self._t0 = t1

    def flush(self):
        if self._n:
            self._m.samples.inc(self._n)
            self._n = 0


def metered(reader, name='reader'):
    """Decorator: count samples (``paddle_tpu_reader_samples_total``)
    and keep a recent samples/sec gauge for the wrapped creator.  A
    no-op pass-through when metrics are disabled."""

    def metered_reader():
        it = reader()
        if not _obs.enabled():
            yield from it
            return
        w = _SampleWindow(_ReaderMetrics.get(name), _RATE_WINDOW)
        try:
            for sample in it:
                # count before the yield: the yield IS the delivery, and
                # a consumer that closes us right after still got it
                w.hit()
                yield sample
        finally:
            w.flush()

    return metered_reader


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """Creator whose samples are ``func(r1_sample, r2_sample, ...)``."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding window of ``buf_size`` samples.

    The stream is consumed in windows of ``buf_size``; each window is
    permuted (module-level ``random``, so ``random.seed`` controls it)
    and drained before the next window is pulled.  Windowing via
    ``itertools.islice`` keeps at most one window resident.
    """

    def data_reader():
        it = iter(reader())
        if buf_size <= 0:  # degenerate window: plain pass-through
            yield from it
            return
        while True:
            window = list(itertools.islice(it, buf_size))
            if not window:
                return
            random.shuffle(window)
            yield from window

    return data_reader


def chain(*readers):
    """Concatenate readers: all of r1, then all of r2, ..."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


def compose(*readers, **kwargs):
    """Zip readers into combined tuples.  With check_alignment=True
    (default) raises ComposeNotAligned if they end at different times."""
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned.")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Pre-read up to ``size`` samples into a queue on a worker thread.
    A source error (e.g. a recordio CRC mismatch) re-raises in the
    consumer instead of silently truncating the stream."""

    class EndSignal(object):
        def __init__(self, error=None):
            self.error = error

    def read_worker(r, q):
        try:
            for d in r:
                q.put(d)
        except BaseException as e:
            q.put(EndSignal(e))
        else:
            q.put(EndSignal())

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        w = _SampleWindow(_ReaderMetrics.get('buffered'),
                          _DEPTH_WINDOW) if _obs.enabled() else None
        e = q.get()
        try:
            while not isinstance(e, EndSignal):
                if w is not None:
                    w.hit(q)
                yield e
                e = q.get()
        finally:
            if w is not None:
                w.flush()
        if e.error is not None:
            raise e.error

    return data_reader


def firstn(reader, n):
    """Truncate the stream after ``n`` samples (``itertools.islice``)."""

    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def cache(reader):
    """Materialise the reader once; replay from memory thereafter."""
    all_data = []
    filled = []

    def cache_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        for item in all_data:
            yield item

    return cache_reader


class XmapEndSignal(object):
    pass


class _XmapError(object):
    """A mapper exception in transit from a worker to the consumer."""

    def __init__(self, error):
        self.error = error


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel ``map``: ``process_num`` worker threads apply ``mapper``
    over samples with a bounded queue of ``buffer_size``.

    Reference parity: decorator.py xmap_readers (threads there too).  When
    the native runtime builds (runtime/native.py), the handoff queues live
    in C++ and their blocking ops release the GIL (N1); this python-queue
    body is the fallback.
    """
    from ..runtime import native as _native
    if _native.available():
        from ..runtime.prefetch import xmap_native
        return xmap_native(mapper, reader, process_num, buffer_size, order)
    end = XmapEndSignal()

    def read_worker(r, in_q):
        for i in r():
            in_q.put(i)
        in_q.put(end)

    def order_read_worker(r, in_q):
        for i, d in enumerate(r()):
            in_q.put((i, d))
        in_q.put(end)

    def handle_worker(in_q, out_q, mapper):
        sample = in_q.get()
        while not isinstance(sample, XmapEndSignal):
            try:
                result = mapper(sample)
            except BaseException as e:
                # surface the mapper error in the consumer instead of
                # dying silently (which would leave out_q one EndSignal
                # short and hang the reader).  The error goes out FIRST —
                # the consumer always drains out_q, while in_q may be
                # full with no other drainer (a blocking put there could
                # deadlock); waking peers is best-effort.
                out_q.put(_XmapError(e))
                try:
                    in_q.put_nowait(end)
                except _queue.Full:
                    pass
                return
            out_q.put(result)
            sample = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def order_handle_worker(in_q, out_q, mapper, turn):
        # ``turn`` is (Condition, [next_index]): a worker may emit its
        # result only when its sample index is the next one due, so the
        # output order matches the input order without a spin-wait.
        cond, nxt = turn
        ins = in_q.get()
        while not isinstance(ins, XmapEndSignal):
            order, sample = ins
            try:
                result = mapper(sample)
            except BaseException as e:
                # still take our turn (so peers blocked on nxt don't
                # wait forever), then surface the error
                with cond:
                    cond.wait_for(lambda: nxt[0] == order)
                    out_q.put(_XmapError(e))
                    nxt[0] += 1
                    cond.notify_all()
                try:
                    in_q.put_nowait(end)
                except _queue.Full:
                    pass
                return
            with cond:
                cond.wait_for(lambda: nxt[0] == order)
                out_q.put(result)
                nxt[0] += 1
                cond.notify_all()
            ins = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        turn = (threading.Condition(), [0])
        target = order_read_worker if order else read_worker
        t = threading.Thread(target=target, args=(reader, in_q))
        t.daemon = True
        t.start()
        target = order_handle_worker if order else handle_worker
        args = (in_q, out_q, mapper, turn) if order else \
            (in_q, out_q, mapper)
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=target, args=args)
            w.daemon = True
            w.start()
            workers.append(w)
        w = _SampleWindow(_ReaderMetrics.get('xmap'),
                          _DEPTH_WINDOW) if _obs.enabled() else None
        finish = 0
        try:
            while finish < process_num:
                sample = out_q.get()
                if isinstance(sample, XmapEndSignal):
                    finish += 1
                elif isinstance(sample, _XmapError):
                    raise sample.error
                else:
                    if w is not None:
                        w.hit(out_q)
                    yield sample
        finally:
            if w is not None:
                w.flush()

    return xreader


class PipeReader(object):
    """Stream samples out of a shell command's stdout (reference:
    decorator.py PipeReader — used for HDFS cat pipelines)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("left_cmd must be a string")
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type
        if file_type == "gzip":
            import zlib
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)

    def _decode(self, raw):
        if self.file_type == "gzip":
            raw = self.dec.decompress(raw)
        elif self.file_type != "plain":
            raise TypeError("file_type %s is not allowed" % self.file_type)
        return raw.decode('utf-8', 'ignore')

    def get_line(self, cut_lines=True, line_break="\n"):
        self.process = subprocess.Popen(
            self.command.split(" "), bufsize=self.bufsize,
            stdout=subprocess.PIPE)
        # Pull fixed-size chunks until EOF (read() returns b'').
        chunks = iter(lambda: self.process.stdout.read(self.bufsize), b'')
        if not cut_lines:
            for raw in chunks:
                yield self._decode(raw)
            return
        pending = ""
        for raw in chunks:
            pending += self._decode(raw)
            complete, sep, pending = pending.rpartition(line_break)
            if sep:
                yield from complete.split(line_break)
        if pending:
            yield pending
