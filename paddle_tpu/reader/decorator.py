"""Reader decorators.

Reference parity: python/paddle/v2/reader/decorator.py (map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers, PipeReader).
Same contracts; implementation is plain python threading — the heavy
multi-process machinery the reference needs for CPU-bound python feeds is
replaced by the native C++ prefetcher for the TPU input pipeline (see
paddle_tpu/runtime/native.py), with these as the portable fallback.
"""
import itertools
import random
import subprocess
import threading
import queue as _queue

__all__ = ['map_readers', 'buffered', 'compose', 'chain', 'shuffle',
           'firstn', 'xmap_readers', 'cache', 'PipeReader',
           'ComposeNotAligned']


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """Creator whose samples are ``func(r1_sample, r2_sample, ...)``."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of ``buf_size`` samples."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers: all of r1, then all of r2, ..."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


def compose(*readers, **kwargs):
    """Zip readers into combined tuples.  With check_alignment=True
    (default) raises ComposeNotAligned if they end at different times."""
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned.")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Pre-read up to ``size`` samples into a queue on a worker thread.
    A source error (e.g. a recordio CRC mismatch) re-raises in the
    consumer instead of silently truncating the stream."""

    class EndSignal(object):
        def __init__(self, error=None):
            self.error = error

    def read_worker(r, q):
        try:
            for d in r:
                q.put(d)
        except BaseException as e:
            q.put(EndSignal(e))
        else:
            q.put(EndSignal())

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while not isinstance(e, EndSignal):
            yield e
            e = q.get()
        if e.error is not None:
            raise e.error

    return data_reader


def firstn(reader, n):
    """Only the first ``n`` samples."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def cache(reader):
    """Materialise the reader once; replay from memory thereafter."""
    all_data = []
    filled = []

    def cache_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        for item in all_data:
            yield item

    return cache_reader


class XmapEndSignal(object):
    pass


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel ``map``: ``process_num`` worker threads apply ``mapper``
    over samples with a bounded queue of ``buffer_size``.

    Reference parity: decorator.py xmap_readers (threads there too).  When
    the native runtime builds (runtime/native.py), the handoff queues live
    in C++ and their blocking ops release the GIL (N1); this python-queue
    body is the fallback.
    """
    from ..runtime import native as _native
    if _native.available():
        from ..runtime.prefetch import xmap_native
        return xmap_native(mapper, reader, process_num, buffer_size, order)
    end = XmapEndSignal()

    def read_worker(r, in_q):
        for i in r():
            in_q.put(i)
        in_q.put(end)

    def order_read_worker(r, in_q):
        for i, d in enumerate(r()):
            in_q.put((i, d))
        in_q.put(end)

    def handle_worker(in_q, out_q, mapper):
        sample = in_q.get()
        while not isinstance(sample, XmapEndSignal):
            out_q.put(mapper(sample))
            sample = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def order_handle_worker(in_q, out_q, mapper, out_order):
        ins = in_q.get()
        while not isinstance(ins, XmapEndSignal):
            order, sample = ins
            result = mapper(sample)
            while order != out_order[0]:
                pass
            out_q.put(result)
            out_order[0] += 1
            ins = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        out_order = [0]
        target = order_read_worker if order else read_worker
        t = threading.Thread(target=target, args=(reader, in_q))
        t.daemon = True
        t.start()
        target = order_handle_worker if order else handle_worker
        args = (in_q, out_q, mapper, out_order) if order else \
            (in_q, out_q, mapper)
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=target, args=args)
            w.daemon = True
            w.start()
            workers.append(w)
        finish = 0
        while finish < process_num:
            sample = out_q.get()
            if isinstance(sample, XmapEndSignal):
                finish += 1
            else:
                yield sample

    return xreader


class PipeReader(object):
    """Stream samples out of a shell command's stdout (reference:
    decorator.py PipeReader — used for HDFS cat pipelines)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("left_cmd must be a string")
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type
        if file_type == "gzip":
            import zlib
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)

    def get_line(self, cut_lines=True, line_break="\n"):
        self.process = subprocess.Popen(
            self.command.split(" "), bufsize=self.bufsize,
            stdout=subprocess.PIPE)
        remained = ""
        while True:
            buff = self.process.stdout.read(self.bufsize)
            if buff:
                if self.file_type == "gzip":
                    decomp_buff = self.dec.decompress(buff).decode('utf-8',
                                                                   'ignore')
                elif self.file_type == "plain":
                    decomp_buff = buff.decode('utf-8', 'ignore')
                else:
                    raise TypeError("file_type %s is not allowed" %
                                    self.file_type)
                if cut_lines:
                    lines = (remained + decomp_buff).split(line_break)
                    remained = lines.pop(-1)
                    for line in lines:
                        yield line
                else:
                    yield decomp_buff
            else:
                if remained:
                    yield remained
                break
