"""Reader decorators.

Reference parity: python/paddle/v2/reader/decorator.py (map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers, PipeReader).
Same contracts; implementation is plain python threading — the heavy
multi-process machinery the reference needs for CPU-bound python feeds is
replaced by the native C++ prefetcher for the TPU input pipeline (see
paddle_tpu/runtime/native.py), with these as the portable fallback.
"""
import itertools
import random
import subprocess
import threading
import queue as _queue

__all__ = ['map_readers', 'buffered', 'compose', 'chain', 'shuffle',
           'firstn', 'xmap_readers', 'cache', 'PipeReader',
           'ComposeNotAligned']


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """Creator whose samples are ``func(r1_sample, r2_sample, ...)``."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding window of ``buf_size`` samples.

    The stream is consumed in windows of ``buf_size``; each window is
    permuted (module-level ``random``, so ``random.seed`` controls it)
    and drained before the next window is pulled.  Windowing via
    ``itertools.islice`` keeps at most one window resident.
    """

    def data_reader():
        it = iter(reader())
        if buf_size <= 0:  # degenerate window: plain pass-through
            yield from it
            return
        while True:
            window = list(itertools.islice(it, buf_size))
            if not window:
                return
            random.shuffle(window)
            yield from window

    return data_reader


def chain(*readers):
    """Concatenate readers: all of r1, then all of r2, ..."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


def compose(*readers, **kwargs):
    """Zip readers into combined tuples.  With check_alignment=True
    (default) raises ComposeNotAligned if they end at different times."""
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned.")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Pre-read up to ``size`` samples into a queue on a worker thread.
    A source error (e.g. a recordio CRC mismatch) re-raises in the
    consumer instead of silently truncating the stream."""

    class EndSignal(object):
        def __init__(self, error=None):
            self.error = error

    def read_worker(r, q):
        try:
            for d in r:
                q.put(d)
        except BaseException as e:
            q.put(EndSignal(e))
        else:
            q.put(EndSignal())

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while not isinstance(e, EndSignal):
            yield e
            e = q.get()
        if e.error is not None:
            raise e.error

    return data_reader


def firstn(reader, n):
    """Truncate the stream after ``n`` samples (``itertools.islice``)."""

    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def cache(reader):
    """Materialise the reader once; replay from memory thereafter."""
    all_data = []
    filled = []

    def cache_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        for item in all_data:
            yield item

    return cache_reader


class XmapEndSignal(object):
    pass


class _XmapError(object):
    """A mapper exception in transit from a worker to the consumer."""

    def __init__(self, error):
        self.error = error


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel ``map``: ``process_num`` worker threads apply ``mapper``
    over samples with a bounded queue of ``buffer_size``.

    Reference parity: decorator.py xmap_readers (threads there too).  When
    the native runtime builds (runtime/native.py), the handoff queues live
    in C++ and their blocking ops release the GIL (N1); this python-queue
    body is the fallback.
    """
    from ..runtime import native as _native
    if _native.available():
        from ..runtime.prefetch import xmap_native
        return xmap_native(mapper, reader, process_num, buffer_size, order)
    end = XmapEndSignal()

    def read_worker(r, in_q):
        for i in r():
            in_q.put(i)
        in_q.put(end)

    def order_read_worker(r, in_q):
        for i, d in enumerate(r()):
            in_q.put((i, d))
        in_q.put(end)

    def handle_worker(in_q, out_q, mapper):
        sample = in_q.get()
        while not isinstance(sample, XmapEndSignal):
            try:
                result = mapper(sample)
            except BaseException as e:
                # surface the mapper error in the consumer instead of
                # dying silently (which would leave out_q one EndSignal
                # short and hang the reader).  The error goes out FIRST —
                # the consumer always drains out_q, while in_q may be
                # full with no other drainer (a blocking put there could
                # deadlock); waking peers is best-effort.
                out_q.put(_XmapError(e))
                try:
                    in_q.put_nowait(end)
                except _queue.Full:
                    pass
                return
            out_q.put(result)
            sample = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def order_handle_worker(in_q, out_q, mapper, turn):
        # ``turn`` is (Condition, [next_index]): a worker may emit its
        # result only when its sample index is the next one due, so the
        # output order matches the input order without a spin-wait.
        cond, nxt = turn
        ins = in_q.get()
        while not isinstance(ins, XmapEndSignal):
            order, sample = ins
            try:
                result = mapper(sample)
            except BaseException as e:
                # still take our turn (so peers blocked on nxt don't
                # wait forever), then surface the error
                with cond:
                    cond.wait_for(lambda: nxt[0] == order)
                    out_q.put(_XmapError(e))
                    nxt[0] += 1
                    cond.notify_all()
                try:
                    in_q.put_nowait(end)
                except _queue.Full:
                    pass
                return
            with cond:
                cond.wait_for(lambda: nxt[0] == order)
                out_q.put(result)
                nxt[0] += 1
                cond.notify_all()
            ins = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        turn = (threading.Condition(), [0])
        target = order_read_worker if order else read_worker
        t = threading.Thread(target=target, args=(reader, in_q))
        t.daemon = True
        t.start()
        target = order_handle_worker if order else handle_worker
        args = (in_q, out_q, mapper, turn) if order else \
            (in_q, out_q, mapper)
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=target, args=args)
            w.daemon = True
            w.start()
            workers.append(w)
        finish = 0
        while finish < process_num:
            sample = out_q.get()
            if isinstance(sample, XmapEndSignal):
                finish += 1
            elif isinstance(sample, _XmapError):
                raise sample.error
            else:
                yield sample

    return xreader


class PipeReader(object):
    """Stream samples out of a shell command's stdout (reference:
    decorator.py PipeReader — used for HDFS cat pipelines)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("left_cmd must be a string")
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type
        if file_type == "gzip":
            import zlib
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)

    def _decode(self, raw):
        if self.file_type == "gzip":
            raw = self.dec.decompress(raw)
        elif self.file_type != "plain":
            raise TypeError("file_type %s is not allowed" % self.file_type)
        return raw.decode('utf-8', 'ignore')

    def get_line(self, cut_lines=True, line_break="\n"):
        self.process = subprocess.Popen(
            self.command.split(" "), bufsize=self.bufsize,
            stdout=subprocess.PIPE)
        # Pull fixed-size chunks until EOF (read() returns b'').
        chunks = iter(lambda: self.process.stdout.read(self.bufsize), b'')
        if not cut_lines:
            for raw in chunks:
                yield self._decode(raw)
            return
        pending = ""
        for raw in chunks:
            pending += self._decode(raw)
            complete, sep, pending = pending.rpartition(line_break)
            if sep:
                yield from complete.split(line_break)
        if pending:
            yield pending
