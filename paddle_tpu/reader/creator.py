"""Reader creators (V1).

Reference parity: python/paddle/v2/reader/creator.py — np_array,
text_file, recordio.  The recordio creator reads the record files
`datasets.common.convert` writes (C++ reader when the native runtime is
built, io_recordio fallback otherwise).
"""
import pickle

__all__ = ['np_array', 'text_file', 'recordio']


def np_array(x):
    """Creator yielding rows of a numpy array (reference np_array)."""

    def reader():
        import numpy as np
        for row in np.asarray(x):
            yield row

    return reader


def text_file(path):
    """Creator yielding stripped lines of a text file."""

    def reader():
        with open(path, 'r') as f:
            for line in f:
                yield line.rstrip('\n')

    return reader


def recordio(paths, buf_size=100):
    """Creator yielding unpickled samples from record files written by
    datasets.common.convert (reference creator.recordio over the cluster
    recordio chunks).  `paths` is a path, a list, or a comma-joined
    string of paths; `buf_size` samples are read ahead on a background
    thread (reference parity)."""
    if isinstance(paths, str):
        paths = paths.split(',')
    elif not isinstance(paths, (list, tuple)):
        paths = [paths]

    def reader():
        from ..runtime.native import NativeRecordReader
        for path in paths:
            with NativeRecordReader(path) as r:
                for blob in r:
                    yield pickle.loads(blob)

    from .decorator import buffered
    return buffered(reader, buf_size) if buf_size else reader
