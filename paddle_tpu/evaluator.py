"""Evaluators — accumulate metrics across minibatches.

Reference parity: python/paddle/v2/fluid/evaluator.py (Accuracy,
ChunkEvaluator).  States are persistable vars updated in-graph; eval() reads
them out of the scope.
"""
import numpy as np

from . import layers
from .core.program import Program, Variable, unique_name
from .initializer import ConstantInitializer
from .layers.layer_helper import LayerHelper

__all__ = ['Accuracy', 'ChunkEvaluator', 'Evaluator', 'StreamingAUC']


def _clone_var_(block, var):
    return block.create_var(
        name=var.name, shape=var.shape, dtype=var.dtype,
        persistable=True)


class Evaluator(object):
    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        if reset_program is None:
            reset_program = Program()
        from .core.program import program_guard
        with program_guard(reset_program):
            for var in self.states:
                g_var = _clone_var_(reset_program.current_block(), var)
                layers.fill_constant(
                    shape=g_var.shape, value=0.0, dtype=g_var.dtype,
                    out=g_var)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def create_state(self, suffix, dtype, shape):
        state = self.helper.create_global_variable(
            name=unique_name(self.helper.name + "_" + suffix),
            persistable=True, dtype=dtype, shape=shape)
        self.helper.set_variable_initializer(state, ConstantInitializer(0.0))
        self.states.append(state)
        return state


class StreamingAUC(object):
    """Mergeable streaming AUC over a fixed-bin rank histogram.

    Scores land in ``bins`` equal-width bins over ``[lo, hi]``; the
    evaluator keeps one positive and one negative count per bin, so the
    whole state is two int64 vectors — O(bins) memory regardless of how
    many samples stream through, updates from any thread or process can
    be :meth:`merge`\\ d exactly (bin counts add), and :meth:`eval` is
    the Mann-Whitney rank statistic over the histogram:

        AUC = sum_b pos_b * (neg_below_b + neg_b / 2) / (P * N)

    which equals the EXACT pairwise AUC of the samples with scores
    quantized to their bins (same-bin pairs count 1/2, the standard tie
    convention) — so the only approximation is the score quantization,
    bounded by the bin width.  This is the ONE AUC implementation the
    online-training eval gate and the live-traffic monitor share
    (``paddle_tpu/online/controller.py``): a gate verdict and the
    post-deploy regression check are never comparing two different
    definitions of the metric.

    Update/merge order is irrelevant (integer adds), so chunked
    updates, a one-shot update, and a merge of per-worker partials are
    bitwise-identical — the property the golden tests pin.
    """

    __slots__ = ('bins', 'lo', 'hi', '_pos', '_neg')

    def __init__(self, bins=2048, lo=0.0, hi=1.0):
        if bins < 2:
            raise ValueError("StreamingAUC needs >= 2 bins, got %d"
                             % bins)
        if not hi > lo:
            raise ValueError("StreamingAUC needs hi > lo, got [%r, %r]"
                             % (lo, hi))
        self.bins = int(bins)
        self.lo = float(lo)
        self.hi = float(hi)
        self._pos = np.zeros(self.bins, dtype=np.int64)
        self._neg = np.zeros(self.bins, dtype=np.int64)

    def update(self, scores, labels):
        """Accumulate a batch: ``scores`` float-like, ``labels`` 0/1
        (anything nonzero counts positive).  Out-of-range scores clamp
        to the edge bins.  Returns self (chainable)."""
        s = np.asarray(scores, dtype=np.float64).reshape(-1)
        y = np.asarray(labels).reshape(-1)
        if s.shape != y.shape:
            raise ValueError(
                "scores and labels disagree: %d vs %d samples"
                % (s.size, y.size))
        if s.size == 0:
            return self
        idx = ((s - self.lo) * (self.bins / (self.hi - self.lo)))
        idx = np.clip(idx.astype(np.int64), 0, self.bins - 1)
        pos = y != 0
        self._pos += np.bincount(idx[pos], minlength=self.bins)
        self._neg += np.bincount(idx[~pos], minlength=self.bins)
        return self

    def merge(self, other):
        """Fold another StreamingAUC's counts into this one (exact:
        histograms add).  Bin layouts must match."""
        if (other.bins, other.lo, other.hi) != (self.bins, self.lo,
                                                self.hi):
            raise ValueError(
                "cannot merge StreamingAUC(bins=%d, [%r, %r]) into "
                "(bins=%d, [%r, %r])" % (other.bins, other.lo, other.hi,
                                         self.bins, self.lo, self.hi))
        self._pos += other._pos
        self._neg += other._neg
        return self

    def eval(self):
        """AUC of everything accumulated so far; 0.5 when either class
        is empty (undefined — the neutral value keeps gate arithmetic
        total)."""
        p = int(self._pos.sum())
        n = int(self._neg.sum())
        if p == 0 or n == 0:
            return 0.5
        neg_below = np.cumsum(self._neg) - self._neg
        num = float(np.sum(self._pos * (neg_below + self._neg * 0.5)))
        return num / (float(p) * float(n))

    @property
    def count(self):
        return int(self._pos.sum() + self._neg.sum())

    @property
    def positives(self):
        return int(self._pos.sum())

    @property
    def negatives(self):
        return int(self._neg.sum())

    def reset(self):
        self._pos[:] = 0
        self._neg[:] = 0
        return self


class Accuracy(Evaluator):
    """Streaming top-k accuracy."""

    def __init__(self, input, label, k=1, **kwargs):
        super(Accuracy, self).__init__("accuracy", **kwargs)
        total = self.create_state(dtype='float32', shape=[1],
                                  suffix='total')
        correct = self.create_state(dtype='float32', shape=[1],
                                    suffix='correct')
        batch_correct = self.helper.create_tmp_variable('int32',
                                                        stop_gradient=True)
        batch_total = self.helper.create_tmp_variable('int32',
                                                      stop_gradient=True)
        acc = layers.accuracy(input=input, label=label, k=k,
                              correct=batch_correct, total=batch_total)
        bc_f = layers.cast(batch_correct, 'float32')
        bt_f = layers.cast(batch_total, 'float32')
        layers.sums(input=[total, bt_f], out=total)
        layers.sums(input=[correct, bc_f], out=correct)
        self.metrics.append(acc)
        self._total = total
        self._correct = correct

    def eval(self, executor, eval_program=None):
        scope = executor  # allow passing executor; read from global scope
        from .core.scope import global_scope
        total = float(global_scope().get_numpy(self._total.name)[0])
        correct = float(global_scope().get_numpy(self._correct.name)[0])
        return np.array([correct / max(total, 1.0)], dtype=np.float32)


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (parity with fluid ChunkEvaluator; counts come
    from the chunk_eval op)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, **kwargs):
        super(ChunkEvaluator, self).__init__("chunk_eval", **kwargs)
        main_program = self.helper.main_program
        num_infer_chunks = self.create_state(
            dtype='float32', shape=[1], suffix='num_infer_chunks')
        num_label_chunks = self.create_state(
            dtype='float32', shape=[1], suffix='num_label_chunks')
        num_correct_chunks = self.create_state(
            dtype='float32', shape=[1], suffix='num_correct_chunks')
        precision, recall, f1, infer_cnt, label_cnt, correct_cnt = \
            layers.chunk_eval(
                input=input, label=label, chunk_scheme=chunk_scheme,
                num_chunk_types=num_chunk_types,
                excluded_chunk_types=excluded_chunk_types)
        layers.sums(input=[num_infer_chunks,
                           layers.cast(infer_cnt, 'float32')],
                    out=num_infer_chunks)
        layers.sums(input=[num_label_chunks,
                           layers.cast(label_cnt, 'float32')],
                    out=num_label_chunks)
        layers.sums(input=[num_correct_chunks,
                           layers.cast(correct_cnt, 'float32')],
                    out=num_correct_chunks)
        self.metrics.extend([precision, recall, f1])
        self._states = (num_infer_chunks, num_label_chunks,
                        num_correct_chunks)

    def eval(self, executor, eval_program=None):
        from .core.scope import global_scope
        infer = float(global_scope().get_numpy(self._states[0].name)[0])
        label = float(global_scope().get_numpy(self._states[1].name)[0])
        correct = float(global_scope().get_numpy(self._states[2].name)[0])
        precision = correct / infer if infer else 0.0
        recall = correct / label if label else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if precision + recall else 0.0
        return np.array([precision, recall, f1], dtype=np.float32)
