"""Optimizers.

Reference parity: python/paddle/v2/fluid/optimizer.py (SGD, Momentum,
Adagrad, Adam, Adamax, DecayedAdagrad; plus Adadelta/RMSProp/Ftrl whose ops
exist in paddle/operators).  minimize() = functional autodiff
(core/backward.py) + clip + regularization + per-param update ops; the whole
thing compiles into the same single XLA program as the forward pass.

AMP contract (PADDLE_TPU_AMP — transpiler/amp.py): every optimizer op is
black-listed, so updates always apply to the f32 master weights.  The pass
never renames a Parameter or an accumulator; under bf16/f16 the gradients
reaching the `Grad` slot are already unscaled f32 (the autodiff casts to
the leaf dtype, check_finite_and_unscale divides the loss scale back out
upstream of clip/regularization), and in f16 mode each optimize-role op is
gated on the overflow flag (`amp_gate_var` attr, applied by
executor._run_one) so a non-finite step leaves params, moments, and the
beta-pow/global-step counters untouched (per loss-group in
multi-minimize programs — each group gates on the verdicts of its own
and earlier autodiffs).  Nothing here needs to know any of that — which
is the point.
"""
from collections import defaultdict

from .core.backward import append_backward
from .core.program import Variable, default_startup_program, unique_name
from .initializer import ConstantInitializer
from .layers.layer_helper import LayerHelper
from .regularizer import append_regularization_ops
from .clip import append_gradient_clip_ops, error_clip_callback

__all__ = [
    'Optimizer', 'SGDOptimizer', 'MomentumOptimizer', 'AdagradOptimizer',
    'AdamOptimizer', 'AdamaxOptimizer', 'DecayedAdagradOptimizer',
    'AdadeltaOptimizer', 'RMSPropOptimizer', 'FtrlOptimizer',
    'SGD', 'Momentum', 'Adagrad', 'Adam', 'Adamax', 'DecayedAdagrad',
    'Adadelta', 'RMSProp', 'Ftrl',
]


class Optimizer(object):
    """Base optimizer.  Subclasses set `type` (the update op) and implement
    _append_optimize_op."""

    type = None

    def __init__(self, learning_rate, global_step=None, regularization=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning rate should be float or Variable")
        self._global_step = global_step
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = defaultdict(dict)
        self.helper = None

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self, program):
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program._uid] = self._learning_rate
            return
        if program._uid in self._learning_rate_map:
            return
        from .layers.tensor import create_global_var
        lr = create_global_var(
            name=unique_name("learning_rate"),
            shape=[1], value=float(self._learning_rate),
            dtype='float32', persistable=True)
        self._learning_rate_map[program._uid] = lr

    def _global_learning_rate(self, program):
        return self._learning_rate_map[program._uid]

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = param.optimize_attr['learning_rate'] \
            if getattr(param, 'optimize_attr', None) else 1.0
        lr = self._global_learning_rate(param.block.program)
        if param_lr == 1.0:
            return lr
        from .layers import ops as layer_ops
        return layer_ops.scale(lr, scale=param_lr)

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, dtype='float32',
                         fill_value=0.0, shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        assert self.helper is not None
        var_name = unique_name(param.name + "_" + name)
        var = self.helper.create_global_variable(
            name=var_name, persistable=True,
            shape=shape or param.shape, dtype=dtype)
        self.helper.set_variable_initializer(
            var, ConstantInitializer(fill_value))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block):
        pass

    def _increment_global_step(self, block):
        if self._global_step is None:
            return
        self.helper.append_op(
            type='increment',
            inputs={'X': [self._global_step]},
            outputs={'Out': [self._global_step]},
            attrs={'step': 1.0},
            infer_shape=False)

    # -- main entry ----------------------------------------------------------
    def create_optimization_pass(self, parameters_and_grads, loss,
                                 startup_program=None):
        program = loss.block.program
        block = program.global_block()
        self.helper = LayerHelper(
            self.__class__.__name__,
            main_program=program,
            startup_program=startup_program or default_startup_program())
        self._create_accumulators(
            block, [p for p, g in parameters_and_grads if g is not None])
        self._create_global_learning_rate(program)

        optimize_ops = []
        with program.op_role_guard('optimize'):
            for param_and_grad in parameters_and_grads:
                if param_and_grad[1] is None:
                    continue
                if getattr(param_and_grad[0], 'trainable', True):
                    optimize_ops.append(
                        self._append_optimize_op(block, param_and_grad))
            self._finish_update(block)
            self._increment_global_step(block)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        # clip/regularization ops transform grads: backward role, so they run
        # at top level after the autodiff op (never re-traced by a later
        # minimize() pass on the same program).
        with loss.block.program.op_role_guard('backward'):
            params_grads = append_gradient_clip_ops(params_grads)
            params_grads = self._apply_regularization(params_grads)
        optimize_ops = self.create_optimization_pass(
            params_grads, loss, startup_program)
        return optimize_ops, params_grads

    def _apply_regularization(self, params_grads):
        """Weave the per-param/global regularizers into the grad
        stream.  The one seam optimizers override when they can fold a
        regularizer into their apply op instead (SGD's fused L2 weight
        decay) — overriding here keeps a single copy of the minimize()
        pipeline."""
        return append_regularization_ops(params_grads,
                                         self.regularization)

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    type = 'sgd'

    def _apply_regularization(self, params_grads):
        """SGD folds L2 weight decay into the sgd op itself
        (`weight_decay` attr → one fused apply pass, incl. the Pallas
        dense kernel's fused arm) instead of weaving scale+sum ops per
        param: p - lr*(g + wd*p) is the identical expression the weave
        builds, minus two ops and one grad-sized buffer per parameter.
        Only DENSE grads of f32-or-wider params fold — a SelectedRows
        grad's row-wise apply never touches untouched rows, while decay
        must shrink the whole table, so sparse params keep the weave;
        a low-precision (bf16/f16) param keeps the weave because its
        scale+sum intermediates round in param dtype, and the fused
        f32 expression would silently change those numerics.  L1 (sign
        chain) and per-param non-L2 regularizers keep the weave too."""
        from .core import datatypes
        from .regularizer import L2DecayRegularizer
        self._fused_decay = {}
        gblock = next((g.block for _, g in params_grads
                       if g is not None), None)
        sparse_grads = set()
        if gblock is not None:
            for op in gblock.ops:
                if op.type == 'sparse_grad_assemble':
                    sparse_grads.update(op.output_arg_names)
        weave = []
        for p, g in params_grads:
            reg = getattr(p, 'regularizer', None)
            if reg is None:
                reg = self.regularization
            if (g is not None and
                    isinstance(reg, L2DecayRegularizer) and
                    reg._regularization_coeff and
                    g.name not in sparse_grads and
                    not datatypes.is_low_precision(p.dtype)):
                self._fused_decay[p.name] = float(
                    reg._regularization_coeff)
            else:
                weave.append((p, g))
        woven = iter(append_regularization_ops(weave,
                                               self.regularization))
        return [(p, g) if p.name in self._fused_decay else next(woven)
                for p, g in params_grads]

    def _append_optimize_op(self, block, param_and_grad):
        attrs = {}
        wd = getattr(self, '_fused_decay', {}).get(
            param_and_grad[0].name)
        if wd:
            attrs['weight_decay'] = wd
        return self.helper.append_op(
            type='sgd',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param_and_grad[0]]},
            attrs=attrs,
            infer_shape=False)


class MomentumOptimizer(Optimizer):
    type = 'momentum'
    _velocity_acc_str = 'velocity'

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 **kwargs):
        super(MomentumOptimizer, self).__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity_acc = self._get_accumulator(self._velocity_acc_str,
                                             param_and_grad[0])
        return self.helper.append_op(
            type='momentum',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'Velocity': [velocity_acc],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param_and_grad[0]],
                     'VelocityOut': [velocity_acc]},
            attrs={'mu': self._momentum,
                   'use_nesterov': self._use_nesterov},
            infer_shape=False)


class AdagradOptimizer(Optimizer):
    type = 'adagrad'
    _moment_acc_str = 'moment'

    def __init__(self, learning_rate, epsilon=1.0e-6, **kwargs):
        super(AdagradOptimizer, self).__init__(learning_rate, **kwargs)
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return self.helper.append_op(
            type='adagrad',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'Moment': [moment_acc],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param_and_grad[0]],
                     'MomentOut': [moment_acc]},
            attrs={'epsilon': self._epsilon},
            infer_shape=False)


class AdamOptimizer(Optimizer):
    type = 'adam'
    _moment1_acc_str = 'moment1'
    _moment2_acc_str = 'moment2'

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super(AdamOptimizer, self).__init__(learning_rate, **kwargs)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
        self._beta1_pow_acc = self.helper.create_global_variable(
            name=unique_name('beta1_pow_acc'), persistable=True,
            shape=[1], dtype='float32')
        self.helper.set_variable_initializer(
            self._beta1_pow_acc, ConstantInitializer(self._beta1))
        self._beta2_pow_acc = self.helper.create_global_variable(
            name=unique_name('beta2_pow_acc'), persistable=True,
            shape=[1], dtype='float32')
        self.helper.set_variable_initializer(
            self._beta2_pow_acc, ConstantInitializer(self._beta2))

    def _append_optimize_op(self, block, param_and_grad):
        moment1 = self._get_accumulator(self._moment1_acc_str,
                                        param_and_grad[0])
        moment2 = self._get_accumulator(self._moment2_acc_str,
                                        param_and_grad[0])
        return self.helper.append_op(
            type='adam',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'LearningRate': [self._create_param_lr(param_and_grad)],
                    'Moment1': [moment1], 'Moment2': [moment2],
                    'Beta1Pow': [self._beta1_pow_acc],
                    'Beta2Pow': [self._beta2_pow_acc]},
            outputs={'ParamOut': [param_and_grad[0]],
                     'Moment1Out': [moment1], 'Moment2Out': [moment2]},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon},
            infer_shape=False)

    def _finish_update(self, block):
        self.helper.append_op(
            type='scale', inputs={'X': [self._beta1_pow_acc]},
            outputs={'Out': [self._beta1_pow_acc]},
            attrs={'scale': self._beta1}, infer_shape=False)
        self.helper.append_op(
            type='scale', inputs={'X': [self._beta2_pow_acc]},
            outputs={'Out': [self._beta2_pow_acc]},
            attrs={'scale': self._beta2}, infer_shape=False)


class AdamaxOptimizer(Optimizer):
    type = 'adamax'
    _moment_acc_str = 'moment'
    _inf_norm_acc_str = 'inf_norm'

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super(AdamaxOptimizer, self).__init__(learning_rate, **kwargs)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
        self._beta1_pow_acc = self.helper.create_global_variable(
            name=unique_name('beta1_pow_acc'), persistable=True,
            shape=[1], dtype='float32')
        self.helper.set_variable_initializer(
            self._beta1_pow_acc, ConstantInitializer(self._beta1))

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str,
                                       param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str,
                                         param_and_grad[0])
        return self.helper.append_op(
            type='adamax',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'LearningRate': [self._create_param_lr(param_and_grad)],
                    'Moment': [moment], 'InfNorm': [inf_norm],
                    'Beta1Pow': [self._beta1_pow_acc]},
            outputs={'ParamOut': [param_and_grad[0]],
                     'MomentOut': [moment], 'InfNormOut': [inf_norm]},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon},
            infer_shape=False)

    def _finish_update(self, block):
        self.helper.append_op(
            type='scale', inputs={'X': [self._beta1_pow_acc]},
            outputs={'Out': [self._beta1_pow_acc]},
            attrs={'scale': self._beta1}, infer_shape=False)


class DecayedAdagradOptimizer(Optimizer):
    type = 'decayed_adagrad'
    _moment_acc_str = 'moment'

    def __init__(self, learning_rate, decay=0.95, epsilon=1.0e-6, **kwargs):
        super(DecayedAdagradOptimizer, self).__init__(learning_rate,
                                                      **kwargs)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return self.helper.append_op(
            type='decayed_adagrad',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'Moment': [moment_acc],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param_and_grad[0]],
                     'MomentOut': [moment_acc]},
            attrs={'decay': self._decay, 'epsilon': self._epsilon},
            infer_shape=False)


class AdadeltaOptimizer(Optimizer):
    type = 'adadelta'

    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1.0e-6,
                 **kwargs):
        super(AdadeltaOptimizer, self).__init__(learning_rate, **kwargs)
        self._rho = rho
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('avg_squared_grad', p)
            self._add_accumulator('avg_squared_update', p)

    def _append_optimize_op(self, block, param_and_grad):
        asg = self._get_accumulator('avg_squared_grad', param_and_grad[0])
        asu = self._get_accumulator('avg_squared_update', param_and_grad[0])
        return self.helper.append_op(
            type='adadelta',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'AvgSquaredGrad': [asg], 'AvgSquaredUpdate': [asu]},
            outputs={'ParamOut': [param_and_grad[0]],
                     'AvgSquaredGradOut': [asg],
                     'AvgSquaredUpdateOut': [asu]},
            attrs={'rho': self._rho, 'epsilon': self._epsilon},
            infer_shape=False)


class RMSPropOptimizer(Optimizer):
    type = 'rmsprop'

    def __init__(self, learning_rate, rho=0.95, epsilon=1.0e-6,
                 momentum=0.0, **kwargs):
        super(RMSPropOptimizer, self).__init__(learning_rate, **kwargs)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('mean_square', p)
            self._add_accumulator('momentum', p)

    def _append_optimize_op(self, block, param_and_grad):
        ms = self._get_accumulator('mean_square', param_and_grad[0])
        mom = self._get_accumulator('momentum', param_and_grad[0])
        return self.helper.append_op(
            type='rmsprop',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'MeanSquare': [ms], 'Moment': [mom],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param_and_grad[0]],
                     'MeanSquareOut': [ms], 'MomentOut': [mom]},
            attrs={'decay': self._rho, 'epsilon': self._epsilon,
                   'momentum': self._momentum},
            infer_shape=False)


class FtrlOptimizer(Optimizer):
    type = 'ftrl'

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        super(FtrlOptimizer, self).__init__(learning_rate, **kwargs)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('squared', p)
            self._add_accumulator('linear', p)

    def _append_optimize_op(self, block, param_and_grad):
        sq = self._get_accumulator('squared', param_and_grad[0])
        lin = self._get_accumulator('linear', param_and_grad[0])
        return self.helper.append_op(
            type='ftrl',
            inputs={'Param': [param_and_grad[0]],
                    'Grad': [param_and_grad[1]],
                    'SquaredAccumulator': [sq], 'LinearAccumulator': [lin],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param_and_grad[0]],
                     'SquaredAccumOut': [sq], 'LinearAccumOut': [lin]},
            attrs={'l1': self._l1, 'l2': self._l2,
                   'lr_power': self._lr_power},
            infer_shape=False)


# fluid-style short aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
