"""V6 — v2 inference: paddle.infer over the Fluid executor.

Reference parity: python/paddle/v2/inference.py (Inference.iter_infer /
infer with field selection).  The output program is the pruned
inference_optimize'd slice ending at `output_layer`.
"""
import numpy as np

from .parameters import Parameters
from ..core.executor import Executor
from ..core.place import default_place
from ..data_feeder import DataFeeder

__all__ = ['Inference', 'infer']


class Inference(object):
    def __init__(self, output_layer, parameters):
        outputs = (output_layer if isinstance(output_layer, (list, tuple))
                   else [output_layer])
        program = outputs[0].block.program
        self.__outputs__ = outputs
        self.__program__ = program.prune(
            targets=list(outputs)).inference_optimize()
        self.__parameters__ = parameters
        self.__exe__ = Executor(default_place())

    def _feed_vars(self, feeding):
        block = self.__program__.global_block()
        # prune() drops ops but keeps var declarations: only data vars some
        # surviving op actually reads are real inputs
        read = set()
        for b in self.__program__.blocks:
            for op in b.ops:
                read.update(op.input_arg_names)
        data_vars = [v for v in block.vars.values()
                     if getattr(v, 'is_data', False) and v.name in read]
        if feeding is None:
            return data_vars
        order = sorted(feeding, key=lambda k: feeding[k])
        return [block.var(n) for n in order]

    def iter_infer_field(self, field, input, feeding=None, batch_size=None):
        assert field == 'value', "only the 'value' field is supported"
        feeder = DataFeeder(place=self.__exe__.place,
                            feed_list=self._feed_vars(feeding))
        bs = batch_size or len(input)
        for i in range(0, len(input), bs):
            outs = self.__exe__.run(
                self.__program__, feed=feeder.feed(input[i:i + bs]),
                fetch_list=[o.name for o in self.__outputs__])
            yield [np.asarray(o) for o in outs]

    def infer(self, input, field='value', feeding=None, batch_size=None):
        parts = list(self.iter_infer_field(field, input, feeding,
                                           batch_size))
        joined = [np.concatenate([p[i] for p in parts], axis=0)
                  for i in range(len(self.__outputs__))]
        return joined[0] if len(joined) == 1 else joined


def infer(output_layer, parameters, input, feeding=None, field='value'):
    """One-shot inference (reference paddle.infer)."""
    return Inference(output_layer, parameters).infer(
        input, field=field, feeding=feeding)
