"""V5 — v2 trainer: the SGD event-loop API over the Fluid executor.

Reference parity: python/paddle/v2/trainer.py:86 (SGD.train) — reader +
topology + update rule in one object, firing BeginPass/BeginIteration/
EndIteration/EndPass events.  The reference drives the legacy C++
GradientMachine; here the same surface drives the one-HLO-per-step
Executor, so a v2-style script runs unchanged on TPU.
"""
import warnings

import numpy as np

from . import event as v2_event
from .parameters import Parameters
from ..core.executor import Executor
from ..core.place import default_place
from ..core.program import default_startup_program
from ..data_feeder import DataFeeder
from ..optimizer import Optimizer

__all__ = ['SGD']


def default_event_handler(event):
    pass


class SGD(object):
    """Trainer: combines cost, Parameters and an optimizer.

    :param cost: fluid loss Variable (the topology's target).
    :param parameters: highlevel.parameters.Parameters (from
        parameters.create(cost)).
    :param update_equation: a fluid optimizer (SGDOptimizer, Adam...).
    :param extra_layers: extra fetch targets kept alive in the program
        (parity with reference extra_layers).
    """

    def __init__(self, cost, parameters, update_equation, extra_layers=None,
                 is_local=True, metrics=None):
        if not isinstance(parameters, Parameters):
            raise TypeError('parameters should be '
                            'highlevel.parameters.Parameters')
        if not isinstance(update_equation, Optimizer):
            raise TypeError('update equation parameter must be a fluid '
                            'optimizer')
        self.__cost__ = cost
        self.__parameters__ = parameters
        self.__program__ = cost.block.program
        self.__metrics__ = dict(metrics or {})  # name -> Variable
        # clone the forward-only program BEFORE optimizer ops are woven in
        self.__test_program__ = self.__program__.clone(for_test=True)
        update_equation.minimize(cost)
        self.__exe__ = Executor(default_place())
        self._startup_catchup()

    def _startup_catchup(self):
        """Run startup ops whose outputs have no value yet (optimizer
        accumulators added after parameters.create ran startup); params the
        user already set stay untouched."""
        from ..core.scope import global_scope
        startup = default_startup_program()
        scope = global_scope()
        missing = [v.name for v in startup.list_vars()
                   if v.persistable and not scope.has(v.name)]
        if missing:
            self.__exe__.run(startup.prune(targets=missing))

    def _feeder(self, feeding, data_batch):
        feed_vars = self._feed_vars(feeding, data_batch)
        feeder = DataFeeder(place=self.__exe__.place, feed_list=feed_vars)
        return feeder

    def _feed_vars(self, feeding, data_batch):
        block = self.__program__.global_block()
        data_vars = [v for v in block.iter_vars()] if hasattr(
            block, 'iter_vars') else list(block.vars.values())
        data_vars = [v for v in data_vars if getattr(v, 'is_data', False)]
        if feeding is None:
            # Declaration order is the only available pairing; it is
            # silently wrong if the reader yields columns in another
            # order, so refuse ambiguous batches and say so once.
            ncols = len(data_batch[0]) if data_batch else len(data_vars)
            if ncols != len(data_vars):
                raise ValueError(
                    "reader yields %d columns but the program declares %d "
                    "data layers (%s); pass feeding={name: column_index} "
                    "to pair them explicitly" %
                    (ncols, len(data_vars), [v.name for v in data_vars]))
            if len(data_vars) > 1 and not getattr(self, '_warned_order', 0):
                self._warned_order = 1
                warnings.warn(
                    "no `feeding` map given; pairing reader columns to "
                    "data layers by declaration order (%s) — pass "
                    "feeding={name: column_index} if the reader's column "
                    "order differs" % [v.name for v in data_vars])
            return data_vars  # program declaration order
        order = sorted(feeding, key=lambda k: feeding[k])
        return [block.var(n) for n in order]

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        """The reference SGD.train event loop (trainer.py:155)."""
        if event_handler is None:
            event_handler = default_event_handler
        fetch = [self.__cost__] + list(self.__metrics__.values())
        names = list(self.__metrics__.keys())
        feeder = None
        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            pass_costs = []
            pass_metrics = {n: [] for n in names}
            for batch_id, data_batch in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                if feeder is None:
                    feeder = self._feeder(feeding, data_batch)
                outs = self.__exe__.run(self.__program__,
                                        feed=feeder.feed(data_batch),
                                        fetch_list=fetch)
                event_handler(v2_event.EndForwardBackward(pass_id,
                                                          batch_id))
                cost = float(np.ravel(outs[0])[0])
                metrics = {n: float(np.ravel(v)[0])
                           for n, v in zip(names, outs[1:])}
                pass_costs.append(cost)
                for n, v in metrics.items():
                    pass_metrics[n].append(v)
                event_handler(v2_event.EndIteration(pass_id, batch_id,
                                                    cost, metrics))
            event_handler(v2_event.EndPass(
                pass_id, {n: float(np.mean(v)) if v else 0.0
                          for n, v in pass_metrics.items()}))

    def test(self, reader, feeding=None):
        """Average cost/metrics over the reader on the for_test program."""
        fetch_names = [self.__cost__.name] + [
            v.name for v in self.__metrics__.values()]
        names = list(self.__metrics__.keys())
        feeder = None
        costs, metrics = [], {n: [] for n in names}
        for data_batch in reader():
            if feeder is None:
                feeder = self._feeder(feeding, data_batch)
            outs = self.__exe__.run(self.__test_program__,
                                    feed=feeder.feed(data_batch),
                                    fetch_list=fetch_names)
            costs.append(float(np.ravel(outs[0])[0]))
            for n, v in zip(names, outs[1:]):
                metrics[n].append(float(np.ravel(v)[0]))
        return v2_event.TestResult(
            float(np.mean(costs)) if costs else 0.0,
            {n: float(np.mean(v)) if v else 0.0
             for n, v in metrics.items()})

    def save_parameter_to_tar(self, f):
        self.__parameters__.to_tar(f)
