"""V7 — v2 Parameters: a dict-like view over the trained parameter values.

Reference parity: python/paddle/v2/parameters.py (create/keys/get/set/
to_tar/from_tar over the GradientMachine).  Here the backing store is the
fluid Scope: `create(cost)` runs the startup program once, then get/set
read/write device arrays by parameter name.
"""
import pickle

import numpy as np

from ..core.program import default_startup_program
from ..core.scope import global_scope

__all__ = ['Parameters', 'create']


class Parameters(object):
    def __init__(self, program, scope=None):
        self._program = program
        self._scope = scope or global_scope()

    # -- dict-like ------------------------------------------------------
    def names(self):
        return [p.name for p in self._program.global_block()
                .all_parameters()]

    def keys(self):
        return self.names()

    def has_key(self, key):
        return key in self.names()

    def __contains__(self, key):
        return self.has_key(key)

    def __iter__(self):
        return iter(self.names())

    def __len__(self):
        return len(self.names())

    def get(self, parameter_name):
        v = self._scope.find_var(parameter_name)
        if v is None:
            raise ValueError("parameter %r has no value; run the trainer "
                             "or set() it first" % parameter_name)
        return np.asarray(v)

    def __getitem__(self, key):
        return self.get(key)

    def set(self, parameter_name, value):
        self._scope.set(parameter_name, np.asarray(value))

    def __setitem__(self, key, value):
        self.set(key, value)

    def get_shape(self, key):
        return tuple(self._program.global_block().var(key).shape)

    # -- serialization (reference to_tar/from_tar -> pickle dict) -------
    def to_tar(self, f):
        pickle.dump({n: self.get(n) for n in self.names()}, f, protocol=2)

    def from_tar(self, f):
        data = pickle.load(f)
        for n, v in data.items():
            self.set(n, v)
        return self

    @staticmethod
    def load(f):
        """Pair of from_tar for a fresh Parameters with no program: returns
        the raw {name: array} dict."""
        return pickle.load(f)


def create(cost, startup_program=None):
    """Materialize parameters for the program that produced `cost` by
    running the startup program (reference: parameters.create(topology))."""
    from ..core.executor import Executor
    from ..core.place import default_place
    program = cost.block.program
    exe = Executor(default_place())
    exe.run(startup_program or default_startup_program())
    return Parameters(program)
