"""V5–V7 — the v2 high-level API (trainer/event/parameters/inference)
over the Fluid executor.

Reference parity: python/paddle/v2/{__init__,trainer,event,parameters,
inference}.py — the v2 user surface (`paddle.init(...)`,
`paddle.parameters.create`, `trainer.SGD(...).train(reader,
event_handler)`, `paddle.infer`) running on the TPU-native core.
"""
import os

from . import event
from . import parameters
from .inference import Inference, infer
from .trainer import SGD

__all__ = ['init', 'event', 'parameters', 'trainer', 'SGD', 'Inference',
           'infer']

from . import trainer  # noqa: E402


def init(**kwargs):
    """Runtime bring-up (reference python/paddle/v2/__init__.py:init).

    The reference parses --use_gpu/--trainer_count into the C++ runtime;
    on TPU there is nothing to flag-parse — XLA owns the device — so
    this absorbs the PADDLE_INIT_* environment the same way and, for
    multi-host runs (trainer_count > 1 with a coordinator configured),
    joins the global mesh via distributed.launch.initialize().
    use_gpu is accepted and ignored (device selection is the Executor
    place).
    """
    merged = {k[len('PADDLE_INIT_'):].lower(): v
              for k, v in os.environ.items()
              if k.startswith('PADDLE_INIT_')}
    merged.update(kwargs)
    count = int(merged.get('trainer_count', 1) or 1)
    if count > 1 and (merged.get('pservers') or
                      os.environ.get('PADDLE_TPU_COORDINATOR')):
        from ..distributed import launch
        pservers = merged.get('pservers') or ''
        # v2 accepts a comma-separated pserver list; the jax coordinator
        # is a single host:port — process 0's address leads the list
        launch.initialize(
            coordinator_address=pservers.split(',')[0] or None,
            num_processes=count,
            process_id=merged.get('trainer_id'))
    return merged
