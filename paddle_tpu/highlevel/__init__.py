"""V5–V7 — the v2 high-level API (trainer/event/parameters/inference)
over the Fluid executor.

Reference parity: python/paddle/v2/{trainer,event,parameters,inference}.py
— the v2 user surface (`paddle.parameters.create`, `trainer.SGD(...).train
(reader, event_handler)`, `paddle.infer`) running on the TPU-native core.
"""
from . import event
from . import parameters
from .inference import Inference, infer
from .trainer import SGD

__all__ = ['event', 'parameters', 'trainer', 'SGD', 'Inference', 'infer']

from . import trainer  # noqa: E402
