"""V5 — v2 training events.

Reference parity: python/paddle/v2/event.py (BeginPass/EndPass/
BeginIteration/EndIteration/EndForwardBackward/TestResult).  The reference
carries a swig Evaluator; here `metrics` is a plain dict filled from the
trainer's fetches.
"""

__all__ = ['EndIteration', 'BeginIteration', 'BeginPass', 'EndPass',
           'TestResult', 'EndForwardBackward']


class WithMetric(object):
    def __init__(self, metrics=None):
        self.metrics = dict(metrics or {})


class TestResult(WithMetric):
    """Result of trainer.test()."""

    def __init__(self, cost, metrics=None):
        super(TestResult, self).__init__(metrics)
        self.cost = cost


class BeginPass(object):
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, metrics=None):
        super(EndPass, self).__init__(metrics)
        self.pass_id = pass_id


class BeginIteration(object):
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndForwardBackward(object):
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, metrics=None):
        super(EndIteration, self).__init__(metrics)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
