"""PipelineTranspiler — Program-level pipeline parallelism.

Reference parity: the reference's distribution story rewrites whole user
programs (python/paddle/v2/fluid/distribute_transpiler.py splits a
Program into trainer/pserver programs); this transpiler gives the same
Program-level capability to pipeline parallelism: it cuts a fluid
Program's forward at user-annotated boundary vars into S stage
subgraphs and trains it with the 1F1B engine
(parallel/pipeline.pipeline_train_1f1b) over a 'pp' mesh axis — the
backward rides the same scan as the forward, so activation liveness is
bounded by the pipeline depth, not the microbatch count.

TPU-native design decisions:
- Stages run as `lax.switch` branches inside ONE SPMD program (the
  mesh stays a single jit; no per-stage processes).  Each member
  executes only its own branch at runtime.
- The stage interface is the cut var, flattened and zero-padded to one
  uniform [mb, W] buffer so heterogeneous cut widths still ride one
  ppermute channel.
- Params are replicated over the pp axis (activation memory is what
  the pipeline axis owns; shard params over an orthogonal fsdp axis
  for param memory).  Each member produces its own stage's grads; one
  psum replicates the full gradient, and the PROGRAM'S OWN
  backward/optimize-role ops (grad clip, regularizers, sgd/adam, LR
  schedules) then run on it — any optimizer the Program was built
  with works unchanged.
- A second mesh axis composes as DATA-PARALLEL replicas of the whole
  pipeline: microbatch contents shard over it, loss/grads pmean, and
  each replica folds its dp index into the PRNG keys (the ParallelDo
  convention).  Deterministic programs train with exact single-device
  parity; stochastic ones draw distinct per-replica randomness.
- The per-microbatch loss must be an example-mean (fluid's
  `mean(...)` convention): the pipeline's total is the mean over
  microbatches, which equals the full-batch loss when the batch splits
  evenly.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.executor import ExecutionContext, _run_one
from ..core.program import Variable, default_main_program
from ..core.scope import global_scope
from ..parallel import collective
from ..parallel.pipeline import pipeline_train_1f1b

__all__ = ['PipelineTranspiler', 'annotate_pp_cut', 'from_mesh']


def annotate_pp_cut(var, program=None):
    """Mark ``var`` as a pipeline-stage boundary candidate.

    The name lands on ``program._pp_cut_names`` where BOTH consumers
    read it: the sharding pass's pp planner (bubble/ppermute terms in
    the cost report when PADDLE_TPU_MESH carries a pp axis) and
    :func:`from_mesh` (actual stage cutting).  Annotating more
    boundaries than stages is encouraged — the planner picks the
    compute-balanced subset (``transpiler.sharding.select_pp_cuts``).
    Returns ``var`` so the call nests inside layer expressions.
    """
    program = program or default_main_program()
    name = var.name if isinstance(var, Variable) else str(var)
    cuts = getattr(program, '_pp_cut_names', None)
    if cuts is None:
        cuts = []
        program._pp_cut_names = cuts
    if name not in cuts:
        cuts.append(name)
    return var


def from_mesh(program=None, pp_axis='pp', cut_vars=None,
              num_microbatches=None):
    """Mesh-driven pipeline entry: the PADDLE_TPU_MESH counterpart of
    hand-constructing a :class:`PipelineTranspiler`.

    Reads the pipeline depth from the mesh flag's ``pp`` axis (e.g.
    ``PADDLE_TPU_MESH=pp2,dp=2`` — compact and ``axis=size`` forms both
    parse), cuts the program at its :func:`annotate_pp_cut` boundaries
    (auto-balancing when more were annotated than needed), builds the
    mesh, and returns the transpiled instance with ``mesh`` and
    ``num_microbatches`` (PADDLE_TPU_PP_MICROBATCHES unless overridden)
    attached — drive steps with :meth:`PipelineTranspiler.run_mesh_step`.

    This is the path the SPMD executor's pp refusal points at: a pp
    axis shards TIME, so it cannot lower as one pjit program — it needs
    the 1F1B engine's per-stage branches and ppermute transfers.
    """
    from ..flags import FLAGS
    from . import _compat
    program = program or default_main_program()
    axes = _compat.mesh_axes_from_flag()
    sizes = dict(axes or ())
    stages = int(sizes.get(pp_axis, 0))
    if stages < 2:
        raise ValueError(
            "from_mesh needs a %r axis of size >= 2 in PADDLE_TPU_MESH "
            "(e.g. PADDLE_TPU_MESH=%s2,dp=2); got %r"
            % (pp_axis, pp_axis, dict(sizes)))
    if cut_vars is None:
        from ..transpiler.sharding import select_pp_cuts
        names = list(getattr(program, '_pp_cut_names', ()) or ())
        cuts = select_pp_cuts(program, names, stages)
        if cuts is None:
            raise ValueError(
                "a %d-stage pipeline needs at least %d annotated "
                "boundaries; annotate forward activations with "
                "distributed.pipeline.annotate_pp_cut(var) (got %d "
                "usable: %s)" % (stages, stages - 1, len(names), names))
        cut_vars = list(cuts)
    t = PipelineTranspiler()
    t.transpile(program, cut_vars=cut_vars, pp_axis=pp_axis)
    t.mesh = _compat.mesh_for(axes)
    t.num_microbatches = max(
        int(num_microbatches or FLAGS.pp_microbatches or 1), 1)
    return t


class PipelineTranspiler(object):
    """Cut a Program at boundary vars and train it pipelined.

    Usage::

        t = PipelineTranspiler()
        t.transpile(main_prog, cut_vars=[h1, h2, h3])   # 4 stages
        with api.mesh_guard(mesh):                      # ('pp', S) axis
            loss = t.run_step(exe, feed={'x': xb, 'y': yb},
                              num_microbatches=8)
    """

    def transpile(self, program=None, cut_vars=None, pp_axis='pp'):
        program = program or default_main_program()
        if not cut_vars:
            raise ValueError("cut_vars: list of boundary Variables "
                             "(S-1 cuts for S stages)")
        self.program = program
        self.pp_axis = pp_axis
        self.cut_names = [v.name if isinstance(v, Variable) else str(v)
                          for v in cut_vars]
        block = program.global_block()
        ops = block.ops

        ad_idxs = [i for i, op in enumerate(ops)
                   if op.type == 'autodiff']
        if len(ad_idxs) != 1:
            raise ValueError(
                "PipelineTranspiler needs a single-minimize Program "
                "(one autodiff op), got %d" % len(ad_idxs))
        ad = ops[ad_idxs[0]]
        self.loss_name = ad.attrs['loss_name']
        self.param_names = list(ad.attrs['param_names'])
        self.grad_names = list(ad.attrs['grad_names'])
        persistable = {v.name for v in program.list_vars()
                       if v.persistable}
        sparse = [n for n in self.param_names if n not in persistable]
        if sparse:
            # core/backward.py swaps is_sparse embedding params to their
            # lookup-output vars; the pipeline's per-stage vjp has no
            # sparse_grad_assemble path
            raise ValueError(
                "program uses sparse-grad (is_sparse=True) embeddings "
                "%s — not supported by PipelineTranspiler; build the "
                "embedding with is_sparse=False" % sparse)
        # everything after the autodiff op (grad clip, regularizers,
        # optimizer rules, LR schedules) replays on the pipeline grads
        self.post_ops = ops[ad_idxs[0] + 1:]
        fwd_ops = [op for op in ops[:ad_idxs[0]]
                   if op.attrs.get('op_role', 'forward') == 'forward']

        # program-order cutting: a stage ends at the op that produces
        # its cut var
        S = len(self.cut_names) + 1
        stage_ops = [[] for _ in range(S)]
        cur = 0
        for op in fwd_ops:
            stage_ops[cur].append(op)
            if cur < S - 1 and self.cut_names[cur] in op.output_arg_names:
                cur += 1
        if cur != S - 1:
            raise ValueError(
                "cut vars %s not produced in program order (stopped at "
                "cut %d)" % (self.cut_names, cur))
        self.stage_ops = stage_ops
        self.num_stages = S

        # classify every stage input: produced upstream (must be the
        # stage's cut), a parameter/persistable, or a data feed (@LEN
        # companions of ragged data vars are data vars themselves —
        # layers/io.py creates them with is_data=True)
        self.data_names = sorted({
            v.name for v in program.list_vars()
            if getattr(v, 'is_data', False)})
        self.stage_params = []
        for s in range(S):
            outs = set()
            for op in stage_ops[s]:
                outs.update(op.output_arg_names)
            ins = set()
            for op in stage_ops[s]:
                ins.update(op.input_arg_names)
            ext = ins - outs
            pp = sorted(n for n in ext if n in persistable)
            bad = [n for n in ext
                   if n not in persistable and n not in self.data_names
                   and not (s > 0 and n == self.cut_names[s - 1])]
            if bad:
                raise ValueError(
                    "stage %d reads %s which is neither its cut input, "
                    "a parameter, nor a data feed — choose cuts so each "
                    "stage depends only on the previous cut" % (s, bad))
            for op in stage_ops[s]:
                wp = [n for n in op.output_arg_names if n in persistable]
                if wp:
                    raise ValueError(
                        "stage %d op %s writes persistable %s — "
                        "in-pipeline state updates (e.g. batch_norm "
                        "running stats) are not supported; use a "
                        "stateless forward" % (s, op.type, wp))
            self.stage_params.append(pp)
        self._plan_cache = {}
        return self

    # ------------------------------------------------------------------
    def _iface(self, scope):
        """(flat width, dtype) of the padded stage-interface buffer.
        The buffer carries activations in the CUT VARS' OWN dtype (all
        cuts must agree) so a bf16 program stays bf16 across stage
        boundaries — numerically the same program as single-device."""
        from ..core import datatypes
        block = self.program.global_block()
        widths, dtypes = [], []
        for n in self.cut_names:
            var = block.var(n)
            v = scope.find_var(n)
            if v is not None:
                shp = np.shape(v)[1:]
            else:
                shp = tuple(int(d) for d in var.shape[1:])
            widths.append(int(np.prod(shp)) if shp else 1)
            dtypes.append(jnp.dtype(datatypes.as_numpy_dtype(var.dtype)))
        if len(set(dtypes)) > 1:
            raise ValueError(
                "cut vars mix dtypes %s — the stage interface needs one"
                % sorted({str(d) for d in dtypes}))
        return max(widths), dtypes[0]

    def _stage_fn(self, s, mb, width, cut_shapes, idt):
        """Build stage s's branch: (params_tuple, x_flat, mb_feeds, m)
        -> (y_flat, loss_mb).  The per-microbatch PRNG key rides the
        feed stream (``__rng__``, derived from the executor's
        (seed, step) chain), so stochastic ops are deterministic,
        advance across steps, and replay identically in the 1F1B
        backward recompute — though the stream itself differs from the
        single-device executor's (per-stage op indexing)."""
        prog = self.program
        S = self.num_stages
        ops = self.stage_ops[s]
        cut_in = self.cut_names[s - 1] if s > 0 else None
        cut_out = self.cut_names[s] if s < S - 1 else None
        loss_name = self.loss_name

        def stage(params_tuple, x_flat, mb_feeds, m):
            env = dict(params_tuple[s])
            env.update(mb_feeds)
            if cut_in is not None:
                shp = cut_shapes[s - 1]
                w = int(np.prod(shp[1:])) if len(shp) > 1 else 1
                env[cut_in] = x_flat[:, :w].reshape(shp)
            ctx = ExecutionContext(prog, prog.global_block(),
                                   mb_feeds['__rng__'],
                                   uid_prefix=2000 + s)
            for i, op in enumerate(ops):
                _run_one(op, env, ctx, i)
            if cut_out is not None:
                y = env[cut_out].reshape(mb, -1).astype(idt)
                pad = width - y.shape[1]
                if pad:
                    y = jnp.pad(y, ((0, 0), (0, pad)))
                loss = jnp.float32(0.0)
            else:
                y = jnp.zeros((mb, width), idt)
                loss = jnp.sum(env[loss_name]).astype(jnp.float32)
            return y, loss

        return stage

    # ------------------------------------------------------------------
    def run_mesh_step(self, exe, feed, scope=None):
        """One pipelined step under the :func:`from_mesh` configuration
        (the flag-derived mesh and microbatch count attached there)."""
        mesh = getattr(self, 'mesh', None)
        if mesh is None:
            raise RuntimeError(
                "run_mesh_step needs a from_mesh()-built transpiler "
                "(no mesh attached); use run_step(exe, feed, M, "
                "mesh=...) directly")
        return self.run_step(exe, feed, self.num_microbatches,
                             scope=scope, mesh=mesh)

    def run_step(self, exe, feed, num_microbatches, scope=None,
                 mesh=None):
        """One pipelined train step: split `feed` into M microbatches,
        run the 1F1B fwd+bwd pipeline over the mesh's pp axis, replay
        the Program's optimizer ops on the psum'd grads, write updated
        persistables back to the scope.  Returns the scalar loss."""
        from ..parallel import api
        scope = scope or global_scope()
        mesh = mesh or api.current_mesh()
        if mesh is None or self.pp_axis not in mesh.axis_names:
            raise RuntimeError(
                "run_step needs a mesh_guard with a %r axis"
                % self.pp_axis)
        S = self.num_stages
        if mesh.shape[self.pp_axis] != S:
            raise ValueError(
                "mesh axis %r has %d members but the program was cut "
                "into %d stages" % (self.pp_axis,
                                    mesh.shape[self.pp_axis], S))
        # any second mesh axis runs data-parallel REPLICAS of the
        # pipeline: microbatch contents shard over it, grads pmean
        other = [a for a in mesh.axis_names if a != self.pp_axis
                 and mesh.shape[a] > 1]
        if len(other) > 1:
            raise ValueError(
                "mesh %s has more than one non-pp axis %s — compose "
                "pp with at most one dp axis" % (dict(mesh.shape),
                                                 other))
        dp_axis = other[0] if other else None
        dp = mesh.shape[dp_axis] if dp_axis else 1
        M = int(num_microbatches)

        # expand feed entries exactly like the executor (ragged
        # (data, lengths) tuples and LoDTensors become the padded array
        # plus an @LEN companion), then split every array into M
        # microbatches along the batch axis — the lengths stream with
        # their data
        from ..core.executor import _to_feed_arrays
        block = self.program.global_block()
        flat = {}
        for name, value in feed.items():
            flat.update(_to_feed_arrays(name, value,
                                        block.vars.get(name)))
        feeds = {}
        for name, value in flat.items():
            # keep device-resident arrays on device (the reshape is
            # metadata-only); np.asarray would round-trip them to host
            arr = value if isinstance(value, jax.Array) \
                else np.asarray(value)
            if arr.shape[0] % (M * dp):
                raise ValueError(
                    "batch %d does not split into %d microbatches x "
                    "%d dp replicas" % (arr.shape[0], M, dp))
            feeds[name] = arr.reshape((M, arr.shape[0] // M)
                                      + tuple(arr.shape[1:]))
        mb = next(iter(feeds.values())).shape[1]

        persist_names = sorted(
            v.name for v in self.program.list_vars()
            if v.persistable and scope.has(v.name))
        key = (self.program._uid, self.program.version, M, mb,
               tuple(sorted((n, v.shape, str(v.dtype))
                            for n, v in feeds.items())), mesh)
        plan = self._plan_cache.get(key)
        if plan is None:
            plan = self._build_plan(mesh, M, mb, feeds, persist_names,
                                    dp_axis)
            self._plan_cache[key] = plan
        fn = plan

        # api._place handles the multi-host mesh (each process holds the
        # same global value and materializes only its addressable
        # shards — device_put cannot target non-addressable devices)
        dev = NamedSharding(mesh, P())
        state = {n: api._place(scope.get(n), dev)
                 for n in persist_names}
        feeds_dev = {n: api._place(v, dev) for n, v in feeds.items()}
        # the executor's (seed, step) PRNG chain drives stochastic ops,
        # exactly as in exe.run; the step advances per pipelined step
        key0 = api._place(exe._rng_key(self.program), dev)
        exe._step += 1
        loss, new_state = fn(state, feeds_dev, key0)
        for n, v in new_state.items():
            scope.set(n, v)
        return api._fetch_np(loss)

    def _build_plan(self, mesh, M, mb, feeds, persist_names,
                    dp_axis=None):
        from jax import lax
        S = self.num_stages
        dp = mesh.shape[dp_axis] if dp_axis else 1
        mb_local = mb // dp  # examples per microbatch per dp replica
        width, idt = self._iface(global_scope())
        block = self.program.global_block()
        scope = global_scope()
        cut_shapes = []
        for n in self.cut_names:
            v = scope.find_var(n)
            if v is not None:
                cut_shapes.append((mb_local,) + tuple(np.shape(v)[1:]))
            else:
                cut_shapes.append(
                    (mb_local,) + tuple(int(d)
                                        for d in block.var(n).shape[1:]))
        stage_fns = [self._stage_fn(s, mb_local, width, cut_shapes, idt)
                     for s in range(S)]
        prog = self.program
        post_ops = self.post_ops
        param_names = self.param_names
        grad_names = self.grad_names
        loss_name = self.loss_name
        pp_axis = self.pp_axis

        def pipe_body(params_tuple, feeds):
            if dp_axis is not None:
                # distinct randomness per dp replica (each holds
                # different examples) — the ParallelDo convention of
                # folding the member index into the key
                r = lax.axis_index(dp_axis)
                feeds = dict(feeds)
                feeds['__rng__'] = jax.vmap(
                    lambda k2: jax.random.fold_in(k2, r))(
                        feeds['__rng__'])
            loss, grads = pipeline_train_1f1b(
                stage_fns, params_tuple, feeds, M, pp_axis,
                (mb_local, width), idt)
            if dp_axis is not None:
                # each replica's loss/grads are means over ITS examples;
                # the global mean is their pmean
                loss = lax.pmean(loss, dp_axis)
                grads = jax.tree_util.tree_map(
                    lambda g: lax.pmean(g, dp_axis), grads)
            return loss, grads

        # microbatch CONTENTS shard over dp (axis 1 of [M, mb, ...]);
        # the per-microbatch PRNG keys and params replicate
        feed_specs = {n: P(None, dp_axis) if dp_axis else P()
                      for n in feeds}
        feed_specs['__rng__'] = P()
        pipe = collective.shard_map(
            pipe_body, mesh=mesh, in_specs=(P(), feed_specs),
            out_specs=(P(), P()), check_vma=False)

        def step(state, feeds, key0):
            # per-microbatch keys stream with the feeds so the stage
            # bodies (fwd AND 1F1B recompute) draw identical randomness
            feeds = dict(feeds)
            feeds['__rng__'] = jax.vmap(
                lambda m: jax.random.fold_in(key0, m))(jnp.arange(M))
            params_tuple = tuple(
                {n: state[n] for n in self.stage_params[s]}
                for s in range(S))
            loss, grads = pipe(params_tuple, feeds)
            env = dict(state)
            env[loss_name] = loss
            # a param shared by several stages contributes one partial
            # gradient per stage — SUM them (overwriting would train on
            # the last stage's share only)
            gsum = {}
            for s in range(S):
                for pn, g in grads[s].items():
                    if pn in param_names:
                        g32 = g.astype(jnp.float32)
                        gsum[pn] = gsum.get(pn, 0.0) + g32
            for pn, g in gsum.items():
                gn = grad_names[param_names.index(pn)]
                env[gn] = g.astype(state[pn].dtype)
            ctx = ExecutionContext(prog, prog.global_block(), key0)
            for i, op in enumerate(post_ops):
                _run_one(op, env, ctx, i)
            new_state = {n: env[n] for n in persist_names}
            return loss, new_state

        return jax.jit(step, donate_argnums=(0,))
