"""Program-level tensor parallelism — the last distribution axis a
fluid Program couldn't ride (dp/fsdp/pp all had front-ends by r4).

Reference parity: python/paddle/v2/fluid/distribute_transpiler.py:76
transpile() — the reference rewrites whole user Programs for
distribution (trainer/pserver split).  TPU-native redesign: ONE program
survives; this transpiler

  1. swaps every ``fused_linear_softmax_ce`` vocab head to the
     ``vocab_parallel_ce`` op (ops/chunked_ce.py), whose shard_map body
     runs parallel/tensor_parallel.vocab_parallel_cross_entropy — the
     full [D, V] head and the [N, V] logits never exist on one chip,
     and the global logsumexp is one pmax + one psum over ICI;
  2. computes a per-parameter PartitionSpec plan: the swapped head W/B
     column-sharded over 'tp', lookup_table embeddings vocab-sharded,
     plus any user-annotated fc params (``shard_specs``) — GSPMD turns
     the plan into activation collectives for everything outside the
     explicit shard_map.

The same transpiled program still runs single-device (the op degrades
to the fused single-chip head when no tp axis is bound), mirroring how
the reference's trainer program remains a plain Program.
"""
import numpy as np

from jax.sharding import PartitionSpec as P

from ..core.program import default_main_program
from ..parallel import api
from .spec_layout import ACC_SUFFIX as _ACC_SUFFIX  # noqa: F401 (compat)
from .spec_layout import extend_to_accumulators


class TensorParallel(object):
    """Runner executing a tp-transpiled program SPMD over the mesh
    (the DataParallel counterpart for the 'tp' axis; composes with a
    'dp' batch axis on a 2-D mesh)."""

    def __init__(self, exe, mesh, shard_plan, batch_axis=None,
                 fsdp_axis=None):
        self.exe = exe
        self.mesh = mesh
        self.shard_plan = dict(shard_plan or {})
        self.batch_axis = batch_axis
        self.fsdp_axis = fsdp_axis

    def run(self, program=None, feed=None, fetch_list=None, scope=None):
        from ..core.scope import global_scope
        scope = scope or global_scope()
        with api.mesh_guard(self.mesh):
            return api.run_sharded(
                self.exe, program, feed=feed, fetch_list=fetch_list,
                scope=scope, batch_axis=self.batch_axis,
                param_axis=self.fsdp_axis, shard_plan=self.shard_plan)

    def run_steps(self, program=None, feed=None, fetch_list=None,
                  scope=None, repeat=None):
        from ..core.scope import global_scope
        scope = scope or global_scope()
        with api.mesh_guard(self.mesh):
            return api.run_steps_sharded(
                self.exe, program, feed=feed, fetch_list=fetch_list,
                scope=scope, batch_axis=self.batch_axis,
                param_axis=self.fsdp_axis, repeat=repeat,
                shard_plan=self.shard_plan)


class TensorParallelTranspiler(object):
    """transpile() rewrites the program's vocab heads and returns the
    shard plan; get_runner() executes it.

    :param shard_specs: optional {param_name: dim} annotations for
        additional fc/embedding params to shard over 'tp' (Megatron
        column-parallel = the weight's output dim).
    """

    def __init__(self):
        self.program = None
        self.mesh = None
        self.tp_axis = 'tp'
        self._plan = {}

    def transpile(self, program=None, mesh=None, trainers=None,
                  tp_axis='tp', shard_specs=None):
        self.program = program or default_main_program()
        if mesh is None:
            if not trainers:
                raise ValueError("transpile needs mesh= or trainers=N")
            mesh = api.make_mesh((int(trainers),), (tp_axis,))
        if tp_axis not in mesh.axis_names:
            raise ValueError("mesh %r has no %r axis"
                             % (mesh.axis_names, tp_axis))
        self.mesh = mesh
        self.tp_axis = tp_axis
        size = mesh.shape[tp_axis]
        plan = {}

        for block in self.program.blocks:
            for op in block.ops:
                if op.type == 'fused_linear_softmax_ce':
                    wname = op.input('W')[0]
                    wvar = block.var_recursive(wname)
                    v = int(wvar.shape[-1])
                    if v % size:
                        continue  # head not divisible: leave single-chip
                    op.type = 'vocab_parallel_ce'
                    op.set_attr('tp_axis', tp_axis)
                    plan[wname] = P(None, tp_axis)
                    bnames = op.input('Bias')
                    if bnames:
                        plan[bnames[0]] = P(tp_axis)
                elif op.type == 'lookup_table':
                    wname = op.input('W')[0]
                    wvar = block.var_recursive(wname)
                    if int(wvar.shape[0]) % size == 0 and \
                            int(wvar.shape[0]) >= 2 * size:
                        # vocab-sharded table: GSPMD partitions the
                        # gather (out-of-shard rows psum to zero), the
                        # TABLE never replicates
                        plan[wname] = P(tp_axis,
                                        *([None] * (len(wvar.shape) - 1)))

        for name, dim in (shard_specs or {}).items():
            var = self.program.global_block().var_recursive(name)
            if int(var.shape[dim]) % size:
                raise ValueError(
                    "shard_specs[%r]: dim %d (%d) not divisible by tp "
                    "size %d" % (name, dim, var.shape[dim], size))
            spec = [None] * len(var.shape)
            spec[dim] = tp_axis
            plan[name] = P(*spec)

        self._plan = plan
        # the sharding-propagation pass (transpiler/sharding.py) folds
        # this per-parameter plan into its canonical spec table — ONE
        # spec source — by reading it off the program; accumulators are
        # extended there (and in shard_plan()) at consumption time, so
        # a minimize() that runs after transpile() is still covered
        self.program._tp_shard_plan = dict(plan)
        self.program._bump_version()  # rewritten ops: invalidate caches
        return self

    def _with_accumulators(self, plan):
        """Extend the param plan to optimizer accumulators — delegates
        to the shared distributed/spec_layout.py rule (the memory win
        argument lives there).  Computed at shard_plan() time, not
        transpile() time, so accumulators created by a minimize() that
        runs after transpile() are still picked up."""
        return extend_to_accumulators(self.program, plan)

    def shard_plan(self):
        """{var_name: PartitionSpec} over the tp axis: the sharded
        params plus their optimizer accumulators."""
        return self._with_accumulators(self._plan)

    def get_trainer_program(self):
        return self.program

    def get_runner(self, exe, batch_axis=None, fsdp_axis=None):
        return TensorParallel(exe, self.mesh, self.shard_plan(),
                              batch_axis=batch_axis, fsdp_axis=fsdp_axis)
