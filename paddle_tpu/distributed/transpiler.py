"""P13/D2 — DistributeTranspiler: the reference's trainer/pserver program
split, re-designed as mesh sharding.

Reference parity: python/paddle/v2/fluid/distribute_transpiler.py — it
rewrites a program into a trainer program (send grads / recv params) and
per-pserver programs (optimizer ops on owned param shards), with
round-robin `split_var` placement.  The TPU-native equivalent keeps ONE
program: parameters and optimizer state are sharded over an 'fsdp' mesh
axis (each "pserver" is a mesh member owning 1/N of every big tensor),
gradients reduce_scatter and params all_gather over ICI — inserted by
GSPMD from the shardings this transpiler computes.  `split_var`'s
round-robin logic survives as the shard-dim choice in fsdp_shardings.
"""
import numpy as np

from ..core.program import default_main_program
from ..parallel import api
from ..parallel.data_parallel import fsdp_shardings

__all__ = ['DistributeTranspiler', 'SimpleDistributeTranspiler',
           'split_dense_variable']


def split_dense_variable(var_list, pserver_count, min_block_size=1024,
                         max_block_size=1048576):
    """Reference split_var parity: chop flat params into blocks balanced
    across pservers.  Used by tests and by fsdp shard planning to validate
    divisibility."""
    blocks = []
    for var in var_list:
        size = int(np.prod(var.shape))
        split_count = min(pserver_count, max(1, size // min_block_size))
        block_size = (size + split_count - 1) // split_count
        # align to the trailing dim so shards keep whole rows
        dim1 = int(np.prod(var.shape[1:])) if len(var.shape) > 1 else 1
        if block_size % dim1 != 0:
            block_size += dim1 - (block_size % dim1)
        remains = size
        curr = 0
        while remains > 0:
            b = min(block_size, remains)
            blocks.append((var.name, curr, b))
            curr += b
            remains -= b
    return blocks


class DistributeTranspiler(object):
    """The reference transpiler's user surface over mesh sharding.

    transpile() computes the fsdp shard plan for every parameter;
    get_runner(exe) returns the DataParallel runner that executes real
    sharded steps over the mesh (tested multi-step in
    tests/test_distributed_models.py); get_trainer_program() returns the
    program unchanged BY DESIGN — GSPMD shards the one program, there is
    no send/recv rewrite to do; get_pserver_program(endpoint) reports the
    shard map a mesh member owns (checkpoint sharding/introspection).
    """

    def __init__(self):
        self.mesh = None
        self.program = None
        self._shard_plan = None

    def transpile(self, trainer_id=0, program=None, pservers=None,
                  trainers=1, split_method=None, mesh=None,
                  fsdp_axis='fsdp'):
        self.program = program or default_main_program()
        if mesh is None:
            n = max(1, trainers)
            mesh = api.make_mesh((n,), (fsdp_axis,))
        self.mesh = mesh
        self.fsdp_axis = fsdp_axis
        self.trainer_id = trainer_id
        params = {
            p.name: p for p in self.program.global_block().all_parameters()
        }
        self._shard_plan = fsdp_shardings(
            mesh, {n: np.zeros(p.shape, dtype=np.float32)
                   for n, p in params.items()}, axis=fsdp_axis)
        return self

    def get_trainer_program(self):
        return self.program

    def get_runner(self, exe):
        """The object that actually runs sharded steps."""
        from ..parallel.data_parallel import DataParallel
        return DataParallel(exe, self.mesh, axis=self.fsdp_axis,
                            fsdp_axis=self.fsdp_axis)

    def get_pserver_program(self, endpoint=None):
        """Return {param_name: PartitionSpec} — what the member owns."""
        return {n: s.spec for n, s in (self._shard_plan or {}).items()}

    def get_startup_program(self, endpoint=None, pserver_program=None):
        return self.program


class SimpleDistributeTranspiler(DistributeTranspiler):
    """Reference SimpleDistributeTranspiler parity: round-robin WHOLE-var
    placement (reference distribute_transpiler_simple round_robin() — no
    intra-var splitting).  Each mesh member owns entire parameters; the
    ownership map drives per-member checkpointing via
    ``save_member_checkpoint`` (each member writes only the whole vars
    it owns — io.py's merged manifests reassemble the full checkpoint)
    and introspection.  Execution keeps tensors replicated — whole-var
    ownership has no intra-tensor split for GSPMD to exploit, so the
    plan is PartitionSpec() for every var."""

    def transpile(self, trainer_id=0, program=None, pservers=None,
                  trainers=1, split_method=None, mesh=None,
                  fsdp_axis='fsdp'):
        self.program = program or default_main_program()
        if mesh is None:
            n = max(1, trainers)
            mesh = api.make_mesh((n,), (fsdp_axis,))
        self.mesh = mesh
        self.fsdp_axis = fsdp_axis
        self.trainer_id = trainer_id
        n_members = int(np.prod(mesh.devices.shape))
        params = self.program.global_block().all_parameters()
        # reference round_robin: walk vars in declaration order, assign
        # each whole var to the next member in turn
        self._placement = {p.name: i % n_members
                           for i, p in enumerate(params)}
        return self

    def get_pserver_program(self, endpoint=None):
        """Return {param_name: member_index} for vars owned by
        `endpoint` (a member index), or the full placement map when
        endpoint is None."""
        placement = getattr(self, '_placement', {})
        if endpoint is None:
            return dict(placement)
        return {n: m for n, m in placement.items() if m == int(endpoint)}

    def member_vars(self, member, main_program=None):
        """The persistable vars member ``member`` checkpoints: the whole
        params the round-robin map assigns it, plus every derived
        persistable riding a param's name (optimizer accumulators are
        named ``<param>_<acc>_<uid>``) — the reference pserver keeps a
        param's optimizer state next to the param.  Unattributable
        persistables (global counters, LR schedules) go to member 0."""
        placement = getattr(self, '_placement', {})
        prog = main_program or self.program
        member = int(member)
        out = []
        for v in prog.list_vars():
            if not v.persistable:
                continue
            owner = placement.get(v.name)
            if owner is None:
                # longest param-name prefix wins ('w' vs 'w_tail')
                best = max((p for p in placement
                            if v.name.startswith(p + '_')),
                           key=len, default=None)
                owner = placement[best] if best is not None else 0
            if owner == member:
                out.append(v)
        return out

    def save_member_checkpoint(self, executor, dirname, member,
                               main_program=None, step=None):
        """Member ``member`` writes only the vars it owns.  Run on every
        member (any order, any process): io's per-process manifests and
        save-generation merge make the union the complete checkpoint,
        loadable with plain io.load_checkpoint."""
        from .. import io
        prog = main_program or self.program
        io.save_vars(executor, dirname, prog,
                     vars=self.member_vars(member, prog),
                     generation=io.step_generation(step))
        if step is not None and int(member) == 0:
            io.write_step_file(dirname, step)
