"""Version-compat shims for the SPMD lowering path.

The container toolchain pins a jax whose public surface moved between
releases: ``jax.shard_map`` only exists as
``jax.experimental.shard_map.shard_map`` here, and newer mesh helpers
(``jax.make_mesh``) are absent.  Every sharding-propagation consumer
(transpiler/sharding.py, core/executor.py, the benches) resolves those
APIs through this module — the PR-4 ``ops/pallas/_compat.py`` pattern —
so the SPMD path degrades per-feature instead of failing at import on
whichever jax the host ships.

Also home of the mesh-flag plumbing: ``PADDLE_TPU_MESH`` parses once
per lookup (cheap string work), and the constructed ``jax.sharding.Mesh``
objects are cached per normalized spec so every plan build under one
configuration shares one Mesh instance (Mesh identity participates in
executor plan-cache keys).
"""
import threading

__all__ = ['resolve_shard_map', 'has_shard_map', 'mesh_axes_from_flag',
           'mesh_for', 'named_sharding', 'spmd_device_count']

_lock = threading.Lock()
_mesh_cache = {}  # canonical spec string -> Mesh


def resolve_shard_map():
    """The shard_map entry point of whatever jax is installed, or None.

    Prefers the stable ``jax.shard_map`` (newer jax), falls back to
    ``jax.experimental.shard_map.shard_map`` (the container's 0.4.x),
    and returns None when neither exists — callers must gate, never
    assume (the pjit/GSPMD lowering below needs no shard_map at all,
    so absence only disables the explicitly-mapped code paths).
    """
    import jax
    sm = getattr(jax, 'shard_map', None)
    if sm is not None and not _is_deprecated_stub(jax, 'shard_map'):
        return sm
    try:
        from jax.experimental.shard_map import shard_map as esm
        return esm
    except Exception:
        return None


def _is_deprecated_stub(mod, name):
    """jax 0.4.x raises through a module __getattr__ deprecation shim
    for names that LOOK present via getattr with a default — probe by
    real attribute access."""
    try:
        getattr(mod, name)
        return False
    except AttributeError:
        return True


def has_shard_map():
    return resolve_shard_map() is not None


def mesh_axes_from_flag(value=None):
    """Normalized ``(('dp', 2), ('tp', 2))``-style axes tuple from the
    PADDLE_TPU_MESH flag (or an explicit ``value``), or None when the
    mesh is off.  Parsing/validation lives in
    distributed/spec_layout.py — ONE spec vocabulary."""
    from .spec_layout import parse_mesh_spec
    if value is None:
        from ..flags import FLAGS
        value = FLAGS.mesh
    value = (value or '').strip()
    if not value:
        return None
    return parse_mesh_spec(value)


def mesh_key(value=None):
    """The canonical plan-cache key component for the mesh flag: the
    normalized ``axis=size`` string, or None when off."""
    axes = mesh_axes_from_flag(value)
    if axes is None:
        return None
    return ','.join('%s=%d' % a for a in axes)


def spmd_device_count(axes):
    n = 1
    for _name, size in axes:
        n *= int(size)
    return n


def mesh_for(axes):
    """The cached ``jax.sharding.Mesh`` for a normalized axes tuple.

    Raises with an actionable message when the backend exposes fewer
    devices than the mesh needs (on CPU:
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``).
    """
    key = ','.join('%s=%d' % a for a in axes)
    with _lock:
        m = _mesh_cache.get(key)
    if m is not None:
        return m
    import numpy as np
    import jax
    from jax.sharding import Mesh
    n = spmd_device_count(axes)
    devices = jax.devices()
    if len(devices) < n:
        raise RuntimeError(
            "PADDLE_TPU_MESH=%s needs %d devices but the %s backend "
            "exposes %d; on CPU force host devices with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=%d"
            % (key, n, devices[0].platform if devices else '?',
               len(devices), n))
    arr = np.array(devices[:n]).reshape([s for _n, s in axes])
    m = Mesh(arr, tuple(name for name, _s in axes))
    with _lock:
        _mesh_cache[key] = m
    return m


def named_sharding(mesh, spec):
    """Tuple-spec -> NamedSharding.  ``spec`` is the hashable per-dim
    tuple the sharding pass stamps (each entry an axis name, a tuple of
    axis names, or None); None means fully replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if spec is None:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(*spec))
