"""D7 — multi-host bring-up.

Reference parity: benchmark/cluster + paddle.job launch env protocol
(PADDLE_INIT_TRAINER_ID / PSERVERS / TRAINER_COUNT ...).  TPU-native:
each host runs the SAME SPMD program; jax.distributed wires the hosts
into one global device mesh over DCN, collectives inside a host ride ICI.

Environment protocol (also accepts the reference's variable names):
  PADDLE_TPU_COORDINATOR  host:port of process 0   (PSERVERS fallback)
  PADDLE_TPU_NUM_PROCS    world size               (TRAINERS fallback)
  PADDLE_TPU_PROC_ID      this process's rank      (TRAINER_ID fallback)
"""
import os

__all__ = ['initialize', 'is_initialized', 'global_mesh', 'shutdown']

_initialized = False


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return default


def initialize(coordinator_address=None, num_processes=None,
               process_id=None):
    """Connect this host into the multi-host run.  No-op when single
    -process (the common single-host case)."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or _env(
        'PADDLE_TPU_COORDINATOR', 'PADDLE_INIT_PSERVERS')
    num_processes = num_processes or _env(
        'PADDLE_TPU_NUM_PROCS', 'PADDLE_INIT_NUM_GRADIENT_SERVERS',
        'PADDLE_INIT_TRAINER_COUNT')
    process_id = process_id if process_id is not None else _env(
        'PADDLE_TPU_PROC_ID', 'PADDLE_INIT_TRAINER_ID')
    if not coordinator_address or num_processes in (None, '1'):
        _initialized = True
        return  # single host
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes),
        process_id=int(process_id or 0))
    _initialized = True


def is_initialized():
    return _initialized


def global_mesh(shape, axis_names):
    """Mesh over ALL hosts' devices (call after initialize()).  Axis order
    should put intra-host axes (tp/sp) innermost so they ride ICI and the
    cross-host axis (dp) outermost over DCN."""
    from ..parallel import api
    import jax
    return api.make_mesh(shape, axis_names, devices=jax.devices())


def shutdown():
    global _initialized
    if _initialized:
        import jax
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        _initialized = False
