"""SpecLayout: the ONE canonical role -> PartitionSpec table.

Reference parity: the Fluid distribute_transpiler hard-coded WHERE each
var lives (trainer vs pserver); the TPU-native question is HOW each var
is partitioned over the device mesh.  This module is the single source
of that answer, the ``SpecLayout`` pattern from SNIPPETS.md [1]
(canonical PartitionSpecs over data/fsdp/tp axes) merged with [3]'s
``batch x model`` mesh setup:

- ``parse_mesh_spec`` normalizes the ``PADDLE_TPU_MESH`` vocabulary
  (``dp=4,tp=2`` / ``fsdp=8``) into an ordered axes tuple — the same
  tuple the pass-manager plan key, the sharding pass, and the executor
  all consume.
- ``SpecLayout`` maps roles to per-dim specs: activations batch-shard
  over ``dp`` (or ``fsdp`` when no dp axis exists — fsdp IS the data
  axis in a pure-ZeRO mesh), parameters shard their largest divisible
  dim over ``fsdp`` (trailing/output dims preferred, the Megatron
  convention ``parallel/api.param_sharding`` already uses), embedding
  tables row-shard over ``(fsdp, tp)`` when both divide.
- ``build_param_specs`` walks a program's persistables into a
  ``{name: spec}`` plan, folding in the TensorParallelTranspiler's
  per-parameter plan (``program._tp_shard_plan``) so tensor-parallel
  heads keep their column split and everything else falls to the fsdp
  rule — ONE spec source, where PR 4's transpiler and the generic fsdp
  heuristic used to disagree.
- ``extend_to_accumulators`` extends any param plan to the optimizer
  accumulators of every sharded param (``<param>_<stem>_<n>`` naming +
  exact shape match — the PR-4 rule, now shared by the tp transpiler
  and the sharding pass): fsdp that shards params but replicates their
  Adam moments saves nothing.

Specs here are plain hashable tuples (one entry per dim: an axis name,
a tuple of axis names, or None) so they can ride op attrs through the
verifier and the infer-cache; ``distributed/_compat.named_sharding``
turns them into jax NamedShardings at jit time.
"""
import re

__all__ = ['parse_mesh_spec', 'SpecLayout', 'build_param_specs',
           'extend_to_accumulators', 'spec_divisor', 'normalize_spec',
           'ACC_SUFFIX', 'AXIS_ALIASES']

# canonical axis vocabulary; aliases normalize on parse so one spelling
# reaches every consumer (plan keys compare strings)
AXIS_ALIASES = {'dp': 'dp', 'data': 'dp',
                'fsdp': 'fsdp', 'zero': 'fsdp',
                'tp': 'tp', 'mp': 'tp', 'model': 'tp',
                'pp': 'pp', 'pipe': 'pp'}

# compact mesh piece: axis name immediately followed by its size
# ('pp2', 'fsdp4') — sugar for the canonical 'axis=size' form
_COMPACT_PIECE = re.compile(r'^([a-z]+?)(\d+)$')

# optimizer accumulator naming: _add_accumulator creates
# unique_name('<param>_<stem>') = '<param>_<stem>_<n>' with the PARAM's
# shape; the stems are the literal _add_accumulator first arguments in
# optimizer.py (ftrl's are plain 'squared'/'linear').  Beta-pow scalars
# are shape [1] and never pass the shape match.
ACC_SUFFIX = re.compile(
    r'(moment\d?|velocity|inf_norm|mean_square|momentum|'
    r'squared|linear|avg_squared_grad|avg_squared_update)_\d+$')


def parse_mesh_spec(s):
    """``'dp=4,tp=2'`` -> ``(('dp', 4), ('tp', 2))`` (ordered, axis
    names canonicalized).  Raises ValueError with the offending piece
    on malformed input — the flag fails loudly, never half-parses."""
    axes = []
    seen = set()
    for piece in str(s).split(','):
        piece = piece.strip()
        if not piece:
            continue
        if '=' not in piece:
            m = _COMPACT_PIECE.match(piece.strip().lower())
            if m is None:
                raise ValueError(
                    "PADDLE_TPU_MESH piece %r is not axis=size "
                    "(or compact axisN, e.g. pp2)" % piece)
            piece = '%s=%s' % (m.group(1), m.group(2))
        name, _, size = piece.partition('=')
        name = AXIS_ALIASES.get(name.strip().lower())
        if name is None:
            raise ValueError(
                "PADDLE_TPU_MESH axis %r is not one of %s"
                % (piece.split('=')[0],
                   sorted(set(AXIS_ALIASES))))
        try:
            size = int(size)
        except ValueError:
            raise ValueError(
                "PADDLE_TPU_MESH size in %r is not an integer" % piece)
        if size < 1:
            raise ValueError(
                "PADDLE_TPU_MESH size in %r must be >= 1" % piece)
        if name in seen:
            raise ValueError(
                "PADDLE_TPU_MESH repeats axis %r" % name)
        seen.add(name)
        axes.append((name, size))
    if not axes:
        raise ValueError("PADDLE_TPU_MESH is set but names no axes")
    return tuple(axes)


def replicated(rank):
    return (None,) * int(rank)


def spec_divisor(spec, axes):
    """How many ways a spec splits one value: the product of the mesh
    sizes of every axis it names.  ``axes`` is {name: size}."""
    if not spec:
        return 1
    d = 1
    for entry in spec:
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, tuple) else (entry,)):
            d *= int(axes.get(ax, 1))
    return d


def normalize_spec(spec, rank, axes):
    """Any PartitionSpec-like (jax P, list, tuple) -> the canonical
    per-dim tuple, padded to ``rank`` and with axes the mesh doesn't
    carry dropped (a tp plan on a dp-only mesh degrades to replication,
    mirroring how vocab_parallel_ce degrades with no tp axis bound)."""
    entries = list(spec or ())
    out = []
    for i in range(int(rank)):
        e = entries[i] if i < len(entries) else None
        if isinstance(e, (list, tuple)):
            kept = tuple(a for a in e if a in axes)
            e = (kept if len(kept) > 1
                 else (kept[0] if kept else None))
        elif e is not None and e not in axes:
            e = None
        out.append(e)
    return tuple(out)


class SpecLayout(object):
    """Role -> spec table over whatever axes the mesh actually has.

    Methods return the canonical tuple spec, or None when the role
    cannot shard on this mesh/shape (caller treats None as replicated).
    """

    def __init__(self, axes, data_axis='dp', fsdp_axis='fsdp',
                 tp_axis='tp', embed_pad=True, pp_axis='pp'):
        self.axes = dict(axes)
        self.data_axis = data_axis if data_axis in self.axes else None
        self.fsdp_axis = fsdp_axis if fsdp_axis in self.axes else None
        self.tp_axis = tp_axis if tp_axis in self.axes else None
        # pp shards TIME (pipeline stages), never tensors: no role
        # below ever names it, so batch/param/embeddings specs are
        # identical with or without a pp axis in the mesh
        self.pp_axis = pp_axis if pp_axis in self.axes else None
        # embed_pad: row-shard lookup tables whose height does NOT
        # divide, relying on the embedding engine's sentinel-row
        # padding (distributed/embedding_engine.pad_height).  The
        # sharding pass pins it to the PADDLE_TPU_EMBED_SHARD mode so
        # an un-padded consumer never sees an indivisible split.
        self.embed_pad = bool(embed_pad)

    @property
    def batch_axis(self):
        """The axis activations batch-shard over: dp when present,
        else fsdp (a pure-fsdp mesh is ZeRO — data-parallel compute
        with sharded state), else nothing."""
        return self.data_axis or self.fsdp_axis

    def axis_size(self, name):
        return int(self.axes.get(name, 1))

    def batch(self, ndim, batch_size=None):
        """Activations/feeds: dim0 over the batch axis when divisible
        (GSPMD handles ragged shards, but an indivisible batch is a
        load imbalance the table should refuse, not paper over)."""
        ax = self.batch_axis
        if ax is None or ndim < 1:
            return None
        if batch_size is not None and batch_size % self.axis_size(ax):
            return None
        return (ax,) + (None,) * (int(ndim) - 1)

    def param(self, shape):
        """fsdp parameters: largest divisible dim over the fsdp axis,
        trailing (output) dims preferred — the Megatron convention
        parallel/api.param_sharding uses, restated over tuple specs."""
        ax = self.fsdp_axis
        if ax is None:
            return None
        size = self.axis_size(ax)
        if size <= 1:
            return None
        shape = tuple(int(d) for d in shape)
        for d in range(len(shape) - 1, -1, -1):
            if shape[d] > 0 and shape[d] % size == 0 and \
                    shape[d] >= 2 * size:
                spec = [None] * len(shape)
                spec[d] = ax
                return tuple(spec)
        return None

    def embeddings(self, shape, allow_pad=True):
        """Embedding tables: ROWS over the model-state axes — SNIPPETS
        [1] ``embeddings(): PS((fsdp, tp), None)`` when both exist,
        degrading to whichever of fsdp/tp the mesh has (a lookup
        table's natural split is its vocab dim: row ownership is what
        makes the all-to-all lookup and the per-shard apply local).
        Non-divisible heights still row-shard when ``embed_pad`` AND
        ``allow_pad`` hold (the engine sentinel-pads the table to the
        next divisible height; callers clear ``allow_pad`` for tables
        with DENSE-grad lookups, whose [V, D] grad would carry the
        indivisible split the verifier rightly rejects); otherwise —
        and for heights too small to matter — falls back to the plain
        param rule."""
        row_axes = tuple(a for a in (self.fsdp_axis, self.tp_axis)
                         if a)
        if row_axes and shape:
            div = 1
            for a in row_axes:
                div *= self.axis_size(a)
            height = int(shape[0])
            if div > 1 and height >= 2 * div and \
                    (height % div == 0 or
                     (self.embed_pad and allow_pad)):
                entry = row_axes if len(row_axes) > 1 else row_axes[0]
                return (entry,) + (None,) * (len(shape) - 1)
        return self.param(shape)


def build_param_specs(program, axes, layout=None):
    """{persistable name: spec} plan for one program on one mesh: the
    tensor-parallel transpiler's plan wins per name (normalized to the
    mesh's axes), the fsdp rule covers the rest, and the whole plan
    extends to optimizer accumulators.  Replicated names are absent."""
    layout = layout or SpecLayout(axes)
    axes_d = layout.axes
    plan = {}
    tp_plan = getattr(program, '_tp_shard_plan', None) or {}
    emb_tables = _embedding_tables(program)
    emb_names = set(emb_tables)
    for var in program.list_vars():
        if not getattr(var, 'persistable', False) or not var.shape:
            continue
        if any(int(d) < 0 for d in var.shape):
            continue  # batch-shaped persistable: not a parameter
        if _accumulator_of(var.name, emb_names):
            # an embedding table's optimizer accumulator must follow
            # the TABLE's row spec (extend_to_accumulators copies it
            # below), never the generic param rule — a moment sharded
            # on D under a row-sharded table could not be sliced in
            # lockstep by the per-shard apply
            continue
        spec = None
        if var.name in tp_plan:
            spec = normalize_spec(tp_plan[var.name], len(var.shape),
                                  axes_d)
            if not any(e is not None for e in spec):
                spec = None  # degraded entirely: fall to the fsdp rule
        if spec is None and var.name in emb_names:
            spec = layout.embeddings(var.shape,
                                     allow_pad=emb_tables[var.name])
        if spec is None:
            spec = layout.param(var.shape)
        if spec is not None:
            plan[var.name] = spec
    return extend_to_accumulators(program, plan)


def _accumulator_of(name, param_names):
    """True when ``name`` is an optimizer-accumulator var of one of
    ``param_names`` (the ``<param>_<stem>_<n>`` naming rule)."""
    for pname in param_names:
        if name.startswith(pname + '_') and \
                ACC_SUFFIX.fullmatch(name[len(pname) + 1:]):
            return True
    return False


def _embedding_param_names(program):
    """Names of lookup-table weights — the params the ``embeddings``
    role ((fsdp, tp) row split) applies to when no explicit tp plan
    claims them."""
    return set(_embedding_tables(program))


def _embedding_tables(program):
    """{lookup-table weight name: every lookup of it is sparse-grad}.
    The bool gates sentinel-padding: a dense-grad lookup (the
    layers.embedding default) autodiffs to a full [V, D] grad that
    would carry the table's indivisible row split — only tables whose
    grads stay SelectedRows (routed through the per-shard apply) may
    pad a non-divisible height."""
    tables = {}
    for block in program.blocks:
        for op in block.ops:
            if op.type != 'lookup_table':
                continue
            sparse = bool(op.attrs.get('is_sparse', False))
            for w in op.inputs.get('W') or ():
                tables[w] = tables.get(w, True) and sparse
    return tables


def extend_to_accumulators(program, plan):
    """Extend a param plan to the optimizer accumulator vars of every
    planned param: a moment/velocity buffer has the param's shape and
    would otherwise replicate — each device holding a full moment per
    sharded param undoes the memory win the plan exists for.  Matched
    by the ``<param>_<stem>_<n>`` accumulator naming plus an exact
    shape match; anything else (beta-pow scalars, unrelated vars)
    keeps its own spec.  Spec-representation agnostic: works for the
    tp transpiler's jax PartitionSpecs and the sharding pass's tuple
    specs alike (values are copied, never inspected)."""
    out = dict(plan)
    if program is None:
        return out
    gb = program.global_block()
    for var in program.list_vars():
        name = var.name
        if not getattr(var, 'persistable', False) or name in out:
            continue
        for pname, spec in plan.items():
            if not name.startswith(pname + '_'):
                continue
            if not ACC_SUFFIX.fullmatch(name[len(pname) + 1:]):
                continue
            try:
                pvar = gb.var_recursive(pname)
            except KeyError:
                continue
            if tuple(var.shape) != tuple(pvar.shape):
                continue
            out[name] = spec
            break
    return out
