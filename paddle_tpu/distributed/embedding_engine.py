"""Sharded embedding engine: mesh-partitioned tables, all-to-all lookup,
per-shard row-sparse apply, hot-row cache.

Reference parity: the Fluid ``distribute_transpiler`` scaled giant CTR
tables by splitting them across parameter servers and rewriting every
lookup into a ``split_ids -> prefetch(pserver RPC) -> merge`` chain
(operators/lookup_table_op + distributed/parameter_prefetch).  The
TPU-native answer keeps the table on the accelerators themselves:
row-shard it over the mesh (``SpecLayout.embeddings``: rows over
``(fsdp, tp)``) and turn the RPC chain into ICI collectives —

    lookup  =  all-to-all of ids -> per-shard LOCAL gather -> all-to-all
               of rows back
    apply   =  bucket the SelectedRows grad by shard -> per-shard Pallas
               row-walk (ops/pallas/table_update.py) on LOCAL rows only,
               donated, in place

Everything here is expressed as static-shape jax the executor traces
into the one compiled step; under ``PADDLE_TPU_MESH`` + GSPMD the
bucket/gather/reassemble structure lowers to exactly the two all-to-alls
the cost model prices (``(N-1)/N x bytes`` per direction).  The ragged
per-shard id buckets reuse the PR-4 sentinel-row contract verbatim: each
shard's bucket is padded to one tile-aligned capacity
(``PADDLE_TPU_EMBED_BUCKET_TILE``) with the shard's LOCAL height as the
sentinel, which both the Pallas kernel (skip) and the XLA scatter oracle
(out-of-bounds drop) treat as an exact no-op — so ragged bucket fills
are bitwise-invisible, the same trick that made ragged touched-row
counts bucketable in PR 4.

Non-divisible vocab heights pad the TABLE, not the math: the height is
rounded up to the next shard-divisible multiple with sentinel rows that
are never gathered (ids stay ``< height``) and never updated (grad rows
stay ``< height``); ``padding_idx`` resolves against the TRUE height, so
its semantics are preserved bitwise.

On top sits the **hot-row cache** (``HotRowCache``): a small replicated
copy of the top-K most frequent rows — Criteo id traffic is heavily
skewed, so a cache of 1e3 rows absorbs most of a 1e6-row table's lookups
— served locally so the common case never crosses the interconnect.
Coherence is write-through: after an apply touches rows, the cached
copies refresh from the updated table; admission re-ranks by observed
frequency and EVICTS (invalidates) displaced rows.  Hit/miss/evict
counters land in the observability registry
(``paddle_tpu_embed_cache_{hits,misses,evictions}_total``).
"""
import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs

__all__ = ['pad_height', 'bucket_cap', 'bucket_ids', 'bucket_rows',
           'sharded_lookup', 'sharded_apply_sgd', 'sharded_apply_adagrad',
           'sharded_apply_adam', 'shard_slices', 'HotRowCache']


def pad_height(height, ways):
    """The next ``ways``-divisible height >= ``height`` — the sentinel-
    padded table height a ``ways``-way row shard stores.  The pad rows
    are never gathered (ids < height) and never updated (grad rows <
    height), so ``padded - height < ways`` dead rows per table is the
    whole cost of a non-divisible vocab."""
    height, ways = int(height), int(ways)
    if ways <= 1:
        return height
    return -(-height // ways) * ways


def bucket_cap(n_ids, tile):
    """Per-shard bucket capacity for ``n_ids`` ids: every shard's bucket
    is padded to ONE tile-aligned size (worst case: all ids land on one
    shard), so the bucketed layout compiles one shape per batch size
    instead of one per id distribution."""
    tile = max(int(tile), 1)
    return max(-(-max(int(n_ids), 1) // tile) * tile, tile)


def _shard_of(ids, local_h, height, ways):
    """(shard, local) for each id, with anything outside [0, height)
    mapped to (0, local_h) — the per-shard sentinel both consumers
    skip.  This is what makes the AMP skip-step contract compose: a
    gated SelectedRows swaps its rows to >= height, and the swap lands
    every slot on a sentinel in every shard."""
    valid = (ids >= 0) & (ids < height)
    shard = jnp.where(valid, ids // local_h, 0)
    local = jnp.where(valid, ids - shard * local_h, local_h)
    return shard.astype(jnp.int32), local.astype(jnp.int32)


def bucket_ids(ids, height, ways, tile=8, padded=None):
    """The all-to-all send layout for one id vector.

    ``ids`` [N] int32 global row ids -> ``(buckets, back)`` where
    ``buckets`` is [ways, cap] of LOCAL row ids (shard s's bucket holds
    the ids it owns, rebased to ``[0, local_h)``; unused slots carry the
    sentinel ``local_h``) and ``back`` is [N] flat indices into the
    [ways * cap] gathered-row buffer that reassemble the original order
    — the return all-to-all.  Stable within each bucket: duplicates of
    one row keep their original slot order, which is what lets the
    per-shard SGD accumulate bitwise like the global scatter."""
    ids = ids.astype(jnp.int32).reshape(-1)
    height = int(height)
    padded = int(padded) if padded else pad_height(height, ways)
    local_h = padded // int(ways)
    n = int(ids.shape[0])
    cap = bucket_cap(n, tile)
    if n == 0:
        return (jnp.full((int(ways), cap), local_h, jnp.int32),
                jnp.zeros((0,), jnp.int32))
    shard, local = _shard_of(ids, local_h, height, ways)
    order = jnp.argsort(shard, stable=True)
    sid = shard[order]
    ones = jnp.ones((n,), jnp.int32)
    counts = jax.ops.segment_sum(ones, shard, num_segments=int(ways))
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix per shard
    pos = jnp.arange(n, dtype=jnp.int32) - offsets[sid]
    buckets = jnp.full((int(ways), cap), local_h, jnp.int32)
    buckets = buckets.at[sid, pos].set(local[order])
    back = jnp.zeros((n,), jnp.int32).at[order].set(sid * cap + pos)
    return buckets, back


def bucket_rows(rows, values, height, ways, tile=8, padded=None):
    """The apply-path counterpart of :func:`bucket_ids`: route a
    SelectedRows grad's ``(rows [K], values [K, D])`` into per-shard
    buckets ``(local_rows [ways, cap], local_vals [ways, cap, D])`` —
    shard s's slice of the grad, rows rebased local, ragged fill padded
    with the sentinel ``local_h`` (fill slots carry zero values;
    invalid input rows keep their values on sentinel slots, which both
    consumers skip by row id — same note as merge_rows_sentinel).
    Slot order within a shard is the original slot order (stable), so
    duplicate-row accumulation is bitwise the global kernel's."""
    rows = rows.astype(jnp.int32).reshape(-1)
    height = int(height)
    padded = int(padded) if padded else pad_height(height, ways)
    local_h = padded // int(ways)
    k = int(rows.shape[0])
    cap = bucket_cap(k, tile)
    width = values.shape[1:]
    if k == 0:
        return (jnp.full((int(ways), cap), local_h, jnp.int32),
                jnp.zeros((int(ways), cap) + width, values.dtype))
    shard, local = _shard_of(rows, local_h, height, ways)
    order = jnp.argsort(shard, stable=True)
    sid = shard[order]
    ones = jnp.ones((k,), jnp.int32)
    counts = jax.ops.segment_sum(ones, shard, num_segments=int(ways))
    offsets = jnp.cumsum(counts) - counts
    pos = jnp.arange(k, dtype=jnp.int32) - offsets[sid]
    local_rows = jnp.full((int(ways), cap), local_h, jnp.int32)
    local_rows = local_rows.at[sid, pos].set(local[order])
    local_vals = jnp.zeros((int(ways), cap) + width, values.dtype)
    local_vals = local_vals.at[sid, pos].set(values[order])
    return local_rows, local_vals


def shard_slices(table, ways, padded=None):
    """The ``ways`` local [local_h, D] row slices of a (padded) table —
    static-bound ``lax.slice_in_dim`` views, so under GSPMD each slice
    is exactly one device's resident rows and the per-shard kernel
    below never reaches across the interconnect."""
    padded = int(padded) if padded else table.shape[0]
    local_h = padded // int(ways)
    return [jax.lax.slice_in_dim(table, s * local_h, (s + 1) * local_h)
            for s in range(int(ways))]


def _ensure_padded(table, padded):
    """Functionally sentinel-pad an unpadded table (the executor pads
    persistable state once at staging; this covers eager/test callers
    and traced programs whose state was staged by an older plan)."""
    padded = int(padded)
    if int(table.shape[0]) >= padded:
        return table
    fill = jnp.zeros((padded - int(table.shape[0]),) + table.shape[1:],
                     table.dtype)
    return jnp.concatenate([table, fill])


# ---------------------------------------------------------------------------
# forward: all-to-all of ids -> per-shard local gather -> all-to-all back
# ---------------------------------------------------------------------------

def sharded_lookup(w, ids, ways, height=None, tile=8, padding_idx=None,
                   cache_rows=None, cache_vals=None):
    """Row-sharded ``lookup_table`` forward.

    Bitwise-identical to ``jnp.take(w[:height], ids, axis=0)`` (plus the
    ``padding_idx`` zero-mask, resolved against the TRUE height): the
    gathered values are exact row copies, only the route changes —
    ids bucket per owning shard (tile-aligned, sentinel-filled), each
    shard gathers its LOCAL rows, and the row buckets reassemble in
    original id order.  Under GSPMD with ``w`` row-sharded, the bucket
    scatter and the reassembly ARE the two all-to-alls.

    With ``cache_rows``/``cache_vals`` (a :class:`HotRowCache` state;
    ``cache_rows`` must be SORTED ascending with the ``height``
    sentinel filling empty slots — HotRowCache maintains exactly
    this), ids present in the cache are served from the replicated
    copy and masked OUT of the sharded route (their bucket slots
    become sentinels), so cache hits move zero interconnect bytes.
    Membership is one ``searchsorted`` over the sorted row set —
    O(N log C), never an [N, C] equality matrix.  Returns
    ``(values, hits)`` in that case (``hits`` = scalar hit count for
    the caller's counters); plain ``values`` otherwise."""
    ways = int(ways)
    height = int(height) if height is not None else int(w.shape[0])
    padded = pad_height(height, ways)
    w = _ensure_padded(w, padded)
    local_h = padded // ways
    width = w.shape[1]
    ids_shape = ids.shape
    flat = ids.astype(jnp.int32).reshape(-1)
    # jnp.take clamps out-of-range ids (XLA gather clip mode); the
    # sharded route must resolve ids the same way before bucketing
    flat = jnp.clip(flat, 0, height - 1)

    n_hits = None
    hit = cpos = None
    route = flat
    if cache_rows is not None and cache_vals is not None and \
            int(cache_rows.shape[0]) > 0:
        c = int(cache_rows.shape[0])
        cpos = jnp.minimum(jnp.searchsorted(cache_rows, flat),
                           c - 1).astype(jnp.int32)
        # sentinel slots hold `height` and flat < height, so an empty
        # slot can never compare equal
        hit = cache_rows[cpos] == flat
        n_hits = jnp.sum(hit.astype(jnp.int32))
        # hits leave the sharded route: their slots turn into sentinels
        # (>= height -> per-shard sentinel in _shard_of), so the
        # all-to-all payload shrinks to the miss set
        route = jnp.where(hit, height, flat)

    buckets, back = bucket_ids(route, height, ways, tile=tile,
                               padded=padded)
    tables = w.reshape(ways, local_h, width)
    safe = jnp.minimum(buckets, local_h - 1)
    gathered = jnp.take_along_axis(tables, safe[..., None], axis=1)
    y = gathered.reshape(-1, width)[back]

    if hit is not None:
        y = jnp.where(hit[:, None], cache_vals[cpos], y)

    y = y.reshape(ids_shape + (width,))
    if padding_idx is not None:
        pad = int(padding_idx)
        if pad < 0:  # fluid convention resolves against the TRUE height
            pad = height + pad
        mask = (ids.astype(jnp.int32) != pad)[..., None]
        y = jnp.where(mask, y, jnp.zeros_like(y))
    if n_hits is not None:
        return y, n_hits
    return y


# ---------------------------------------------------------------------------
# backward/apply: per-shard Pallas row-walk on LOCAL rows only
# ---------------------------------------------------------------------------

def _per_shard(tables, rows, values, height, ways, tile, padded, apply):
    """Drive ``apply(shard_tables, local_rows, local_vals) -> updated
    shard tables`` over every shard and reassemble.  ``tables`` is a
    list of [H, D] state tables (param + moments) updated together;
    each shard sees only its LOCAL [local_h, D] slices and LOCAL row
    ids — the verifier's "sharded apply addresses local row ranges
    only" claim is true by construction here, not by convention."""
    padded = int(padded) if padded else pad_height(height, ways)
    tables = [_ensure_padded(t, padded) for t in tables]
    local_rows, local_vals = bucket_rows(rows, values, height, ways,
                                         tile=tile, padded=padded)
    slices = [shard_slices(t, ways, padded) for t in tables]
    outs = [[] for _ in tables]
    for s in range(int(ways)):
        upd = apply([sl[s] for sl in slices], local_rows[s],
                    local_vals[s])
        if not isinstance(upd, (list, tuple)):
            upd = (upd,)
        for o, u in zip(outs, upd):
            o.append(u)
    return tuple(jnp.concatenate(o) for o in outs)


def sharded_apply_sgd(param, rows, values, lr, ways, height=None,
                      tile=8, interpret=None):
    """Row-sharded sparse SGD: each shard's slice of the SelectedRows
    grad runs the PR-4 Pallas row-walk (``sparse_apply_sgd``) on its
    LOCAL rows, donated in place.  Bitwise the single-device kernel
    (and therefore the XLA scatter): per-row slot order is preserved
    by the stable bucketing, and shards touch disjoint rows."""
    from ..ops.pallas.table_update import sparse_apply_sgd
    height = int(height) if height is not None else int(param.shape[0])
    (p_new,) = _per_shard(
        [param], rows, values, height, ways, tile, None,
        lambda tabs, r, v: sparse_apply_sgd(tabs[0], r, v, lr,
                                            interpret=interpret))
    return p_new


def sharded_apply_adagrad(param, moment, rows, values, lr, epsilon,
                          ways, height=None, tile=8, interpret=None):
    """Row-sharded fused sparse Adagrad (param + moment, one pass per
    shard, local rows only).  Returns ``(param_new, moment_new)``."""
    from ..ops.pallas.table_update import sparse_apply_adagrad
    height = int(height) if height is not None else int(param.shape[0])
    return _per_shard(
        [param, moment], rows, values, height, ways, tile, None,
        lambda tabs, r, v: sparse_apply_adagrad(
            tabs[0], tabs[1], r, v, lr, epsilon, interpret=interpret))


def sharded_apply_adam(param, moment1, moment2, rows, values, lr_t,
                       beta1, beta2, epsilon, ways, height=None, tile=8,
                       interpret=None):
    """Row-sharded fused lazy sparse Adam (param + both moments, one
    pass per shard, local rows only — sentinel slots decay nothing).
    Returns ``(param_new, m1_new, m2_new)``."""
    from ..ops.pallas.table_update import sparse_apply_adam
    height = int(height) if height is not None else int(param.shape[0])
    return _per_shard(
        [param, moment1, moment2], rows, values, height, ways, tile,
        None,
        lambda tabs, r, v: sparse_apply_adam(
            tabs[0], tabs[1], tabs[2], r, v, lr_t, beta1, beta2,
            epsilon, interpret=interpret))


# ---------------------------------------------------------------------------
# hot-row cache
# ---------------------------------------------------------------------------

class _CacheMetrics(object):
    """Registry handles, allocated on first enabled use (the PR-2
    zero-cost-when-disabled contract)."""

    def __init__(self):
        r = _obs.registry()
        self.hits = r.counter(
            'paddle_tpu_embed_cache_hits_total',
            'embedding lookups served from the replicated hot-row '
            'cache (no interconnect crossing)').child()
        self.misses = r.counter(
            'paddle_tpu_embed_cache_misses_total',
            'embedding lookups that missed the hot-row cache and took '
            'the sharded all-to-all route').child()
        self.evictions = r.counter(
            'paddle_tpu_embed_cache_evictions_total',
            'hot-row cache rows displaced (invalidated) by admission '
            're-ranking').child()


_cache_metrics = None


def _cm():
    global _cache_metrics
    if _cache_metrics is None:
        _cache_metrics = _CacheMetrics()
    return _cache_metrics


class HotRowCache(object):
    """Replicated cache of the top-K most frequent embedding rows.

    State is two device arrays — ``rows`` [C] int32 (``height`` =
    empty-slot sentinel) and ``vals`` [C, D] — small enough to
    replicate on every device, so a hit is a local read.  The policy
    half runs on the host:

    - ``observe(ids)`` folds a batch's ids into the frequency ranking
      (exact counts via ``np.unique`` — the id vectors are batch-sized,
      not table-sized).
    - ``admit(lookup_fn)`` re-ranks: the top-C observed rows become the
      cache set, displaced rows are EVICTED (counted + invalidated —
      their slots are overwritten, so a stale read is impossible), and
      the new set's values load through ``lookup_fn`` (one sharded
      gather).
    - ``write_through(rows, table)`` keeps hits coherent with training:
      after an apply touched ``rows``, every touched row present in the
      cache refreshes from the UPDATED table — update-then-lookup
      through the cache is bitwise the uncached lookup.

    ``lookup(table, ids, ...)`` routes through
    :func:`sharded_lookup`'s cache arguments and accumulates
    hit/miss counters (host-side, read from the returned hit count).
    """

    def __init__(self, capacity, height, width, ways=1, tile=8,
                 dtype=jnp.float32):
        self.capacity = int(capacity)
        self.height = int(height)
        self.width = int(width)
        self.ways = int(ways)
        self.tile = int(tile)
        self.rows = jnp.full((self.capacity,), self.height, jnp.int32)
        self.vals = jnp.zeros((self.capacity, self.width), dtype)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._freq = {}

    # -- policy (host) --------------------------------------------------

    def observe(self, ids):
        u, c = np.unique(np.asarray(ids).reshape(-1), return_counts=True)
        for i, n in zip(u.tolist(), c.tolist()):
            if 0 <= i < self.height:
                self._freq[i] = self._freq.get(i, 0) + n

    def top_rows(self):
        """The current top-C observed rows (host ranking)."""
        ranked = sorted(self._freq.items(), key=lambda kv: (-kv[1],
                                                            kv[0]))
        return [r for r, _n in ranked[:self.capacity]]

    def admit(self, table):
        """Re-rank and reload: cache the top-C observed rows, evicting
        (invalidating) whatever the new set displaces.  ``table`` is
        the CURRENT [H, D] table (or a ``lookup(ids) -> [n, D]``
        callable) the admitted values load from."""
        new = self.top_rows()
        old = set(int(r) for r in np.asarray(self.rows).tolist()
                  if 0 <= int(r) < self.height)
        evicted = old - set(new)
        if evicted:
            self.evictions += len(evicted)
            if _obs.enabled():
                _cm().evictions.inc(len(evicted))
        rows = np.full((self.capacity,), self.height, np.int32)
        # stored SORTED (sentinels sort to the tail naturally): the
        # read path's membership test is one searchsorted
        rows[:len(new)] = np.sort(np.asarray(new, np.int32))
        self.rows = jnp.asarray(rows)
        vals = np.zeros((self.capacity, self.width),
                        np.asarray(self.vals).dtype)
        if new:
            fetch = jnp.asarray(rows[:len(new)])
            if callable(table):
                got = table(fetch)
            else:
                got = sharded_lookup(table, fetch, self.ways,
                                     height=self.height, tile=self.tile)
            vals[:len(new)] = np.asarray(got)
        self.vals = jnp.asarray(vals)
        return len(new), len(evicted)

    # -- coherence ------------------------------------------------------

    def write_through(self, touched_rows, table):
        """Refresh cached copies of rows an apply just touched, from
        the UPDATED table — the write-through half of coherence.  Rows
        not in the cache are ignored; cache slots not touched keep
        their values (still coherent: the apply didn't move them)."""
        touched = jnp.asarray(touched_rows).astype(jnp.int32).reshape(-1)
        if int(touched.shape[0]) == 0 or self.capacity == 0:
            return
        ts = jnp.sort(touched)
        pos = jnp.minimum(jnp.searchsorted(ts, self.rows),
                          int(ts.shape[0]) - 1)
        in_cache = (ts[pos] == self.rows) & (self.rows < self.height)
        safe = jnp.minimum(self.rows, self.height - 1)
        if callable(table):
            fresh = table(safe)
        else:
            fresh = sharded_lookup(table, safe, self.ways,
                                   height=self.height, tile=self.tile)
        self.vals = jnp.where(in_cache[:, None], fresh, self.vals)

    # -- the read path --------------------------------------------------

    def lookup(self, table, ids, padding_idx=None, observe=True):
        """Cached sharded lookup: hits serve from the replicated copy,
        misses take the all-to-all route; bitwise the uncached lookup
        as long as coherence held (write_through after every apply)."""
        if observe:
            self.observe(ids)
        y, n_hits = sharded_lookup(
            table, ids, self.ways, height=self.height, tile=self.tile,
            padding_idx=padding_idx, cache_rows=self.rows,
            cache_vals=self.vals)
        h = int(n_hits)
        m = int(np.prod(np.asarray(ids).shape)) - h
        self.hits += h
        self.misses += m
        if _obs.enabled():
            cm = _cm()
            if h:
                cm.hits.inc(h)
            if m:
                cm.misses.inc(m)
        return y

    def hit_rate(self):
        total = self.hits + self.misses
        return (self.hits / total) if total else 0.0

    def stats(self):
        return {'hits': self.hits, 'misses': self.misses,
                'evictions': self.evictions,
                'hit_rate': self.hit_rate(),
                'resident_rows': int(np.sum(
                    np.asarray(self.rows) < self.height))}
