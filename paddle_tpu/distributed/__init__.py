from . import launch, transpiler
from .pipeline import PipelineTranspiler
from .tensor_parallel import TensorParallel, TensorParallelTranspiler
from .transpiler import DistributeTranspiler, SimpleDistributeTranspiler

__all__ = ['transpiler', 'launch', 'DistributeTranspiler',
           'SimpleDistributeTranspiler', 'PipelineTranspiler',
           'TensorParallelTranspiler', 'TensorParallel']
