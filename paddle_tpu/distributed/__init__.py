from . import launch, transpiler
from .transpiler import DistributeTranspiler, SimpleDistributeTranspiler

__all__ = ['transpiler', 'launch', 'DistributeTranspiler',
           'SimpleDistributeTranspiler']
