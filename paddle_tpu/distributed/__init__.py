from . import launch, transpiler
from .pipeline import PipelineTranspiler
from .transpiler import DistributeTranspiler, SimpleDistributeTranspiler

__all__ = ['transpiler', 'launch', 'DistributeTranspiler',
           'SimpleDistributeTranspiler', 'PipelineTranspiler']
