from . import launch, transpiler
from .embedding_engine import HotRowCache
from .pipeline import PipelineTranspiler
from .spec_layout import SpecLayout, parse_mesh_spec
from .tensor_parallel import TensorParallel, TensorParallelTranspiler
from .transpiler import DistributeTranspiler, SimpleDistributeTranspiler

__all__ = ['transpiler', 'launch', 'DistributeTranspiler',
           'SimpleDistributeTranspiler', 'PipelineTranspiler',
           'TensorParallelTranspiler', 'TensorParallel',
           'SpecLayout', 'parse_mesh_spec', 'HotRowCache']
