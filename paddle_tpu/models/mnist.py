"""M2 — recognize_digits MLP + conv on MNIST.

Reference parity: fluid/tests/book/test_recognize_digits_{mlp,conv}.py.
"""
import paddle_tpu as fluid

__all__ = ['mlp', 'convnet', 'build']


def mlp(img, label):
    hidden1 = fluid.layers.fc(input=img, size=128, act='relu')
    hidden2 = fluid.layers.fc(input=hidden1, size=64, act='relu')
    prediction = fluid.layers.fc(input=hidden2, size=10, act='softmax')
    return prediction


def convnet(img, label):
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    prediction = fluid.layers.fc(input=conv_pool_2, size=10, act="softmax")
    return prediction


def build(nn_type='conv'):
    """Returns (img, label, prediction, avg_cost, acc)."""
    img = fluid.layers.data(name='img', shape=[1, 28, 28], dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    net = convnet if nn_type == 'conv' else mlp
    prediction = net(img, label)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return img, label, prediction, avg_cost, acc
