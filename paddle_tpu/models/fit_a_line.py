"""M1 — linear regression on uci_housing.

Reference parity: python/paddle/v2/fluid/tests/book/test_fit_a_line.py.
"""
import paddle_tpu as fluid

__all__ = ['build']


def build():
    """Returns (x, y, y_predict, avg_cost)."""
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    cost = fluid.layers.square_error_cost(input=y_predict, label=y)
    avg_cost = fluid.layers.mean(x=cost)
    return x, y, y_predict, avg_cost
