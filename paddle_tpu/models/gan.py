"""M13 — DCGAN on MNIST/CIFAR.

Reference parity: v1_api_demo/gan (generator/discriminator adversarial
training).  TPU-native: BOTH updates live in one Program — two
`minimize()` passes (D then G) — and the executor's multi-minimize
semantics take each gradient at program-order-consistent values, so one
jitted step does a full D+G alternation without host round-trips.
"""
import paddle_tpu as fluid

__all__ = ['generator', 'discriminator', 'build']

NOISE_DIM = 64


def generator(noise, out_dim=784, hidden=256):
    h1 = fluid.layers.fc(input=noise, size=hidden, act='relu',
                         param_attr='g_fc1_w', bias_attr='g_fc1_b')
    h2 = fluid.layers.fc(input=h1, size=hidden, act='relu',
                         param_attr='g_fc2_w', bias_attr='g_fc2_b')
    return fluid.layers.fc(input=h2, size=out_dim, act='tanh',
                           param_attr='g_out_w', bias_attr='g_out_b')


def discriminator(img, hidden=256, prefix='d_'):
    h1 = fluid.layers.fc(input=img, size=hidden, act='relu',
                         param_attr=prefix + 'fc1_w',
                         bias_attr=prefix + 'fc1_b')
    h2 = fluid.layers.fc(input=h1, size=hidden, act='relu',
                         param_attr=prefix + 'fc2_w',
                         bias_attr=prefix + 'fc2_b')
    return fluid.layers.fc(input=h2, size=1, act=None,
                           param_attr=prefix + 'out_w',
                           bias_attr=prefix + 'out_b')


def build(img_dim=784, lr=2e-4):
    """Returns (img, noise, d_loss, g_loss, fake).  Call inside a
    program_guard; both losses already have their minimize() appended."""
    img = fluid.layers.data(name='img', shape=[img_dim], dtype='float32')
    noise = fluid.layers.data(name='noise', shape=[NOISE_DIM],
                              dtype='float32')

    fake = generator(noise, out_dim=img_dim)
    logit_real = discriminator(img)
    logit_fake = discriminator(fake)

    ones = fluid.layers.fill_constant_batch_size_like(
        input=logit_real, shape=[-1, 1], dtype='float32', value=1.0)
    zeros = fluid.layers.fill_constant_batch_size_like(
        input=logit_fake, shape=[-1, 1], dtype='float32', value=0.0)

    d_loss = fluid.layers.mean(
        x=fluid.layers.sums(input=[
            fluid.layers.sigmoid_cross_entropy_with_logits(
                x=logit_real, label=ones),
            fluid.layers.sigmoid_cross_entropy_with_logits(
                x=logit_fake, label=zeros),
        ]))
    g_loss = fluid.layers.mean(
        x=fluid.layers.sigmoid_cross_entropy_with_logits(
            x=logit_fake, label=ones))

    prog = fluid.default_main_program()
    d_params = [p for p in prog.global_block().all_parameters()
                if p.name.startswith('d_')]
    g_params = [p for p in prog.global_block().all_parameters()
                if p.name.startswith('g_')]

    fluid.optimizer.AdamOptimizer(learning_rate=lr, beta1=0.5).minimize(
        d_loss, parameter_list=[p.name for p in d_params])
    fluid.optimizer.AdamOptimizer(learning_rate=lr, beta1=0.5).minimize(
        g_loss, parameter_list=[p.name for p in g_params])
    return img, noise, d_loss, g_loss, fake
