"""Model zoo — the reference's book chapters + benchmark nets, built on
the paddle_tpu layers API (SURVEY.md §2.5).

Each module exposes builder functions that append to the current default
program (use inside ``fluid.program_guard`` for isolation), mirroring how
the reference's book scripts are written.
"""
from . import (alexnet, fit_a_line, gan, googlenet, mnist, recommender,
               resnet, rnn_lm, sentiment, seq2seq, smallnet, srl, vgg,
               word2vec, ctr)

__all__ = [
    'fit_a_line', 'mnist', 'resnet', 'vgg', 'alexnet', 'googlenet',
    'smallnet', 'word2vec', 'sentiment', 'rnn_lm', 'seq2seq', 'srl',
    'recommender', 'ctr', 'gan',
]
