"""M6 — understand_sentiment on IMDB: conv net, dynamic LSTM, and the
stacked bidirectional LSTM.

Reference parity: fluid/tests/book/test_understand_sentiment_{conv,
dynamic_lstm,lstm}.py.
"""
import paddle_tpu as fluid

__all__ = ['convolution_net', 'dynamic_lstm_net', 'stacked_lstm_net',
           'build']


def convolution_net(data, label, input_dim, class_dim=2, emb_dim=32,
                    hid_dim=32):
    emb = fluid.layers.embedding(input=data, size=[input_dim, emb_dim])
    conv_3 = fluid.nets.sequence_conv_pool(
        input=emb, num_filters=hid_dim, filter_size=3, act="tanh",
        pool_type="sqrt")
    conv_4 = fluid.nets.sequence_conv_pool(
        input=emb, num_filters=hid_dim, filter_size=4, act="tanh",
        pool_type="sqrt")
    prediction = fluid.layers.fc(input=[conv_3, conv_4], size=class_dim,
                                 act="softmax")
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction


def dynamic_lstm_net(data, label, input_dim, class_dim=2, emb_dim=32,
                     lstm_size=32):
    emb = fluid.layers.embedding(input=data, size=[input_dim, emb_dim])
    fc0 = fluid.layers.fc(input=emb, size=lstm_size * 4, num_flatten_dims=2)
    lstm_h, _ = fluid.layers.dynamic_lstm(
        input=fc0, size=lstm_size * 4, is_reverse=False)
    lstm_max = fluid.layers.sequence_pool(input=lstm_h, pool_type='max')
    prediction = fluid.layers.fc(input=lstm_max, size=class_dim,
                                 act='softmax')
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction


def stacked_lstm_net(data, label, input_dim, class_dim=2, emb_dim=128,
                     hid_dim=512, stacked_num=3):
    assert stacked_num % 2 == 1
    emb = fluid.layers.embedding(input=data, size=[input_dim, emb_dim])

    fc1 = fluid.layers.fc(input=emb, size=hid_dim, num_flatten_dims=2)
    lstm1, cell1 = fluid.layers.dynamic_lstm(input=fc1, size=hid_dim)

    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = fluid.layers.fc(input=inputs, size=hid_dim, num_flatten_dims=2)
        lstm, cell = fluid.layers.dynamic_lstm(
            input=fc, size=hid_dim, is_reverse=(i % 2) == 0)
        inputs = [fc, lstm]

    fc_last = fluid.layers.sequence_pool(input=inputs[0], pool_type='max')
    lstm_last = fluid.layers.sequence_pool(input=inputs[1], pool_type='max')
    prediction = fluid.layers.fc(
        input=[fc_last, lstm_last], size=class_dim, act='softmax')
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction


def build(input_dim, net='conv', class_dim=2):
    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    fn = {'conv': convolution_net, 'dynamic_lstm': dynamic_lstm_net,
          'stacked_lstm': stacked_lstm_net}[net]
    avg_cost, acc, prediction = fn(data, label, input_dim,
                                   class_dim=class_dim)
    return data, label, avg_cost, acc, prediction
