"""M4 — GoogLeNet (Inception-v1).

Reference parity: benchmark/paddle/image/googlenet.py.
"""
import paddle_tpu as fluid

__all__ = ['googlenet']


def inception(input, c1, c3r, c3, c5r, c5, proj):
    conv1 = fluid.layers.conv2d(
        input=input, num_filters=c1, filter_size=1, act='relu')
    conv3r = fluid.layers.conv2d(
        input=input, num_filters=c3r, filter_size=1, act='relu')
    conv3 = fluid.layers.conv2d(
        input=conv3r, num_filters=c3, filter_size=3, padding=1, act='relu')
    conv5r = fluid.layers.conv2d(
        input=input, num_filters=c5r, filter_size=1, act='relu')
    conv5 = fluid.layers.conv2d(
        input=conv5r, num_filters=c5, filter_size=5, padding=2, act='relu')
    pool = fluid.layers.pool2d(
        input=input, pool_size=3, pool_stride=1, pool_padding=1)
    convprj = fluid.layers.conv2d(
        input=pool, num_filters=proj, filter_size=1, act='relu')
    return fluid.layers.concat([conv1, conv3, conv5, convprj], axis=1)


def googlenet(input, num_classes=1000):
    conv = fluid.layers.conv2d(
        input=input, num_filters=64, filter_size=7, stride=2, padding=3,
        act='relu')
    pool = fluid.layers.pool2d(
        input=conv, pool_size=3, pool_stride=2, pool_type='max')
    conv = fluid.layers.conv2d(
        input=pool, num_filters=64, filter_size=1, act='relu')
    conv = fluid.layers.conv2d(
        input=conv, num_filters=192, filter_size=3, padding=1, act='relu')
    pool = fluid.layers.pool2d(
        input=conv, pool_size=3, pool_stride=2, pool_type='max')

    ince3a = inception(pool, 64, 96, 128, 16, 32, 32)
    ince3b = inception(ince3a, 128, 128, 192, 32, 96, 64)
    pool3 = fluid.layers.pool2d(
        input=ince3b, pool_size=3, pool_stride=2, pool_type='max')
    ince4a = inception(pool3, 192, 96, 208, 16, 48, 64)
    ince4b = inception(ince4a, 160, 112, 224, 24, 64, 64)
    ince4c = inception(ince4b, 128, 128, 256, 24, 64, 64)
    ince4d = inception(ince4c, 112, 144, 288, 32, 64, 64)
    ince4e = inception(ince4d, 256, 160, 320, 32, 128, 128)
    pool4 = fluid.layers.pool2d(
        input=ince4e, pool_size=3, pool_stride=2, pool_type='max')
    ince5a = inception(pool4, 256, 160, 320, 32, 128, 128)
    ince5b = inception(ince5a, 384, 192, 384, 48, 128, 128)
    pool5 = fluid.layers.pool2d(
        input=ince5b, pool_size=7, pool_type='avg', global_pooling=True)
    drop = fluid.layers.dropout(x=pool5, dropout_prob=0.4)
    return fluid.layers.fc(input=drop, size=num_classes, act='softmax')
