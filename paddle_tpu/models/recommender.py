"""M10 — recommender system on MovieLens.

Reference parity: fluid/tests/book/test_recommender_system.py — user/movie
feature fusion networks + cos_sim, squared-error regression on the scaled
rating.
"""
import paddle_tpu as fluid
from ..datasets import movielens

__all__ = ['build']


def get_usr_combined_features():
    USR_DICT_SIZE = movielens.max_user_id() + 1
    uid = fluid.layers.data(name='user_id', shape=[1], dtype='int64')
    usr_emb = fluid.layers.embedding(
        input=uid, dtype='float32', size=[USR_DICT_SIZE, 32],
        param_attr='user_table', is_sparse=True)
    usr_fc = fluid.layers.fc(input=usr_emb, size=32)

    USR_GENDER_DICT_SIZE = 2
    usr_gender_id = fluid.layers.data(name='gender_id', shape=[1],
                                      dtype='int64')
    usr_gender_emb = fluid.layers.embedding(
        input=usr_gender_id, size=[USR_GENDER_DICT_SIZE, 16],
        param_attr='gender_table', is_sparse=True)
    usr_gender_fc = fluid.layers.fc(input=usr_gender_emb, size=16)

    USR_AGE_DICT_SIZE = len(movielens.age_table)
    usr_age_id = fluid.layers.data(name='age_id', shape=[1], dtype="int64")
    usr_age_emb = fluid.layers.embedding(
        input=usr_age_id, size=[USR_AGE_DICT_SIZE, 16], is_sparse=True,
        param_attr='age_table')
    usr_age_fc = fluid.layers.fc(input=usr_age_emb, size=16)

    USR_JOB_DICT_SIZE = movielens.max_job_id() + 1
    usr_job_id = fluid.layers.data(name='job_id', shape=[1], dtype="int64")
    usr_job_emb = fluid.layers.embedding(
        input=usr_job_id, size=[USR_JOB_DICT_SIZE, 16],
        param_attr='job_table', is_sparse=True)
    usr_job_fc = fluid.layers.fc(input=usr_job_emb, size=16)

    concat_embed = fluid.layers.concat(
        input=[usr_fc, usr_gender_fc, usr_age_fc, usr_job_fc], axis=1)
    return fluid.layers.fc(input=concat_embed, size=200, act="tanh")


def get_mov_combined_features():
    MOV_DICT_SIZE = movielens.max_movie_id() + 1
    mov_id = fluid.layers.data(name='movie_id', shape=[1], dtype='int64')
    mov_emb = fluid.layers.embedding(
        input=mov_id, dtype='float32', size=[MOV_DICT_SIZE, 32],
        param_attr='movie_table', is_sparse=True)
    mov_fc = fluid.layers.fc(input=mov_emb, size=32)

    CATEGORY_DICT_SIZE = len(movielens.movie_categories())
    category_id = fluid.layers.data(name='category_id', shape=[1],
                                    dtype='int64', lod_level=1)
    mov_categories_emb = fluid.layers.embedding(
        input=category_id, size=[CATEGORY_DICT_SIZE, 32], is_sparse=True)
    mov_categories_hidden = fluid.layers.sequence_pool(
        input=mov_categories_emb, pool_type="sum")

    MOV_TITLE_DICT_SIZE = len(movielens.get_movie_title_dict())
    mov_title_id = fluid.layers.data(name='movie_title', shape=[1],
                                     dtype='int64', lod_level=1)
    mov_title_emb = fluid.layers.embedding(
        input=mov_title_id, size=[MOV_TITLE_DICT_SIZE, 32], is_sparse=True)
    mov_title_conv = fluid.nets.sequence_conv_pool(
        input=mov_title_emb, num_filters=32, filter_size=3, act="tanh",
        pool_type="sum")

    concat_embed = fluid.layers.concat(
        input=[mov_fc, mov_categories_hidden, mov_title_conv], axis=1)
    return fluid.layers.fc(input=concat_embed, size=200, act="tanh")


def build():
    """Returns (feed_order, scale_infer, avg_cost).  Feed order matches the
    movielens reader's 8 slots."""
    usr_combined_features = get_usr_combined_features()
    mov_combined_features = get_mov_combined_features()

    inference = fluid.layers.cos_sim(X=usr_combined_features,
                                     Y=mov_combined_features)
    scale_infer = fluid.layers.scale(x=inference, scale=5.0)

    label = fluid.layers.data(name='score', shape=[1], dtype='float32')
    square_cost = fluid.layers.square_error_cost(input=scale_infer,
                                                 label=label)
    avg_cost = fluid.layers.mean(x=square_cost)
    feed_order = ['user_id', 'gender_id', 'age_id', 'job_id', 'movie_id',
                  'category_id', 'movie_title', 'score']
    return feed_order, scale_infer, avg_cost
