"""Decoder-only transformer LM (the GPT-style flagship, ISSUE 19).

Pre-norm blocks composed from existing layers: flash_attention with a
causal mask (nets.scaled_dot_product_attention), the FFN via fc (mul
matmuls with fp32 master weights), and the fused vocab-projection +
softmax-CE head (ops/chunked_ce.py) so the [N, V] logits never
materialize in HBM.  Train-ready under the AMP pass and PADDLE_TPU_MESH
— everything MXU-shaped is AMP_WHITE, and the dp/fsdp/tp SpecLayout
specs from PR 12 were written for exactly these qkv/attn-out/ffn
projections.

Every parameter carries a FIXED name (``tr_*``) so an inference build
(``build_logits``) and the autoregressive decode engine
(inference/decode.py) reuse the trained weights: the engine pulls the
``tr_*`` tensors straight out of the scope by name and runs the same
math against its paged KV cache.
"""
import paddle_tpu as fluid

__all__ = ['build', 'build_logits', 'param_names']


def _attr(name):
    from paddle_tpu.param_attr import ParamAttr
    return ParamAttr(name=name)


def _block(x, i, d_model, n_heads, d_ff):
    """One pre-norm decoder block: x + attn(ln(x)), then x + ffn(ln(x))."""
    layers = fluid.layers
    # -- causal self-attention ------------------------------------------
    ln1 = layers.layer_norm(
        input=x, begin_norm_axis=2,
        param_attr=_attr('tr_l%d_ln_attn_w' % i),
        bias_attr=_attr('tr_l%d_ln_attn_b' % i))
    qkv = layers.fc(input=ln1, size=3 * d_model, num_flatten_dims=2,
                    param_attr=_attr('tr_l%d_qkv_w' % i),
                    bias_attr=_attr('tr_l%d_qkv_b' % i))
    q, k, v = layers.split(qkv, num_or_sections=3, dim=-1)
    ctx = fluid.nets.scaled_dot_product_attention(
        q, k, v, num_heads=n_heads, causal=True)
    proj = layers.fc(input=ctx, size=d_model, num_flatten_dims=2,
                     param_attr=_attr('tr_l%d_proj_w' % i),
                     bias_attr=_attr('tr_l%d_proj_b' % i))
    x = layers.elementwise_add(x=x, y=proj)
    # -- position-wise FFN ----------------------------------------------
    ln2 = layers.layer_norm(
        input=x, begin_norm_axis=2,
        param_attr=_attr('tr_l%d_ln_ffn_w' % i),
        bias_attr=_attr('tr_l%d_ln_ffn_b' % i))
    h = layers.fc(input=ln2, size=d_ff, num_flatten_dims=2, act='relu',
                  param_attr=_attr('tr_l%d_ffn_up_w' % i),
                  bias_attr=_attr('tr_l%d_ffn_up_b' % i))
    h = layers.fc(input=h, size=d_model, num_flatten_dims=2,
                  param_attr=_attr('tr_l%d_ffn_down_w' % i),
                  bias_attr=_attr('tr_l%d_ffn_down_b' % i))
    return layers.elementwise_add(x=x, y=h)


def _trunk(src, vocab_size, seq_len, n_layers, d_model, n_heads, d_ff,
           dtype):
    layers = fluid.layers
    emb = layers.embedding(input=src, size=[vocab_size, d_model],
                           param_attr=_attr('tr_embed'))
    # learned positional table [T, D]; broadcasts over the batch dim
    pos = layers.create_parameter(shape=[seq_len, d_model],
                                  dtype='float32', attr=_attr('tr_pos'))
    x = layers.elementwise_add(x=emb, y=pos)
    if dtype in ('bfloat16', 'float16'):
        x = layers.cast(x=x, dtype=dtype)
    for i in range(n_layers):
        x = _block(x, i, d_model, n_heads, d_ff)
    return layers.layer_norm(input=x, begin_norm_axis=2,
                             param_attr=_attr('tr_ln_f_w'),
                             bias_attr=_attr('tr_ln_f_b'))


def build(vocab_size, seq_len=128, n_layers=2, d_model=128, n_heads=4,
          d_ff=None, dtype='float32'):
    """Train graph: returns (src, target, avg_cost).

    src is a dense [B, T] int64 token grid (next-token prediction over
    fixed-length windows — the packed-LM convention, no ragged LoD);
    target is src shifted by one, fed as [B, T, 1].  The vocab head is
    the fused projection+CE op; its ``tr_head_*`` params are reused by
    ``build_logits`` and the decode engine."""
    if d_ff is None:
        d_ff = 4 * d_model
    if d_model % n_heads:
        raise ValueError("d_model %d not divisible by n_heads %d"
                         % (d_model, n_heads))
    layers = fluid.layers
    src = layers.data(name='src', shape=[seq_len], dtype='int64')
    target = layers.data(name='target', shape=[seq_len, 1],
                         dtype='int64')
    x = _trunk(src, vocab_size, seq_len, n_layers, d_model, n_heads,
               d_ff, dtype)
    cost = layers.fused_linear_softmax_ce(
        input=x, label=target, size=vocab_size, num_flatten_dims=2,
        param_attr=_attr('tr_head_w'), bias_attr=_attr('tr_head_b'))
    avg_cost = layers.mean(x=cost)
    return src, target, avg_cost


def build_logits(vocab_size, seq_len=128, n_layers=2, d_model=128,
                 n_heads=4, d_ff=None, dtype='float32'):
    """Inference graph sharing every ``tr_*`` param with ``build``:
    returns (src, logits) with logits [B, T, V] — the full-context
    forward the decode engine's paged path is pinned against
    (tests/test_decode.py)."""
    if d_ff is None:
        d_ff = 4 * d_model
    layers = fluid.layers
    src = layers.data(name='src', shape=[seq_len], dtype='int64')
    x = _trunk(src, vocab_size, seq_len, n_layers, d_model, n_heads,
               d_ff, dtype)
    logits = layers.fc(input=x, size=vocab_size, num_flatten_dims=2,
                       param_attr=_attr('tr_head_w'),
                       bias_attr=_attr('tr_head_b'))
    if dtype in ('bfloat16', 'float16'):
        logits = layers.cast(x=logits, dtype='float32')
    return src, logits


def param_names(n_layers):
    """Every fixed parameter name ``build`` creates, in layer order —
    the extraction manifest the decode engine loads from a scope."""
    names = ['tr_embed', 'tr_pos']
    per_layer = ('ln_attn_w', 'ln_attn_b', 'qkv_w', 'qkv_b', 'proj_w',
                 'proj_b', 'ln_ffn_w', 'ln_ffn_b', 'ffn_up_w',
                 'ffn_up_b', 'ffn_down_w', 'ffn_down_b')
    for i in range(n_layers):
        names.extend('tr_l%d_%s' % (i, s) for s in per_layer)
    names.extend(['tr_ln_f_w', 'tr_ln_f_b', 'tr_head_w', 'tr_head_b'])
    return names
