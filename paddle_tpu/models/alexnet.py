"""M4 — AlexNet.  Reference parity: benchmark/paddle/image/alexnet.py."""
import paddle_tpu as fluid

__all__ = ['alexnet']


def alexnet(input, num_classes=1000):
    conv1 = fluid.layers.conv2d(
        input=input, num_filters=64, filter_size=11, stride=4, padding=2,
        act='relu')
    pool1 = fluid.layers.pool2d(input=conv1, pool_size=3, pool_stride=2)
    conv2 = fluid.layers.conv2d(
        input=pool1, num_filters=192, filter_size=5, padding=2, act='relu')
    pool2 = fluid.layers.pool2d(input=conv2, pool_size=3, pool_stride=2)
    conv3 = fluid.layers.conv2d(
        input=pool2, num_filters=384, filter_size=3, padding=1, act='relu')
    conv4 = fluid.layers.conv2d(
        input=conv3, num_filters=256, filter_size=3, padding=1, act='relu')
    conv5 = fluid.layers.conv2d(
        input=conv4, num_filters=256, filter_size=3, padding=1, act='relu')
    pool5 = fluid.layers.pool2d(input=conv5, pool_size=3, pool_stride=2)
    fc1 = fluid.layers.fc(input=pool5, size=4096, act='relu')
    drop1 = fluid.layers.dropout(x=fc1, dropout_prob=0.5)
    fc2 = fluid.layers.fc(input=drop1, size=4096, act='relu')
    drop2 = fluid.layers.dropout(x=fc2, dropout_prob=0.5)
    return fluid.layers.fc(input=drop2, size=num_classes, act='softmax')
