"""M11 — CTR prediction: wide&deep and DeepFM with high-dim sparse
embedding tables (BASELINE.json config 5).

TPU-native design: the sparse id features feed `lookup_table` gathers
whose gradients come back as SelectedRows (rows, values) and are applied
with a segment-sum — the table itself never materialises a dense gradient
(core/selected_rows.py, ops/embedding.py).
"""
import paddle_tpu as fluid

__all__ = ['wide_and_deep', 'deepfm', 'build']

SPARSE_FEATURE_DIM = 100003  # ~1e5 hashed id space per slot
NUM_SLOTS = 8
DENSE_DIM = 13

# Criteo-class preset (BASELINE config 5 "high-dim sparse"): 26 sparse
# slots x ~1e6-row hashed tables + 13 dense features — the scale where
# SelectedRows matters (table >> HBM-comfortable, update << table)
CRITEO_SPARSE_DIM = 1000003
CRITEO_NUM_SLOTS = 26


def _sparse_slots(num_slots=None):
    return [
        fluid.layers.data(name='sparse_%d' % i, shape=[1], dtype='int64',
                          lod_level=1)
        for i in range(num_slots or NUM_SLOTS)
    ]


def wide_and_deep(dense, sparse_slots, label, embed_dim=16,
                  hidden=(256, 128, 64), sparse_dim=None):
    sparse_dim = sparse_dim or SPARSE_FEATURE_DIM
    # deep: per-slot embeddings, sum-pooled over the slot's ids
    embeds = [
        fluid.layers.sequence_pool(
            input=fluid.layers.embedding(
                input=s, size=[sparse_dim, embed_dim],
                is_sparse=True, param_attr='embed_%d' % i),
            pool_type='sum') for i, s in enumerate(sparse_slots)
    ]
    deep = fluid.layers.concat(input=embeds + [dense], axis=1)
    for h in hidden:
        deep = fluid.layers.fc(input=deep, size=h, act='relu')
    # wide: 1-d embedding per slot (linear term over sparse ids) + dense
    wides = [
        fluid.layers.sequence_pool(
            input=fluid.layers.embedding(
                input=s, size=[sparse_dim, 1], is_sparse=True,
                param_attr='wide_%d' % i),
            pool_type='sum') for i, s in enumerate(sparse_slots)
    ]
    wide = fluid.layers.concat(input=wides + [dense], axis=1)
    both = fluid.layers.concat(input=[deep, wide], axis=1)
    predict = fluid.layers.fc(input=both, size=2, act='softmax')
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    auc = fluid.layers.auc(input=predict, label=label)
    return predict, avg_cost, auc


def deepfm(dense, sparse_slots, label, embed_dim=16, hidden=(128, 128),
           sparse_dim=None):
    """DeepFM: linear + pairwise FM interaction + deep MLP, shared
    per-slot factor embeddings."""
    sparse_dim = sparse_dim or SPARSE_FEATURE_DIM
    factors = [
        fluid.layers.sequence_pool(
            input=fluid.layers.embedding(
                input=s, size=[sparse_dim, embed_dim],
                is_sparse=True, param_attr='fm_embed_%d' % i),
            pool_type='sum') for i, s in enumerate(sparse_slots)
    ]
    linear = [
        fluid.layers.sequence_pool(
            input=fluid.layers.embedding(
                input=s, size=[sparse_dim, 1], is_sparse=True,
                param_attr='fm_w_%d' % i),
            pool_type='sum') for i, s in enumerate(sparse_slots)
    ]
    # FM second-order: 0.5 * ((sum v)^2 - sum v^2), summed over factor dim
    stacked = fluid.layers.sums(input=factors)  # [B, K]
    sum_sq = fluid.layers.elementwise_mul(x=stacked, y=stacked)
    sq_sum = fluid.layers.sums(
        input=[fluid.layers.elementwise_mul(x=f, y=f) for f in factors])
    fm2 = fluid.layers.scale(
        x=fluid.layers.reduce_sum(
            fluid.layers.elementwise_sub(x=sum_sq, y=sq_sum),
            dim=1, keep_dim=True),
        scale=0.5)
    deep = fluid.layers.concat(input=factors + [dense], axis=1)
    for h in hidden:
        deep = fluid.layers.fc(input=deep, size=h, act='relu')
    head = fluid.layers.concat(input=linear + [fm2, deep, dense], axis=1)
    predict = fluid.layers.fc(input=head, size=2, act='softmax')
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    auc = fluid.layers.auc(input=predict, label=label)
    return predict, avg_cost, auc


def build(arch='wide_and_deep', sparse_dim=None, num_slots=None,
          embed_dim=16):
    """Returns (feed vars, predict, avg_cost, auc).  Defaults keep the
    8-slot/1e5 layout; pass sparse_dim=CRITEO_SPARSE_DIM,
    num_slots=CRITEO_NUM_SLOTS for the Criteo-class config."""
    dense = fluid.layers.data(name='dense', shape=[DENSE_DIM],
                              dtype='float32')
    sparse_slots = _sparse_slots(num_slots)
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    fn = {'wide_and_deep': wide_and_deep, 'deepfm': deepfm}[arch]
    predict, avg_cost, auc = fn(dense, sparse_slots, label,
                                embed_dim=embed_dim,
                                sparse_dim=sparse_dim)
    return [dense] + sparse_slots + [label], predict, avg_cost, auc


def synthetic_reader(split='train', size=4096):
    """CTR samples: (dense[13], 8 sparse id lists, label) — label is a
    noisy function of planted id/dense interactions."""
    import numpy as np
    from ..datasets import common

    def reader():
        rng = common.rng_for('ctr', split)
        w = common.rng_for('ctr', 'coef').normal(size=DENSE_DIM)
        for _ in range(common.data_size(size)):
            dense = rng.normal(size=DENSE_DIM).astype(np.float32)
            slots = []
            score = float(dense @ w)
            for i in range(NUM_SLOTS):
                n_ids = int(rng.integers(1, 4))
                ids = rng.integers(0, SPARSE_FEATURE_DIM,
                                   size=n_ids).astype(np.int64)
                slots.append(ids.tolist())
                score += 0.3 * np.sum((ids % 17) - 8) / 8.0
            label = int(score + rng.normal() > 0)
            yield tuple([dense] + slots + [label])

    return reader
