"""M4 — SmallNet.  Reference parity:
benchmark/paddle/image/smallnet_mnist_cifar.py (small conv net)."""
import paddle_tpu as fluid

__all__ = ['smallnet']


def smallnet(input, num_classes=10):
    conv1 = fluid.layers.conv2d(
        input=input, num_filters=32, filter_size=5, padding=2, act='relu')
    pool1 = fluid.layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                                pool_type='max')
    conv2 = fluid.layers.conv2d(
        input=pool1, num_filters=32, filter_size=5, padding=2, act='relu')
    pool2 = fluid.layers.pool2d(input=conv2, pool_size=3, pool_stride=2,
                                pool_type='avg')
    conv3 = fluid.layers.conv2d(
        input=pool2, num_filters=64, filter_size=5, padding=2, act='relu')
    pool3 = fluid.layers.pool2d(input=conv3, pool_size=3, pool_stride=2,
                                pool_type='avg')
    fc1 = fluid.layers.fc(input=pool3, size=64, act='relu')
    return fluid.layers.fc(input=fc1, size=num_classes, act='softmax')
