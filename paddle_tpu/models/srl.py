"""M9 — label_semantic_roles: deep bidirectional LSTM + CRF on CoNLL05.

Reference parity: fluid/tests/book/test_label_semantic_roles.py (8 input
sequences, stacked alternating-direction LSTMs, linear_chain_crf loss,
crf_decoding inference).
"""
import paddle_tpu as fluid

__all__ = ['db_lstm', 'build']

word_dim = 32
mark_dim = 5
hidden_dim = 512
depth = 4
mix_hidden_lr = 1e-3


def db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark,
            word_dict_len, pred_dict_len, mark_dict_len, label_dict_len):
    predicate_embedding = fluid.layers.embedding(
        input=predicate, size=[pred_dict_len, word_dim],
        dtype='float32', param_attr='vemb')
    mark_embedding = fluid.layers.embedding(
        input=mark, size=[mark_dict_len, mark_dim], dtype='float32')

    word_input = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    emb_layers = [
        fluid.layers.embedding(
            size=[word_dict_len, word_dim], input=x,
            param_attr=fluid.ParamAttr(name='word_emb', trainable=False))
        for x in word_input
    ]
    emb_layers.append(predicate_embedding)
    emb_layers.append(mark_embedding)

    hidden_0_layers = [
        fluid.layers.fc(input=emb, size=hidden_dim, num_flatten_dims=2)
        for emb in emb_layers
    ]
    hidden_0 = fluid.layers.sums(input=hidden_0_layers)

    lstm_0, _ = fluid.layers.dynamic_lstm(
        input=hidden_0, size=hidden_dim,
        candidate_activation='relu',
        gate_activation='sigmoid',
        cell_activation='sigmoid')

    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = fluid.layers.sums(input=[
            fluid.layers.fc(input=input_tmp[0], size=hidden_dim,
                            num_flatten_dims=2),
            fluid.layers.fc(input=input_tmp[1], size=hidden_dim,
                            num_flatten_dims=2)
        ])
        lstm, _ = fluid.layers.dynamic_lstm(
            input=mix_hidden, size=hidden_dim,
            candidate_activation='relu',
            gate_activation='sigmoid',
            cell_activation='sigmoid',
            is_reverse=((i % 2) == 1))
        input_tmp = [mix_hidden, lstm]

    feature_out = fluid.layers.sums(input=[
        fluid.layers.fc(input=input_tmp[0], size=label_dict_len,
                        num_flatten_dims=2),
        fluid.layers.fc(input=input_tmp[1], size=label_dict_len,
                        num_flatten_dims=2)
    ])
    return feature_out


def build(word_dict_len, pred_dict_len, mark_dict_len, label_dict_len):
    """Returns (feed_order vars, feature_out, crf_decode, avg_cost)."""
    def seq_data(name):
        return fluid.layers.data(name=name, shape=[1], dtype='int64',
                                 lod_level=1)

    word = seq_data('word_data')
    ctx_n2 = seq_data('ctx_n2_data')
    ctx_n1 = seq_data('ctx_n1_data')
    ctx_0 = seq_data('ctx_0_data')
    ctx_p1 = seq_data('ctx_p1_data')
    ctx_p2 = seq_data('ctx_p2_data')
    predicate = seq_data('verb_data')
    mark = seq_data('mark_data')
    target = seq_data('target')

    feature_out = db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1,
                          ctx_p2, mark, word_dict_len, pred_dict_len,
                          mark_dict_len, label_dict_len)

    crf_cost = fluid.layers.linear_chain_crf(
        input=feature_out, label=target,
        param_attr=fluid.ParamAttr(name='crfw', learning_rate=mix_hidden_lr))
    avg_cost = fluid.layers.mean(x=crf_cost)
    crf_decode = fluid.layers.crf_decoding(
        input=feature_out, param_attr=fluid.ParamAttr(name='crfw'))

    feeds = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, predicate, mark,
             target]
    return feeds, feature_out, crf_decode, avg_cost
