"""M7 — stacked-LSTM language model.

Reference parity: benchmark/paddle/rnn/rnn.py (LSTM LM over imdb/PTB-style
sequences, next-token prediction).
"""
import paddle_tpu as fluid

__all__ = ['build']


def build(vocab_size, emb_dim=128, hidden_dim=256, num_layers=2,
          dtype='float32', fuse_vocab_loss=True):
    """Returns (src, target, avg_cost).  src/target are token-id sequences
    (lod_level=1); target is src shifted by one.

    dtype='bfloat16' runs the projection/vocab-head matmuls in bf16 with
    fp32 master weights (layers/nn.py fc keeps p_dtype fp32); the LSTM
    recurrence and the softmax head stay fp32.  The loss defaults to
    the fused vocab-projection + softmax-CE (ops/chunked_ce.py — only a
    half-width logits residual in HBM, backward = softmax − onehot);
    fuse_vocab_loss=False keeps the naive cross_entropy(softmax(x))
    composition for A/B."""
    from paddle_tpu.param_attr import ParamAttr
    src = fluid.layers.data(name='src', shape=[1], dtype='int64',
                            lod_level=1)
    target = fluid.layers.data(name='target', shape=[1], dtype='int64',
                               lod_level=1)
    emb = fluid.layers.embedding(input=src, size=[vocab_size, emb_dim])
    x = emb
    if dtype in ('bfloat16', 'float16'):
        x = fluid.layers.cast(x=x, dtype=dtype)
    for i in range(num_layers):
        fc = fluid.layers.fc(input=x, size=hidden_dim * 4,
                             num_flatten_dims=2)
        h, _ = fluid.layers.dynamic_lstm(input=fc, size=hidden_dim * 4)
        x = h
    if fuse_vocab_loss:
        # head params carry fixed names so an inference/decode build
        # (the fc path below) reuses the trained weights
        cost = fluid.layers.fused_linear_softmax_ce(
            input=x, label=target, size=vocab_size, num_flatten_dims=2,
            param_attr=ParamAttr(name='lm_out_w'),
            bias_attr=ParamAttr(name='lm_out_b'))
    else:
        # vocab-head matmul in the activation dtype; softmax in fp32
        logits = fluid.layers.fc(
            input=x, size=vocab_size, num_flatten_dims=2, act=None,
            param_attr=ParamAttr(name='lm_out_w'),
            bias_attr=ParamAttr(name='lm_out_b'))
        if dtype in ('bfloat16', 'float16'):
            logits = fluid.layers.cast(x=logits, dtype='float32')
        probs = fluid.layers.softmax(x=logits)
        cost = fluid.layers.cross_entropy(input=probs, label=target,
                                          soft_label=False)
    # mask out padded steps via sequence-average
    avg_cost = fluid.layers.mean(
        x=fluid.layers.sequence_pool(input=cost, pool_type='average'))
    return src, target, avg_cost
