"""M7 — stacked-LSTM language model.

Reference parity: benchmark/paddle/rnn/rnn.py (LSTM LM over imdb/PTB-style
sequences, next-token prediction).
"""
import paddle_tpu as fluid

__all__ = ['build']


def build(vocab_size, emb_dim=128, hidden_dim=256, num_layers=2):
    """Returns (src, target, avg_cost).  src/target are token-id sequences
    (lod_level=1); target is src shifted by one."""
    src = fluid.layers.data(name='src', shape=[1], dtype='int64',
                            lod_level=1)
    target = fluid.layers.data(name='target', shape=[1], dtype='int64',
                               lod_level=1)
    emb = fluid.layers.embedding(input=src, size=[vocab_size, emb_dim])
    x = emb
    for i in range(num_layers):
        fc = fluid.layers.fc(input=x, size=hidden_dim * 4,
                             num_flatten_dims=2)
        h, _ = fluid.layers.dynamic_lstm(input=fc, size=hidden_dim * 4)
        x = h
    logits = fluid.layers.fc(input=x, size=vocab_size, num_flatten_dims=2,
                             act='softmax')
    cost = fluid.layers.cross_entropy(input=logits, label=target,
                                      soft_label=False)
    # mask out padded steps via sequence-average
    avg_cost = fluid.layers.mean(
        x=fluid.layers.sequence_pool(input=cost, pool_type='average'))
    return src, target, avg_cost
