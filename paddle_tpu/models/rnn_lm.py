"""M7 — stacked-LSTM language model.

Reference parity: benchmark/paddle/rnn/rnn.py (LSTM LM over imdb/PTB-style
sequences, next-token prediction).
"""
import paddle_tpu as fluid

__all__ = ['build']


def build(vocab_size, emb_dim=128, hidden_dim=256, num_layers=2,
          dtype='float32'):
    """Returns (src, target, avg_cost).  src/target are token-id sequences
    (lod_level=1); target is src shifted by one.

    dtype='bfloat16' runs the projection/vocab-head matmuls in bf16 with
    fp32 master weights (layers/nn.py fc keeps p_dtype fp32); the LSTM
    recurrence and the softmax head stay fp32."""
    src = fluid.layers.data(name='src', shape=[1], dtype='int64',
                            lod_level=1)
    target = fluid.layers.data(name='target', shape=[1], dtype='int64',
                               lod_level=1)
    emb = fluid.layers.embedding(input=src, size=[vocab_size, emb_dim])
    x = emb
    if dtype in ('bfloat16', 'float16'):
        x = fluid.layers.cast(x=x, dtype=dtype)
    for i in range(num_layers):
        fc = fluid.layers.fc(input=x, size=hidden_dim * 4,
                             num_flatten_dims=2)
        h, _ = fluid.layers.dynamic_lstm(input=fc, size=hidden_dim * 4)
        x = h
    # vocab-head matmul in the activation dtype; softmax in fp32
    logits = fluid.layers.fc(input=x, size=vocab_size, num_flatten_dims=2,
                             act=None)
    if dtype in ('bfloat16', 'float16'):
        logits = fluid.layers.cast(x=logits, dtype='float32')
    probs = fluid.layers.softmax(x=logits)
    cost = fluid.layers.cross_entropy(input=probs, label=target,
                                      soft_label=False)
    # mask out padded steps via sequence-average
    avg_cost = fluid.layers.mean(
        x=fluid.layers.sequence_pool(input=cost, pool_type='average'))
    return src, target, avg_cost
