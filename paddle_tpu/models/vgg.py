"""M3 — VGG-16.

Reference parity: book image_classification vgg16_bn_drop (cifar) and
benchmark/paddle/image/vgg.py (ImageNet VGG-16/19).
"""
import paddle_tpu as fluid

__all__ = ['vgg16_bn_drop', 'vgg_imagenet']


def vgg16_bn_drop(input, num_classes=10):
    def conv_block(ipt, num_filter, groups, dropouts):
        return fluid.nets.img_conv_group(
            input=ipt,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act='relu',
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type='max')

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = fluid.layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = fluid.layers.fc(input=drop, size=512, act=None)
    bn = fluid.layers.batch_norm(input=fc1, act='relu')
    drop2 = fluid.layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = fluid.layers.fc(input=drop2, size=512, act=None)
    return fluid.layers.fc(input=fc2, size=num_classes, act='softmax')


def vgg_imagenet(input, num_classes=1000, depth=16, layout='NCHW'):
    """benchmark/paddle/image/vgg.py layout (plain convs, no BN).

    layout='NHWC' keeps channels minor (the MXU-preferred layout); feed
    bf16 input for the bf16 MXU path — the classifier head's final fc
    runs fp32 so the softmax stays well-conditioned."""
    cfg = {16: [2, 2, 3, 3, 3], 19: [2, 2, 4, 4, 4]}[depth]

    def conv_block(ipt, num_filter, groups):
        return fluid.nets.img_conv_group(
            input=ipt,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act='relu',
            conv_with_batchnorm=False,
            pool_type='max',
            data_format=layout)

    out = input
    for num_filter, groups in zip([64, 128, 256, 512, 512], cfg):
        out = conv_block(out, num_filter, groups)
    fc1 = fluid.layers.fc(input=out, size=4096, act='relu')
    drop1 = fluid.layers.dropout(x=fc1, dropout_prob=0.5)
    fc2 = fluid.layers.fc(input=drop1, size=4096, act='relu')
    drop2 = fluid.layers.dropout(x=fc2, dropout_prob=0.5)
    head = fluid.layers.cast(x=drop2, dtype='float32')
    return fluid.layers.fc(input=head, size=num_classes, act='softmax')
