"""M8 — machine translation: seq2seq encoder-decoder with attention.

Reference parity: fluid/tests/book/test_machine_translation.py (GRU
encoder, attention decoder, beam-search generation on WMT14).

TPU-native design note: the reference's decoder is a DynamicRNN that
re-computes attention per interpreted step.  Here training-time attention
is the batched Luong form — decoder GRU runs over the whole (teacher
-forced) target in one `lax.scan`, then attention over the padded encoder
states is ONE [B,Td,H]x[B,H,Ts] matmul (MXU) with a length mask — which is
mathematically the same attention, but rides two large matmuls instead of
Ts small ones.  Generation (`decode`) runs the same shared-weight decoder
cell step-by-step inside a `layers.While` loop (lowered to one
`lax.scan`), pruning a dense [B, K] beam lattice per step with
`layers.beam_search` and backtracking with `layers.beam_search_decode` —
the static-shape counterpart of beam_search_op.cc's host-side LoD pruning.
All parameters carry fixed names (``mt_*``) so a decode program built in
the same scope reuses the trained weights.
"""
import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr

__all__ = ['encoder', 'train_net', 'build', 'decode']


def _attr(name):
    return ParamAttr(name=name)


def encoder(src_word_id, dict_size, word_dim=32, hidden_dim=32,
            dtype='float32'):
    # is_sparse: lazy SelectedRows Adam touches only the looked-up rows
    # — a dense update streams the full [dict_size, word_dim] moments
    # every step (profiled as the largest seq2seq fusion at 30k vocab)
    src_embedding = fluid.layers.embedding(
        input=src_word_id, size=[dict_size, word_dim], dtype='float32',
        is_sparse=True, param_attr=_attr('mt_src_emb'))
    if dtype in ('bfloat16', 'float16'):
        src_embedding = fluid.layers.cast(x=src_embedding, dtype=dtype)
    fc_forward = fluid.layers.fc(
        input=src_embedding, size=hidden_dim * 3, num_flatten_dims=2,
        param_attr=_attr('mt_enc_fc_fwd_w'),
        bias_attr=_attr('mt_enc_fc_fwd_b'))
    src_forward = fluid.layers.dynamic_gru(
        input=fc_forward, size=hidden_dim,
        param_attr=_attr('mt_enc_gru_fwd_w'),
        bias_attr=_attr('mt_enc_gru_fwd_b'))
    fc_backward = fluid.layers.fc(
        input=src_embedding, size=hidden_dim * 3, num_flatten_dims=2,
        param_attr=_attr('mt_enc_fc_bwd_w'),
        bias_attr=_attr('mt_enc_fc_bwd_b'))
    src_backward = fluid.layers.dynamic_gru(
        input=fc_backward, size=hidden_dim, is_reverse=True,
        param_attr=_attr('mt_enc_gru_bwd_w'),
        bias_attr=_attr('mt_enc_gru_bwd_b'))
    encoded = fluid.layers.concat(input=[src_forward, src_backward], axis=2)
    return encoded


def _decoder_init(encoded, hidden_dim):
    """Decoder h0 from the encoder's last step (shared weights)."""
    enc_last = fluid.layers.sequence_last_step(input=encoded)
    return fluid.layers.fc(input=enc_last, size=hidden_dim, act='tanh',
                           param_attr=_attr('mt_dec_h0_w'),
                           bias_attr=_attr('mt_dec_h0_b'))


def _enc_proj(encoded, hidden_dim):
    return fluid.layers.fc(input=encoded, size=hidden_dim,
                           num_flatten_dims=2,
                           param_attr=_attr('mt_enc_proj_w'),
                           bias_attr=_attr('mt_enc_proj_b'))


def _attend_combined(dec_states, encoded, enc_proj):
    """Shared Luong attention: dec_states [B, Td|K, H] against the
    padded encoder states — scores, masked softmax, context concat.
    Used verbatim by BOTH the teacher-forced train path and the per-step
    beam decode so the two can never drift."""
    scores = fluid.layers.matmul(dec_states, enc_proj, transpose_y=True)
    attn = fluid.layers.sequence_softmax(
        input=scores, length_input=encoded, axis=2)
    context = fluid.layers.matmul(attn, encoded)
    return fluid.layers.concat(input=[dec_states, context], axis=2)


def _attend_hidden(dec_states, encoded, enc_proj, hidden_dim):
    """Luong attentional hidden state (Luong'15 eq. 5): h̃ = tanh(W_c
    [context; dec_state]).  The vocab head reads this H-wide h̃, not the
    3H-wide concat — matching the reference book decoder whose head is
    likewise hidden_dim-wide (test_machine_translation.py:66-69 projects
    fc1 of size decoder_size to the vocab).  Projecting the raw concat
    would triple the FLOPs and optimizer state of the dominant vocab
    matmuls for the same model capacity (measured 3×377 GFLOP/step at
    the bench config — PERF.md)."""
    combined = _attend_combined(dec_states, encoded, enc_proj)
    return fluid.layers.fc(
        input=combined, size=hidden_dim, act='tanh', num_flatten_dims=2,
        param_attr=_attr('mt_att_ht_w'), bias_attr=_attr('mt_att_ht_b'))


def _attend_logits(dec_states, encoded, enc_proj, dict_size, hidden_dim):
    """Attention + vocab head up to the fp32 LOGITS.  Under bf16
    activations the vocab matmul runs bf16 and only what follows the
    logits is fp32."""
    att_h = _attend_hidden(dec_states, encoded, enc_proj, hidden_dim)
    logits = fluid.layers.fc(
        input=att_h, size=dict_size, num_flatten_dims=2, act=None,
        param_attr=_attr('mt_out_fc_w'), bias_attr=_attr('mt_out_fc_b'))
    if logits.dtype in ('bfloat16', 'float16'):
        logits = fluid.layers.cast(x=logits, dtype='float32')
    return logits


def _attend_and_score(dec_states, encoded, enc_proj, dict_size,
                      hidden_dim):
    return fluid.layers.softmax(
        x=_attend_logits(dec_states, encoded, enc_proj, dict_size,
                         hidden_dim))


def train_net(src, trg, label, dict_size, word_dim=32, hidden_dim=32,
              dtype='float32', fuse_vocab_loss=True):
    encoded = encoder(src, dict_size, word_dim, hidden_dim, dtype=dtype)
    dec_h0 = _decoder_init(encoded, hidden_dim)

    trg_embedding = fluid.layers.embedding(
        input=trg, size=[dict_size, word_dim], dtype='float32',
        is_sparse=True, param_attr=_attr('mt_trg_emb'))
    if dtype in ('bfloat16', 'float16'):
        trg_embedding = fluid.layers.cast(x=trg_embedding, dtype=dtype)
    dec_fc = fluid.layers.fc(
        input=trg_embedding, size=hidden_dim * 3, num_flatten_dims=2,
        param_attr=_attr('mt_dec_fc_w'), bias_attr=_attr('mt_dec_fc_b'))
    dec_out = fluid.layers.dynamic_gru(
        input=dec_fc, size=hidden_dim, h_0=dec_h0,
        param_attr=_attr('mt_dec_gru_w'), bias_attr=_attr('mt_dec_gru_b'))

    # Luong attention: scores over padded encoder states, masked
    # softmax, then the eq.-5 bottleneck h̃ the vocab head reads
    enc_proj = _enc_proj(encoded, hidden_dim)
    att_h = _attend_hidden(dec_out, encoded, enc_proj, hidden_dim)
    # prediction kept for parity consumers (fetch/inference) — when only
    # the loss is fetched XLA dead-code-eliminates this whole branch
    logits = fluid.layers.fc(
        input=att_h, size=dict_size, num_flatten_dims=2, act=None,
        param_attr=_attr('mt_out_fc_w'), bias_attr=_attr('mt_out_fc_b'))
    if logits.dtype in ('bfloat16', 'float16'):
        logits = fluid.layers.cast(x=logits, dtype='float32')
    prediction = fluid.layers.softmax(x=logits)
    if fuse_vocab_loss:
        # TPU-first loss: vocab projection + softmax-CE in one chunked
        # op — the [B·T, dict_size] logits never reach HBM (the same
        # head params as the fc above, so decode/inference reuse the
        # trained weights).  ops/chunked_ce.py has the analysis.
        cost = fluid.layers.fused_linear_softmax_ce(
            input=att_h, label=label, size=dict_size,
            num_flatten_dims=2, param_attr=_attr('mt_out_fc_w'),
            bias_attr=_attr('mt_out_fc_b'))
    else:
        # dense reference path: fused softmax_with_cross_entropy on the
        # materialized logits (backward = one softmax − onehot pass)
        cost = fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                       label=label)
    avg_cost = fluid.layers.mean(
        x=fluid.layers.sequence_pool(input=cost, pool_type='sum'))
    return prediction, avg_cost


def build(dict_size, word_dim=32, hidden_dim=32, dtype='float32',
          fuse_vocab_loss=True):
    """Returns (src, trg, label, prediction, avg_cost).  dtype='bfloat16'
    runs embeddings/projections/GRU gates/vocab head in bf16 with fp32
    master weights; the softmax and loss stay fp32."""
    src = fluid.layers.data(name='src_word_id', shape=[1], dtype='int64',
                            lod_level=1)
    trg = fluid.layers.data(name='target_language_word', shape=[1],
                            dtype='int64', lod_level=1)
    label = fluid.layers.data(name='target_language_next_word', shape=[1],
                              dtype='int64', lod_level=1)
    prediction, avg_cost = train_net(src, trg, label, dict_size, word_dim,
                                     hidden_dim, dtype=dtype,
                                     fuse_vocab_loss=fuse_vocab_loss)
    return src, trg, label, prediction, avg_cost


def decode(src, dict_size, word_dim=32, hidden_dim=32, beam_size=4,
           max_len=16, start_id=0, end_id=1):
    """Beam-search generation program (reference book decode path).

    Builds the shared-weight decoder unrolled as a While loop: each tick
    embeds the current [B, K] beam tokens, advances the GRU cell, attends
    over the encoder states, scores the vocab, and prunes to the top K
    continuations.  Returns (sentence_ids [B, K, max_len] end_id-padded,
    sentence_scores [B, K]) best-first along K.
    """
    layers = fluid.layers
    encoded = encoder(src, dict_size, word_dim, hidden_dim)
    dec_h0 = _decoder_init(encoded, hidden_dim)          # [B, H]
    enc_proj = _enc_proj(encoded, hidden_dim)            # [B, Ts, H]

    pre_ids, pre_scores = layers.beam_search_init(
        dec_h0, beam_size=beam_size, start_id=start_id)  # [B, K]
    hidden = layers.expand(
        layers.reshape(dec_h0, shape=[-1, 1, hidden_dim]),
        expand_times=[1, beam_size, 1])                   # [B, K, H]

    counter = layers.zeros(shape=[1], dtype='int64')
    limit = layers.fill_constant(shape=[1], dtype='int64', value=max_len)
    cond = layers.less_than(x=counter, y=limit)

    ids_arr = layers.create_array('int64')
    parents_arr = layers.create_array('int64')
    scores_arr = layers.create_array('float32')

    while_op = layers.While(cond=cond, max_iters=max_len)
    with while_op.block():
        emb = layers.embedding(
            input=pre_ids, size=[dict_size, word_dim], dtype='float32',
            param_attr=_attr('mt_trg_emb'))
        # lookup_table squeezes a trailing size-1 axis (fluid's [N, 1] id
        # convention) which eats the beam axis when K == 1 — restore it
        emb = layers.reshape(emb, shape=[-1, beam_size, word_dim])
        step_fc = layers.fc(
            input=emb, size=hidden_dim * 3, num_flatten_dims=2,
            param_attr=_attr('mt_dec_fc_w'), bias_attr=_attr('mt_dec_fc_b'))
        flat_in = layers.reshape(step_fc, shape=[-1, hidden_dim * 3])
        flat_h = layers.reshape(hidden, shape=[-1, hidden_dim])
        new_h_flat, _, _ = layers.gru_unit(
            input=flat_in, hidden=flat_h, size=hidden_dim * 3,
            param_attr=_attr('mt_dec_gru_w'),
            bias_attr=_attr('mt_dec_gru_b'))              # [B*K, H]
        new_h = layers.reshape(new_h_flat,
                               shape=[-1, beam_size, hidden_dim])

        probs = _attend_and_score(new_h, encoded, enc_proj, dict_size,
                                  hidden_dim)
        logp = layers.log(probs)                          # [B, K, V]

        sel_ids, sel_scores, parents = layers.beam_search(
            pre_ids=pre_ids, pre_scores=pre_scores, scores=logp,
            beam_size=beam_size, end_id=end_id)

        layers.array_write(sel_ids, counter, ids_arr, capacity=max_len)
        layers.array_write(parents, counter, parents_arr, capacity=max_len)
        layers.array_write(sel_scores, counter, scores_arr,
                           capacity=max_len)

        # carry: beams + beam-reordered decoder state
        layers.assign(layers.beam_gather(new_h, parents), hidden)
        layers.assign(sel_ids, pre_ids)
        layers.assign(sel_scores, pre_scores)
        layers.increment(x=counter, value=1, in_place=True)
        layers.less_than(x=counter, y=limit, cond=cond)

    seq_ids, seq_scores = layers.beam_search_decode(
        ids_arr, parents_arr, scores_arr, end_id=end_id)
    return seq_ids, seq_scores
