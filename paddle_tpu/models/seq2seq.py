"""M8 — machine translation: seq2seq encoder-decoder with attention.

Reference parity: fluid/tests/book/test_machine_translation.py (GRU
encoder, attention decoder, beam-search generation on WMT14).

TPU-native design note: the reference's decoder is a DynamicRNN that
re-computes attention per interpreted step.  Here training-time attention
is the batched Luong form — decoder GRU runs over the whole (teacher
-forced) target in one `lax.scan`, then attention over the padded encoder
states is ONE [B,Td,H]x[B,H,Ts] matmul (MXU) with a length mask — which is
mathematically the same attention, but rides two large matmuls instead of
Ts small ones.  Generation-time beam search lives in
`layers.beam_search` (static-shape scan, models/seq2seq.py: decode()).
"""
import paddle_tpu as fluid

__all__ = ['encoder', 'train_net', 'build']


def encoder(src_word_id, dict_size, word_dim=32, hidden_dim=32):
    src_embedding = fluid.layers.embedding(
        input=src_word_id, size=[dict_size, word_dim], dtype='float32')
    fc_forward = fluid.layers.fc(
        input=src_embedding, size=hidden_dim * 3, num_flatten_dims=2)
    src_forward = fluid.layers.dynamic_gru(input=fc_forward, size=hidden_dim)
    fc_backward = fluid.layers.fc(
        input=src_embedding, size=hidden_dim * 3, num_flatten_dims=2)
    src_backward = fluid.layers.dynamic_gru(
        input=fc_backward, size=hidden_dim, is_reverse=True)
    encoded = fluid.layers.concat(input=[src_forward, src_backward], axis=2)
    return encoded


def train_net(src, trg, label, dict_size, word_dim=32, hidden_dim=32):
    encoded = encoder(src, dict_size, word_dim, hidden_dim)

    # decoder init state from the encoder's last step
    enc_last = fluid.layers.sequence_last_step(input=encoded)
    dec_h0 = fluid.layers.fc(input=enc_last, size=hidden_dim, act='tanh')

    trg_embedding = fluid.layers.embedding(
        input=trg, size=[dict_size, word_dim], dtype='float32')
    dec_fc = fluid.layers.fc(
        input=trg_embedding, size=hidden_dim * 3, num_flatten_dims=2)
    dec_out = fluid.layers.dynamic_gru(
        input=dec_fc, size=hidden_dim, h_0=dec_h0)

    # Luong attention: scores over padded encoder states, masked softmax
    enc_proj = fluid.layers.fc(
        input=encoded, size=hidden_dim, num_flatten_dims=2)
    scores = fluid.layers.matmul(dec_out, enc_proj, transpose_y=True)
    attn = fluid.layers.sequence_softmax(
        input=scores, length_input=encoded, axis=2)
    context = fluid.layers.matmul(attn, encoded)
    combined = fluid.layers.concat(input=[dec_out, context], axis=2)

    prediction = fluid.layers.fc(
        input=combined, size=dict_size, num_flatten_dims=2, act='softmax')
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(
        x=fluid.layers.sequence_pool(input=cost, pool_type='sum'))
    return prediction, avg_cost


def build(dict_size, word_dim=32, hidden_dim=32):
    """Returns (src, trg, label, prediction, avg_cost)."""
    src = fluid.layers.data(name='src_word_id', shape=[1], dtype='int64',
                            lod_level=1)
    trg = fluid.layers.data(name='target_language_word', shape=[1],
                            dtype='int64', lod_level=1)
    label = fluid.layers.data(name='target_language_next_word', shape=[1],
                              dtype='int64', lod_level=1)
    prediction, avg_cost = train_net(src, trg, label, dict_size, word_dim,
                                     hidden_dim)
    return src, trg, label, prediction, avg_cost
