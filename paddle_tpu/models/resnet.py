"""M3 flagship — ResNet for CIFAR (reference book
image_classification resnet_cifar10) and ImageNet (reference
benchmark/paddle/image/resnet.py: depth 18/34/50/101/152).

TPU notes: 3x3/1x1 convs land on the MXU via lax.conv_general_dilated;
train with dtype='bfloat16' activations (batch_norm keeps fp32 stats) for
the bench path; XLA fuses the bn+relu chains into the conv epilogues.
"""
import paddle_tpu as fluid

__all__ = ['resnet_cifar10', 'resnet_imagenet', 'build_imagenet']


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act='relu',
                  bias_attr=False, layout='NCHW'):
    tmp = fluid.layers.conv2d(
        input=input,
        filter_size=filter_size,
        num_filters=ch_out,
        stride=stride,
        padding=padding,
        act=None,
        bias_attr=bias_attr,
        data_format=layout)
    return fluid.layers.batch_norm(input=tmp, act=act, data_layout=layout)


def shortcut(input, ch_in, ch_out, stride, layout='NCHW'):
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             layout=layout)
    return input


def basicblock(input, ch_in, ch_out, stride, layout='NCHW'):
    short = shortcut(input, ch_in, ch_out, stride, layout)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, layout=layout)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, layout=layout)
    return fluid.layers.elementwise_add(x=short, y=conv2, act='relu')


def bottleneck(input, ch_in, ch_out, stride, layout='NCHW'):
    short = shortcut(input, ch_in, ch_out * 4, stride, layout)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, layout=layout)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, layout=layout)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          layout=layout)
    return fluid.layers.elementwise_add(x=short, y=conv3, act='relu')


def layer_warp(block_func, input, ch_in, ch_out, count, stride,
               layout='NCHW'):
    res_out = block_func(input, ch_in, ch_out, stride, layout)
    ch_in = ch_out * (4 if block_func is bottleneck else 1)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_in, ch_out, 1, layout)
    return res_out


def resnet_cifar10(ipt, depth=32, num_classes=10):
    """Reference: book/.../image_classification resnet_cifar10 (depth 32)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(ipt, ch_out=16, filter_size=3, stride=1, padding=1)
    res1 = layer_warp(basicblock, conv1, 16, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 16, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 32, 64, n, 2)
    pool = fluid.layers.pool2d(
        input=res3, pool_size=8, pool_type='avg', pool_stride=1)
    return fluid.layers.fc(input=pool, size=num_classes, act='softmax')


_DEPTH_CFG = {
    18: (basicblock, [2, 2, 2, 2]),
    34: (basicblock, [3, 4, 6, 3]),
    50: (bottleneck, [3, 4, 6, 3]),
    101: (bottleneck, [3, 4, 23, 3]),
    152: (bottleneck, [3, 8, 36, 3]),
}


def _space_to_depth_stem(input, layout):
    """TPU stem: rearrange 2x2 pixel blocks into channels, then a 4x4
    stride-1 conv in block space.

    The reference's 7x7/2 stem conv (benchmark/paddle/image/resnet.py)
    puts a 3-channel input on the MXU, wasting most of the 128-lane
    contraction dimension.  Re-basing to 2x2 blocks ([B,224,224,3] ->
    [B,112,112,12]) makes the contraction 4x denser at identical math:
    a zero-padded 8x8/2 conv over pixels IS a 4x4/1 conv over blocks
    (window [2o-4, 2o+3] = blocks o-2..o+1 -> block pad (2,1), VALID).
    Trained from scratch the 8x8 basis is a strict superset of the 7x7.
    """
    if layout == 'NHWC':
        b, h, w, c = input.shape
        x = fluid.layers.reshape(input, [b, h // 2, 2, w // 2, 2, c])
        x = fluid.layers.transpose(x, [0, 1, 3, 2, 4, 5])
        x = fluid.layers.reshape(x, [b, h // 2, w // 2, 4 * c])
        x = fluid.layers.pad(x, [0, 0, 2, 1, 2, 1, 0, 0])
    else:
        b, c, h, w = input.shape
        x = fluid.layers.reshape(input, [b, c, h // 2, 2, w // 2, 2])
        x = fluid.layers.transpose(x, [0, 1, 3, 5, 2, 4])
        x = fluid.layers.reshape(x, [b, 4 * c, h // 2, w // 2])
        x = fluid.layers.pad(x, [0, 0, 0, 0, 2, 1, 2, 1])
    return conv_bn_layer(x, ch_out=64, filter_size=4, stride=1, padding=0,
                         layout=layout)


def resnet_imagenet(input, depth=50, num_classes=1000, layout='NCHW',
                    stem='7x7'):
    """Reference: benchmark/paddle/image/resnet.py (ImageNet layout).

    stem='space_to_depth' swaps the 7x7/2 first conv for the MXU-dense
    block-space equivalent (see _space_to_depth_stem)."""
    block, counts = _DEPTH_CFG[depth]
    if stem == 'space_to_depth':
        conv1 = _space_to_depth_stem(input, layout)
    else:
        conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                              padding=3, layout=layout)
    pool1 = fluid.layers.pool2d(
        input=conv1, pool_size=3, pool_stride=2, pool_padding=1,
        pool_type='max', data_format=layout)
    ch_in = 64
    out = pool1
    for i, (ch_out, count) in enumerate(zip([64, 128, 256, 512], counts)):
        stride = 1 if i == 0 else 2
        out = layer_warp(block, out, ch_in, ch_out, count, stride, layout)
        ch_in = ch_out * (4 if block is bottleneck else 1)
    pool2 = fluid.layers.pool2d(
        input=out, pool_size=7, pool_type='avg', global_pooling=True,
        data_format=layout)
    # classifier head in fp32: softmax/cross-entropy stay well-conditioned
    head = fluid.layers.cast(x=pool2, dtype='float32')
    return fluid.layers.fc(input=head, size=num_classes, act='softmax')


def build_imagenet(depth=50, num_classes=1000, image_shape=(3, 224, 224),
                   dtype='float32', layout='NCHW', stem='7x7'):
    """Returns (img, label, prediction, avg_cost, acc) — the bench model.

    dtype='bfloat16' runs conv/matmul activations in bf16 with fp32
    accumulation (ops/conv.py preferred_element_type) and fp32 BN stats;
    layout='NHWC' keeps channels minor — the MXU-preferred layout (feed
    `image_shape` already permuted, e.g. (224, 224, 3)).
    """
    img = fluid.layers.data(name='img', shape=list(image_shape),
                            dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    x = img
    if dtype == 'bfloat16':
        x = fluid.layers.cast(x=x, dtype='bfloat16')
    prediction = resnet_imagenet(x, depth=depth, num_classes=num_classes,
                                 layout=layout, stem=stem)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return img, label, prediction, avg_cost, acc
