"""M5 — word2vec N-gram LM on imikolov.

Reference parity: fluid/tests/book/test_word2vec.py (4-word context
predicts the 5th; shared embedding table).
"""
import paddle_tpu as fluid

__all__ = ['build']

EMBED_SIZE = 32
HIDDEN_SIZE = 256
N = 5


def build(dict_size):
    """Returns (word_vars, next_word, predict, avg_cost)."""
    names = ['firstw', 'secondw', 'thirdw', 'forthw']
    words = [fluid.layers.data(name=n, shape=[1], dtype='int64')
             for n in names]
    next_word = fluid.layers.data(name='nextw', shape=[1], dtype='int64')

    embeds = [
        fluid.layers.embedding(
            input=w,
            size=[dict_size, EMBED_SIZE],
            dtype='float32',
            is_sparse=True,
            param_attr=fluid.ParamAttr(name='shared_w')) for w in words
    ]
    concat_embed = fluid.layers.concat(input=embeds, axis=1)
    hidden1 = fluid.layers.fc(input=concat_embed, size=HIDDEN_SIZE,
                              act='sigmoid')
    predict_word = fluid.layers.fc(input=hidden1, size=dict_size,
                                   act='softmax')
    cost = fluid.layers.cross_entropy(input=predict_word, label=next_word)
    avg_cost = fluid.layers.mean(x=cost)
    return words, next_word, predict_word, avg_cost
