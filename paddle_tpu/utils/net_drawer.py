"""P17 — net_drawer: render a Program as graphviz dot text.

Reference parity: python/paddle/v2/fluid/net_drawer.py (draw_graph over
ops/vars with graphviz).  Pure-text .dot output — no graphviz binary
needed; `dot -Tpng` renders it wherever available.
"""
import html

__all__ = ['draw_graph', 'draw_block_graphviz']

OP_STYLE = 'shape=box, style=rounded, fillcolor="#a0d0ff", style=filled'
VAR_STYLE = 'shape=ellipse, fillcolor="#dddddd", style=filled'
PARAM_STYLE = 'shape=ellipse, fillcolor="#ffe0a0", style=filled'


def _q(name):
    return '"%s"' % html.escape(str(name), quote=False).replace('"', "'")


def draw_block_graphviz(block, highlights=None, path=None):
    """Dot text for one block: op nodes + var nodes + data edges."""
    highlights = set(highlights or [])
    lines = ['digraph G {', '  rankdir=TB;']
    params = {p.name for p in block.all_parameters()} if hasattr(
        block, 'all_parameters') else set()
    seen_vars = set()

    def var_node(name):
        if name in seen_vars:
            return
        seen_vars.add(name)
        style = PARAM_STYLE if name in params else VAR_STYLE
        if name in highlights:
            style += ', color=red, penwidth=2'
        lines.append('  %s [%s];' % (_q(name), style))

    for i, op in enumerate(block.ops):
        op_id = 'op_%d_%s' % (i, op.type)
        lines.append('  %s [label=%s, %s];' % (_q(op_id), _q(op.type),
                                               OP_STYLE))
        for name in op.input_arg_names:
            var_node(name)
            lines.append('  %s -> %s;' % (_q(name), _q(op_id)))
        for name in op.output_arg_names:
            var_node(name)
            lines.append('  %s -> %s;' % (_q(op_id), _q(name)))
    lines.append('}')
    dot = '\n'.join(lines)
    if path:
        with open(path, 'w') as f:
            f.write(dot)
    return dot


def draw_graph(startup_program, main_program, path=None, **kwargs):
    """Reference draw_graph signature: renders main_program's global
    block (startup accepted for parity)."""
    return draw_block_graphviz(main_program.global_block(), path=path,
                               **kwargs)
