from . import net_drawer  # noqa: F401

__all__ = ['net_drawer']
